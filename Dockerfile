# ai_crypto_trader_tpu — single-process deployment.
# The reference ships 16 containers wired by Redis (docker-compose.yml:1-419);
# this framework is one process per host: the compute core runs inside the
# JAX runtime, services share one event loop, /metrics + /health are served
# in-process. On TPU VMs, base this on a jax[tpu]-provisioned image.
FROM python:3.12-slim

WORKDIR /app
COPY ai_crypto_trader_tpu ./ai_crypto_trader_tpu
COPY bench.py __graft_entry__.py ./

# jax/flax/optax/orbax are expected from the accelerator base image on TPU
# hosts; for CPU paper-trading installs:
RUN pip install --no-cache-dir "jax[cpu]" flax optax orbax-checkpoint chex einops

EXPOSE 9090
ENTRYPOINT ["python", "-m", "ai_crypto_trader_tpu.cli"]
CMD ["trade", "--paper", "--ticks", "1000"]
