"""ai_crypto_trader_tpu — a TPU-native quantitative crypto-trading framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
system (zd87pl/ai-crypto-trader): technical-indicator analytics, vectorized
backtesting, Monte-Carlo risk simulation, neural price prediction, DQN
reinforcement learning, genetic strategy evolution, market-regime detection,
chart-pattern recognition, portfolio risk management, and a live-trading host
shell — all with the heavy compute expressed as pure, jit-compiled functions
that scale over a `jax.sharding.Mesh`.

Design stance (vs the reference's 16 Redis-pub/sub microservices):
a single-process-per-host compute core (pure JAX, jit/vmap/shard_map) plus a
thin async host shell for exchange/LLM/news I/O.  Numeric data travels over
ICI via XLA collectives, never over a network bus.
"""

__version__ = "0.1.0"

from ai_crypto_trader_tpu.config import FrameworkConfig, load_config  # noqa: F401
