from ai_crypto_trader_tpu.backtest.signals import (  # noqa: F401
    SignalFeatures,
    compute_signal_features,
    position_size,
    reference_signal,
)
from ai_crypto_trader_tpu.backtest.strategy import (  # noqa: F401
    PARAM_RANGES,
    StrategyParams,
    clamp_params,
    default_params,
    sample_params,
)
from ai_crypto_trader_tpu.backtest.engine import (  # noqa: F401
    BacktestStats,
    prepare_inputs,
    run_backtest,
    sweep,
)
from ai_crypto_trader_tpu.backtest.metrics import compute_metrics  # noqa: F401
from ai_crypto_trader_tpu.backtest.portfolio import (  # noqa: F401
    portfolio_backtest,
    shared_capital_backtest,
    stack_symbol_inputs,
)
