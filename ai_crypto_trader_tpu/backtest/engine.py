"""The vectorized backtest engine — a `lax.scan` over the candle axis.

TPU-native re-expression of the reference's sequential replay loop
(`backtesting/strategy_tester.py:190-300`): one Python iteration + one
OpenAI round-trip per candle becomes one fused scan step over a whole
population of strategies at once —

    lax.scan   over candles           (inherently sequential position state)
    vmap       over strategy params   (the GA population / param grids)
    vmap       over symbols           (portfolio axis)
    shard_map  over the device mesh   (population sharded over ICI)

The scan carries fixed-size position state (no Python dicts — SURVEY §7.4),
and the AI gate is an input array of per-candle confidences/decisions, so a
learned policy, a recorded LLM trace, or the constant technical rule can all
drive the same compiled program (the LLM itself stays host-side; see
SURVEY §7.4 "The AI (GPT) gate").

Parity contract (tests/test_backtest_parity.py pins this against a scalar
Python port of the reference loop):
  * first `warmup` candles skipped (strategy_tester.py:192),
  * SL/TP checked against realized pnl% before any open, a position closed
    at candle t may be re-opened at t (pop → re-entry, lines 202-277),
  * balance changes only on close — opens don't reserve capital, equity is
    realized-only (open_position books no debit, lines 314-335),
  * win = pnl > 0, loss otherwise; profit_factor left 0 when no losses
    (calculate_final_stats:403-413),
  * Sharpe = mean/std of per-candle equity returns × √252 with an initial
    zero return, population std (lines 415-430).

`reference_quirks=True` additionally reproduces the reference's SL/TP unit
bug: PositionSizer returns fractional stops (0.02) that strategy_tester
compares against percent PnL (`strategy_tester.py:209` vs
`binance_ml_strategy.py:260`), firing stops 100× tighter than intended.
Default False interprets them as percent (the intended 2%).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ai_crypto_trader_tpu.backtest import signals as sig
from ai_crypto_trader_tpu.backtest.strategy import StrategyParams
from ai_crypto_trader_tpu.obs import tickpath
from ai_crypto_trader_tpu.utils import devprof, meshprof, tracing


def _traced_entry(name: str, close, attrs_fn, call):
    """Host-side span around a jitted entry point. Pass-through when
    tracing is off OR when the call happens inside a jax transform (the
    argument is a Tracer: opening host spans mid-trace would record
    garbage timings once per trace, not per execution)."""
    if tracing.active() is None or isinstance(close, jax.core.Tracer):
        return call()
    return tracing.traced_dispatch(name, call, service="backtest",
                                   attrs_fn=attrs_fn)


class BacktestInputs(NamedTuple):
    """Per-candle arrays consumed by the scan (all shape [T]).

    sl_pct / tp_pct are OPTIONAL per-candle exit levels (percent) captured
    at entry time — the ATR-adaptive stop path
    (`portfolio_risk_service.py:489-547` applied per entry). NaN means
    "no override": the engine falls back to StrategyParams / the sizer."""

    close: jnp.ndarray
    signal: jnp.ndarray        # int32 {-1,0,1}
    strength: jnp.ndarray      # f32 [0,100]
    volatility: jnp.ndarray    # ATR/close
    volume: jnp.ndarray        # avg quote volume
    confidence: jnp.ndarray    # AI-gate confidence in [0,1]
    decision: jnp.ndarray      # AI-gate decision int32 {-1,0,1}
    sl_pct: jnp.ndarray        # per-candle SL override (NaN = none)
    tp_pct: jnp.ndarray        # per-candle TP override (NaN = none)


class CarryState(NamedTuple):
    balance: jnp.ndarray
    in_pos: jnp.ndarray        # bool
    entry: jnp.ndarray
    qty: jnp.ndarray
    sl: jnp.ndarray            # stop-loss threshold, percent units
    tp: jnp.ndarray
    max_equity: jnp.ndarray
    max_dd: jnp.ndarray
    max_dd_pct: jnp.ndarray
    trades: jnp.ndarray        # i32 closed trades
    wins: jnp.ndarray
    total_profit: jnp.ndarray
    total_loss: jnp.ndarray
    sum_r: jnp.ndarray         # streaming return moments for Sharpe/Sortino
    sum_r2: jnp.ndarray
    sum_neg_r2: jnp.ndarray
    n_r: jnp.ndarray
    cur_win_streak: jnp.ndarray
    cur_loss_streak: jnp.ndarray
    max_win_streak: jnp.ndarray
    max_loss_streak: jnp.ndarray


class BacktestStats(NamedTuple):
    """Raw scan outputs; compute_metrics() derives the full metric suite."""

    initial_balance: jnp.ndarray
    final_balance: jnp.ndarray
    total_trades: jnp.ndarray
    winning_trades: jnp.ndarray
    losing_trades: jnp.ndarray
    total_profit: jnp.ndarray
    total_loss: jnp.ndarray
    max_drawdown: jnp.ndarray
    max_drawdown_pct: jnp.ndarray
    sum_r: jnp.ndarray
    sum_r2: jnp.ndarray
    sum_neg_r2: jnp.ndarray
    n_r: jnp.ndarray
    max_win_streak: jnp.ndarray
    max_loss_streak: jnp.ndarray


@functools.partial(jax.jit, static_argnames=("per_candle_trend",))
def prepare_inputs(ind: dict, confidence=None, decision=None,
                   per_candle_trend: bool = True) -> BacktestInputs:
    """Indicator table → scan inputs. The AI gate defaults to pass-through
    (confidence 1, decision = technical signal), i.e. the reproducible
    configuration BASELINE.md prescribes for batch replay."""
    feats = sig.compute_signal_features(ind, per_candle_trend=per_candle_trend)
    signal, strength = sig.reference_signal(feats)
    T = feats.close.shape[-1]
    if confidence is None:
        confidence = jnp.ones((T,), jnp.float32)
    if decision is None:
        decision = signal
    nan = jnp.full((T,), jnp.nan, jnp.float32)
    return BacktestInputs(
        close=feats.close, signal=signal, strength=strength,
        volatility=feats.volatility, volume=feats.volume,
        confidence=confidence, decision=decision,
        sl_pct=nan, tp_pct=nan,
    )


def _init_state(initial_balance) -> CarryState:
    f = lambda v: jnp.asarray(v, jnp.float32)
    i = lambda v: jnp.asarray(v, jnp.int32)
    return CarryState(
        balance=f(initial_balance), in_pos=jnp.asarray(False),
        entry=f(0.0), qty=f(0.0), sl=f(0.0), tp=f(0.0),
        max_equity=f(initial_balance), max_dd=f(0.0), max_dd_pct=f(0.0),
        trades=i(0), wins=i(0), total_profit=f(0.0), total_loss=f(0.0),
        # n_r starts at 1: the reference's equity curve holds an initial
        # point whose return is 0 (strategy_tester.py:166-169, 417-423).
        sum_r=f(0.0), sum_r2=f(0.0), sum_neg_r2=f(0.0), n_r=i(1),
        cur_win_streak=i(0), cur_loss_streak=i(0),
        max_win_streak=i(0), max_loss_streak=i(0),
    )


def _book_close(s: CarryState, price, do_close):
    """Close the open position where do_close — returns updated state."""
    pnl = (price - s.entry) * s.qty
    win = pnl > 0.0
    new_balance = s.balance + jnp.where(do_close, pnl, 0.0)
    cw = jnp.where(do_close, jnp.where(win, s.cur_win_streak + 1, 0), s.cur_win_streak)
    cl = jnp.where(do_close, jnp.where(win, 0, s.cur_loss_streak + 1), s.cur_loss_streak)
    return s._replace(
        balance=new_balance,
        in_pos=s.in_pos & ~do_close,
        trades=s.trades + do_close.astype(jnp.int32),
        wins=s.wins + (do_close & win).astype(jnp.int32),
        total_profit=s.total_profit + jnp.where(do_close & win, pnl, 0.0),
        total_loss=s.total_loss + jnp.where(do_close & ~win, -pnl, 0.0),
        cur_win_streak=cw, cur_loss_streak=cl,
        max_win_streak=jnp.maximum(s.max_win_streak, cw),
        max_loss_streak=jnp.maximum(s.max_loss_streak, cl),
    )


@functools.partial(
    jax.jit,
    static_argnames=("warmup", "reference_quirks", "use_param_sl_tp",
                     "return_curve", "unroll", "sell_exits"),
)
def _run_backtest_jit(
    inputs: BacktestInputs,
    params: StrategyParams | None = None,
    initial_balance: float = 10_000.0,
    ai_confidence_threshold: float = 0.7,
    min_signal_strength: float = 70.0,
    warmup: int = 10,
    reference_quirks: bool = False,
    use_param_sl_tp: bool = False,
    return_curve: bool = False,
    unroll: int = 8,
    sell_exits: bool = False,
):
    """Run one full backtest as a single compiled scan.

    With ``use_param_sl_tp`` the evolvable StrategyParams stop_loss /
    take_profit (percent) override the PositionSizer's volatility ladder —
    this is the mode GA evolution drives.  ``sell_exits`` adds an explicit
    SELL-signal close on top of SL/TP (off by default: the reference replay
    is long-only with SL/TP-only exits).  Batched axes broadcast: vmap this
    function over params and/or inputs for population/symbol sweeps.
    """
    T = inputs.close.shape[-1]
    steps = jnp.arange(T, dtype=jnp.int32)
    step = replay_step(
        params, warmup=warmup,
        ai_confidence_threshold=ai_confidence_threshold,
        min_signal_strength=min_signal_strength,
        reference_quirks=reference_quirks, use_param_sl_tp=use_param_sl_tp,
        return_curve=return_curve, sell_exits=sell_exits)

    init = _init_state(initial_balance)
    xs = (steps,) + tuple(inputs)
    final, curve = lax.scan(step, init, xs, unroll=unroll)

    stats = finalize_stats(final, inputs.close[-1], initial_balance)
    return (stats, curve) if return_curve else stats


def replay_step(params: StrategyParams | None, *, warmup: int,
                ai_confidence_threshold, min_signal_strength,
                reference_quirks: bool, use_param_sl_tp: bool,
                return_curve: bool, sell_exits: bool):
    """THE per-candle replay transition, extracted so every scan in the
    repo — the plain replay, the vmapped sweep, and the GA's fused
    signal+replay program (backtest/evolvable.py) — runs the SAME
    position-bookkeeping code.  Returns ``step(state, x)`` where ``x`` is
    (t, close, signal, strength, volatility, volume, confidence,
    decision, sl_override, tp_override) — scalars or same-shaped arrays."""

    def step(s: CarryState, x):
        (t, close, signal, strength, vol, volume, conf, decision,
         sl_override, tp_override) = x
        active = t >= warmup
        prev_balance = s.balance

        # --- SL/TP scan on the open position (strategy_tester.py:202-218) ---
        entry_safe = jnp.where(s.entry == 0.0, 1.0, s.entry)
        pnl_pct = (close - s.entry) / entry_safe * 100.0
        hit_sl = active & s.in_pos & (pnl_pct <= -s.sl)
        hit_tp = active & s.in_pos & ~hit_sl & (pnl_pct >= s.tp)
        # Optional signal-exit: an explicit SELL closes the open position
        # (the live executor's sell-condition close, not part of the
        # reference backtester's SL/TP-only replay — off by default so the
        # parity contract is untouched; structure-generated strategies turn
        # it on so their sell thresholds are a live search dimension).
        hit_sell = (active & s.in_pos & ~hit_sl & ~hit_tp
                    & (signal == sig.SELL)) if sell_exits else jnp.asarray(False)
        closing = hit_sl | hit_tp | hit_sell
        # A position that survives the candle short-circuits the rest of the
        # loop body (`if symbol in open_positions: continue`,
        # strategy_tester.py:221-222): no entry attempt, and — reference
        # semantics — no equity point / drawdown / return observation.
        survived = s.in_pos & ~closing
        s = _book_close(s, close, closing)

        # --- entry gate (strategy_tester.py:221-277, 371-401) ---
        gate = (
            active
            & ~s.in_pos
            & (conf >= ai_confidence_threshold)
            & (strength >= min_signal_strength)
            & (signal == decision)
            & (decision == sig.BUY)
        )
        plan = sig.position_size(s.balance, vol, volume)
        if use_param_sl_tp:
            assert params is not None
            sl_new = params.stop_loss
            tp_new = params.take_profit
            size = plan.size
        else:
            unit = 1.0 if reference_quirks else 100.0
            sl_new = plan.stop_loss_pct * unit
            tp_new = plan.take_profit_pct * unit
            size = plan.size
        # per-candle overrides (ATR-adaptive stops) win where provided
        sl_new = jnp.where(jnp.isnan(sl_override), sl_new, sl_override)
        tp_new = jnp.where(jnp.isnan(tp_override), tp_new, tp_override)
        s = s._replace(
            in_pos=s.in_pos | gate,
            entry=jnp.where(gate, close, s.entry),
            qty=jnp.where(gate, size / close, s.qty),
            sl=jnp.where(gate, sl_new, s.sl),
            tp=jnp.where(gate, tp_new, s.tp),
        )

        # --- equity point + drawdown (strategy_tester.py:280-300), only on
        # candles the reference reaches (not short-circuited by `continue`) ---
        book = active & ~survived
        equity = s.balance
        max_eq = jnp.where(book, jnp.maximum(s.max_equity, equity), s.max_equity)
        dd = max_eq - equity
        dd_pct = dd / max_eq * 100.0
        new_max = book & (dd > s.max_dd)
        r = jnp.where(book, (equity - prev_balance) / prev_balance, 0.0)
        s = s._replace(
            max_equity=max_eq,
            max_dd=jnp.where(new_max, dd, s.max_dd),
            max_dd_pct=jnp.where(new_max, dd_pct, s.max_dd_pct),
            sum_r=s.sum_r + r,
            sum_r2=s.sum_r2 + r * r,
            sum_neg_r2=s.sum_neg_r2 + jnp.where(r < 0, r * r, 0.0),
            n_r=s.n_r + book.astype(jnp.int32),
        )
        return s, (equity if return_curve else None)

    return step


def finalize_stats(final: CarryState, last_close,
                   initial_balance) -> BacktestStats:
    """Close any remaining position at the last price ("End of Test",
    strategy_tester.py:302-307) and assemble the raw stats — shared by
    every scan that drives `replay_step`."""
    final = _book_close(final, last_close, final.in_pos)
    return BacktestStats(
        initial_balance=jnp.asarray(initial_balance, jnp.float32),
        final_balance=final.balance,
        total_trades=final.trades,
        winning_trades=final.wins,
        losing_trades=final.trades - final.wins,
        total_profit=final.total_profit,
        total_loss=final.total_loss,
        max_drawdown=final.max_dd,
        max_drawdown_pct=final.max_dd_pct,
        sum_r=final.sum_r,
        sum_r2=final.sum_r2,
        sum_neg_r2=final.sum_neg_r2,
        n_r=final.n_r,
        max_win_streak=final.max_win_streak,
        max_loss_streak=final.max_loss_streak,
    )


def run_backtest(inputs: BacktestInputs,
                 params: StrategyParams | None = None, *args, **kw):
    """Host entry for `_run_backtest_jit` (same signature): when tracing is
    active and this is a real host-side dispatch (not a call inside vmap /
    jit tracing), the run gets a `backtest.run` span with compile-vs-execute
    attribution. Otherwise it is a direct pass-through."""
    return _traced_entry(
        "backtest.run", inputs.close,
        lambda: {"candles": int(inputs.close.shape[-1])},
        lambda: _run_backtest_jit(inputs, params, *args, **kw))


@functools.partial(
    jax.jit,
    static_argnames=("warmup", "reference_quirks", "return_curve", "unroll"),
)
def _sweep_jit(inputs: BacktestInputs, params: StrategyParams,
               initial_balance: float = 10_000.0,
               ai_confidence_threshold: float = 0.7,
               min_signal_strength: float = 70.0,
               warmup: int = 10, reference_quirks: bool = False,
               return_curve: bool = False, unroll: int = 8):
    """vmap the backtester over a stacked StrategyParams population, as ONE
    compiled program (on the remote-compiled TPU backend, anything outside
    jit pays an op-by-op compile round-trip — never run this path eagerly).

    This is the inner loop the GA calls; `run_multiple_backtests`'s
    sequential nested for-loops (`backtest_engine.py:127-178`) become one
    device program.

    `inputs` must carry NaN sl_pct/tp_pct columns (as `prepare_inputs`
    builds them): finite per-candle overrides win over every genome's
    stop_loss/take_profit, which would silently deaden those population
    dimensions. Per-genome ATR-adaptive inputs belong in
    `evolvable.population_backtest`, which rebuilds inputs per member."""
    fn = lambda p: _run_backtest_jit(
        inputs, p, initial_balance=initial_balance,
        ai_confidence_threshold=ai_confidence_threshold,
        min_signal_strength=min_signal_strength, warmup=warmup,
        reference_quirks=reference_quirks, use_param_sl_tp=True,
        return_curve=return_curve, unroll=unroll)
    return jax.vmap(fn)(params)


# The non-population arguments of _sweep_jit in positional order, so the
# partitioned path can fold them into its cached closure (they are rare
# and hashable — statics or scalar budgets).
_SWEEP_ARG_NAMES = ("initial_balance", "ai_confidence_threshold",
                    "min_signal_strength", "warmup", "reference_quirks",
                    "return_curve", "unroll")


@functools.lru_cache(maxsize=16)
def _sweep_partitioned(partitioner, kw_items: tuple):
    """One cached sharded sweep program per (partitioner, settings): the
    population axis splits over the mesh data axis, each device runs its
    strategy shard over the replicated candle arrays, and results are
    all-gathered over ICI (the collective that replaces the reference's
    "publish fitness to Redis", SURVEY §2.7).  Ragged populations pad +
    slice inside the partitioner (repeating the last individual)."""
    kw = dict(kw_items)
    return partitioner.population_eval(
        lambda p_shard, inputs: _sweep_jit(inputs, p_shard, **kw),
        name="population_sweep")


def sweep(inputs: BacktestInputs, params: StrategyParams, *args,
          partitioner=None, **kw):
    """Host entry for the population sweep (same signature as `_sweep_jit`
    plus ``partitioner``), with a `backtest.sweep` span + compile/execute
    attribution when traced and a one-shot ``backtest_sweep`` devprof cost
    card (FLOPs/bytes only: the sweep program is the largest in the repo,
    so the card skips the AOT backend re-compile that memory_analysis
    would cost — see utils/devprof.py).

    ``partitioner`` (parallel/partitioner.py) shards the population over
    the mesh data axis — `parallel.get_partitioner()` to use every
    visible device; None / single-device runs the plain jit program.
    Results are identical either way (the mesh-invariance contract,
    tests/test_partitioner.py)."""
    sharded = (partitioner is not None
               and getattr(partitioner, "device_count", 1) > 1)
    if sharded:
        kw = {**dict(zip(_SWEEP_ARG_NAMES, args)), **kw}
        fn = _sweep_partitioned(partitioner, tuple(sorted(kw.items())))
        call = lambda: fn(params, inputs)  # noqa: E731
        card, card_fn, card_args = ("population_sweep", fn, (params, inputs))
    else:
        call = lambda: _sweep_jit(inputs, params, *args, **kw)  # noqa: E731
        card, card_fn, card_args = ("backtest_sweep", _sweep_jit,
                                    (inputs, params) + args)
    if (devprof.active() is not None
            and not isinstance(inputs.close, jax.core.Tracer)
            and not devprof.has_card(card)):
        devprof.cost_card(card, card_fn, *card_args,
                          _memory_analysis=False,
                          **({} if sharded else kw))
    if (meshprof.active() is not None
            and not isinstance(inputs.close, jax.core.Tracer)):
        # meshprof watch: compile attribution for the sweep dispatch.  A
        # never-seen (population, window, settings, devices) combination
        # compiles by design — mark its window cold so only an UNEXPECTED
        # re-trace at a seen shape counts as a steady-state recompile.
        shape_key = (card, int(jax.tree.leaves(params)[0].shape[0]),
                     int(inputs.close.shape[-1]), args,
                     tuple(sorted(kw.items())),
                     getattr(partitioner, "device_count", 1))
        cold = shape_key not in _SWEEP_SHAPES_SEEN
        _SWEEP_SHAPES_SEEN.add(shape_key)
        inner = call
        call = lambda: _watched(card, cold, inner)  # noqa: E731
    return _traced_entry(
        "backtest.sweep", inputs.close,
        lambda: {"candles": int(inputs.close.shape[-1]),
                 "population": int(jax.tree.leaves(params)[0].shape[0]),
                 "devices": getattr(partitioner, "device_count", 1)},
        call)


# (card, pop, T, args, kw, devices) combinations already dispatched once —
# the sweep's cold-run ledger for the recompile sentinel
_SWEEP_SHAPES_SEEN: set = set()


def _watched(card: str, cold: bool, call):
    with tickpath.coldstart(card, cold=cold), \
            meshprof.watch(card, cold=cold):
        return call()
