"""The evolvable strategy: all 18 GA parameters live in one compiled program.

The reference evolution service mutates parameters that its own backtester
never consumes (its GA fitness is a heuristic score —
`strategy_evolution_service.py:542-641`; its CV simulator is a placeholder
RSI rule — `strategy_evaluation_system.py:358-431`).  Here the full
parameter vector drives a real backtest:

  periods → dynamic-window kernels (ops/dynamic.py, traced under vmap)
  thresholds → the vote-based signal rule (same scoring shape as
               TradingSignal, with parameterized cut-offs)
  stop_loss / take_profit / atr_multiplier → the scan engine's exit logic
  social thresholds → votes from (optional) social metric arrays

so GA fitness = real vectorized backtest Sharpe, evaluated for the whole
population in one vmap and sharded over the mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu.backtest.engine import BacktestInputs, run_backtest
from ai_crypto_trader_tpu.backtest.strategy import PARAM_RANGES, StrategyParams
from ai_crypto_trader_tpu.ops import dynamic as dyn
from ai_crypto_trader_tpu.ops import indicators as ind_ops
from ai_crypto_trader_tpu.backtest import signals as sig

# Static loop bounds from the parameter ranges (PARAM_RANGES highs).
WMAX_BB = int(PARAM_RANGES["bollinger_period"][1])      # 30
WMAX_VOL = int(PARAM_RANGES["volume_ma_period"][1])     # 30


class SocialInputs(NamedTuple):
    """Optional per-candle social metrics (sentiment 0-100, volume,
    engagement) — the axes the social thresholds gate on."""

    sentiment: jnp.ndarray
    volume: jnp.ndarray
    engagement: jnp.ndarray


def evolvable_signal(ohlcv: dict, p: StrategyParams,
                     social: SocialInputs | None = None):
    """Per-candle (signal ∈ {-1,0,1}, strength ∈ [0,100], volatility) for
    one parameter vector. vmap over a stacked StrategyParams for the
    population axis."""
    close, high, low, volume = (ohlcv[k] for k in ("close", "high", "low", "volume"))

    rsi = ind_ops.nanfill(dyn.rsi_dyn(close, p.rsi_period))
    macd_line, _, _ = dyn.macd_dyn(close, p.macd_fast, p.macd_slow, p.macd_signal)
    macd_line = ind_ops.nanfill(macd_line)
    _, _, _, _, bb_pos = dyn.bollinger_dyn(close, p.bollinger_period,
                                           p.bollinger_std, WMAX_BB)
    bb_pos = ind_ops.nanfill(bb_pos)
    ema_s = ind_ops.nanfill(dyn.ema_dyn(close, p.ema_short))
    ema_l = ind_ops.nanfill(dyn.ema_dyn(close, p.ema_long))
    atr = ind_ops.nanfill(dyn.atr_dyn(high, low, close, p.atr_period))
    vol_ma = ind_ops.nanfill(dyn.rolling_mean_dyn(volume, p.volume_ma_period, WMAX_VOL))

    volatility = atr / close
    uptrend = ema_s > ema_l
    downtrend = ema_s < ema_l
    trend_strength = jnp.abs((ema_s - ema_l) / ema_l * 100.0)

    # --- votes: the TradingSignal scoring shape with evolved thresholds ---
    votes = jnp.where(rsi < p.rsi_oversold, 3.0,
                      jnp.where(rsi < p.rsi_oversold + 10.0, 2.0, 0.0))
    votes += jnp.where(macd_line > 0.0, 2.0, 0.0)
    votes += jnp.where(bb_pos < 0.2, 3.0, jnp.where(bb_pos < 0.4, 2.0, 0.0))
    votes += jnp.where(uptrend & (trend_strength > 1.0), 3.0,
                       jnp.where(uptrend, 2.0, 0.0))
    votes += jnp.where(volume > vol_ma, 2.0, 0.0)
    total = 5.0

    if social is not None:
        s_vote = (
            (social.sentiment > p.social_sentiment_threshold).astype(jnp.float32)
            + (social.volume > p.social_volume_threshold).astype(jnp.float32)
            + (social.engagement > p.social_engagement_threshold).astype(jnp.float32)
        )
        votes += jnp.where(s_vote >= 2.0, 3.0, jnp.where(s_vote >= 1.0, 1.0, 0.0))
        total += 1.0

    overbought = (rsi > p.rsi_overbought) | (bb_pos > 0.8)
    ratio = votes / (3.0 * total)
    signal = jnp.where(overbought, sig.SELL,
                       jnp.where(ratio >= 0.6, sig.BUY,
                                 jnp.where(ratio <= 0.15, sig.SELL, sig.NEUTRAL)))
    signal = signal.astype(jnp.int32)

    # --- strength: same weighting scheme as TradingSignal._calculate_strength ---
    is_buy = signal == sig.BUY
    rsi_str = jnp.where(is_buy,
                        (p.rsi_oversold + 10.0 - jnp.minimum(rsi, p.rsi_oversold + 10.0)) / 15.0,
                        (jnp.maximum(rsi, p.rsi_overbought) - p.rsi_overbought) / 15.0)
    macd_str = jnp.minimum(jnp.abs(macd_line), 1.0)
    bb_str = jnp.where(is_buy, jnp.maximum(0.4 - bb_pos, 0.0) / 0.4,
                       jnp.maximum(bb_pos - 0.6, 0.0) / 0.4)
    trend_str = jnp.minimum(trend_strength / 5.0, 1.0)
    aligned = (is_buy & uptrend) | ((signal == sig.SELL) & downtrend)
    strength = (rsi_str * 30.0 + macd_str * 20.0 + bb_str * 20.0
                + jnp.where(aligned, trend_str * 15.0, 0.0)
                + jnp.where(volume > vol_ma, 15.0, 0.0))
    strength = jnp.where(signal == sig.NEUTRAL, 0.0, jnp.clip(strength, 0.0, 100.0))
    return signal, strength, volatility


def evolvable_inputs(ohlcv: dict, p: StrategyParams,
                     social: SocialInputs | None = None) -> BacktestInputs:
    signal, strength, volatility = evolvable_signal(ohlcv, p, social)
    close = ohlcv["close"]
    avg_volume = jnp.mean(ohlcv["volume"]) * jnp.mean(close)
    T = close.shape[-1]
    # ATR-adaptive exits — an EXTENSION inspired by the reference's adaptive
    # stop-loss concept (`portfolio_risk_service.py:489-547` scales only the
    # stop, from annualized std). Here both SL and TP scale with *relative*
    # volatility (current ATR vs the series median, preserving the genome's
    # reward:risk ratio), bounded to the same 0.5-2.0 factor range.
    # atr_multiplier=2 at median volatility is the neutral anchor; this makes
    # both ATR genome dims live in fitness (volatility =
    # atr_dyn(p.atr_period)/close).
    vol_ref = jnp.maximum(jnp.median(volatility), 1e-8)
    factor = jnp.clip(p.atr_multiplier * volatility / (2.0 * vol_ref),
                      0.5, 2.0)
    sl_t = p.stop_loss * factor
    tp_t = p.take_profit * factor
    return BacktestInputs(
        close=close, signal=signal, strength=strength, volatility=volatility,
        volume=jnp.full((T,), avg_volume, jnp.float32),
        confidence=jnp.ones((T,), jnp.float32),
        decision=signal,
        sl_pct=sl_t, tp_pct=tp_t,
    )


@functools.partial(jax.jit, static_argnames=("min_signal_strength", "warmup"))
def evolvable_backtest(ohlcv: dict, p: StrategyParams,
                       initial_balance: float = 10_000.0,
                       min_signal_strength: float = 50.0,
                       warmup: int = 10,
                       social: SocialInputs | None = None):
    """Full pipeline for one parameter vector: dynamic indicators → signal →
    scan backtest with the params' SL/TP. The GA's fitness kernel.

    ``social`` (dense per-candle arrays from
    `social.provider.SocialDataProvider.social_inputs`) adds the social
    vote axis and makes the three social threshold genome dims live."""
    inputs = evolvable_inputs(ohlcv, p, social)
    return run_backtest(inputs, p, initial_balance=initial_balance,
                        min_signal_strength=min_signal_strength,
                        use_param_sl_tp=True, warmup=warmup)


@functools.partial(jax.jit, static_argnames=("min_signal_strength", "warmup"))
def population_backtest(ohlcv: dict, population: StrategyParams,
                        initial_balance: float = 10_000.0,
                        min_signal_strength: float = 50.0, warmup: int = 10,
                        social: SocialInputs | None = None):
    """vmap the full dynamic pipeline over a stacked population (one
    compiled program — see engine.sweep note on eager dispatch)."""
    return jax.vmap(lambda p: evolvable_backtest(
        ohlcv, p, initial_balance=initial_balance,
        min_signal_strength=min_signal_strength, warmup=warmup,
        social=social))(population)
