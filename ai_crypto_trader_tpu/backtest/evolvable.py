"""The evolvable strategy: all 18 GA parameters live in one compiled program.

The reference evolution service mutates parameters that its own backtester
never consumes (its GA fitness is a heuristic score —
`strategy_evolution_service.py:542-641`; its CV simulator is a placeholder
RSI rule — `strategy_evaluation_system.py:358-431`).  Here the full
parameter vector drives a real backtest:

  periods → dynamic-window kernels (ops/dynamic.py, traced under vmap)
  thresholds → the vote-based signal rule (same scoring shape as
               TradingSignal, with parameterized cut-offs)
  stop_loss / take_profit / atr_multiplier → the scan engine's exit logic
  social thresholds → votes from (optional) social metric arrays

so GA fitness = real vectorized backtest Sharpe, evaluated for the whole
population in one vmap and sharded over the mesh.

Period-table fast path (ISSUE 11): every period dimension the GA evolves
is a SMALL INTEGER RANGE (PARAM_RANGES marks them integer; the GA rounds
them), so per-genome indicator values are draws from a finite menu.
`build_indicator_tables` computes every integer period's indicator row
ONCE per market window ([n_periods, T] tables, built by vmapping the very
same dynamic kernels over the period grid — the same math as the
per-genome computation; XLA's per-context FMA choices can wobble the last
f32 bit of a row, which the parity tests bound), and the population eval
gathers rows by genome period instead of re-running ~12 length-T kernels
per genome per generation.  At bench scale (pop 256 × 43 200 candles) the
indicator pipeline was ~95 % of fitness-eval wall time; the tables turn
that into seven gathers, and `evolvable_fused_backtest` folds the vote
rule into the replay scan so nothing [pop, T]-sized is materialized
between gather and replay.  `tables=None` keeps the direct per-genome
path — the parity oracle the tests pin the gather path against.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu.backtest.engine import BacktestInputs, run_backtest
from ai_crypto_trader_tpu.backtest.strategy import PARAM_RANGES, StrategyParams
from ai_crypto_trader_tpu.ops import dynamic as dyn
from ai_crypto_trader_tpu.ops import indicators as ind_ops
from ai_crypto_trader_tpu.backtest import signals as sig

# Static loop bounds from the parameter ranges (PARAM_RANGES highs).
WMAX_BB = int(PARAM_RANGES["bollinger_period"][1])      # 30
WMAX_VOL = int(PARAM_RANGES["volume_ma_period"][1])     # 30

# Integer period grids (inclusive) — the finite menus the GA draws from.
# One EMA grid serves ema_short, ema_long, macd_fast AND macd_slow (the
# MACD line is just a difference of two EMA rows).
_EMA_LO = int(min(PARAM_RANGES["ema_short"][0], PARAM_RANGES["macd_fast"][0]))
_EMA_HI = int(max(PARAM_RANGES["ema_long"][1], PARAM_RANGES["macd_slow"][1]))
_RSI_LO, _RSI_HI = (int(v) for v in PARAM_RANGES["rsi_period"][:2])
_ATR_LO, _ATR_HI = (int(v) for v in PARAM_RANGES["atr_period"][:2])
_BB_LO, _BB_HI = (int(v) for v in PARAM_RANGES["bollinger_period"][:2])
_VOL_LO, _VOL_HI = (int(v) for v in PARAM_RANGES["volume_ma_period"][:2])


class SocialInputs(NamedTuple):
    """Optional per-candle social metrics (sentiment 0-100, volume,
    engagement) — the axes the social thresholds gate on."""

    sentiment: jnp.ndarray
    volume: jnp.ndarray
    engagement: jnp.ndarray


class IndicatorTables(NamedTuple):
    """Per-integer-period indicator rows over one market window.

    Every leaf is [n_periods, T] except ``atr_median`` ([n_periods] —
    the per-period median of ATR/close, so the adaptive-exit reference
    level costs a gather instead of a per-genome sort).  The `_fill`
    tables store nanfill-ed rows (nanfill commutes with the row gather,
    so filling once per PERIOD replaces two associative scans per GENOME
    per generation); `ema_raw` keeps the warmup NaNs because the MACD
    line needs the raw difference (see `_filled_indicators`)."""

    ema_raw: jnp.ndarray     # spans _EMA_LO.._EMA_HI, warmup NaN
    ema_fill: jnp.ndarray    # nanfill(ema_raw) — the trend EMAs
    rsi_fill: jnp.ndarray    # periods _RSI_LO.._RSI_HI
    atr_fill: jnp.ndarray    # periods _ATR_LO.._ATR_HI
    atr_median: jnp.ndarray  # median(nanfill(atr)/close) per atr period
    bb_mid: jnp.ndarray      # bollinger middle band per period (raw)
    bb_sd: jnp.ndarray       # bollinger rolling std per period (raw)
    vol_ma_fill: jnp.ndarray  # volume MA per period


def _grid(lo: int, hi: int) -> jnp.ndarray:
    return jnp.arange(lo, hi + 1, dtype=jnp.float32)


@jax.jit
def build_indicator_tables(ohlcv: dict) -> IndicatorTables:
    """All integer-period indicator rows for one window, one compiled
    program.  Rows are produced by vmapping the SAME traced-window kernels
    (and the same nanfill) the direct path runs per genome — identical
    math; XLA's fusion context may differ in the last f32 bit
    (tests/test_evolve.py bounds it and pins the replay stats)."""
    close, high, low, volume = (ohlcv[k]
                                for k in ("close", "high", "low", "volume"))
    ema_raw = jax.vmap(lambda w: dyn.ema_dyn(close, w))(
        _grid(_EMA_LO, _EMA_HI))
    atr_fill = jax.vmap(
        lambda w: ind_ops.nanfill(dyn.atr_dyn(high, low, close, w)))(
        _grid(_ATR_LO, _ATR_HI))
    return IndicatorTables(
        ema_raw=ema_raw,
        ema_fill=jax.vmap(ind_ops.nanfill)(ema_raw),
        rsi_fill=jax.vmap(
            lambda w: ind_ops.nanfill(dyn.rsi_dyn(close, w)))(
            _grid(_RSI_LO, _RSI_HI)),
        atr_fill=atr_fill,
        # median of what the signal path calls `volatility` for this period
        atr_median=jax.vmap(lambda row: jnp.median(row / close))(atr_fill),
        bb_mid=jax.vmap(lambda w: dyn.rolling_mean_dyn(close, w, WMAX_BB))(
            _grid(_BB_LO, _BB_HI)),
        bb_sd=jax.vmap(lambda w: dyn.rolling_std_dyn(close, w, WMAX_BB))(
            _grid(_BB_LO, _BB_HI)),
        vol_ma_fill=jax.vmap(
            lambda w: ind_ops.nanfill(dyn.rolling_mean_dyn(volume, w,
                                                           WMAX_VOL)))(
            _grid(_VOL_LO, _VOL_HI)),
    )


def _row(table: jnp.ndarray, period, lo: int, hi: int) -> jnp.ndarray:
    """Gather one period's row; clip guards a just-out-of-range float
    (clamp_params keeps genomes in range, but a hand-built param must not
    index out of bounds)."""
    idx = jnp.clip(jnp.round(period).astype(jnp.int32) - lo, 0, hi - lo)
    return table[idx]


def _filled_indicators(ohlcv: dict, p: StrategyParams,
                       tables: IndicatorTables | None):
    """(rsi, macd_line, bb_pos, ema_s, ema_l, atr, vol_ma), all
    nanfill-ed — gathered from the period tables when provided, else
    computed per genome (the parity oracle)."""
    close, high, low, volume = (ohlcv[k]
                                for k in ("close", "high", "low", "volume"))
    nf = ind_ops.nanfill
    if tables is None:
        macd_raw, _, _ = dyn.macd_dyn(close, p.macd_fast, p.macd_slow,
                                      p.macd_signal)
        _, _, _, _, bb_raw = dyn.bollinger_dyn(close, p.bollinger_period,
                                               p.bollinger_std, WMAX_BB)
        return (nf(dyn.rsi_dyn(close, p.rsi_period)), nf(macd_raw),
                nf(bb_raw),
                nf(dyn.ema_dyn(close, p.ema_short)),
                nf(dyn.ema_dyn(close, p.ema_long)),
                nf(dyn.atr_dyn(high, low, close, p.atr_period)),
                nf(dyn.rolling_mean_dyn(volume, p.volume_ma_period,
                                        WMAX_VOL)))

    # MACD line = fast EMA row − slow EMA row on the RAW table.  Its NaN
    # set is the leading warmup run t < slow-1 (slow ≥ fast by range, no
    # interior NaNs), so nanfill (ffill→bfill→0) reduces EXACTLY to
    # "backfill with the first valid value, diff[slow-1]" — one gather +
    # select instead of two associative scans per genome.  (The signal
    # line is dead code in the vote rule either way.)
    diff = (_row(tables.ema_raw, p.macd_fast, _EMA_LO, _EMA_HI)
            - _row(tables.ema_raw, p.macd_slow, _EMA_LO, _EMA_HI))
    T = close.shape[-1]
    first_valid = jnp.clip(jnp.round(p.macd_slow).astype(jnp.int32) - 1,
                           0, T - 1)
    t_idx = jnp.arange(T, dtype=jnp.int32)
    macd_line = jnp.nan_to_num(
        jnp.where(t_idx < first_valid, jnp.take(diff, first_valid, axis=-1),
                  diff))
    # Bollinger %B from the (mid, sd) rows — bollinger_dyn's exact
    # formula, then the genome's own nanfill (sd==0 windows put interior
    # NaNs at data-dependent spots, so this one can't precompute).
    mid = _row(tables.bb_mid, p.bollinger_period, _BB_LO, _BB_HI)
    sd = _row(tables.bb_sd, p.bollinger_period, _BB_LO, _BB_HI)
    hi_band, lo_band = mid + p.bollinger_std * sd, mid - p.bollinger_std * sd
    rng = hi_band - lo_band
    bb_pos = nf((close - lo_band) / jnp.where(rng == 0.0, jnp.nan, rng))
    return (_row(tables.rsi_fill, p.rsi_period, _RSI_LO, _RSI_HI),
            macd_line, bb_pos,
            _row(tables.ema_fill, p.ema_short, _EMA_LO, _EMA_HI),
            _row(tables.ema_fill, p.ema_long, _EMA_LO, _EMA_HI),
            _row(tables.atr_fill, p.atr_period, _ATR_LO, _ATR_HI),
            _row(tables.vol_ma_fill, p.volume_ma_period, _VOL_LO, _VOL_HI))


def _vote_signal(p: StrategyParams, close, volume, rsi, macd_line, bb_pos,
                 ema_s, ema_l, atr, vol_ma,
                 social: SocialInputs | None = None):
    """The vote rule as pure elementwise ops — shape-polymorphic, so the
    SAME code scores a whole [T] window (evolvable_signal) and a single
    candle inside the fused replay scan (evolvable_fused_backtest).
    Returns (signal, strength, volatility)."""
    volatility = atr / close
    uptrend = ema_s > ema_l
    downtrend = ema_s < ema_l
    trend_strength = jnp.abs((ema_s - ema_l) / ema_l * 100.0)

    # --- votes: the TradingSignal scoring shape with evolved thresholds ---
    votes = jnp.where(rsi < p.rsi_oversold, 3.0,
                      jnp.where(rsi < p.rsi_oversold + 10.0, 2.0, 0.0))
    votes += jnp.where(macd_line > 0.0, 2.0, 0.0)
    votes += jnp.where(bb_pos < 0.2, 3.0, jnp.where(bb_pos < 0.4, 2.0, 0.0))
    votes += jnp.where(uptrend & (trend_strength > 1.0), 3.0,
                       jnp.where(uptrend, 2.0, 0.0))
    votes += jnp.where(volume > vol_ma, 2.0, 0.0)
    total = 5.0

    if social is not None:
        s_vote = (
            (social.sentiment > p.social_sentiment_threshold).astype(jnp.float32)
            + (social.volume > p.social_volume_threshold).astype(jnp.float32)
            + (social.engagement > p.social_engagement_threshold).astype(jnp.float32)
        )
        votes += jnp.where(s_vote >= 2.0, 3.0, jnp.where(s_vote >= 1.0, 1.0, 0.0))
        total += 1.0

    overbought = (rsi > p.rsi_overbought) | (bb_pos > 0.8)
    ratio = votes / (3.0 * total)
    signal = jnp.where(overbought, sig.SELL,
                       jnp.where(ratio >= 0.6, sig.BUY,
                                 jnp.where(ratio <= 0.15, sig.SELL, sig.NEUTRAL)))
    signal = signal.astype(jnp.int32)

    # --- strength: same weighting scheme as TradingSignal._calculate_strength ---
    is_buy = signal == sig.BUY
    rsi_str = jnp.where(is_buy,
                        (p.rsi_oversold + 10.0 - jnp.minimum(rsi, p.rsi_oversold + 10.0)) / 15.0,
                        (jnp.maximum(rsi, p.rsi_overbought) - p.rsi_overbought) / 15.0)
    macd_str = jnp.minimum(jnp.abs(macd_line), 1.0)
    bb_str = jnp.where(is_buy, jnp.maximum(0.4 - bb_pos, 0.0) / 0.4,
                       jnp.maximum(bb_pos - 0.6, 0.0) / 0.4)
    trend_str = jnp.minimum(trend_strength / 5.0, 1.0)
    aligned = (is_buy & uptrend) | ((signal == sig.SELL) & downtrend)
    strength = (rsi_str * 30.0 + macd_str * 20.0 + bb_str * 20.0
                + jnp.where(aligned, trend_str * 15.0, 0.0)
                + jnp.where(volume > vol_ma, 15.0, 0.0))
    strength = jnp.where(signal == sig.NEUTRAL, 0.0, jnp.clip(strength, 0.0, 100.0))
    return signal, strength, volatility


def evolvable_signal(ohlcv: dict, p: StrategyParams,
                     social: SocialInputs | None = None,
                     tables: IndicatorTables | None = None):
    """Per-candle (signal ∈ {-1,0,1}, strength ∈ [0,100], volatility) for
    one parameter vector. vmap over a stacked StrategyParams for the
    population axis; pass ``tables`` to gather indicator rows instead of
    recomputing them per genome."""
    close, volume = ohlcv["close"], ohlcv["volume"]
    rsi, macd_line, bb_pos, ema_s, ema_l, atr, vol_ma = \
        _filled_indicators(ohlcv, p, tables)
    return _vote_signal(p, close, volume, rsi, macd_line, bb_pos,
                        ema_s, ema_l, atr, vol_ma, social)


def evolvable_inputs(ohlcv: dict, p: StrategyParams,
                     social: SocialInputs | None = None,
                     tables: IndicatorTables | None = None) -> BacktestInputs:
    signal, strength, volatility = evolvable_signal(ohlcv, p, social, tables)
    close = ohlcv["close"]
    avg_volume = jnp.mean(ohlcv["volume"]) * jnp.mean(close)
    T = close.shape[-1]
    # ATR-adaptive exits — an EXTENSION inspired by the reference's adaptive
    # stop-loss concept (`portfolio_risk_service.py:489-547` scales only the
    # stop, from annualized std). Here both SL and TP scale with *relative*
    # volatility (current ATR vs the series median, preserving the genome's
    # reward:risk ratio), bounded to the same 0.5-2.0 factor range.
    # atr_multiplier=2 at median volatility is the neutral anchor; this makes
    # both ATR genome dims live in fitness (volatility =
    # atr_dyn(p.atr_period)/close).  With tables, the median comes from the
    # per-period table instead of a per-genome sort.
    if tables is None:
        vol_ref = jnp.maximum(jnp.median(volatility), 1e-8)
    else:
        vol_ref = jnp.maximum(
            _row(tables.atr_median, p.atr_period, _ATR_LO, _ATR_HI), 1e-8)
    factor = jnp.clip(p.atr_multiplier * volatility / (2.0 * vol_ref),
                      0.5, 2.0)
    sl_t = p.stop_loss * factor
    tp_t = p.take_profit * factor
    return BacktestInputs(
        close=close, signal=signal, strength=strength, volatility=volatility,
        volume=jnp.full((T,), avg_volume, jnp.float32),
        confidence=jnp.ones((T,), jnp.float32),
        decision=signal,
        sl_pct=sl_t, tp_pct=tp_t,
    )


@functools.partial(jax.jit, static_argnames=("min_signal_strength", "warmup"))
def evolvable_backtest(ohlcv: dict, p: StrategyParams,
                       initial_balance: float = 10_000.0,
                       min_signal_strength: float = 50.0,
                       warmup: int = 10,
                       social: SocialInputs | None = None,
                       tables: IndicatorTables | None = None):
    """Full pipeline for one parameter vector: dynamic indicators → signal →
    scan backtest with the params' SL/TP. The GA's fitness kernel.

    ``social`` (dense per-candle arrays from
    `social.provider.SocialDataProvider.social_inputs`) adds the social
    vote axis and makes the three social threshold genome dims live.
    ``tables`` (build_indicator_tables) swaps the per-genome indicator
    recomputation for period-row gathers — same values, a fraction of the
    work when vmapped over a population."""
    inputs = evolvable_inputs(ohlcv, p, social, tables)
    return run_backtest(inputs, p, initial_balance=initial_balance,
                        min_signal_strength=min_signal_strength,
                        use_param_sl_tp=True, warmup=warmup)


@functools.partial(jax.jit, static_argnames=("min_signal_strength", "warmup"))
def evolvable_fused_backtest(ohlcv: dict, p: StrategyParams,
                             tables: IndicatorTables,
                             initial_balance: float = 10_000.0,
                             min_signal_strength: float = 50.0,
                             warmup: int = 10):
    """The GA's fitness kernel with the signal rule FUSED INTO the replay
    scan.

    The tabled-but-unfused path still materializes ~30 [pop, T]
    intermediates (votes, strength, exit ladders) between the gathers and
    the scan — at bench scale that memory traffic, not the replay, is the
    eval.  Here the scan consumes the seven gathered indicator rows
    directly and computes votes → signal/strength → adaptive exits
    per candle in registers via the SAME `_vote_signal` elementwise block
    and the SAME `engine.replay_step` transition — the replay stats land
    bit-equal to `evolvable_backtest(..., tables=...)` (pinned in
    tests/test_evolve.py) at a fraction of the wall time.  Requires
    tables; no social axis (the unfused path serves both)."""
    from ai_crypto_trader_tpu.backtest.engine import (
        _init_state,
        finalize_stats,
        replay_step,
    )
    from jax import lax

    close, volume = ohlcv["close"], ohlcv["volume"]
    rsi, macd_line, bb_pos, ema_s, ema_l, atr, vol_ma = \
        _filled_indicators(ohlcv, p, tables)
    avg_volume = jnp.mean(ohlcv["volume"]) * jnp.mean(close)
    vol_ref = jnp.maximum(
        _row(tables.atr_median, p.atr_period, _ATR_LO, _ATR_HI), 1e-8)
    conf = jnp.float32(1.0)
    inner = replay_step(p, warmup=warmup, ai_confidence_threshold=0.7,
                        min_signal_strength=min_signal_strength,
                        reference_quirks=False, use_param_sl_tp=True,
                        return_curve=False, sell_exits=False)

    def step(s, x):
        t, c, v, rsi_t, macd_t, bb_t, es_t, el_t, atr_t, vma_t = x
        sig_t, str_t, vol_t = _vote_signal(p, c, v, rsi_t, macd_t, bb_t,
                                           es_t, el_t, atr_t, vma_t)
        factor = jnp.clip(p.atr_multiplier * vol_t / (2.0 * vol_ref),
                          0.5, 2.0)
        return inner(s, (t, c, sig_t, str_t, vol_t, avg_volume, conf,
                         sig_t, p.stop_loss * factor, p.take_profit * factor))

    T = close.shape[-1]
    steps = jnp.arange(T, dtype=jnp.int32)
    final, _ = lax.scan(step, _init_state(initial_balance),
                        (steps, close, volume, rsi, macd_line, bb_pos,
                         ema_s, ema_l, atr, vol_ma), unroll=8)
    return finalize_stats(final, close[-1], initial_balance)


@functools.partial(jax.jit, static_argnames=("min_signal_strength", "warmup"))
def population_backtest(ohlcv: dict, population: StrategyParams,
                        initial_balance: float = 10_000.0,
                        min_signal_strength: float = 50.0, warmup: int = 10,
                        social: SocialInputs | None = None,
                        tables: IndicatorTables | None = None):
    """vmap the full dynamic pipeline over a stacked population (one
    compiled program — see engine.sweep note on eager dispatch)."""
    return jax.vmap(lambda p: evolvable_backtest(
        ohlcv, p, initial_balance=initial_balance,
        min_signal_strength=min_signal_strength, warmup=warmup,
        social=social, tables=tables))(population)
