"""Performance-metric suite derived from BacktestStats.

Replicates the reference's metric definitions so numbers are comparable:
  * win rate / profit factor / Sharpe —
    `backtesting/strategy_tester.py:403-430` (Sharpe: per-candle equity
    returns, population std, ×√252, 0 when degenerate; profit factor left
    at 0 when there are no losing trades — reference behavior, preserved),
  * Sortino / Calmar / expectancy / recovery / streaks —
    `services/strategy_evaluation.py:231-319` (StrategyPerformanceMetrics
    "advanced metrics").

Everything is computed from the streaming moments the scan carries, so the
full suite costs O(1) per backtest regardless of T, and vmaps trivially.
"""

from __future__ import annotations

import jax.numpy as jnp

from ai_crypto_trader_tpu.backtest.engine import BacktestStats


def compute_metrics(s: BacktestStats, annualization: float = 252.0) -> dict:
    n = jnp.maximum(s.n_r, 1).astype(jnp.float32)
    mean_r = s.sum_r / n
    var_r = jnp.maximum(s.sum_r2 / n - mean_r * mean_r, 0.0)
    std_r = jnp.sqrt(var_r)
    sqrt_ann = jnp.sqrt(annualization)

    sharpe = jnp.where(
        (s.n_r > 1) & (std_r > 0.0), mean_r / jnp.where(std_r > 0, std_r, 1.0) * sqrt_ann, 0.0
    )

    downside = jnp.sqrt(s.sum_neg_r2 / n)
    sortino = jnp.where(downside > 0.0, mean_r / jnp.where(downside > 0, downside, 1.0) * sqrt_ann, 0.0)

    total_trades = s.total_trades.astype(jnp.float32)
    win_rate = jnp.where(s.total_trades > 0, s.winning_trades / jnp.maximum(total_trades, 1.0) * 100.0, 0.0)
    profit_factor = jnp.where(s.total_loss > 0.0, s.total_profit / jnp.where(s.total_loss > 0, s.total_loss, 1.0), 0.0)

    total_return_pct = (s.final_balance - s.initial_balance) / s.initial_balance * 100.0
    ann_return_pct = mean_r * annualization * 100.0
    calmar = jnp.where(s.max_drawdown_pct > 0.0,
                       ann_return_pct / jnp.where(s.max_drawdown_pct > 0, s.max_drawdown_pct, 1.0), 0.0)

    avg_win = jnp.where(s.winning_trades > 0, s.total_profit / jnp.maximum(s.winning_trades, 1), 0.0)
    avg_loss = jnp.where(s.losing_trades > 0, s.total_loss / jnp.maximum(s.losing_trades, 1), 0.0)
    wr = win_rate / 100.0
    expectancy = wr * avg_win - (1.0 - wr) * avg_loss

    net_profit = s.final_balance - s.initial_balance
    recovery = jnp.where(s.max_drawdown > 0.0, net_profit / jnp.where(s.max_drawdown > 0, s.max_drawdown, 1.0), 0.0)

    return {
        "initial_balance": s.initial_balance,
        "final_balance": s.final_balance,
        "total_trades": s.total_trades,
        "winning_trades": s.winning_trades,
        "losing_trades": s.losing_trades,
        "win_rate": win_rate,
        "profit_factor": profit_factor,
        "total_profit": s.total_profit,
        "total_loss": s.total_loss,
        "max_drawdown": s.max_drawdown,
        "max_drawdown_pct": s.max_drawdown_pct,
        "sharpe_ratio": sharpe,
        "sortino_ratio": sortino,
        "calmar_ratio": calmar,
        "total_return_pct": total_return_pct,
        "annualized_return_pct": ann_return_pct,
        "expectancy": expectancy,
        "avg_win": avg_win,
        "avg_loss": avg_loss,
        "recovery_factor": recovery,
        "max_win_streak": s.max_win_streak,
        "max_loss_streak": s.max_loss_streak,
    }
