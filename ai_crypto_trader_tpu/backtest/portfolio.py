"""Portfolio-level backtesting: (strategy × symbol) in one program.

The reference iterates symbols × intervals sequentially
(`run_multiple_backtests`, `backtesting/backtest_engine.py:127-178` /
`strategy_tester.py:460-487`).  Here the symbol axis is just another vmap:
stack per-symbol BacktestInputs (pad to a common length) and evaluate
every (strategy, symbol) cell at once; portfolio metrics aggregate across
the symbol axis on-device.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ai_crypto_trader_tpu import ops
from ai_crypto_trader_tpu.backtest import signals as sig
from ai_crypto_trader_tpu.backtest.engine import (
    BacktestInputs,
    BacktestStats,
    prepare_inputs,
    run_backtest,
)
from ai_crypto_trader_tpu.backtest.metrics import compute_metrics
from ai_crypto_trader_tpu.backtest.strategy import StrategyParams


def stack_symbol_inputs(per_symbol: dict[str, dict]) -> tuple[BacktestInputs, list[str]]:
    """{symbol: ohlcv dict} → BacktestInputs with a leading symbol axis.

    Shorter series are LEFT-padded by repeating their first candle (prices
    flat → no trades during padding; masks stay static) — padding + masking
    per SURVEY §7.4 'Ragged reality'."""
    symbols = sorted(per_symbol)
    T = max(len(np.asarray(d["close"])) for d in per_symbol.values())

    def pad(d):
        arrays = {}
        for k in ("open", "high", "low", "close", "volume"):
            v = np.asarray(d[k], np.float32)
            if len(v) < T:
                v = np.concatenate([np.full(T - len(v), v[0], np.float32), v])
            arrays[k] = jnp.asarray(v)
        return arrays

    stacked_inputs = []
    for s in symbols:
        ind = ops.compute_indicators(pad(per_symbol[s]))
        stacked_inputs.append(prepare_inputs(ind))
    batched = jax.tree.map(lambda *xs: jnp.stack(xs), *stacked_inputs)
    return batched, symbols


class SharedCarry(NamedTuple):
    """Scan carry for the shared-capital portfolio replay: ONE balance, an
    [S]-slot position table, portfolio-level stat accumulators, and the
    per-symbol realized P&L / trade counts."""

    balance: jnp.ndarray       # scalar f32 — the shared capital pool
    last_booked: jnp.ndarray   # balance at the last booked equity point
    n_open: jnp.ndarray        # scalar i32 — open slots used (global cap)
    in_pos: jnp.ndarray        # [S] bool
    entry: jnp.ndarray         # [S]
    qty: jnp.ndarray           # [S]
    sl: jnp.ndarray            # [S] percent units
    tp: jnp.ndarray            # [S]
    max_equity: jnp.ndarray
    max_dd: jnp.ndarray
    max_dd_pct: jnp.ndarray
    trades: jnp.ndarray        # i32
    wins: jnp.ndarray
    total_profit: jnp.ndarray
    total_loss: jnp.ndarray
    sum_r: jnp.ndarray
    sum_r2: jnp.ndarray
    sum_neg_r2: jnp.ndarray
    n_r: jnp.ndarray
    cur_win_streak: jnp.ndarray
    cur_loss_streak: jnp.ndarray
    max_win_streak: jnp.ndarray
    max_loss_streak: jnp.ndarray
    sym_trades: jnp.ndarray    # [S] i32
    sym_pnl: jnp.ndarray       # [S] realized P&L per symbol


def _shared_close(c: SharedCarry, s: int, price, do_close) -> SharedCarry:
    """Book a close of symbol slot ``s`` where ``do_close`` (traced bool):
    realize P&L into the shared balance, free the slot, update streaks."""
    pnl = (price - c.entry[s]) * c.qty[s]
    win = pnl > 0.0
    closed = do_close.astype(jnp.int32)
    won = (do_close & win).astype(jnp.int32)
    cw = jnp.where(do_close, jnp.where(win, c.cur_win_streak + 1, 0),
                   c.cur_win_streak)
    cl = jnp.where(do_close, jnp.where(win, 0, c.cur_loss_streak + 1),
                   c.cur_loss_streak)
    return c._replace(
        balance=c.balance + jnp.where(do_close, pnl, 0.0),
        n_open=c.n_open - closed,
        in_pos=c.in_pos.at[s].set(c.in_pos[s] & ~do_close),
        trades=c.trades + closed,
        wins=c.wins + won,
        total_profit=c.total_profit + jnp.where(do_close & win, pnl, 0.0),
        total_loss=c.total_loss + jnp.where(do_close & ~win, -pnl, 0.0),
        cur_win_streak=cw, cur_loss_streak=cl,
        max_win_streak=jnp.maximum(c.max_win_streak, cw),
        max_loss_streak=jnp.maximum(c.max_loss_streak, cl),
        sym_trades=c.sym_trades.at[s].add(closed),
        sym_pnl=c.sym_pnl.at[s].add(jnp.where(do_close, pnl, 0.0)),
    )


def _book_equity(c: SharedCarry, book, baseline) -> SharedCarry:
    """Book one equity/return/drawdown point where ``book`` (traced bool):
    return measured vs ``baseline`` (the last booked balance for the
    reference's per-update cadence; the candle-open balance per-candle)."""
    equity = c.balance
    max_eq = jnp.where(book, jnp.maximum(c.max_equity, equity), c.max_equity)
    dd = max_eq - equity
    dd_pct = dd / max_eq * 100.0
    new_max = book & (dd > c.max_dd)
    r = jnp.where(book, (equity - baseline) / baseline, 0.0)
    return c._replace(
        last_booked=jnp.where(book, equity, c.last_booked),
        max_equity=max_eq,
        max_dd=jnp.where(new_max, dd, c.max_dd),
        max_dd_pct=jnp.where(new_max, dd_pct, c.max_dd_pct),
        sum_r=c.sum_r + r,
        sum_r2=c.sum_r2 + r * r,
        sum_neg_r2=c.sum_neg_r2 + jnp.where(r < 0, r * r, 0.0),
        n_r=c.n_r + book.astype(jnp.int32),
    )


@functools.partial(
    jax.jit,
    static_argnames=("max_positions", "warmup", "use_param_sl_tp", "unroll",
                     "equity_cadence"),
)
def shared_capital_backtest(
    inputs: BacktestInputs,
    params: StrategyParams | None = None,
    initial_balance: float = 10_000.0,
    max_positions: int = 5,
    ai_confidence_threshold: float = 0.7,
    min_signal_strength: float = 70.0,
    warmup: int = 10,
    use_param_sl_tp: bool = False,
    unroll: int = 1,
    equity_cadence: str = "per_update",
):
    """Multi-symbol replay over ONE capital pool with a global position cap.

    This is the semantics the per-symbol vmap cannot express: the reference
    books every open/close against a single ``self.balance`` and refuses
    entries once ``len(open_positions) >= max_positions``
    (`backtesting/strategy_tester.py:225,314-369`; config.json
    trading_params.max_positions = 5), so position sizing in one symbol
    depends on capital realized — and slots consumed — by all the others.

    Contract (pinned by tests/test_portfolio_shared.py's scalar oracle):
      * ``inputs`` carries a leading symbol axis [S, T];
      * within a candle, symbols are processed in ascending index order:
        symbol 0's exit frees capital and a slot that symbol 1's entry sees
        in the SAME candle (the deterministic analog of the reference's
        update-arrival order);
      * exits before entries per symbol; a closed slot may re-enter at the
        same candle (matching the single-symbol engine);
      * entries are sized by `sig.position_size` on the RUNNING shared
        balance and admitted only while ``n_open < max_positions``;
      * equity cadence (VERDICT r4 weak#6, reconciled):
        ``"per_update"`` (default) books one equity/return/drawdown point
        per symbol-update exactly like the reference loop — skipped while
        that symbol still holds a position after exits or when the slot
        cap is reached (`strategy_tester.py:220-225` ``continue`` before
        the booking at `:280-300`), with returns measured against the
        PREVIOUSLY BOOKED balance; ``"per_candle"`` books once per active
        candle on the realized balance (the previous behavior, kept for
        comparison);
      * at the end every open slot is liquidated at its last close, in
        symbol order.

    The symbol loop is a Python ``for`` (S is small and static), so XLA sees
    straight-line code per scan step — exact sequential semantics with no
    nested while-loop dispatch. vmap over ``params`` for population sweeps.
    """
    if equity_cadence not in ("per_update", "per_candle"):
        raise ValueError(f"unknown equity_cadence {equity_cadence!r}")
    S, T = inputs.close.shape
    f = lambda v: jnp.asarray(v, jnp.float32)
    i = lambda v: jnp.asarray(v, jnp.int32)
    init = SharedCarry(
        balance=f(initial_balance), last_booked=f(initial_balance), n_open=i(0),
        in_pos=jnp.zeros((S,), bool), entry=jnp.zeros((S,), jnp.float32),
        qty=jnp.zeros((S,), jnp.float32), sl=jnp.zeros((S,), jnp.float32),
        tp=jnp.zeros((S,), jnp.float32),
        max_equity=f(initial_balance), max_dd=f(0.0), max_dd_pct=f(0.0),
        trades=i(0), wins=i(0), total_profit=f(0.0), total_loss=f(0.0),
        sum_r=f(0.0), sum_r2=f(0.0), sum_neg_r2=f(0.0), n_r=i(1),
        cur_win_streak=i(0), cur_loss_streak=i(0),
        max_win_streak=i(0), max_loss_streak=i(0),
        sym_trades=jnp.zeros((S,), jnp.int32),
        sym_pnl=jnp.zeros((S,), jnp.float32),
    )

    steps = jnp.arange(T, dtype=jnp.int32)
    xs = (steps,) + tuple(jnp.moveaxis(a, 0, 1) for a in inputs)  # [T, S]

    def step(c: SharedCarry, x):
        (t, close, signal, strength, vol, volume, conf, decision,
         slov, tpov) = x
        active = t >= warmup
        prev_balance = c.balance
        for s in range(S):
            # --- exit scan on slot s ---
            entry_safe = jnp.where(c.entry[s] == 0.0, 1.0, c.entry[s])
            pnl_pct = (close[s] - c.entry[s]) / entry_safe * 100.0
            hit_sl = active & c.in_pos[s] & (pnl_pct <= -c.sl[s])
            hit_tp = active & c.in_pos[s] & ~hit_sl & (pnl_pct >= c.tp[s])
            c = _shared_close(c, s, close[s], hit_sl | hit_tp)

            # the reference 'continue's past the booking when the symbol
            # still holds after exits or the slot cap binds (:220-225)
            reaches_booking = active & ~c.in_pos[s] & (c.n_open < max_positions)

            # --- entry gate: shared balance + global slot cap ---
            gate = (
                reaches_booking
                & (conf[s] >= ai_confidence_threshold)
                & (strength[s] >= min_signal_strength)
                & (signal[s] == decision[s])
                & (decision[s] == sig.BUY)
            )
            plan = sig.position_size(c.balance, vol[s], volume[s])
            if use_param_sl_tp:
                assert params is not None
                sl_new, tp_new = params.stop_loss, params.take_profit
            else:
                sl_new = plan.stop_loss_pct * 100.0
                tp_new = plan.take_profit_pct * 100.0
            sl_new = jnp.where(jnp.isnan(slov[s]), sl_new, slov[s])
            tp_new = jnp.where(jnp.isnan(tpov[s]), tp_new, tpov[s])
            c = c._replace(
                n_open=c.n_open + gate.astype(jnp.int32),
                in_pos=c.in_pos.at[s].set(c.in_pos[s] | gate),
                entry=c.entry.at[s].set(jnp.where(gate, close[s], c.entry[s])),
                qty=c.qty.at[s].set(
                    jnp.where(gate, plan.size / close[s], c.qty[s])),
                sl=c.sl.at[s].set(jnp.where(gate, sl_new, c.sl[s])),
                tp=c.tp.at[s].set(jnp.where(gate, tp_new, c.tp[s])),
            )

            if equity_cadence == "per_update":
                # reference booking (:280-300): one point per update that
                # reached it, vs the previously BOOKED balance
                c = _book_equity(c, reaches_booking, c.last_booked)

        if equity_cadence == "per_candle":
            # one equity point per active candle, vs the candle-open balance
            c = _book_equity(c, active, prev_balance)
        return c, None

    final, _ = lax.scan(step, init, xs, unroll=unroll)

    # liquidate remaining slots at their last close ("End of Test")
    for s in range(S):
        final = _shared_close(final, s, inputs.close[s, -1], final.in_pos[s])

    stats = BacktestStats(
        initial_balance=jnp.asarray(initial_balance, jnp.float32),
        final_balance=final.balance,
        total_trades=final.trades,
        winning_trades=final.wins,
        losing_trades=final.trades - final.wins,
        total_profit=final.total_profit,
        total_loss=final.total_loss,
        max_drawdown=final.max_dd,
        max_drawdown_pct=final.max_dd_pct,
        sum_r=final.sum_r,
        sum_r2=final.sum_r2,
        sum_neg_r2=final.sum_neg_r2,
        n_r=final.n_r,
        max_win_streak=final.max_win_streak,
        max_loss_streak=final.max_loss_streak,
    )
    per_symbol = {"trades": final.sym_trades, "realized_pnl": final.sym_pnl}
    return stats, per_symbol


@functools.partial(jax.jit, static_argnames=("use_param_sl_tp", "shared_capital",
                                             "max_positions"))
def portfolio_backtest(inputs: BacktestInputs, params: StrategyParams | None = None,
                       initial_balance_per_symbol: float = 10_000.0,
                       use_param_sl_tp: bool = False,
                       shared_capital: bool = False,
                       max_positions: int = 5):
    """Run every symbol (leading axis of `inputs`) under one strategy.

    ``shared_capital=False`` (legacy): symbols run in independent capital
    silos via vmap — per-symbol stats batched, plus portfolio aggregates.
    ``shared_capital=True``: symbols compete for ONE pool of
    ``initial_balance_per_symbol × n_symbols`` (total capitalization is the
    same in both modes, so flipping the flag compares capital models, not
    capital amounts) under ``max_positions`` global slots
    (`shared_capital_backtest`), matching the reference's single-pool
    booking; per-symbol stats reduce to trade counts and realized P&L
    (positions are not independent, so per-symbol Sharpe is not defined,
    and the drawdown key is portfolio-level: ``max_drawdown_pct``)."""
    if shared_capital:
        n_symbols = inputs.close.shape[0]
        stats, per_symbol = shared_capital_backtest(
            inputs, params,
            initial_balance=initial_balance_per_symbol * n_symbols,
            max_positions=max_positions, use_param_sl_tp=use_param_sl_tp)
        m = compute_metrics(stats)
        portfolio = {
            "total_initial": stats.initial_balance,
            "total_final": stats.final_balance,
            "total_return_pct": (stats.final_balance - stats.initial_balance)
            / stats.initial_balance * 100.0,
            "total_trades": stats.total_trades,
            "mean_sharpe": m["sharpe_ratio"],
            "max_drawdown_pct": stats.max_drawdown_pct,
            "per_symbol_trades": per_symbol["trades"],
            "per_symbol_realized_pnl": per_symbol["realized_pnl"],
        }
        return stats, m, portfolio
    stats = jax.vmap(lambda inp: run_backtest(
        inp, params, initial_balance=initial_balance_per_symbol,
        use_param_sl_tp=use_param_sl_tp))(inputs)
    m = compute_metrics(stats)
    n = stats.final_balance.shape[0]
    total_initial = initial_balance_per_symbol * n
    total_final = jnp.sum(stats.final_balance)
    portfolio = {
        "total_initial": jnp.asarray(total_initial, jnp.float32),
        "total_final": total_final,
        "total_return_pct": (total_final - total_initial) / total_initial * 100.0,
        "total_trades": jnp.sum(stats.total_trades),
        "mean_sharpe": jnp.mean(m["sharpe_ratio"]),
        "worst_symbol_drawdown_pct": jnp.max(stats.max_drawdown_pct),
    }
    return stats, m, portfolio
