"""Portfolio-level backtesting: (strategy × symbol) in one program.

The reference iterates symbols × intervals sequentially
(`run_multiple_backtests`, `backtesting/backtest_engine.py:127-178` /
`strategy_tester.py:460-487`).  Here the symbol axis is just another vmap:
stack per-symbol BacktestInputs (pad to a common length) and evaluate
every (strategy, symbol) cell at once; portfolio metrics aggregate across
the symbol axis on-device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ai_crypto_trader_tpu import ops
from ai_crypto_trader_tpu.backtest.engine import BacktestInputs, prepare_inputs, run_backtest
from ai_crypto_trader_tpu.backtest.metrics import compute_metrics
from ai_crypto_trader_tpu.backtest.strategy import StrategyParams


def stack_symbol_inputs(per_symbol: dict[str, dict]) -> tuple[BacktestInputs, list[str]]:
    """{symbol: ohlcv dict} → BacktestInputs with a leading symbol axis.

    Shorter series are LEFT-padded by repeating their first candle (prices
    flat → no trades during padding; masks stay static) — padding + masking
    per SURVEY §7.4 'Ragged reality'."""
    symbols = sorted(per_symbol)
    T = max(len(np.asarray(d["close"])) for d in per_symbol.values())

    def pad(d):
        arrays = {}
        for k in ("open", "high", "low", "close", "volume"):
            v = np.asarray(d[k], np.float32)
            if len(v) < T:
                v = np.concatenate([np.full(T - len(v), v[0], np.float32), v])
            arrays[k] = jnp.asarray(v)
        return arrays

    stacked_inputs = []
    for s in symbols:
        ind = ops.compute_indicators(pad(per_symbol[s]))
        stacked_inputs.append(prepare_inputs(ind))
    batched = jax.tree.map(lambda *xs: jnp.stack(xs), *stacked_inputs)
    return batched, symbols


@functools.partial(jax.jit, static_argnames=("use_param_sl_tp",))
def portfolio_backtest(inputs: BacktestInputs, params: StrategyParams | None = None,
                       initial_balance_per_symbol: float = 10_000.0,
                       use_param_sl_tp: bool = False):
    """Run every symbol (leading axis of `inputs`) under one strategy; the
    per-symbol stats come back batched, plus portfolio aggregates."""
    stats = jax.vmap(lambda inp: run_backtest(
        inp, params, initial_balance=initial_balance_per_symbol,
        use_param_sl_tp=use_param_sl_tp))(inputs)
    m = compute_metrics(stats)
    n = stats.final_balance.shape[0]
    total_initial = initial_balance_per_symbol * n
    total_final = jnp.sum(stats.final_balance)
    portfolio = {
        "total_initial": jnp.asarray(total_initial, jnp.float32),
        "total_final": total_final,
        "total_return_pct": (total_final - total_initial) / total_initial * 100.0,
        "total_trades": jnp.sum(stats.total_trades),
        "mean_sharpe": jnp.mean(m["sharpe_ratio"]),
        "worst_symbol_drawdown_pct": jnp.max(stats.max_drawdown_pct),
    }
    return stats, m, portfolio
