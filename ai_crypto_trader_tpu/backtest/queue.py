"""Queued backtest execution.

Capability parity with BacktestEngine's asyncio task queue
(`backtesting/backtest_engine.py:217-304`: `add_backtest_task` /
`process_task_queue`): callers enqueue named backtest jobs, a worker drains
them, results land in a store + the bus.  Jobs run the vectorized engine,
so "queueing" is for orchestration (many symbols/param sets arriving over
time), not for parallelism — each job is already device-parallel inside.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

import numpy as np


@dataclass
class BacktestQueue:
    bus: object | None = None
    now_fn: any = time.time
    results: dict = field(default_factory=dict)
    _queue: asyncio.Queue = field(default_factory=asyncio.Queue)
    _counter: int = 0

    def add_backtest_task(self, ohlcv: dict, params=None, *,
                          name: str | None = None, **kw) -> str:
        """Enqueue; returns the task id (`add_backtest_task:217`)."""
        self._counter += 1
        task_id = name or f"bt_{self._counter}"
        self._queue.put_nowait(
            {"id": task_id, "ohlcv": ohlcv, "params": params, "kw": kw,
             "enqueued_at": self.now_fn()})
        return task_id

    async def process_task_queue(self, max_tasks: int | None = None) -> int:
        """Drain the queue (`process_task_queue:268-304`); returns #run."""
        from ai_crypto_trader_tpu.backtest.evolvable import evolvable_backtest
        from ai_crypto_trader_tpu.backtest.metrics import compute_metrics
        from ai_crypto_trader_tpu.backtest.strategy import default_params

        n = 0
        while not self._queue.empty():
            if max_tasks is not None and n >= max_tasks:
                break
            task = self._queue.get_nowait()
            params = task["params"] if task["params"] is not None else default_params()
            stats = evolvable_backtest(task["ohlcv"], params, **task["kw"])
            metrics = {k: float(np.asarray(v))
                       for k, v in compute_metrics(stats).items()}
            record = {"id": task["id"], "metrics": metrics,
                      "completed_at": self.now_fn(),
                      "queue_latency_s": self.now_fn() - task["enqueued_at"]}
            self.results[task["id"]] = record
            if self.bus is not None:
                await self.bus.publish("backtest_results", record)
            n += 1
        return n

    def get_result(self, task_id: str) -> dict | None:
        return self.results.get(task_id)

    @property
    def pending(self) -> int:
        return self._queue.qsize()
