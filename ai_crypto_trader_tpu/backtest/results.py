"""Backtest result analysis: load/filter, reports, comparisons, plots.

Capability parity with ResultAnalyzer (`backtesting/result_analyzer.py`):
load + filter saved JSON results (:23-71), equity-curve/drawdown plot
(:73-148), trade-analysis panel (:150-224), multi-run summary report
(:226-328), and metric comparison chart (:330-415) — rendered as the same
dependency-free inline-SVG HTML the dashboard uses (matplotlib optional,
never required).
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np

from ai_crypto_trader_tpu.shell.dashboard import _svg_line, _table


def load_results(results_dir: str = "backtesting/results",
                 symbol: str | None = None,
                 strategy: str | None = None) -> list[dict]:
    """(:23-71)"""
    out = []
    for path in sorted(glob.glob(os.path.join(results_dir, "*.json"))):
        try:
            with open(path) as f:
                r = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        r["_file"] = os.path.basename(path)
        if symbol and r.get("symbol") != symbol:
            continue
        if strategy and r.get("strategy") != strategy:
            continue
        out.append(r)
    return out


def summary_report(results: list[dict]) -> dict:
    """Multi-run aggregation (:226-328)."""
    if not results:
        return {"n_runs": 0}
    def col(key):
        return np.asarray([r.get(key, 0.0) or 0.0 for r in results], float)
    # rank/average sharpe only over runs that actually carry one (same rule
    # as profitable_runs below: missing metrics must not coerce to 0)
    scored = [(i, float(r["sharpe_ratio"])) for i, r in enumerate(results)
              if isinstance(r.get("sharpe_ratio"), (int, float))]
    if scored:
        best_i = max(scored, key=lambda t: t[1])[0]
        mean_sharpe = float(np.mean([s for _, s in scored]))
        best_sharpe = float(dict(scored)[best_i])
    else:
        best_i, mean_sharpe, best_sharpe = 0, 0.0, 0.0
    # profitability judged only on runs that actually carry both balances —
    # a missing initial_balance must not coerce to 0 and count as a win
    with_balances = [r for r in results
                     if "initial_balance" in r and "final_balance" in r]
    profitable = (sum(r["final_balance"] > r["initial_balance"]
                      for r in with_balances) if with_balances else None)
    return {
        "n_runs": len(results),
        "symbols": sorted({r.get("symbol", "?") for r in results}),
        "mean_sharpe": mean_sharpe,
        "best_sharpe": best_sharpe,
        "best_run": results[best_i].get("_file", f"run_{best_i}"),
        "mean_win_rate": float(col("win_rate").mean()),
        "mean_return_pct": float(col("total_return_pct").mean()),
        "total_trades": int(col("total_trades").sum()),
        "profitable_runs": profitable,
    }


def comparison_table(results: list[dict],
                     metrics=("sharpe_ratio", "win_rate", "total_return_pct",
                              "max_drawdown_pct", "total_trades")) -> dict:
    """Metric comparison across runs (:330-415)."""
    rows = {r.get("_file", f"run_{i}"): {m: r.get(m) for m in metrics}
            for i, r in enumerate(results)}
    ranked = sorted(rows, key=lambda k: -(rows[k].get("sharpe_ratio") or 0.0))
    return {"rows": rows, "ranked": ranked}


def render_report_html(results: list[dict], path: str,
                       equity_curve=None, drawdown_curve=None) -> str:
    """Equity/drawdown plots + summary + comparison as one HTML artifact
    (:73-224 equivalents)."""
    sections = []
    if equity_curve is not None:
        sections.append(_svg_line(equity_curve, label="equity", color="#2a7"))
    if drawdown_curve is not None:
        sections.append(_svg_line(drawdown_curve, label="drawdown %", color="#d55"))
    summary = summary_report(results)
    sections.append(_table({k: v for k, v in summary.items()
                            if not isinstance(v, list)}, "Summary"))
    cmp_ = comparison_table(results)
    for name in cmp_["ranked"][:10]:
        sections.append(_table(cmp_["rows"][name], name))
    html = ("<!doctype html><html><head><meta charset='utf-8'>"
            "<style>body{background:#0a0a0a;color:#ddd;font-family:system-ui}"
            ".card{background:#161616;border-radius:6px;padding:12px;margin:8px;"
            "display:inline-block;vertical-align:top}"
            "td{padding:2px 10px;border-bottom:1px solid #222}"
            "h3{margin:0 0 8px 0;font-size:14px;color:#8ac}</style></head><body>"
            "<h2>Backtest report</h2>" + "\n".join(sections) + "</body></html>")
    with open(path, "w") as f:
        f.write(html)
    return path
