"""Vectorized trading-signal scoring — branch-free TradingSignal parity.

Re-expresses the reference's per-candle if/else trees
(`binance_ml_strategy.py:470-581` TradingSignal, `:184-203` get_trend,
`:251-291` PositionSizer) as `jnp.where` arithmetic over whole candle axes,
so one jit call scores every candle of every symbol at once instead of
constructing one Python object per candle.

Semantics are kept *exactly*, including the reference's quirks, because the
golden parity tests (tests/test_backtest_parity.py) diff this code against a
scalar port of the reference logic:

  * the MACD "strong momentum" branch `macd > 0 and macd > macd * 1.1`
    (`binance_ml_strategy.py:509`) is unsatisfiable for positive macd —
    algebraically it requires macd < 0 — so only the +2.0 branch can fire;
  * `if self.williams_r and ...` / `if self.bb_position and ...` treat an
    exact 0.0 as "missing" (Python falsiness), so a 0.0 feature contributes
    no votes; reproduced with explicit != 0 masks;
  * 'SELL' fires whenever the *buy* vote ratio is ≤ 0.3 — there are no
    sell-side votes in the reference.

Signals are encoded as int32: +1 BUY, 0 NEUTRAL, -1 SELL.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

BUY, NEUTRAL, SELL = 1, 0, -1


class SignalFeatures(NamedTuple):
    """Per-candle feature set consumed by the signal rule — the array form of
    the dict built by the reference's prepare_market_data
    (`backtesting/strategy_tester.py:100-118`)."""

    close: jnp.ndarray
    rsi: jnp.ndarray
    stoch_k: jnp.ndarray
    macd: jnp.ndarray
    williams_r: jnp.ndarray
    bb_position: jnp.ndarray
    trend: jnp.ndarray           # +1 uptrend / 0 sideways / -1 downtrend
    trend_strength: jnp.ndarray  # percent distance from SMAs
    volatility: jnp.ndarray      # ATR / close  (get_volatility, line 205-211)
    volume: jnp.ndarray          # avg volume in quote units (scalar broadcast)


def compute_signal_features(ind: dict, per_candle_trend: bool = True) -> SignalFeatures:
    """Build SignalFeatures from a compute_indicators() output dict.

    ``per_candle_trend=True`` evaluates trend/volatility at every candle
    (what live mode needs); the reference's backtester froze the final row's
    values for all candles (`strategy_tester.py:100-118`) — passing False
    reproduces that for parity testing by broadcasting the last value.
    """
    close, sma20, sma50 = ind["close"], ind["sma_20"], ind["sma_50"]
    up = (close > sma20) & (sma20 > sma50)
    dn = (close < sma20) & (sma20 < sma50)
    trend = jnp.where(up, 1, jnp.where(dn, -1, 0)).astype(jnp.int32)
    strength = jnp.abs(
        ((close - sma20) / sma20 * 100.0 + (close - sma50) / sma50 * 100.0) / 2.0
    )
    vol = ind["atr"] / close
    avg_volume = jnp.mean(ind["volume"], axis=-1, keepdims=True) * jnp.mean(
        close, axis=-1, keepdims=True
    )
    feats = SignalFeatures(
        close=close,
        rsi=ind["rsi"],
        stoch_k=ind["stoch_k"],
        macd=ind["macd"],
        williams_r=ind["williams_r"],
        bb_position=ind["bb_position"],
        trend=trend,
        trend_strength=strength,
        volatility=vol,
        volume=jnp.broadcast_to(avg_volume, close.shape),
    )
    if not per_candle_trend:
        last = lambda x: jnp.broadcast_to(x[..., -1:], x.shape)
        feats = feats._replace(
            rsi=last(feats.rsi), stoch_k=last(feats.stoch_k),
            macd=last(feats.macd), williams_r=last(feats.williams_r),
            bb_position=last(feats.bb_position), trend=last(feats.trend),
            trend_strength=last(feats.trend_strength),
            volatility=last(feats.volatility),
        )
    return feats


def reference_signal(f: SignalFeatures):
    """TradingSignal._calculate_signal + _calculate_strength, vectorized.

    Returns (signal int32 ∈ {-1,0,1}, strength f32 ∈ [0,100]).
    Reference: `binance_ml_strategy.py:489-581`.
    """
    zero = jnp.zeros_like(f.rsi)

    # --- votes (lines 489-534); 6 voters, 3.0 strong / 2.0 moderate ---
    buy = jnp.where(f.rsi < 35.0, 3.0, jnp.where(f.rsi < 45.0, 2.0, 0.0))
    buy += jnp.where(f.stoch_k < 20.0, 3.0, jnp.where(f.stoch_k < 30.0, 2.0, 0.0))
    # macd>0 and macd>macd*1.1 is unsatisfiable → only the +2 branch exists.
    buy += jnp.where(f.macd > 0.0, 2.0, 0.0)
    w_valid = f.williams_r != 0.0  # Python truthiness of the reference
    buy += jnp.where(w_valid & (f.williams_r < -80.0), 3.0,
                     jnp.where(w_valid & (f.williams_r < -65.0), 2.0, 0.0))
    ts_valid = f.trend_strength != 0.0
    uptrend = f.trend == 1
    buy += jnp.where(uptrend & ts_valid & (f.trend_strength > 10.0), 3.0,
                     jnp.where(uptrend & ts_valid & (f.trend_strength > 5.0), 2.0, 0.0))
    bb_valid = f.bb_position != 0.0
    buy += jnp.where(bb_valid & (f.bb_position < 0.2), 3.0,
                     jnp.where(bb_valid & (f.bb_position < 0.4), 2.0, 0.0))

    ratio = buy / 6.0
    signal = jnp.where(ratio >= 0.6, BUY, jnp.where(ratio <= 0.3, SELL, NEUTRAL))
    signal = signal.astype(jnp.int32)

    # --- strength (lines 545-581) ---
    is_buy = signal == BUY
    is_sell = signal == SELL

    rsi_str = jnp.where(is_buy, (45.0 - jnp.minimum(f.rsi, 45.0)) / 15.0,
                        (jnp.maximum(f.rsi, 55.0) - 55.0) / 15.0)
    stoch_str = jnp.where(is_buy, (30.0 - jnp.minimum(f.stoch_k, 30.0)) / 30.0,
                          (jnp.maximum(f.stoch_k, 70.0) - 70.0) / 30.0)
    macd_str = jnp.minimum(jnp.abs(f.macd), 1.0)
    volume_str = jnp.minimum(f.volume / 100_000.0, 1.0)
    trend_str = jnp.minimum(f.trend_strength / 20.0, 1.0)
    trend_aligned = (is_buy & (f.trend == 1)) | (is_sell & (f.trend == -1))

    strength = (
        rsi_str * 30.0
        + stoch_str * 20.0
        + macd_str * 20.0
        + volume_str * 15.0
        + jnp.where(ts_valid & trend_aligned, trend_str * 15.0, 0.0)
    )
    strength = jnp.clip(strength, 0.0, 100.0)
    strength = jnp.where(signal == NEUTRAL, zero, strength)
    return signal, strength


class PositionPlan(NamedTuple):
    size: jnp.ndarray            # quote-currency position size
    stop_loss_pct: jnp.ndarray   # reference units: FRACTION (0.02 = "2%")
    take_profit_pct: jnp.ndarray
    trailing_activation: jnp.ndarray
    trailing_distance: jnp.ndarray


def position_size(total_capital, volatility, volume,
                  max_risk_per_trade: float = 0.15) -> PositionPlan:
    """PositionSizer.calculate_position_size, vectorized
    (reference `binance_ml_strategy.py:251-291`).

    Note on units: the reference returns stop_loss_pct as a *fraction*
    (0.02) but its backtester compares it against a PnL expressed in
    *percent* (`strategy_tester.py:206-218`), making stops ~100× tighter
    than intended.  This function reproduces the raw sizer; the engine
    decides the interpretation via its `reference_quirks` flag.
    """
    volatility = jnp.asarray(volatility)
    hi = volatility > 0.02
    mid = (~hi) & (volatility > 0.01)
    position_pct = jnp.where(hi, 0.25, jnp.where(mid, 0.20, 0.15))
    sl = jnp.where(hi, 0.02, jnp.where(mid, 0.015, 0.01))

    volume_factor = jnp.minimum(volume / 50_000.0, 1.0)
    size = total_capital * position_pct * volume_factor
    size = jnp.minimum(size, total_capital * max_risk_per_trade / sl)
    size = jnp.minimum(size, total_capital * 0.20)
    size = jnp.maximum(size, total_capital * 0.10)
    size = jnp.maximum(size, 40.0)

    return PositionPlan(
        size=size,
        stop_loss_pct=sl,
        take_profit_pct=sl * 2.0,
        trailing_activation=sl * 1.5,
        trailing_distance=sl * 0.75,
    )
