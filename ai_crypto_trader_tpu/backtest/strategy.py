"""The evolvable strategy-parameter space.

Mirrors the 18-dimensional parameter space of the reference evolution brain
(`services/strategy_evolution_service.py:98-117`) as a NamedTuple of f32
leaves, so a whole GA population is just a stacked StrategyParams with a
leading population axis — vmap-able through the signal rule and backtester.

The reference *defines* these ranges but never actually backtests them (its
GA fitness is a heuristic score, `strategy_evolution_service.py:542-641`).
Here every parameter is live: periods feed the dynamic-window indicator
kernels (ops/dynamic.py) and thresholds/SL/TP feed the scan backtester, so
fitness is a real vectorized backtest.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class StrategyParams(NamedTuple):
    rsi_period: jnp.ndarray
    rsi_overbought: jnp.ndarray
    rsi_oversold: jnp.ndarray
    macd_fast: jnp.ndarray
    macd_slow: jnp.ndarray
    macd_signal: jnp.ndarray
    bollinger_period: jnp.ndarray
    bollinger_std: jnp.ndarray
    atr_period: jnp.ndarray
    atr_multiplier: jnp.ndarray
    ema_short: jnp.ndarray
    ema_long: jnp.ndarray
    volume_ma_period: jnp.ndarray
    social_sentiment_threshold: jnp.ndarray
    social_volume_threshold: jnp.ndarray
    social_engagement_threshold: jnp.ndarray
    stop_loss: jnp.ndarray      # percent (1 = 1%)
    take_profit: jnp.ndarray    # percent


# (low, high, integer?) per dimension — strategy_evolution_service.py:98-117.
PARAM_RANGES: dict[str, tuple[float, float, bool]] = {
    "rsi_period": (5, 30, True),
    "rsi_overbought": (65, 85, False),
    "rsi_oversold": (15, 35, False),
    "macd_fast": (8, 20, True),
    "macd_slow": (20, 40, True),
    "macd_signal": (5, 15, True),
    "bollinger_period": (10, 30, True),
    "bollinger_std": (1.5, 3.0, False),
    "atr_period": (7, 25, True),
    "atr_multiplier": (1.0, 4.0, False),
    "ema_short": (5, 20, True),
    "ema_long": (20, 100, True),
    "volume_ma_period": (5, 30, True),
    "social_sentiment_threshold": (50, 80, False),
    "social_volume_threshold": (5_000, 50_000, False),
    "social_engagement_threshold": (1_000, 20_000, False),
    "stop_loss": (1.0, 5.0, False),
    "take_profit": (1.0, 10.0, False),
}

import numpy as _np

N_PARAMS = len(PARAM_RANGES)
# Plain NumPy so importing the module never initializes a JAX backend (on
# this environment an eager jnp constant would grab the single TPU chip).
_LOWS = _np.asarray([r[0] for r in PARAM_RANGES.values()], _np.float32)
_HIGHS = _np.asarray([r[1] for r in PARAM_RANGES.values()], _np.float32)
_IS_INT = _np.asarray([r[2] for r in PARAM_RANGES.values()], bool)


def default_params(batch: tuple[int, ...] = ()) -> StrategyParams:
    """Range midpoints (the reference seeds evolution with current params;
    midpoints are the neutral starting point)."""
    mid = (_LOWS + _HIGHS) / 2.0
    mid = jnp.where(_IS_INT, jnp.round(mid), mid)
    leaves = [jnp.broadcast_to(m, batch) for m in mid]
    return StrategyParams(*leaves)


@functools.partial(jax.jit, static_argnames=("n",))
def sample_params(key: jax.Array, n: int) -> StrategyParams:
    """Uniform population sample within ranges (GA seeding,
    `services/genetic_algorithm.py:83-117`)."""
    u = jax.random.uniform(key, (n, N_PARAMS))
    vals = _LOWS + u * (_HIGHS - _LOWS)
    vals = jnp.where(_IS_INT, jnp.round(vals), vals)
    return StrategyParams(*[vals[:, i] for i in range(N_PARAMS)])


def clamp_params(p: StrategyParams) -> StrategyParams:
    """Clamp to ranges + round integer dims (the reference clamps GPT/GA
    outputs the same way, `strategy_evolution_service.py:excerpt 487-511`)."""
    leaves = []
    for i, leaf in enumerate(p):
        v = jnp.clip(leaf, _LOWS[i], _HIGHS[i])
        v = jnp.where(_IS_INT[i], jnp.round(v), v)
        leaves.append(v)
    return StrategyParams(*leaves)


def stack_params(p: StrategyParams) -> jnp.ndarray:
    """[..., N_PARAMS] matrix view (for GA genome ops)."""
    return jnp.stack(list(p), axis=-1)


def unstack_params(m: jnp.ndarray) -> StrategyParams:
    return StrategyParams(*[m[..., i] for i in range(N_PARAMS)])
