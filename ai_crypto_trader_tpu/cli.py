"""Command-line interface.

Mirrors the reference CLIs (`run_backtest.py:24-59` — fetch / backtest /
list / analyze; `run_ai_model_services.py`; `run_trader.py`) plus the
compute commands this framework adds:

    python -m ai_crypto_trader_tpu.cli fetch     --symbol BTCUSDC --days 30
    python -m ai_crypto_trader_tpu.cli backtest  --symbol BTCUSDC [--sweep N]
    python -m ai_crypto_trader_tpu.cli list
    python -m ai_crypto_trader_tpu.cli analyze   --file <result.json>
    python -m ai_crypto_trader_tpu.cli train     --model lstm --epochs 5
    python -m ai_crypto_trader_tpu.cli evolve    --generations 5
    python -m ai_crypto_trader_tpu.cli mc        --paths 10000 --days 30
    python -m ai_crypto_trader_tpu.cli trade     --paper --ticks 100
    python -m ai_crypto_trader_tpu.cli profile   --ticks 10 --out profiles/x
    python -m ai_crypto_trader_tpu.cli dashboard --out dashboard.html

With no network, `fetch` generates the deterministic synthetic series into
the same CSV layout the reference caches (`backtesting/data/market/...`);
when a CSV for the symbol exists it is used instead.
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import time

import numpy as np

RESULTS_DIR = "backtesting/results"
DATA_DIR = "backtesting/data"


def _load_or_generate(symbol: str, candles: int, seed: int = 0):
    from ai_crypto_trader_tpu.data.ingest import load_csv
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv

    path = os.path.join(DATA_DIR, "market", symbol, f"{symbol}_1m.csv")
    if os.path.exists(path):
        d = load_csv(path, symbol=symbol)
        return {"open": d.open, "high": d.high, "low": d.low,
                "close": d.close, "volume": d.volume}
    return {k: v for k, v in generate_ohlcv(n=candles, seed=seed).items()
            if k != "regime"}


def cmd_fetch(args):
    """`run_backtest.py fetch` parity. --source binance runs the real
    paginated fetch (`data_manager.py:47-114` semantics) over the network;
    the default synthesizes (this dev environment has no egress)."""
    from ai_crypto_trader_tpu.data.ingest import from_dict, save_csv
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv

    n = args.days * 1440
    if args.source == "binance":
        from ai_crypto_trader_tpu.data.fetchers import (
            UrllibTransport,
            fetch_klines_ohlcv,
        )

        end_ms = int(time.time() * 1000)
        series = asyncio.run(fetch_klines_ohlcv(
            UrllibTransport(), args.symbol, "1m",
            end_ms - args.days * 86_400_000, end_ms))
        n = len(series)
    else:
        d = generate_ohlcv(n=n, seed=args.seed)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol=args.symbol, interval="1m")
    path = save_csv(series, DATA_DIR)
    print(f"saved {n} candles -> {path}")


def cmd_backtest(args):
    import jax
    import jax.numpy as jnp

    from ai_crypto_trader_tpu import ops
    from ai_crypto_trader_tpu.backtest import (
        compute_metrics, default_params, prepare_inputs, run_backtest,
        sample_params, sweep,
    )

    d = _load_or_generate(args.symbol, args.days * 1440, args.seed)
    arrays = {k: jnp.asarray(np.asarray(v)) for k, v in d.items()}
    ind = ops.compute_indicators(arrays)
    inp = prepare_inputs(ind)

    t0 = time.perf_counter()
    if args.sweep > 1:
        params = sample_params(jax.random.PRNGKey(args.seed), args.sweep)
        stats = sweep(inp, params)
        jax.block_until_ready(stats.final_balance)
        metrics = compute_metrics(stats)
        best = int(np.argmax(np.asarray(metrics["sharpe_ratio"])))
        result = {k: float(np.asarray(v)[best]) for k, v in metrics.items()}
        result["sweep_size"] = args.sweep
        result["best_index"] = best
    else:
        stats, curve = run_backtest(inp, default_params(), use_param_sl_tp=True,
                                    return_curve=True)
        jax.block_until_ready(stats.final_balance)
        result = {k: float(v) for k, v in compute_metrics(stats).items()}
        # downsampled realized-equity curve for `report` plots
        c = np.asarray(curve)
        step = max(len(c) // 500, 1)
        result["equity_curve"] = [round(float(v), 2) for v in c[::step]]
    dt = time.perf_counter() - t0
    n_candles = int(arrays["close"].shape[0]) * max(args.sweep, 1)
    result.update({"symbol": args.symbol, "interval": "1m",
                   "candles_per_sec": n_candles / dt, "wall_s": dt,
                   "strategy": "evolvable_default" if args.sweep <= 1 else "sweep"})

    os.makedirs(RESULTS_DIR, exist_ok=True)
    fname = os.path.join(
        RESULTS_DIR,
        f"tpu_{args.symbol}_1m_{time.strftime('%Y%m%d_%H%M%S')}.json")
    with open(fname, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps({k: v for k, v in result.items()
                      if k in ("final_balance", "total_trades", "win_rate",
                               "sharpe_ratio", "max_drawdown_pct",
                               "candles_per_sec")}, indent=2))
    print(f"saved -> {fname}")


def cmd_list(args):
    files = sorted(glob.glob(os.path.join(RESULTS_DIR, "*.json")))
    if not files:
        print("no results yet — run `backtest` first")
    for f in files:
        with open(f) as fh:
            r = json.load(fh)
        print(f"{os.path.basename(f)}: sharpe={r.get('sharpe_ratio', 0):.2f} "
              f"trades={r.get('total_trades', 0)} "
              f"final=${r.get('final_balance', 0):,.2f}")


def cmd_analyze(args):
    with open(args.file) as f:
        r = json.load(f)
    print(json.dumps(r, indent=2))


def cmd_report(args):
    from ai_crypto_trader_tpu.backtest.results import (
        load_results, render_report_html, summary_report,
    )

    results = load_results(RESULTS_DIR, symbol=args.symbol or None)
    summary = summary_report(results)
    print(json.dumps(summary, indent=2))
    if results:
        # best run's saved equity curve drives the report plots
        best = next((r for r in results
                     if r.get("_file") == summary.get("best_run")), results[0])
        eq = best.get("equity_curve")
        dd = None
        if eq:
            eq_arr = np.asarray(eq, float)
            peak = np.maximum.accumulate(eq_arr)
            dd = (peak - eq_arr) / peak * 100.0
        path = render_report_html(results, args.out,
                                  equity_curve=eq, drawdown_curve=dd)
        print(f"wrote {path}")


def cmd_train(args):
    import jax

    from ai_crypto_trader_tpu import ops
    import jax.numpy as jnp
    from ai_crypto_trader_tpu.models import predict_prices, train_model

    d = _load_or_generate(args.symbol, args.days * 1440, args.seed)
    arrays = {k: jnp.asarray(np.asarray(v)) for k, v in d.items()}
    ind = ops.compute_indicators(arrays)
    feats = np.stack([np.asarray(ind[k]) for k in
                      ("close", "volume", "rsi", "macd", "bb_position",
                       "stoch_k", "atr")], axis=1)
    r = train_model(jax.random.PRNGKey(args.seed), feats, args.model,
                    seq_len=args.seq_len, epochs=args.epochs,
                    batch_size=args.batch_size, precision=args.precision,
                    verbose=True)
    pred = predict_prices(r, feats, seq_len=args.seq_len)
    print(json.dumps({"model": args.model, "best_val_loss": r.best_val_loss,
                      "epochs_run": r.epochs_run,
                      "predicted_price": float(np.ravel(pred["predicted_price"])[0]),
                      "confidence": pred["confidence"]}, indent=2))


def cmd_evolve(args):
    import jax
    import jax.numpy as jnp

    from ai_crypto_trader_tpu.backtest import default_params
    from ai_crypto_trader_tpu.config import GAParams
    from ai_crypto_trader_tpu.evolve import backtest_fitness, run_ga
    from ai_crypto_trader_tpu.parallel import get_partitioner

    d = _load_or_generate(args.symbol, args.days * 1440, args.seed)
    arrays = {k: jnp.asarray(np.asarray(v)) for k, v in d.items()}
    cfg = GAParams(population_size=args.population, generations=args.generations)
    # the whole GA runs as ONE compiled scan; the partitioner shards the
    # population eval over every visible device (single-device fallback
    # on a 1-chip host)
    partitioner = get_partitioner()
    best, hist = run_ga(jax.random.PRNGKey(args.seed),
                        backtest_fitness(arrays), cfg,
                        seed_params=default_params(),
                        partitioner=partitioner)
    print(json.dumps({"history": hist,
                      "devices": partitioner.device_count,
                      "best_params": {k: float(v) for k, v in
                                      best._asdict().items()}}, indent=2))


def cmd_rl(args):
    """Population-based RL: PBT-train a DQN fleet inside the LOB
    simulator and print the fitness/lineage table.  Fully local (the
    `cli fleet` demo-mode pattern): synthesized scenario markets, no
    --url, no venue — the smallest end-to-end PBT session that exercises
    the real sharded program."""
    import jax

    from ai_crypto_trader_tpu.parallel import get_partitioner
    from ai_crypto_trader_tpu.rl import (
        DQNConfig, PBTConfig, adopt_winner, obs_size, pbt_env_params,
        train_pbt)

    key = jax.random.PRNGKey(args.seed)
    env, _labels = pbt_env_params(key, num_scenarios=args.scenarios,
                                  steps=args.steps,
                                  episode_len=args.episode_len,
                                  dynamics=args.dynamics)
    cfg = DQNConfig(state_size=obs_size(env), num_envs=args.envs,
                    rollout_len=args.rollout, replay_capacity=2048,
                    batch_size=32)
    pcfg = PBTConfig(population=args.population,
                     generations=args.generations,
                     iters_per_generation=args.iters)
    partitioner = get_partitioner()

    # --resume: rebuild the fleet from the newest intact checkpoint and
    # continue on the ABSOLUTE generation counter — the key stream (and
    # therefore the run) is bit-identical to one that never died.
    # Population/config drift is rejected loudly by restore_checkpoint.
    init_pop, start_gen, prior_history = None, 0, []
    if args.resume:
        from ai_crypto_trader_tpu.rl import load_checkpoint, restore_checkpoint

        payload, stats = load_checkpoint(args.resume)
        if payload is None:
            raise SystemExit(
                f"no intact checkpoint in {args.resume} "
                f"(corrupt_records={stats['corrupt_records']}, "
                f"torn_tail={stats['torn_tail']})")
        init_pop = restore_checkpoint(payload, cfg, pcfg, env)
        start_gen = int(payload["generation"])
        prior_history = list(payload.get("history") or [])

    # --checkpoint: journal the full fleet every N generations through
    # the same codec the trainer service uses
    on_generation, journal = None, None
    full_history = list(prior_history)
    if args.checkpoint:
        from ai_crypto_trader_tpu.rl.trainer_service import (
            PBT_CHECKPOINT_KIND, checkpoint_payload)
        from ai_crypto_trader_tpu.utils.journal import SnapshotJournal

        journal = SnapshotJournal(args.checkpoint, kind=PBT_CHECKPOINT_KIND)

        def on_generation(g, pop, row):
            full_history.append(row)
            if (g + 1) % max(args.checkpoint_every, 1) == 0:
                journal.write(checkpoint_payload(
                    pop, generation=g + 1, cfg=cfg, pcfg=pcfg,
                    seed=args.seed, history=full_history))

    res = train_pbt(key, env, cfg, pcfg, partitioner=partitioner,
                    init_pop=init_pop, start_generation=start_gen,
                    on_generation=on_generation)
    if journal is not None:
        journal.close()

    print(f"population={pcfg.population} devices={partitioner.device_count} "
          f"dynamics={args.dynamics} scenarios={args.scenarios}")
    if args.resume:
        print(f"resumed@gen={start_gen} from {args.resume} "
              f"({len(prior_history)} prior generations)")
    # 'src' is the provenance column: ckpt rows replayed from the resumed
    # checkpoint's history, live rows trained by THIS process
    print(f"{'gen':>3} {'src':>4} {'best':>9} {'mean':>9} {'exploited':>9} "
          f"{'quar':>4} {'loss':>9}")
    for h in prior_history + res.history:
        src = "ckpt" if h["generation"] < start_gen else "live"
        print(f"{h['generation']:>3} {src:>4} {h['best_fitness']:>9.4f} "
              f"{h['mean_fitness']:>9.4f} {h['n_exploited']:>9} "
              f"{h.get('n_quarantined', 0):>4} {h['loss']:>9.4f}")
    last = res.history[-1]
    hy = last["hypers"]
    print("\nfinal fleet (* = winner; 'from' = PBT lineage, the member "
          "this slot last copied):")
    print(f"{'member':>6} {'fitness':>9} {'from':>5} {'lr':>9} "
          f"{'gamma':>7} {'eps_decay':>10} {'eps_min':>8} {'sync':>5}")
    for i in range(pcfg.population):
        star = "*" if i == res.best_member else " "
        print(f"{i:>5}{star} {last['fitness'][i]:>9.4f} "
              f"{last['lineage'][i]:>5} "
              f"{hy['learning_rate'][i]:>9.2e} {hy['gamma'][i]:>7.4f} "
              f"{hy['epsilon_decay'][i]:>10.5f} "
              f"{hy['epsilon_min'][i]:>8.4f} "
              f"{int(hy['target_sync_every'][i]):>5}")
    if args.registry:
        from ai_crypto_trader_tpu.obs.scorecard import Scorecard
        from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

        out = adopt_winner(res, ModelRegistry(path=args.registry),
                           Scorecard())
        print(f"\nregistered {out['version']} "
              f"({'ACTIVE' if out['adopted'] else 'SHADOW'}: "
              f"{out['reason']}) fitness={out['fitness']:.4f}")


def cmd_generate(args):
    """Strategy-structure generation (`ai_strategy_evaluator.py:732`):
    search rule compositions with real CV backtests, register improvements,
    report the held-out comparison."""
    import asyncio

    from ai_crypto_trader_tpu.parallel import get_partitioner
    from ai_crypto_trader_tpu.strategy.generator import StrategyGenerator
    from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

    d = _load_or_generate(args.symbol, args.days * 1440, args.seed)
    reg = ModelRegistry(path=args.registry)
    gen = StrategyGenerator(registry=reg, cv_folds=args.folds,
                            pool_size=args.pool, max_rounds=args.rounds,
                            seed=args.seed, partitioner=get_partitioner())
    out = asyncio.run(gen.generate(d))

    def finite(x):
        # -inf marks a never-trading structure (generator sentinel);
        # json.dumps would print invalid `-Infinity` for it
        return float(x) if np.isfinite(x) else None

    print(json.dumps({
        "best_structure": out["structure"].to_payload(),
        "cv_sharpe": finite(out["cv_sharpe"]),
        "seed_cv_sharpe": finite(out["seed_cv_sharpe"]),
        "holdout_sharpe_seed": finite(out["holdout_sharpe_seed"]),
        "holdout_sharpe_best": finite(out["holdout_sharpe_best"]),
        "versions": out["versions"], "rounds": out["rounds"],
    }, indent=2))


def cmd_mc(args):
    import jax

    from ai_crypto_trader_tpu import mc as mc_mod

    d = _load_or_generate(args.symbol, args.days * 1440 + 1000, args.seed)
    close = np.asarray(d["close"])
    rets = np.diff(np.log(close))[-2000:]
    out = {}
    for scenario in ("base", "bull", "bear", "volatile", "crab"):
        sim = mc_mod.run_simulation(jax.random.PRNGKey(args.seed),
                                    float(close[-1]), rets, days=args.days,
                                    num_sims=args.paths, scenario=scenario)
        out[scenario] = {
            "expected_pct": float(sim["expected_pct_change"]),
            "var": abs(float(sim["var"])), "cvar": abs(float(sim["cvar"])),
            "prob_profit": float(sim["prob_profit"]),
            "max_dd_mean": float(sim["max_drawdown_mean"]),
        }
    print(json.dumps(out, indent=2))


def cmd_trade(args):
    from ai_crypto_trader_tpu.data.ingest import from_dict
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
    from ai_crypto_trader_tpu.shell.exchange import make_exchange
    from ai_crypto_trader_tpu.shell.launcher import TradingSystem

    if not args.paper:
        print("live trading requires an injected exchange client; "
              "use --paper in this environment")
        return
    d = generate_ohlcv(n=args.ticks + 600, seed=args.seed)
    series = from_dict({k: v for k, v in d.items() if k != "regime"},
                       symbol=args.symbol)
    clock = {"t": 0.0}                   # virtual clock shared by all layers
    # Paper mode rides the same resilient adapter seam as live trading
    # (breaker + rate limit + retries around every exchange call), on the
    # virtual clock so rate limiting never sleeps real wall-clock time.
    ex = make_exchange(
        "fake", resilient=True,
        resilient_opts={"now_fn": lambda: clock["t"],
                        "sleep": lambda s: clock.__setitem__("t", clock["t"] + s)},
        series={args.symbol: series}, quote_balance=10_000.0)
    ex.advance(args.symbol, steps=600)   # warm history so the monitor has a
    #                                      full fixed-shape indicator window
    resume = bool(args.journal) and os.path.exists(args.journal)
    system = TradingSystem(ex, [args.symbol], now_fn=lambda: clock["t"],
                           dashboard_path=args.dashboard,
                           log_path=os.environ.get("LOG_PATH"),
                           enable_tracing=bool(args.trace_jsonl),
                           trace_jsonl=args.trace_jsonl,
                           journal_path=args.journal,
                           enable_devprof=args.devprof,
                           enable_meshprof=args.meshprof,
                           enable_fleetscope=args.fleetscope,
                           flightrec_path=args.flightrec,
                           pipelined=args.pipelined,
                           precision=args.precision,
                           aot_cache_dir=args.aot_cache)
    if args.full_stack:
        from ai_crypto_trader_tpu.shell.stack import build_full_stack
        from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

        registry = ModelRegistry(path=args.registry)
        system.registry = registry
        names = [s.name for s in build_full_stack(
            system, registry=registry, grid_symbol=args.symbol,
            dca_symbol=args.symbol)]
        print(f"full stack: {', '.join(names)}", flush=True)

    server = None
    if args.serve is not None:
        from ai_crypto_trader_tpu.shell.dashboard_server import DashboardServer

        server = DashboardServer(system, port=args.serve).start()
        print(f"dashboard: http://127.0.0.1:{server.port}/", flush=True)

    metrics_port = int(os.environ.get("METRICS_PORT", "0"))

    async def go():
        msrv = None
        if resume:
            # crash/restart recovery: replay the write-ahead journal, then
            # reconcile the books against the exchange before trading
            report = await system.recover()
            print(json.dumps({"recovered": {
                k: v for k, v in report.items() if k != "journal"}},
                default=str), flush=True)
        if metrics_port:
            # Prometheus scrape target (compose: prometheus → trader:9091)
            msrv = await system.metrics.serve("0.0.0.0", metrics_port)
            print(f"metrics: http://127.0.0.1:{metrics_port}/metrics",
                  flush=True)
        try:
            for _ in range(args.ticks):
                ex.advance(args.symbol)
                clock["t"] += 60.0
                await system.tick()
                # tick()'s awaits all complete synchronously (in-process
                # bus), so without an explicit suspension the loop never
                # schedules the metrics server's connection handlers
                await asyncio.sleep(0)
            # pipelined tick path: the last dispatch is still inflight —
            # drain it so its decisions publish before the status dump
            await system.monitor.flush_pipeline()
        finally:
            if msrv is not None:
                msrv.close()
        print(json.dumps(system.status(), indent=2, default=str))

    try:
        asyncio.run(go())
        if server is not None and args.serve_hold_s > 0:
            time.sleep(args.serve_hold_s)
    finally:
        if server is not None:
            server.stop()
        system.shutdown()          # deactivate tracer + close span JSONL


def cmd_why(args):
    """Decision provenance for one symbol (obs/flightrec.py): the last N
    decisions with their rejecting gate or execution chain
    (signal → client_order_id → fill → closure PnL) plus the structured
    explanation narrative.  Reads the checksummed decision JSONL a run
    wrote (`trade --flightrec PATH`, `load --vmapped --flightrec PATH`),
    or queries a live dashboard server's /decisions endpoint with --url.
    `--lane N` filters to one vmapped tenant lane's sampled provenance
    (obs/fleetscope.py crc32 lane sample) — the fleet twin of the
    per-symbol question."""
    from ai_crypto_trader_tpu.obs.flightrec import format_why, load_decisions

    if args.url:
        import urllib.parse
        import urllib.request

        params = {"symbol": args.symbol, "limit": args.last}
        if args.lane is not None:
            params["lane"] = args.lane
        query = urllib.parse.urlencode(params)
        with urllib.request.urlopen(f"{args.url}/decisions?{query}",
                                    timeout=10) as resp:
            records = json.loads(resp.read())
    else:
        if not os.path.exists(args.file):
            print(f"no decision journal at {args.file} — run "
                  f"`trade --paper --flightrec {args.file}` first, "
                  f"or query a live server with --url")
            return
        records, stats = load_decisions(args.file)
        records = [r for r in records if r.get("symbol") == args.symbol]
        if args.lane is not None:
            records = [r for r in records if r.get("lane") == args.lane]
        records = list(reversed(records[-args.last:]))
        if stats.get("corrupt_records") or stats.get("torn_tail"):
            print(f"(journal: {stats['corrupt_records']} corrupt records "
                  f"skipped, torn tail={stats['torn_tail']})")
    if not records:
        where = f"{args.symbol}" + (f" lane {args.lane}"
                                    if args.lane is not None else "")
        print(f"no recorded decisions for {where}")
        return
    for line in format_why(records):
        print(line)


def cmd_profile(args):
    """On-demand device profiler capture (the CLI twin of the dashboard's
    `/profile?seconds=N`): run a short paper-trading burst with the
    devprof observatory on, wrap it in `utils/profiling.trace`, and dump
    a TensorBoard-loadable XPlane trace plus the cost cards / SLO
    summaries the run produced.  Load the artifact with
    `tensorboard --logdir <out>` (Profile plugin)."""
    from ai_crypto_trader_tpu.data.ingest import from_dict
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
    from ai_crypto_trader_tpu.shell.exchange import make_exchange
    from ai_crypto_trader_tpu.shell.launcher import TradingSystem
    from ai_crypto_trader_tpu.utils import profiling

    d = generate_ohlcv(n=args.ticks + 600, seed=args.seed)
    series = from_dict({k: v for k, v in d.items() if k != "regime"},
                       symbol=args.symbol)
    clock = {"t": 0.0}
    ex = make_exchange("fake", series={args.symbol: series},
                       quote_balance=10_000.0)
    ex.advance(args.symbol, steps=600)
    system = TradingSystem(ex, [args.symbol], now_fn=lambda: clock["t"],
                           enable_devprof=True)
    out_dir = args.out or time.strftime("profiles/xplane_%Y%m%d_%H%M%S")
    os.makedirs(out_dir, exist_ok=True)

    async def go():
        for _ in range(args.ticks):
            ex.advance(args.symbol)
            clock["t"] += 60.0
            await system.tick()

    try:
        with profiling.trace(out_dir):
            asyncio.run(go())
        print(json.dumps({"artifact": out_dir, "ticks": args.ticks,
                          "devprof": system.devprof.status()}, indent=2,
                         default=str))
    finally:
        system.shutdown()


def cmd_load(args):
    """Load & capacity harness (testing/loadgen.py): drive N synthetic
    tenant decision lanes over an S-symbol universe through the real
    stream → fused tick engine → analyzer/executor path and print the
    measured tick-latency/saturation report.  `--ramp` runs the
    closed-loop controller instead: tenants step up a doubling schedule
    until the p99 tick latency breaches `--slo-ms`, and the report names
    the max sustainable tenants×symbols point plus the stage the
    saturation gauges attribute the breach to."""
    from ai_crypto_trader_tpu.testing.loadgen import (
        LoadConfig, ramp, run_load)

    cfg = LoadConfig(tenants=args.tenants, symbols=args.symbols,
                     ticks=args.ticks, window=args.window,
                     slo_p99_ms=args.slo_ms, seed=args.seed,
                     mode=getattr(args, "mode", "objects"),
                     fleetscope=not args.no_fleetscope,
                     flightrec_path=args.flightrec)
    if args.ramp:
        out = ramp(cfg)
    else:
        out = run_load(cfg)
    print(json.dumps(out, indent=2, default=str))


def cmd_scan(args):
    """Market-wide pair discovery + ranking (CryptoScanner.scan_market,
    `binance_ml_strategy.py:293-468`). Paper mode synthesizes a universe of
    pairs with varied volatility/volume profiles; a live run would inject a
    real client behind the same adapter."""
    from ai_crypto_trader_tpu.data.ingest import from_dict
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
    from ai_crypto_trader_tpu.shell.exchange import make_exchange
    from ai_crypto_trader_tpu.shell.scanner import MarketScanner

    n_hist = args.lookback + 8
    series = {}
    for i in range(args.pairs):
        sym = f"A{i:03d}USDC"
        d = generate_ohlcv(
            n=n_hist, seed=args.seed + i, s0=100.0 * (1 + i),
            base_vol=0.0004 * (1 + (i % 9)),
            base_volume=40.0 * (1 + (i % 13)))
        series[sym] = from_dict({k: v for k, v in d.items() if k != "regime"},
                                symbol=sym)
    ex = make_exchange("fake", series=series)
    ex.advance(steps=n_hist)
    sc = MarketScanner(ex, lookback=args.lookback, top_k=args.top)
    ranked = sc.scan()
    print(f"{'symbol':<12}{'score':>8}{'vol':>9}{'qvol':>14}"
          f"{'strength':>10}{'chg%':>8}")
    for o in ranked:
        print(f"{o['symbol']:<12}{o['score']:>8.3f}{o['volatility']:>9.4f}"
              f"{o['quote_volume']:>14,.0f}{o['strength']:>10.1f}"
              f"{o['change_pct']:>8.2f}")
    print(json.dumps({"discovered": len(series), "ranked": ranked}))


def _fetch_state(url: str) -> dict:
    """One live-state fetch for the operator commands (`mesh`, `status`):
    a running dashboard server's /state.json."""
    import urllib.request

    with urllib.request.urlopen(f"{url}/state.json", timeout=10) as resp:
        return json.loads(resp.read())


def cmd_mesh(args):
    """Mesh layout inspector (the mesh runtime observatory's REPL-free
    surface, ISSUE 12): the active Partitioner layout (kind, mesh shape,
    axis, device kinds), a per-device card for every visible chip (id,
    kind, platform, allocator stats where the backend exposes them), and
    the pad/mask arithmetic for a given population — the same numbers the
    `mesh_pad_fraction` / `mesh_device_members` gauges publish.  With
    `--url`, reads a LIVE system's `/state.json` mesh block (layout cards,
    sentinel counters) instead of building a local partitioner."""
    if args.url:
        state = _fetch_state(args.url)
        print(json.dumps(state.get("mesh", {"error": "no mesh block"}),
                         indent=2, default=str))
        return
    import jax

    from ai_crypto_trader_tpu.parallel import get_partitioner

    part = get_partitioner()
    desc = part.describe()
    print(json.dumps({"partitioner": desc}, indent=2, default=str))
    print(f"\n{'id':>4} {'kind':<16} {'platform':<10} {'memory':<16} role")
    trial_devs = {str(d) for d in part.trial_devices()}
    for d in jax.devices():
        stats = ""
        try:
            ms = d.memory_stats()
            if ms:
                stats = f"{ms.get('bytes_in_use', 0):,}B in use"
        except Exception:              # noqa: BLE001 — CPU backends
            pass                       # expose no allocator stats
        role = "trial farm" if str(d) in trial_devs else "default"
        print(f"{d.id:>4} {str(getattr(d, 'device_kind', d.platform)):<16} "
              f"{d.platform:<10} {stats:<16} {role}")
    n = part.device_count
    pad = (-args.pop) % n
    padded = args.pop + pad
    print(f"\npopulation {args.pop} on {n} device(s): "
          f"pad {pad} → {padded} lanes "
          f"({padded // n}/device), pad_fraction "
          f"{pad / padded if padded else 0.0:.4f}"
          + (" — MeshPaddingWasteHigh would fire"
             if padded and pad / padded > 0.25 else ""))


def _render_fleet(block: dict, containment: dict | None = None) -> None:
    """Operator rendering of a fleet-observatory status block
    (obs/fleetscope.py): headline, quarantine, gate mix, dispersion,
    rank table.  ``containment`` (local runs with engine access) adds
    the per-lane quarantine table; a remote /state.json block carries
    the bounded counts only (the cardinality discipline)."""
    if not block:
        print("no fleet block — is the fleet observatory enabled and a "
              "vmapped tenant engine deciding?")
        return
    print(f"fleet: {block.get('tenants', 0)} tenants "
          f"({block.get('active_lanes', 0)} active lanes), "
          f"{block.get('decides', 0)} decides, "
          f"{block.get('decisions', 0)} decisions last tick "
          f"({block.get('executable', 0)} executable)")
    sampled = block.get("sampled_lanes", [])
    n_sampled = block.get("sampled_lane_count", len(sampled))
    more = ", …" if n_sampled > len(sampled) else ""
    print(f"starved lanes (windowed min): {block.get('starved_lanes', 0)}; "
          f"balance drift max: {block.get('balance_drift_max', 0.0)}; "
          f"sampled lanes ({n_sampled}): {sampled}{more}")
    n_quar = int(block.get("quarantined_lanes", 0) or 0)
    heals = int(block.get("heals_total", 0) or 0)
    rows = (containment or {}).get("quarantined") or []
    print(f"quarantine: {n_quar} lane(s) quarantined, "
          f"{heals} heal(s) completed"
          + (f", {containment.get('degraded_ticks', 0)} degraded tick(s)"
             if containment else ""))
    if rows:
        print(f"  {'lane':>6} {'gate':<18}{'cooldown left':>14}")
        for r in rows:
            print(f"  {r.get('lane', ''):>6} "
                  f"{r.get('gate', 'lane_quarantined'):<18}"
                  f"{r.get('cooldown', 0):>14}")
    mix = block.get("gate_mix") or {}
    total = sum(mix.values()) or 1
    if mix:
        print("\ngate mix (windowed):")
        for gate, count in sorted(mix.items(), key=lambda kv: -kv[1]):
            bar = "#" * max(int(40 * count / total), 1)
            print(f"  {gate:<22}{count:>9}  {count / total:>7.1%} {bar}")
        if block.get("dominant_gate"):
            print(f"  dominant veto gate: {block['dominant_gate']} "
                  f"({block.get('gate_dominance', 0.0):.1%} of vetoes)")
    pnl, bal = block.get("pnl") or {}, block.get("balance") or {}
    if pnl:
        print("\ndispersion over lanes:")
        qs = sorted(set(pnl) | set(bal))
        print("  " + "".join(f"{q:>14}" for q in [""] + qs))
        print("  " + f"{'pnl':<2}" + "".join(
            f"{pnl.get(q, float('nan')):>14,.2f}" for q in qs)
            + f"   spread {block.get('pnl_spread', 0.0):,.2f}")
        print("  " + f"{'balance':<2}" + "".join(
            f"{bal.get(q, float('nan')):>14,.2f}" for q in qs))
        if block.get("max_drawdown_max") is not None:
            print(f"  worst max-drawdown: "
                  f"{block['max_drawdown_max']:,.2f}")
    best, worst = block.get("best") or [], block.get("worst") or []
    if best:
        print("\nlane rank (rolling PnL):")
        print(f"  {'':>4}{'best lane':>10}{'pnl':>12}   "
              f"{'worst lane':>10}{'pnl':>12}")
        for i in range(max(len(best), len(worst))):
            b = best[i] if i < len(best) else {}
            w = worst[i] if i < len(worst) else {}
            print(f"  #{i:<3}{b.get('lane', ''):>10}"
                  f"{b.get('pnl', float('nan')):>12,.2f}   "
                  f"{w.get('lane', ''):>10}"
                  f"{w.get('pnl', float('nan')):>12,.2f}")


def cmd_fleet(args):
    """Fleet observatory operator view (obs/fleetscope.py, ISSUE 15): the
    device-aggregated health of a vmapped tenant fleet — lane rank table
    by rolling PnL, the windowed veto-gate mix, PnL/balance dispersion
    quantiles, starvation and balance-drift signals.  With `--url`, reads
    a LIVE system's /state.json `fleet` block; without it, drives a short
    local vmapped load burst (testing/loadgen.py) so the view is
    demonstrable on any dev host."""
    if args.url:
        state = _fetch_state(args.url)
        _render_fleet(state.get("fleet") or {})
        return
    from ai_crypto_trader_tpu.testing.loadgen import LoadConfig, run_load

    cfg = LoadConfig(tenants=args.tenants, symbols=args.symbols,
                     ticks=args.ticks, seed=args.seed, mode="vmapped",
                     min_samples=2)
    rep = run_load(cfg)
    print(f"(local demo fleet: {args.tenants} tenants × {args.symbols} "
          f"symbols, {args.ticks} measured ticks, p99 "
          f"{rep['p99_ms']:.1f} ms)\n")
    _render_fleet(rep.get("fleet") or {}, rep.get("containment"))


def _render_latency(tickpath_block: dict, coldstart_block: dict,
                    build_block: dict | None = None) -> None:
    """Operator rendering of the decision critical-path observatory
    (obs/tickpath.py): the per-phase waterfall table, bottleneck + overlap
    headroom headline, the event→decision SLO line, and the per-program
    cold-start ledger."""
    if not tickpath_block:
        print("no tickpath block — is the decision critical-path "
              "observatory enabled? (it is on by default; "
              "TradingSystem(enable_tickpath=False) turns it off)")
        return
    phases = tickpath_block.get("phases") or {}
    bottleneck = tickpath_block.get("bottleneck")
    print("decision critical path (per-phase waterfall, ms):")
    print(f"  {'phase':<16}{'count':>7}{'p50':>10}{'p99':>10}{'last':>10}")
    for name, row in phases.items():
        if not row.get("count"):
            continue
        mark = "  ◀ bottleneck" if name == bottleneck else ""
        print(f"  {name:<16}{row['count']:>7}{row['p50_ms']:>10.2f}"
              f"{row['p99_ms']:>10.2f}{row['last_ms']:>10.2f}{mark}")
    if not any(row.get("count") for row in phases.values()):
        print("  (no phases observed yet)")
    overlap = tickpath_block.get("overlap_headroom_ms") or {}
    if overlap.get("p50") is not None:
        print(f"\noverlap headroom (dispatch→ready host-idle wait "
              f"pipelining can reclaim): p50 {overlap['p50']:.2f} ms, "
              f"p99 {overlap.get('p99', 0.0):.2f} ms")
    age = tickpath_block.get("event_age_ms") or {}
    if age.get("count"):
        print(f"event→decision age: p50 {age.get('p50', 0.0):.0f} ms, "
              f"p99 {age.get('p99', 0.0):.0f} ms over {age['count']} "
              f"decisions (budget {age.get('budget_ms', 0.0):.0f} ms)")
    skew = tickpath_block.get("clock_skew_total", 0)
    if skew:
        print(f"clock-skew clamps (venue event ahead of host clock): {skew}")
    programs = (coldstart_block or {}).get("programs") or {}
    if programs:
        print("\ncold-start ledger (first-compile cost per program):")
        print(f"  {'program':<24}{'wall_ms':>10}{'compile_ms':>12}"
              f"{'compiles':>10}")
        for name, row in sorted(programs.items(),
                                key=lambda kv: -kv[1]["wall_ms"]):
            print(f"  {name:<24}{row['wall_ms']:>10.1f}"
                  f"{row['compile_ms']:>12.1f}{row['compiles']:>10}")
        print(f"  total: wall {coldstart_block.get('total_wall_ms', 0.0):,.1f}"
              f" ms (compile "
              f"{coldstart_block.get('total_compile_ms', 0.0):,.1f} ms)")
    if build_block:
        print(f"\nbuild: jax {build_block.get('jax_version')} on "
              f"{build_block.get('backend')} "
              f"({build_block.get('device_kind')}), process start "
              f"{build_block.get('process_start')}")


def _run_latency_burst(symbol: str, ticks: int, seed: int,
                       pipelined: bool = False) -> tuple[dict, dict, dict]:
    """One local paper burst for the latency views: builds a fresh
    TradingSystem (serial or pipelined tick path), drives `ticks` ticks on
    the virtual clock, and returns its (tickpath, coldstart, build)
    status blocks.  The pipelined/serial toggle is the SAME TickEngine
    ctor knob the parity tests flip — what `--compare` renders is the
    exact configuration the contract suite certifies."""
    from ai_crypto_trader_tpu.data.ingest import from_dict
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
    from ai_crypto_trader_tpu.shell.exchange import make_exchange
    from ai_crypto_trader_tpu.shell.launcher import TradingSystem

    d = generate_ohlcv(n=ticks + 600, seed=seed)
    series = from_dict({k: v for k, v in d.items() if k != "regime"},
                       symbol=symbol)
    # virtual clock aligned to the synthetic candle open-times (i*60_000
    # epoch-ms), so the demo's event→decision ages read as a real feed's
    # would instead of clamping to zero or blowing past the budget
    clock = {"t": 600 * 60.0}
    ex = make_exchange("fake", series={symbol: series},
                       quote_balance=10_000.0)
    ex.advance(symbol, steps=600)
    system = TradingSystem(ex, [symbol], now_fn=lambda: clock["t"],
                           pipelined=pipelined)

    async def go():
        for _ in range(ticks):
            ex.advance(symbol)
            clock["t"] += 60.0
            await system.tick()
        # drain the last inflight dispatch so the final decision publishes
        # and no donated buffer is abandoned mid-flight
        await system.monitor.flush_pipeline()

    try:
        asyncio.run(go())
        return (system.tickpath.status(),
                system.tickpath.coldstart_status(),
                system.build_info)
    finally:
        system.shutdown()


def _render_latency_compare(serial_tp: dict, pipe_tp: dict,
                            ticks: int) -> None:
    """Side-by-side serial vs pipelined waterfalls: per-phase p50 columns
    with deltas, then the overlap story — how much dispatch→ready host
    idle the serial path exposes (headroom) and how much of it the
    pipelined path actually filled with host work (reclaimed)."""
    s_phases = serial_tp.get("phases") or {}
    p_phases = pipe_tp.get("phases") or {}
    names = [n for n in s_phases
             if (s_phases.get(n, {}).get("count")
                 or p_phases.get(n, {}).get("count"))]
    print(f"serial vs pipelined tick path ({ticks} paper ticks each, "
          f"phase p50 ms):")
    print(f"  {'phase':<16}{'serial':>10}{'pipelined':>12}{'delta':>10}")
    s_total = p_total = 0.0
    for name in names:
        s50 = s_phases.get(name, {}).get("p50_ms", 0.0) or 0.0
        p50 = p_phases.get(name, {}).get("p50_ms", 0.0) or 0.0
        s_total += s50
        p_total += p50
        print(f"  {name:<16}{s50:>10.2f}{p50:>12.2f}{p50 - s50:>+10.2f}")
    print(f"  {'(sum of p50s)':<16}{s_total:>10.2f}{p_total:>12.2f}"
          f"{p_total - s_total:>+10.2f}")
    s_head = (serial_tp.get("overlap_headroom_ms") or {}).get("p50")
    p_head = (pipe_tp.get("overlap_headroom_ms") or {}).get("p50")
    reclaimed = (pipe_tp.get("overlap_reclaimed_ms") or {}).get("p50")
    if s_head is not None:
        print(f"\noverlap headroom (host-idle dispatch→ready wait): "
              f"serial p50 {s_head:.2f} ms"
              + (f" → pipelined p50 {p_head:.2f} ms"
                 if p_head is not None else ""))
    if reclaimed is not None:
        print(f"overlap reclaimed by pipelining (device compute hidden "
              f"behind host work): p50 {reclaimed:.2f} ms/tick")
    s_age = serial_tp.get("event_age_ms") or {}
    p_age = pipe_tp.get("event_age_ms") or {}
    if s_age.get("count") and p_age.get("count"):
        print(f"event→decision age p50: serial {s_age.get('p50', 0.0):.0f} "
              f"ms, pipelined {p_age.get('p50', 0.0):.0f} ms (budget "
              f"{s_age.get('budget_ms', 0.0):.0f} ms; pipelined publishes "
              f"tick T at T+1's poll)")


def cmd_latency(args):
    """Decision critical-path operator view (obs/tickpath.py): WHERE each
    tick's time goes (phase waterfall), the overlap headroom pipelining
    could reclaim, the event→decision age SLO reading, and the cold-start
    ledger (first-compile cost per hot program).  With `--url`, reads a
    LIVE system's /state.json tickpath/coldstart blocks (no jax import);
    without it, drives a short local paper burst so the view is
    demonstrable on any dev host.  `--compare` drives the burst TWICE —
    serial then pipelined — and renders the waterfalls side by side."""
    if args.url:
        state = _fetch_state(args.url)
        _render_latency(state.get("tickpath") or {},
                        state.get("coldstart") or {},
                        state.get("build"))
        return
    if args.compare:
        serial_tp, _, _ = _run_latency_burst(args.symbol, args.ticks,
                                             args.seed, pipelined=False)
        pipe_tp, _, _ = _run_latency_burst(args.symbol, args.ticks,
                                           args.seed, pipelined=True)
        print(f"(local demo: 2×{args.ticks} paper ticks on {args.symbol}; "
              f"point --url at a running `trade --serve` for live state)\n")
        _render_latency_compare(serial_tp, pipe_tp, args.ticks)
        return
    tp, cold, build = _run_latency_burst(args.symbol, args.ticks, args.seed)
    print(f"(local demo: {args.ticks} paper ticks on {args.symbol}; "
          f"point --url at a running `trade --serve` for live state)\n")
    _render_latency(tp, cold, build)


def cmd_status(args):
    """Operator status without a REPL (ISSUE 12 satellite): queries a live
    dashboard server's `/state.json` and prints a compact summary — the
    active mesh/partitioner layout, portfolio, alerts, capacity bottleneck
    and (when the observatories are on) devprof/meshprof headlines.
    Without `--url` it reports the LOCAL process view: the partitioner
    layout `get_partitioner()` would serve this host."""
    if not args.url:
        from ai_crypto_trader_tpu.parallel import get_partitioner

        print(json.dumps({"live": False,
                          "partitioner": get_partitioner().describe()},
                         indent=2, default=str))
        print("(no --url given: showing the local partitioner layout; "
              "point --url at a running `trade --serve` for live state)")
        return
    state = _fetch_state(args.url)
    status = state.get("status", {})
    out = {
        "live": True,
        "portfolio_value_usd": status.get("portfolio_value_usd"),
        "open_trades": len(status.get("active_trades", {})),
        "closed_trades": status.get("closed_trades"),
        "total_pnl": status.get("total_pnl"),
        "alerts": status.get("alerts", []),
    }
    if "mesh" in state:
        out["mesh"] = state["mesh"]
    cap = state.get("capacity")
    if cap:
        out["bottleneck_stage"] = cap.get("bottleneck_stage")
    dev = state.get("devprof")
    if dev:
        out["slo_burn_rates"] = dev.get("burn_rates")
        out["donation_failures"] = dev.get("donation_failures")
    # process provenance (shell/launcher.py build_info): which jax /
    # backend / device produced every number above — the first question
    # when two operators compare readings from different hosts
    if "build" in state:
        out["build"] = state["build"]
    tp = state.get("tickpath")
    if tp:
        out["tickpath_bottleneck"] = tp.get("bottleneck")
        out["event_age_p99_ms"] = (tp.get("event_age_ms") or {}).get("p99")
    # continuous PBT training service: generation counter, quarantined
    # members, checkpoint/recalibration staleness (rl/trainer_service.py)
    tr = state.get("training")
    if tr:
        out["training"] = {
            "generation": tr.get("generation"),
            "best_fitness": tr.get("best_fitness"),
            "quarantined_members": tr.get("quarantined_members"),
            "checkpoint_age_s": tr.get("checkpoint_age_s"),
            "last_recalibration": tr.get("last_recalibration"),
            "resumed_at": tr.get("resumed_at"),
        }
    print(json.dumps(out, indent=2, default=str))


def cmd_registry(args):
    """Model-registry operations (`run_ai_model_services.py` surface)."""
    from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

    reg = ModelRegistry(path=args.path)
    if args.best:
        print(json.dumps(reg.best(args.kind) or {"status": "no_entries"},
                         indent=2, default=str))
    else:
        rows = [{"version": e["version"], "kind": e["kind"],
                 "status": e["status"],
                 "sharpe": e.get("performance", {}).get("sharpe_ratio")}
                for e in reg.entries.values()]
        print(json.dumps(rows, indent=2))


def cmd_dashboard(args):
    from ai_crypto_trader_tpu.shell.dashboard import write_dashboard

    d = _load_or_generate(args.symbol, 2000, args.seed)
    path = write_dashboard(args.out, price_series=np.asarray(d["close"])[-500:])
    print(f"wrote {path}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ai_crypto_trader_tpu",
                                description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp):
        sp.add_argument("--symbol", default="BTCUSDC")
        sp.add_argument("--days", type=int, default=7)
        sp.add_argument("--seed", type=int, default=0)

    sp = sub.add_parser("fetch", help="fetch (or synthesize) candles to CSV")
    sp.add_argument("--source", choices=("synthetic", "binance"),
                    default="synthetic")
    common(sp); sp.set_defaults(fn=cmd_fetch)
    sp = sub.add_parser("backtest", help="run a vectorized backtest")
    common(sp)
    sp.add_argument("--sweep", type=int, default=1,
                    help="strategy-population width (vmap)")
    sp.set_defaults(fn=cmd_backtest)
    sp = sub.add_parser("list", help="list saved results")
    sp.set_defaults(fn=cmd_list)
    sp = sub.add_parser("analyze", help="pretty-print a result file")
    sp.add_argument("--file", required=True)
    sp.set_defaults(fn=cmd_analyze)
    sp = sub.add_parser("report", help="multi-run summary + HTML report")
    sp.add_argument("--symbol", default="")
    sp.add_argument("--out", default="backtest_report.html")
    sp.set_defaults(fn=cmd_report)
    sp = sub.add_parser("train", help="train a price model")
    common(sp)
    sp.add_argument("--model", default="lstm")
    sp.add_argument("--epochs", type=int, default=5)
    sp.add_argument("--seq-len", type=int, default=60)
    sp.add_argument("--batch-size", type=int, default=32)
    sp.add_argument("--precision", choices=("f32", "bf16"), default="f32",
                    help="matmul precision for the compiled training "
                         "epoch (bf16 = MXU-native on TPU)")
    sp.set_defaults(fn=cmd_train)
    sp = sub.add_parser("evolve", help="GA-evolve strategy parameters")
    common(sp)
    sp.add_argument("--population", type=int, default=20)
    sp.add_argument("--generations", type=int, default=10)
    sp.set_defaults(fn=cmd_evolve)
    sp = sub.add_parser("rl", help="population-based RL: PBT-train a DQN "
                        "fleet inside the LOB simulator (local, no venue)")
    sp.add_argument("--population", type=int, default=8)
    sp.add_argument("--generations", type=int, default=4)
    sp.add_argument("--iters", type=int, default=4,
                    help="train iterations per member per generation")
    sp.add_argument("--envs", type=int, default=16)
    sp.add_argument("--rollout", type=int, default=8)
    sp.add_argument("--scenarios", type=int, default=8)
    sp.add_argument("--steps", type=int, default=1024)
    sp.add_argument("--episode-len", type=int, default=256)
    sp.add_argument("--dynamics", choices=("lob", "gbm"), default="lob")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--registry", default=None,
                    help="register + scorecard-gate the winner into this "
                         "registry JSON")
    sp.add_argument("--checkpoint", default=None,
                    help="journal full fleet snapshots to this path every "
                         "--checkpoint-every generations (resume-able)")
    sp.add_argument("--checkpoint-every", type=int, default=1)
    sp.add_argument("--resume", default=None,
                    help="resume from the newest intact checkpoint in this "
                         "journal: generation counter, fitness history and "
                         "hypers continue bit-identically to a run that "
                         "never died")
    sp.set_defaults(fn=cmd_rl)
    sp = sub.add_parser("generate",
                        help="generate strategy structures (real-CV search)")
    sp.add_argument("--folds", type=int, default=3)
    sp.add_argument("--pool", type=int, default=16)
    sp.add_argument("--rounds", type=int, default=6)
    sp.add_argument("--registry", default="registry.json")
    common(sp); sp.set_defaults(fn=cmd_generate)
    sp = sub.add_parser("mc", help="Monte-Carlo risk simulation")
    common(sp)
    sp.add_argument("--paths", type=int, default=10_000)
    sp.set_defaults(fn=cmd_mc)
    sp = sub.add_parser("trade", help="run the live loop (paper mode)")
    common(sp)
    sp.add_argument("--paper", action="store_true")
    sp.add_argument("--ticks", type=int, default=100)
    sp.add_argument("--dashboard", default=None,
                    help="write a static HTML snapshot per tick to this path")
    sp.add_argument("--serve", type=int, default=None, metavar="PORT",
                    help="serve the LIVE dashboard on this port during the "
                         "run (reference dashboard.py :8050 behavior)")
    sp.add_argument("--full-stack", action="store_true",
                    help="register the reference's full service roster "
                         "(social/news/patterns/regime/NN/evolver/"
                         "generator/grid/DCA) on the paper loop")
    sp.add_argument("--registry", default="models/registry.json",
                    help="model-registry file for --full-stack versioning")
    sp.add_argument("--trace-jsonl", default=None, metavar="PATH",
                    help="enable end-to-end tracing and append every "
                         "finished span to this JSONL file "
                         "(utils/tracing.py; /traces on --serve)")
    sp.add_argument("--journal", default=None, metavar="PATH",
                    help="crash-safe state: write-ahead journal every "
                         "order intent/ack/closure to PATH; if the file "
                         "already exists, replay + reconcile it against "
                         "the exchange before trading (utils/journal.py)")
    sp.add_argument("--serve-hold-s", type=float, default=0.0,
                    help="keep serving this many seconds after the ticks")
    sp.add_argument("--devprof", action="store_true",
                    help="device-runtime observatory (utils/devprof.py): "
                         "program cost cards + donation verification, "
                         "live-memory watermarks, latency SLO gauges")
    sp.add_argument("--flightrec", default=None, metavar="PATH",
                    help="persist the decision-provenance flight recorder "
                         "(obs/flightrec.py) as checksummed JSONL to PATH "
                         "— queryable offline via `why --file PATH`")
    sp.add_argument("--meshprof", action="store_true",
                    help="mesh runtime observatory (utils/meshprof.py): "
                         "recompile/transfer sentinels on the hot "
                         "dispatches, sharded-program layout cards, "
                         "per-device memory-imbalance gauges")
    sp.add_argument("--fleetscope", action="store_true",
                    help="fleet observatory (obs/fleetscope.py): device-"
                         "aggregated lane telemetry for any vmapped "
                         "tenant engine in this process — fleet_* "
                         "gauges, /state.json fleet block, Fleet* alerts")
    sp.add_argument("--pipelined", action="store_true",
                    help="pipelined tick path (ops/tick_engine.py): "
                         "double-buffered candle ring + async host_read "
                         "— publish tick T−1 while T computes on device")
    sp.add_argument("--precision", default=None,
                    metavar="{f32,bf16,tf32}",
                    help="matmul precision for the fused decide programs "
                         "(default full f32; bf16 trades tolerance-"
                         "bounded decision drift for device throughput)")
    sp.add_argument("--aot-cache", default=None, metavar="DIR",
                    help="persistent AOT compile cache rooted at DIR "
                         "(utils/aotcache.py): restarts replay the hot "
                         "set's executables instead of recompiling")
    sp.set_defaults(fn=cmd_trade)
    sp = sub.add_parser("why", help="decision provenance for a symbol "
                                    "(flight-recorder query)")
    sp.add_argument("symbol")
    sp.add_argument("--file", default="decisions.jsonl",
                    help="decision JSONL written by trade --flightrec")
    sp.add_argument("--url", default=None,
                    help="query a live dashboard server instead "
                         "(e.g. http://127.0.0.1:8050)")
    sp.add_argument("--last", type=int, default=10)
    sp.add_argument("--lane", type=int, default=None,
                    help="filter to one vmapped tenant lane's sampled "
                         "provenance (fleet observatory crc32 sample)")
    sp.set_defaults(fn=cmd_why)
    sp = sub.add_parser("profile",
                        help="capture a TensorBoard XPlane device profile "
                             "of a short paper-trading burst")
    common(sp)
    sp.add_argument("--ticks", type=int, default=10)
    sp.add_argument("--out", default=None,
                    help="artifact directory (default profiles/xplane_<ts>)")
    sp.set_defaults(fn=cmd_profile)
    sp = sub.add_parser("load", help="tenants×symbols load harness "
                                     "(saturation report; --ramp finds "
                                     "the max sustainable point)")
    sp.add_argument("--tenants", type=int, default=4,
                    help="tenant decision lanes (the ramp's cap)")
    sp.add_argument("--symbols", type=int, default=4,
                    help="synthetic symbol universe size")
    sp.add_argument("--ticks", type=int, default=12,
                    help="measured ticks per load point")
    sp.add_argument("--window", type=int, default=64,
                    help="candle window (engine/monitor kline_limit)")
    sp.add_argument("--slo-ms", type=float, default=250.0,
                    help="p99 tick-latency SLO the ramp holds")
    sp.add_argument("--ramp", action="store_true",
                    help="closed-loop ramp: step tenants until the p99 "
                         "SLO breaches; report max sustainable point + "
                         "the telemetry-named saturated stage")
    mode = sp.add_mutually_exclusive_group()
    mode.add_argument("--vmapped", dest="mode", action="store_const",
                      const="vmapped",
                      help="tenants as a batch axis: ONE TenantEngine "
                           "dispatch per tick for all N tenants "
                           "(ops/tenant_engine.py)")
    mode.add_argument("--object-lanes", dest="mode", action="store_const",
                      const="objects",
                      help="per-tenant Python SignalAnalyzer/TradeExecutor "
                           "lanes (the PR 10 baseline / parity oracle)")
    sp.set_defaults(mode="objects")
    sp.add_argument("--no-fleetscope", action="store_true",
                    help="measure the bare vmapped engine (no fleet "
                         "observatory — the overhead-probe configuration)")
    sp.add_argument("--flightrec", default=None, metavar="PATH",
                    help="persist sampled-lane decision provenance as "
                         "checksummed JSONL (vmapped mode; query with "
                         "`why SYMBOL --lane N --file PATH`)")
    sp.add_argument("--seed", type=int, default=0)
    sp.set_defaults(fn=cmd_load)
    sp = sub.add_parser("scan", help="discover + rank tradable pairs")
    sp.add_argument("--pairs", type=int, default=64,
                    help="synthetic universe size (paper mode)")
    sp.add_argument("--lookback", type=int, default=256)
    sp.add_argument("--top", type=int, default=10)
    sp.add_argument("--seed", type=int, default=0)
    sp.set_defaults(fn=cmd_scan)
    sp = sub.add_parser("mesh", help="partitioner layout + per-device "
                                     "cards (mesh runtime observatory)")
    sp.add_argument("--pop", type=int, default=256,
                    help="population size for the pad/mask arithmetic")
    sp.add_argument("--url", default=None,
                    help="read a live system's /state.json mesh block "
                         "instead (e.g. http://127.0.0.1:8050)")
    sp.set_defaults(fn=cmd_mesh)
    sp = sub.add_parser("fleet", help="fleet observatory operator view: "
                                      "lane rank table, gate mix, "
                                      "dispersion (obs/fleetscope.py)")
    sp.add_argument("--url", default=None,
                    help="read a live system's /state.json fleet block "
                         "instead of running a local demo fleet")
    sp.add_argument("--tenants", type=int, default=8,
                    help="local demo fleet size (no --url)")
    sp.add_argument("--symbols", type=int, default=4)
    sp.add_argument("--ticks", type=int, default=6)
    sp.add_argument("--seed", type=int, default=0)
    sp.set_defaults(fn=cmd_fleet)
    sp = sub.add_parser("latency", help="decision critical-path view: "
                                        "tick-phase waterfall, overlap "
                                        "headroom, cold-start ledger "
                                        "(obs/tickpath.py)")
    sp.add_argument("--url", default=None,
                    help="read a live system's /state.json tickpath/"
                         "coldstart blocks instead of running a local "
                         "demo burst")
    sp.add_argument("--symbol", default="BTCUSDC")
    sp.add_argument("--ticks", type=int, default=12,
                    help="local demo burst length (no --url)")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--compare", action="store_true",
                    help="run the local burst twice — serial and "
                         "pipelined tick path — and render the phase "
                         "waterfalls side by side (no --url)")
    sp.set_defaults(fn=cmd_latency)
    sp = sub.add_parser("status", help="operator summary from a live "
                                       "dashboard server (/state.json)")
    sp.add_argument("--url", default=None,
                    help="dashboard server base URL "
                         "(e.g. http://127.0.0.1:8050)")
    sp.set_defaults(fn=cmd_status)
    sp = sub.add_parser("registry", help="inspect the model registry")
    sp.add_argument("--path", default="models/registry.json")
    sp.add_argument("--kind", default="strategy_params")
    sp.add_argument("--best", action="store_true")
    sp.set_defaults(fn=cmd_registry)
    sp = sub.add_parser("dashboard", help="render the HTML dashboard")
    common(sp)
    sp.add_argument("--out", default="dashboard.html")
    sp.set_defaults(fn=cmd_dashboard)
    return p


_JAX_COMMANDS = {"backtest", "train", "evolve", "mc", "trade", "dashboard",
                 "scan", "profile", "load", "mesh", "fleet", "latency"}


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.command in _JAX_COMMANDS:
        # Persistent XLA compilation cache: the big replay/indicator graphs
        # take tens of seconds to compile on TPU; pay it once per machine,
        # not per invocation (VERDICT r2 weak#5). Guarded by subcommand so
        # `list` / `analyze` / `--help` keep their no-jax startup.
        from ai_crypto_trader_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()
    args.fn(args)


if __name__ == "__main__":
    main()
