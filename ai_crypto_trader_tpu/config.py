"""Typed, validated configuration tree for the framework.

Replaces the reference's three uncoordinated config layers — the 900-line
``config.json`` read ad hoc by every service, dotenv env vars, and scattered
argparse flags (reference: ``config.json``, ``.env-sample``,
``run_backtest.py:24-59``) — with one frozen dataclass tree.  Nothing mutates
config at runtime (the reference's MonteCarloService *writes back* defaults
into config.json, ``services/monte_carlo_service.py:97-101``; we do not).

All defaults mirror the reference's semantics (``config.json`` values) so a
user of the reference finds the same knobs with the same meanings.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence


def _freeze(obj):
    if isinstance(obj, dict):
        return {k: _freeze(v) for k, v in obj.items()}
    return obj


@dataclass(frozen=True)
class TradingParams:
    """Mirrors reference config.json `trading_params` (lines 2-15)."""

    min_volume_usdc: float = 50_000.0
    min_price_change_pct: float = 0.5
    position_size: float = 0.4          # fraction of capital offered to sizer
    max_positions: int = 5
    stop_loss_pct: float = 2.0
    take_profit_pct: float = 4.0
    min_trade_amount: float = 40.0
    ai_analysis_interval: float = 60.0
    ai_confidence_threshold: float = 0.7
    min_signal_strength: float = 70.0   # gate in strategy_tester.py:383
    candle_interval: str = "1m"
    initial_balance: float = 10_000.0
    warmup_candles: int = 10            # strategy_tester.py:192 skips first 10
    fee_rate: float = 0.0               # reference models zero fees


@dataclass(frozen=True)
class TrailingStopParams:
    """Mirrors `risk_management.trailing_stop_settings` and the four
    strategies of TrailingStopManager (trade_executor_service.py:55-398)."""

    strategy: str = "percent_based"  # percent_based|atr_based|volatility_based|fixed_amount
    activation_threshold_pct: float = 1.0
    trail_percent: float = 0.8
    step_size: float = 0.2
    min_price_movement_pct: float = 0.5
    atr_multiplier: float = 2.0
    atr_min_periods: int = 14
    volatility_multiplier: float = 1.5
    volatility_lookback: int = 20
    fixed_trail_amount: float = 5.0
    min_trail_distance_pct: float = 0.5


@dataclass(frozen=True)
class SocialRiskParams:
    """Mirrors `risk_management.social_risk_adjustment` (config.json:82-…)."""

    enabled: bool = True
    position_size_impact: float = 0.3
    stop_loss_impact: float = 0.2
    take_profit_impact: float = 0.4
    correlation_impact: float = 0.25
    sentiment_half_life_hours: float = 6.0
    min_data_quality: float = 0.5
    bullish_threshold: float = 0.65
    bearish_threshold: float = 0.35
    max_adjustment_percent: float = 0.5
    sentiment_weights: Mapping[str, float] = field(
        default_factory=lambda: {
            "twitter_sentiment": 0.35,
            "reddit_sentiment": 0.30,
            "news_sentiment": 0.25,
            "overall_sentiment": 0.10,
        }
    )


@dataclass(frozen=True)
class RiskParams:
    """Mirrors `risk_management` (config.json:16-111) + PortfolioRiskService."""

    max_portfolio_var: float = 0.05
    confidence_level: float = 0.95
    var_lookback_days: int = 30
    max_portfolio_allocation: float = 0.25
    correlation_threshold: float = 0.7
    min_volatility_factor: float = 0.5
    max_volatility_factor: float = 2.0
    volatility_lookback_days: int = 14
    max_drawdown_limit: float = 0.15
    position_sizing_method: str = "equal_risk"
    adaptive_stop_loss_enabled: bool = True
    trailing_stop: TrailingStopParams = field(default_factory=TrailingStopParams)
    social: SocialRiskParams = field(default_factory=SocialRiskParams)


@dataclass(frozen=True)
class MonteCarloParams:
    """Mirrors monte_carlo config (config.json:87-103) — 1 000 paths ×
    30-day horizon, five scenarios scaling drift & vol."""

    num_simulations: int = 1_000
    horizon_days: int = 30
    confidence_level: float = 0.95
    method: str = "gbm"  # gbm | bootstrap
    # scenario -> (drift multiplier, vol multiplier); config.json:97-103
    scenarios: Mapping[str, tuple] = field(
        default_factory=lambda: {
            "base": (1.0, 1.0),
            "bull": (1.5, 0.8),
            "bear": (-1.0, 1.3),
            "volatile": (1.0, 2.0),
            "crab": (0.2, 0.6),
        }
    )


@dataclass(frozen=True)
class GAParams:
    """Mirrors GA budgets (strategy_evolution_service.py:78-79, config:213)."""

    population_size: int = 20
    generations: int = 10
    elite_size: int = 2
    tournament_size: int = 3
    crossover_rate: float = 0.7
    mutation_rate: float = 0.2
    mutation_scale: float = 0.2  # fraction of range


@dataclass(frozen=True)
class RLParams:
    """Mirrors DQN budgets (reinforcement_learning.py:33-97)."""

    state_size: int = 10
    action_size: int = 3            # BUY / HOLD / SELL
    hidden_sizes: Sequence[int] = (24, 24)
    gamma: float = 0.95
    epsilon: float = 1.0
    epsilon_min: float = 0.01
    epsilon_decay: float = 0.995
    learning_rate: float = 1e-3
    replay_capacity: int = 10_000
    batch_size: int = 64
    target_sync_every: int = 100
    num_envs: int = 64              # new: vmapped parallel envs


@dataclass(frozen=True)
class NNParams:
    """Mirrors `neural_network` (config.json:403-500)."""

    model_type: str = "lstm"
    sequence_length: int = 60
    lookback_days: int = 60
    epochs: int = 100
    batch_size: int = 32
    units: int = 64
    num_layers: int = 2
    dropout: float = 0.2
    learning_rate: float = 1e-3
    early_stopping_patience: int = 10
    reduce_lr_patience: int = 5
    reduce_lr_factor: float = 0.5
    hpo_trials: int = 20
    prediction_horizons: Sequence[int] = (1, 3, 5)   # multitask heads
    feature_names: Sequence[str] = (
        "close", "volume", "rsi", "macd", "macd_signal", "bb_position",
        "stoch_k", "williams_r", "atr", "ema_12",
    )


@dataclass(frozen=True)
class PatternParams:
    """Mirrors `pattern_recognition` (config.json:501-557)."""

    sequence_length: int = 60
    stride: int = 5
    confidence_threshold: float = 0.5
    signal_strength_threshold: float = 0.3
    model_type: str = "cnn"  # cnn | lstm | cnn_lstm


@dataclass(frozen=True)
class RegimeParams:
    """Mirrors `market_regime` config + MarketRegimeDetector defaults."""

    n_regimes: int = 4
    method: str = "kmeans"  # kmeans | gmm | hmm | rules | hybrid
    lookback: int = 500
    pca_components: int = 5
    kmeans_iters: int = 100
    em_iters: int = 50
    hmm_iters: int = 30


@dataclass(frozen=True)
class MeshParams:
    """Device-mesh / distribution config (new — the reference has no
    multi-device concept; see SURVEY §2.7)."""

    data_axis: str = "data"
    model_axis: str = "model"
    data_parallel: int = -1   # -1 = all devices
    model_parallel: int = 1
    use_distributed_init: bool = False  # jax.distributed for multi-host


@dataclass(frozen=True)
class EvolutionParams:
    """Mirrors `evolution` (config.json:207-294): hybrid GA/RL/LLM dispatch,
    monitoring thresholds, and the 18-dim strategy parameter space ranges
    (strategy_evolution_service.py:98-117)."""

    method: str = "hybrid"  # ga | rl | llm | hybrid
    monitor_frequency_s: float = 3600.0
    min_sharpe: float = 1.2
    max_drawdown: float = 0.15
    min_win_rate: float = 0.52
    min_profit_factor: float = 1.2
    ga: GAParams = field(default_factory=GAParams)


@dataclass(frozen=True)
class LLMParams:
    """LLM client settings and prompts-as-config, mirroring the reference's
    `openai` block (config.json:112-121): model/temperature/max_tokens plus
    the five prompt templates AITrader formats (analysis, explainable
    analysis, risk sizing, market-wide, explainable market-wide —
    `services/ai_trader.py:36-342`).  Templates are re-derived with the same
    placeholder fields and the same required JSON reply contract; missing
    context keys degrade to the raw-JSON context block (the reference wraps
    `.format` in try/except and logs, ai_trader.py:81-85)."""

    model: str = "gpt-4o"
    temperature: float = 0.7
    max_tokens: int = 2000
    base_url: str = "https://api.openai.com/v1"
    api_key_env: str = "OPENAI_API_KEY"   # never the key itself in config
    explainable: bool = True              # prefer explainable_* templates
    analysis_prompt: str = (
        "You are an expert cryptocurrency trading analyst. Evaluate {symbol}.\n"
        "Price ${price:.8f}, 24h volume ${volume:.2f}; change 1m "
        "{price_change_1m:.2f}% / 3m {price_change_3m:.2f}% / 5m "
        "{price_change_5m:.2f}% / 15m {price_change_15m:.2f}%.\n"
        "Indicators: RSI {rsi:.2f}, stochastic %K {stoch:.2f}, MACD "
        "{macd:.8f}, Williams %R {williams_r:.2f}, Bollinger position "
        "{bb_position:.4f}.\nTrend: {trend} (strength {trend_strength:.4f}).\n"
        "Combined indicator read: {combined_summary}\n"
        "Social: volume {social_volume}, engagement {social_engagement}, "
        "contributors {social_contributors}, sentiment {social_sentiment}.\n"
        "Recent news: {recent_news}\nMarket context: {market_context}\n"
        "Weigh price momentum, trend, combined signals, social/news impact, "
        "volume, and risk. Reply with ONLY a JSON object with keys: "
        "decision ('BUY'|'SELL'|'HOLD'), confidence (0-1), reasoning, "
        "risk_level ('LOW'|'MEDIUM'|'HIGH'), key_indicators (list).")
    explainable_analysis_prompt: str = (
        "You are an expert cryptocurrency trading analyst. Evaluate {symbol}.\n"
        "Price ${price:.8f}, 24h volume ${volume:.2f}; change 1m "
        "{price_change_1m:.2f}% / 3m {price_change_3m:.2f}% / 5m "
        "{price_change_5m:.2f}% / 15m {price_change_15m:.2f}%.\n"
        "Indicators: RSI {rsi:.2f}, stochastic %K {stoch:.2f}, MACD "
        "{macd:.8f}, Williams %R {williams_r:.2f}, Bollinger position "
        "{bb_position:.4f}.\nTrend: {trend} (strength {trend_strength:.4f}).\n"
        "Combined indicator read: {combined_summary}\n"
        "Social: volume {social_volume}, engagement {social_engagement}, "
        "contributors {social_contributors}, sentiment {social_sentiment}.\n"
        "Recent news: {recent_news}\nMarket context: {market_context}\n"
        "Weigh price momentum, trend, combined signals, social/news impact, "
        "volume, and risk. Reply with ONLY a JSON object with keys: "
        "decision ('BUY'|'SELL'|'HOLD'), confidence (0-1), reasoning, "
        "risk_level ('LOW'|'MEDIUM'|'HIGH'), key_indicators (list), "
        "explanation (object with summary, technical_factors, social_factors,"
        " news_analysis, key_indicators list, risk_assessment), and "
        "factor_weights (object with technical_indicators {{rsi, macd, "
        "bollinger_bands, price_action, other}}, price_action {{momentum, "
        "volatility, volume}}, social_metrics {{sentiment, volume, "
        "engagement}}, news_analysis {{sentiment, relevance, recency}}, "
        "market_context — every weight in 0-1).")
    risk_prompt: str = (
        "Size a {symbol} position. Available capital ${capital:.2f}, "
        "volatility {volatility:.2f}, price ${price:.8f}, trend strength "
        "{trend_strength:.4f}.\nReply with ONLY a JSON object with keys: "
        "position_size (decimal 0-1 of capital), stop_loss_pct, "
        "take_profit_pct, reasoning.")
    market_prompt: str = (
        "Assess overall cryptocurrency market conditions from this data:\n"
        "{market_data}\nReply with ONLY a JSON object with keys: "
        "market_sentiment ('BULLISH'|'BEARISH'|'NEUTRAL'), "
        "top_opportunities (list of symbols), risks (list), reasoning.")
    explainable_market_prompt: str = (
        "Assess overall cryptocurrency market conditions from this data:\n"
        "{market_data}\nReply with ONLY a JSON object with keys: "
        "market_sentiment ('BULLISH'|'BEARISH'|'NEUTRAL'), "
        "top_opportunities (list of symbols), risks (list), reasoning, "
        "explanation (object with summary, market_factors, key_trends list, "
        "risk_factors list, sentiment_indicators list, "
        "recommendation_rationale), and factor_weights (object with "
        "price_action, technical_indicators, volume_analysis, "
        "social_sentiment, market_trends — every weight in 0-1).")


@dataclass(frozen=True)
class BacktestParams:
    """Backtest engine knobs (backtesting/ in the reference)."""

    initial_balance: float = 10_000.0
    warmup: int = 10
    max_positions: int = 5
    annualization: float = 252.0  # strategy_tester.py:430 uses sqrt(252)
    param_grid_size: int = 1024   # default vmap width for sweeps


@dataclass(frozen=True)
class FrameworkConfig:
    """Root of the config tree."""

    trading: TradingParams = field(default_factory=TradingParams)
    risk: RiskParams = field(default_factory=RiskParams)
    monte_carlo: MonteCarloParams = field(default_factory=MonteCarloParams)
    evolution: EvolutionParams = field(default_factory=EvolutionParams)
    rl: RLParams = field(default_factory=RLParams)
    nn: NNParams = field(default_factory=NNParams)
    patterns: PatternParams = field(default_factory=PatternParams)
    regime: RegimeParams = field(default_factory=RegimeParams)
    mesh: MeshParams = field(default_factory=MeshParams)
    backtest: BacktestParams = field(default_factory=BacktestParams)
    llm: LLMParams = field(default_factory=LLMParams)
    seed: int = 0

    def replace(self, **kw) -> "FrameworkConfig":
        return dataclasses.replace(self, **kw)


def _build(cls, data: Mapping[str, Any]):
    """Recursively build a dataclass from a nested mapping, ignoring unknown
    keys (forward compatibility).  Scalar leaves are type-checked against the
    field default so a mis-typed config.json fails at load time, not as a jit
    trace error deep in the compute path."""
    kwargs = {}
    for key, value in data.items():
        if key not in {f.name for f in dataclasses.fields(cls)}:
            continue
        default = getattr(cls(), key)
        if dataclasses.is_dataclass(type(default)) and isinstance(value, Mapping):
            kwargs[key] = _build(type(default), value)
        else:
            kwargs[key] = _check_leaf(cls.__name__, key, default, _freeze(value))
    return cls(**kwargs)


def _check_leaf(owner: str, key: str, default, value):
    if isinstance(default, bool):
        ok = isinstance(value, bool)
    elif isinstance(default, int):
        ok = isinstance(value, int) and not isinstance(value, bool)
    elif isinstance(default, float):
        ok = isinstance(value, (int, float)) and not isinstance(value, bool)
        value = float(value) if ok else value
    elif isinstance(default, str):
        ok = isinstance(value, str)
    else:
        ok = True  # sequences / mappings: structural, checked by consumers
    if not ok:
        raise TypeError(
            f"config {owner}.{key}: expected {type(default).__name__}, "
            f"got {type(value).__name__} ({value!r})"
        )
    return value


def load_config(path: str | None = None, overrides: Mapping[str, Any] | None = None) -> FrameworkConfig:
    """Load config from a JSON file (same shape as this tree) with optional
    dotted-path overrides, e.g. ``{"trading.stop_loss_pct": 1.5}``."""
    cfg_dict: dict = {}
    if path is not None:
        with open(path) as f:
            cfg_dict = json.load(f)
    cfg = _build(FrameworkConfig, cfg_dict)
    if overrides:
        for dotted, value in overrides.items():
            cfg = _override(cfg, dotted.split("."), value)
    return cfg


def _override(node, parts, value):
    if isinstance(node, Mapping):
        if parts[0] not in node:
            raise KeyError(f"unknown config key {parts[0]!r} in mapping override")
        if len(parts) == 1:
            return {**node, parts[0]: value}
        return {**node, parts[0]: _override(node[parts[0]], parts[1:], value)}
    if len(parts) == 1:
        return dataclasses.replace(node, **{parts[0]: value})
    child = getattr(node, parts[0])
    return dataclasses.replace(node, **{parts[0]: _override(child, parts[1:], value)})
