from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv  # noqa: F401
from ai_crypto_trader_tpu.data.ingest import (  # noqa: F401
    OHLCV,
    klines_to_arrays,
    load_csv,
    load_social_csv,
    save_csv,
    save_social_csv,
)
