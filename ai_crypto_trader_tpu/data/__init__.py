from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv  # noqa: F401
from ai_crypto_trader_tpu.data.ingest import (  # noqa: F401
    OHLCV,
    klines_to_arrays,
    load_csv,
    save_csv,
)
