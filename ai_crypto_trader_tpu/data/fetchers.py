"""Network data-source fetchers over an injectable transport.

The fetch/pagination/parse logic of the reference's three network surfaces,
implemented against a transport seam so the logic is fully testable (and
usable) in a zero-egress environment:

  * paginated Binance klines — `backtesting/data_manager.py:47-114`
    (1000/request, cursor = last row's open-time + 1 ms, 0.1 s pacing);
  * LunarCrush daily social timeseries — `backtesting/data_manager.py:116-172`
    (single call, 90-day API cap, bearer auth, timeSeries extraction);
  * news sources — `services/utils/news_analyzer.py:144-370`
    (CryptoPanic JSON, LunarCrush feeds JSON, CoinDesk / CoinTelegraph
    HTML scraping, URL-based dedup).

A transport is any async callable `(url, params, headers) -> Response`.
`UrllibTransport` is the real-network implementation; tests inject
`recorded fixtures` (see tests/test_fetchers.py). Every fetcher is pure
parse/paginate logic — no config reads, no env vars, no wall clock.
"""

from __future__ import annotations

import asyncio
import json
import re
from dataclasses import dataclass, field
from typing import Awaitable, Callable

import numpy as np

from ai_crypto_trader_tpu.data.ingest import OHLCV, klines_to_arrays

BINANCE_API = "https://api.binance.com/api/v3"
LUNARCRUSH_API = "https://lunarcrush.com/api/v4"
CRYPTOPANIC_API = "https://cryptopanic.com/api/v1/posts/"


@dataclass
class Response:
    status: int
    body: str = ""
    _json: object = None

    def json(self):
        if self._json is None:
            self._json = json.loads(self.body)
        return self._json


Transport = Callable[..., Awaitable[Response]]


class UrllibTransport:
    """Real-network transport (stdlib only; the environment this framework
    develops in has no egress, so this is exercised by users, not tests)."""

    def __init__(self, timeout_s: float = 15.0):
        self.timeout_s = timeout_s

    async def __call__(self, url: str, params: dict | None = None,
                       headers: dict | None = None) -> Response:
        import urllib.parse
        import urllib.request

        if params:
            url = f"{url}?{urllib.parse.urlencode(params)}"
        req = urllib.request.Request(url, headers=headers or {})

        def fetch():
            import urllib.error

            try:
                with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                    return Response(r.status, r.read().decode())
            except urllib.error.HTTPError as e:
                # error statuses must surface as Response objects so the
                # fetchers' non-200 degradation branches run (urlopen
                # raises instead of returning on 4xx/5xx)
                return Response(e.code, e.read().decode(errors="replace"))

        return await asyncio.to_thread(fetch)


# --------------------------------------------------------------------------
# Binance klines (paginated)
# --------------------------------------------------------------------------

async def fetch_klines(transport: Transport, symbol: str, interval: str,
                       start_ms: int, end_ms: int, *, limit: int = 1000,
                       pace_s: float = 0.1,
                       sleep=asyncio.sleep) -> list[list]:
    """Paginated klines fetch (`data_manager.py:47-114` semantics): request
    `limit` rows from the cursor, append, advance cursor to last open-time
    + 1 ms, stop on an empty page or when the cursor passes `end_ms`.
    Raises on any non-200 (the reference raises and aborts the fetch)."""
    rows: list[list] = []
    cursor = int(start_ms)
    while cursor < end_ms:
        r = await transport(f"{BINANCE_API}/klines", params={
            "symbol": symbol, "interval": interval, "startTime": cursor,
            "endTime": int(end_ms), "limit": limit})
        if r.status != 200:
            raise RuntimeError(f"klines fetch failed: HTTP {r.status} "
                               f"{r.body[:200]}")
        page = r.json()
        if not page:
            break
        rows.extend(page)
        cursor = int(page[-1][0]) + 1
        await sleep(pace_s)              # reference's inter-page pacing
    return rows


async def fetch_klines_ohlcv(transport: Transport, symbol: str,
                             interval: str, start_ms: int, end_ms: int,
                             **kw) -> OHLCV:
    rows = await fetch_klines(transport, symbol, interval, start_ms, end_ms,
                              **kw)
    return klines_to_arrays(rows, symbol=symbol, interval=interval)


# --------------------------------------------------------------------------
# LunarCrush daily social metrics
# --------------------------------------------------------------------------

@dataclass
class SocialDaily:
    """Daily social metrics columns (epoch-s timestamps), the input to
    social.provider.SocialDataProvider."""

    timestamp: np.ndarray                 # int64 epoch-seconds, ascending
    columns: dict = field(default_factory=dict)   # name -> f32[n]

    def __len__(self):
        return int(self.timestamp.shape[0])


async def fetch_social_daily(transport: Transport, symbol: str,
                             start_s: int, end_s: int, *, api_key: str,
                             max_days: int = 90) -> SocialDaily:
    """Daily social timeseries (`data_manager.py:116-172`): one call, days
    capped at the API's 90, bearer auth, rows filtered to [start, end]."""
    base = _base_ticker(symbol)
    days = min(int((end_s - start_s) // 86_400) + 1, max_days)
    r = await transport(
        f"{LUNARCRUSH_API}/assets",
        params={"symbol": base, "interval": "1d", "days": days},
        headers={"Authorization": f"Bearer {api_key}",
                 "Accept": "application/json"})
    if r.status != 200:
        return SocialDaily(np.zeros(0, np.int64))
    data = r.json().get("data") or []
    series = data[0].get("timeSeries", []) if data else []
    rows = [row for row in series
            if start_s <= int(row.get("time", 0)) <= end_s]
    if not rows:
        return SocialDaily(np.zeros(0, np.int64))
    rows.sort(key=lambda row: int(row["time"]))
    ts = np.asarray([int(row["time"]) for row in rows], np.int64)
    numeric = {k for row in rows for k, v in row.items()
               if k != "time" and isinstance(v, (int, float))}
    cols = {k: np.asarray([float(row.get(k, np.nan)) for row in rows],
                          np.float32) for k in sorted(numeric)}
    return SocialDaily(ts, cols)


# --------------------------------------------------------------------------
# News sources
# --------------------------------------------------------------------------

def _base_ticker(symbol: str) -> str:
    from ai_crypto_trader_tpu.utils.symbols import base_asset

    return base_asset(symbol)


async def fetch_cryptopanic(transport: Transport, symbol: str, *,
                            api_key: str) -> list[dict]:
    """`news_analyzer.py:178-215`: posts API, important-news filter."""
    r = await transport(CRYPTOPANIC_API, params={
        "auth_token": api_key, "currencies": _base_ticker(symbol),
        "kind": "news", "public": "true", "filter": "important"})
    if r.status != 200:
        return []
    return [{"title": it.get("title", ""), "url": it.get("url", ""),
             "source": "CryptoPanic",
             "published_at": it.get("published_at", ""),
             "content": it.get("body", "")}
            for it in r.json().get("results", [])]


async def fetch_lunarcrush_news(transport: Transport, symbol: str, *,
                                api_key: str, limit: int = 10) -> list[dict]:
    """`news_analyzer.py:217-268`: feeds API, news source filter."""
    r = await transport(
        f"{LUNARCRUSH_API}/feeds",
        params={"symbol": _base_ticker(symbol), "limit": limit,
                "sources": "news"},
        headers={"Authorization": f"Bearer {api_key}"})
    if r.status != 200:
        return []
    return [{"title": it.get("title", ""), "url": it.get("url", ""),
             "source": "LunarCrush",
             "published_at": it.get("time", 0),
             "content": it.get("body", ""),
             "sentiment": it.get("sentiment", 0)}
            for it in r.json().get("data", [])]


_HTML_SOURCES = {
    # source -> (url builder, item regex with (?P<title>)/(?P<url>) groups,
    #            date regex, link base). Title and URL are captured by ONE
    #            structural regex so they can never be paired by unrelated
    #            index position (a bare href findall would sweep up every
    #            nav/header anchor on the page).
    "coindesk": (
        lambda t: f"https://www.coindesk.com/search?s={t}",
        r'<h4[^>]*class="[^"]*title[^"]*"[^>]*>(?P<title>[^<]+)</h4>'
        r'\s*<a[^>]*href="(?P<url>[^"]+)"',
        r'<time[^>]*datetime="([^"]+)"[^>]*>',
        "https://www.coindesk.com"),
    "cointelegraph": (
        lambda t: f"https://cointelegraph.com/tags/{t.lower()}",
        r'<a[^>]*class="[^"]*post-card__title-link[^"]*"[^>]*'
        r'href="(?P<url>[^"]+)"[^>]*>(?P<title>[^<]+)</a>',
        r'<time[^>]*datetime="([^"]+)"[^>]*>',
        "https://cointelegraph.com"),
}


async def fetch_html_news(transport: Transport, symbol: str, source: str,
                          *, max_items: int = 5) -> list[dict]:
    """CoinDesk / CoinTelegraph page scraping
    (`news_analyzer.py:270-370`: regex title/url/date extraction, first 5,
    relative links resolved against the site base)."""
    build_url, item_re, date_re, base = _HTML_SOURCES[source]
    r = await transport(build_url(_base_ticker(symbol)))
    if r.status != 200:
        return []
    matches = list(re.finditer(item_re, r.body))[:max_items]
    dates = re.findall(date_re, r.body)
    items = []
    for i, m in enumerate(matches):
        url = m.group("url")
        if not url.startswith("http"):
            url = f"{base}{url}"
        items.append({"title": m.group("title").strip(), "url": url,
                      "source": source.capitalize(),
                      "published_at": dates[i] if i < len(dates) else "",
                      "content": ""})
    return items


async def fetch_news(transport: Transport, symbol: str, *,
                     sources: list[str] | None = None,
                     api_keys: dict | None = None) -> list[dict]:
    """Fan out to all sources, tolerate per-source failures, dedup by URL
    (`news_analyzer.py:144-176`)."""
    sources = sources or ["cryptopanic", "lunarcrush", "coindesk",
                          "cointelegraph"]
    api_keys = api_keys or {}
    out: list[dict] = []
    for source in sources:
        try:
            if source == "cryptopanic":
                items = await fetch_cryptopanic(
                    transport, symbol, api_key=api_keys.get(source, ""))
            elif source == "lunarcrush":
                items = await fetch_lunarcrush_news(
                    transport, symbol, api_key=api_keys.get(source, ""))
            else:
                items = await fetch_html_news(transport, symbol, source)
        except Exception:                              # noqa: BLE001
            continue                       # per-source failures tolerated
        out.extend(items)
    seen: dict[str, dict] = {}
    for item in out:
        if item.get("url") and item["url"] not in seen:
            seen[item["url"]] = item
    return list(seen.values())
