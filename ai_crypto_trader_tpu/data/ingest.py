"""OHLCV ingest: exchange klines / CSV → dense float32 arrays.

Replaces the reference's pandas-everywhere data path
(`backtesting/data_manager.py:47-317`: paginated klines → DataFrame → CSV
cache).  Host-side ingest stays in plain NumPy/CSV; the compute path only
ever sees dense ``f32[T]`` arrays (SURVEY §2.6 "pandas" row).

CSV layout is compatible with the reference's cache
(``backtesting/data/market/<symbol>/<symbol>_<interval>.csv``) so existing
downloaded datasets can be reused directly.
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass
from typing import Mapping, Sequence

import numpy as np

FIELDS = ("open", "high", "low", "close", "volume")


@dataclass
class OHLCV:
    """A column-oriented candle series. ``timestamp`` is epoch-ms int64."""

    timestamp: np.ndarray
    open: np.ndarray
    high: np.ndarray
    low: np.ndarray
    close: np.ndarray
    volume: np.ndarray
    symbol: str = ""
    interval: str = "1m"

    def __len__(self):
        return int(self.close.shape[0])

    def as_dict(self) -> dict:
        return {f: getattr(self, f) for f in FIELDS}

    def slice(self, start: int, stop: int) -> "OHLCV":
        return OHLCV(
            timestamp=self.timestamp[start:stop],
            **{f: getattr(self, f)[start:stop] for f in FIELDS},
            symbol=self.symbol,
            interval=self.interval,
        )


def klines_to_arrays(klines: Sequence[Sequence], symbol: str = "", interval: str = "1m") -> OHLCV:
    """Convert Binance-format klines (12-column rows, reference
    `binance_ml_strategy.py:313-317`) to an OHLCV array bundle."""
    arr = np.asarray([row[:6] for row in klines], dtype=np.float64)
    return OHLCV(
        timestamp=arr[:, 0].astype(np.int64),
        open=arr[:, 1].astype(np.float32),
        high=arr[:, 2].astype(np.float32),
        low=arr[:, 3].astype(np.float32),
        close=arr[:, 4].astype(np.float32),
        volume=arr[:, 5].astype(np.float32),
        symbol=symbol,
        interval=interval,
    )


def from_dict(d: Mapping[str, np.ndarray], symbol: str = "", interval: str = "1m") -> OHLCV:
    n = len(d["close"])
    ts = d.get("timestamp", np.arange(n, dtype=np.int64) * 60_000)
    return OHLCV(timestamp=np.asarray(ts, dtype=np.int64),
                 **{f: np.asarray(d[f], np.float32) for f in FIELDS},
                 symbol=symbol, interval=interval)


def save_csv(data: OHLCV, root: str) -> str:
    path = os.path.join(root, "market", data.symbol or "UNKNOWN")
    os.makedirs(path, exist_ok=True)
    fname = os.path.join(path, f"{data.symbol}_{data.interval}.csv")
    with open(fname, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(("timestamp",) + FIELDS)
        for i in range(len(data)):
            w.writerow([int(data.timestamp[i])] + [float(getattr(data, k)[i]) for k in FIELDS])
    return fname


def load_csv(path: str, symbol: str = "", interval: str = "1m") -> OHLCV:
    rows = []
    with open(path, newline="") as f:
        r = csv.reader(f)
        header = next(r)
        idx = {name: header.index(name) for name in ("timestamp",) + FIELDS}
        for row in r:
            rows.append([row[idx["timestamp"]]] + [row[idx[k]] for k in FIELDS])
    arr = np.asarray(rows, dtype=np.float64)
    return OHLCV(
        timestamp=arr[:, 0].astype(np.int64),
        open=arr[:, 1].astype(np.float32),
        high=arr[:, 2].astype(np.float32),
        low=arr[:, 3].astype(np.float32),
        close=arr[:, 4].astype(np.float32),
        volume=arr[:, 5].astype(np.float32),
        symbol=symbol,
        interval=interval,
    )


def save_social_csv(daily, symbol: str, root: str) -> str:
    """Persist a SocialDaily series cache-compatibly with the reference
    layout (`backtesting/data/social/<symbol>/`, data_manager.py:174-212)."""
    path = os.path.join(root, "social", symbol or "UNKNOWN")
    os.makedirs(path, exist_ok=True)
    fname = os.path.join(path, f"{symbol}_daily.csv")
    names = sorted(daily.columns)
    with open(fname, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["timestamp"] + names)
        for i in range(len(daily)):
            w.writerow([int(daily.timestamp[i])]
                       + [float(daily.columns[k][i]) for k in names])
    return fname


def load_social_csv(path: str):
    """Load a SocialDaily series saved by save_social_csv."""
    from ai_crypto_trader_tpu.data.fetchers import SocialDaily

    with open(path, newline="") as f:
        r = csv.reader(f)
        header = next(r)
        rows = [row for row in r]
    if not rows:
        return SocialDaily(np.zeros(0, np.int64))
    arr = np.asarray(rows, dtype=np.float64)
    cols = {name: arr[:, j + 1].astype(np.float32)
            for j, name in enumerate(header[1:])}
    return SocialDaily(arr[:, 0].astype(np.int64), cols)
