"""Deterministic synthetic OHLCV generation.

The reference has no data fixtures at all — its tests require live Binance
and OpenAI credentials (`tests/run_tests.py:29-37`; SURVEY §4).  This module
is the test substrate the rebuild creates: seeded, regime-switching GBM
candles with intrabar high/low structure, shaped like Binance klines.

The regime chain is fully vectorized (no per-candle Python loop): a regime
at candle i is the choice drawn at the LAST switch candle ≤ i, which is a
running-maximum scan over switch indices — the same cummax trick
`mc/engine.py` uses for drawdowns, shared with the traced generators in
`sim/paths.py` (which import `REGIME_DRIFT_MULT` / `REGIME_VOL_MULT` and
re-express `regime_chain` with `lax.associative_scan`).  `seed` may be a
sequence, in which case one call returns a whole batch of independent
series with a leading [B] axis, each row bit-identical to the scalar call
with that seed.
"""

from __future__ import annotations

import numpy as np

# Per-regime (quiet / trending / volatile) drift & vol multipliers — the
# single source of truth for the regime dynamics, shared with sim/paths.py.
REGIME_DRIFT_MULT = np.array([0.0, 8.0, -3.0])
REGIME_VOL_MULT = np.array([0.6, 1.2, 2.5])


def regime_chain(switches: np.ndarray, choices: np.ndarray) -> np.ndarray:
    """Vectorized 3-regime Markov chain over the trailing axis.

    ``switches`` [..., n] bool marks candles where the state re-draws;
    ``choices`` [..., n] int holds the redrawn state per candle.  The state
    at candle i is ``choices`` at the last switch ≤ i (initial state 0), so
    the whole chain is one running-max over switch indices + one gather —
    identical semantics to the sequential loop it replaces.
    """
    n = switches.shape[-1]
    idx = np.maximum.accumulate(
        np.where(switches, np.arange(n), -1), axis=-1)
    filled = np.take_along_axis(np.asarray(choices), np.maximum(idx, 0),
                                axis=-1)
    return np.where(idx >= 0, filled, 0).astype(np.int64)


def generate_ohlcv(
    n: int = 10_000,
    seed: int | list | tuple | np.ndarray = 0,
    s0: float = 40_000.0,
    base_drift: float = 0.00002,
    base_vol: float = 0.0015,
    regime_switch_p: float = 0.002,
    base_volume: float = 25.0,
):
    """Return a dict of float32 arrays: open/high/low/close/volume, length n.

    A 3-regime (quiet / trending / volatile) Markov chain modulates drift and
    vol so regime-detection components have something real to find.

    ``seed`` may be a sequence of B seeds: the result then carries a leading
    [B] batch axis on every array, row b bit-identical to
    ``generate_ohlcv(n, seed=seed[b], ...)`` — one call, B independent
    series (the shape `sim/` consumes for scenario sweeps).
    """
    batched = np.ndim(seed) > 0
    seeds = [int(s) for s in np.atleast_1d(np.asarray(seed))]

    # Per-seed draws in the scalar call's exact order (bit-compat per row);
    # everything downstream is vectorized over the [B, n] stack.
    draws = []
    for s in seeds:
        rng = np.random.default_rng(s)
        draws.append((rng.random(n) < regime_switch_p,
                      rng.integers(0, 3, size=n),
                      rng.standard_normal(n),
                      np.abs(rng.standard_normal((2, n))),
                      rng.standard_normal(n)))
    switches, choices, z, wick_z, vol_z = (np.stack(a) for a in zip(*draws))

    regimes = regime_chain(switches, choices)
    rets = (base_drift * REGIME_DRIFT_MULT[regimes]
            + base_vol * REGIME_VOL_MULT[regimes] * z)
    close = s0 * np.exp(np.cumsum(rets, axis=-1))
    open_ = np.concatenate(
        [np.full_like(close[..., :1], s0), close[..., :-1]], axis=-1)

    # Intrabar range: wick sizes scale with the bar's regime vol.
    wick = wick_z * base_vol * REGIME_VOL_MULT[regimes][..., None, :] * \
        close[..., None, :]
    high = np.maximum(open_, close) + wick[..., 0, :]
    low = np.minimum(open_, close) - wick[..., 1, :]

    volume = (base_volume * np.exp(0.35 * vol_z)
              * REGIME_VOL_MULT[regimes])

    out = {
        "open": open_.astype(np.float32),
        "high": high.astype(np.float32),
        "low": low.astype(np.float32),
        "close": close.astype(np.float32),
        "volume": volume.astype(np.float32),
        "regime": regimes,
    }
    if not batched:
        out = {k: v[0] for k, v in out.items()}
    return out
