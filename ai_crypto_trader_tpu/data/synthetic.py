"""Deterministic synthetic OHLCV generation.

The reference has no data fixtures at all — its tests require live Binance
and OpenAI credentials (`tests/run_tests.py:29-37`; SURVEY §4).  This module
is the test substrate the rebuild creates: seeded, regime-switching GBM
candles with intrabar high/low structure, shaped like Binance klines.
"""

from __future__ import annotations

import numpy as np


def generate_ohlcv(
    n: int = 10_000,
    seed: int = 0,
    s0: float = 40_000.0,
    base_drift: float = 0.00002,
    base_vol: float = 0.0015,
    regime_switch_p: float = 0.002,
    base_volume: float = 25.0,
):
    """Return a dict of float32 arrays: open/high/low/close/volume, length n.

    A 3-regime (quiet / trending / volatile) Markov chain modulates drift and
    vol so regime-detection components have something real to find.
    """
    rng = np.random.default_rng(seed)
    drift_mult = np.array([0.0, 8.0, -3.0])
    vol_mult = np.array([0.6, 1.2, 2.5])

    regimes = np.empty(n, dtype=np.int64)
    state = 0
    switches = rng.random(n) < regime_switch_p
    choices = rng.integers(0, 3, size=n)
    for i in range(n):
        if switches[i]:
            state = choices[i]
        regimes[i] = state

    z = rng.standard_normal(n)
    rets = base_drift * drift_mult[regimes] + base_vol * vol_mult[regimes] * z
    close = s0 * np.exp(np.cumsum(rets))
    open_ = np.concatenate([[s0], close[:-1]])

    # Intrabar range: wick sizes scale with the bar's regime vol.
    wick = np.abs(rng.standard_normal((2, n))) * base_vol * vol_mult[regimes] * close
    high = np.maximum(open_, close) + wick[0]
    low = np.minimum(open_, close) - wick[1]

    volume = base_volume * np.exp(0.35 * rng.standard_normal(n)) * vol_mult[regimes]

    out = {
        "open": open_.astype(np.float32),
        "high": high.astype(np.float32),
        "low": low.astype(np.float32),
        "close": close.astype(np.float32),
        "volume": volume.astype(np.float32),
        "regime": regimes,
    }
    return out
