from ai_crypto_trader_tpu.evolve.ga import (  # noqa: F401
    GAState,
    backtest_fitness,
    evolve_step,
    population_diversity,
    run_ga,
)
from ai_crypto_trader_tpu.evolve.selection import (  # noqa: F401
    quantile_split,
    tournament,
)
