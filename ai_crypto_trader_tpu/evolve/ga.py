"""Genetic strategy evolution with *real* backtest fitness, compiled
end-to-end: the whole G-generation GA is ONE jitted `lax.scan`.

Capability parity with `services/genetic_algorithm.py` (seeded init :83-117,
elitism + tournament-3 selection :135-161, uniform crossover :163-189,
int/float mutation :191-223, per-generation history + diversity :293-348) —
but the structural flaws of the reference are fixed by design:

  * its fitness evaluation is a **sequential Python loop** over individuals
    (`genetic_algorithm.py:119-133`) — here the whole population evaluates
    as one vmapped program, optionally sharded over the mesh data axis via
    the `Partitioner` seam (parallel/partitioner.py) with fitness values
    all-gathered over ICI (replacing "publish fitness to Redis",
    SURVEY §2.7);
  * its production fitness is a **heuristic score**, not a backtest
    (`strategy_evolution_service.py:542-641`) — here fitness is the Sharpe
    (blended with drawdown/win-rate exactly where the reference's
    _needs_improvement thresholds look, strategy_evolution_service.py:
    1571-1582) of a full dynamic-period backtest (backtest/evolvable.py);
  * its generation loop is host-driven — and so was ours until ISSUE 11:
    the old `run_ga` dispatched the evaluator once per generation and
    synced THREE scalars back per generation for the history record
    (3G+1 host round-trips).  `run_ga` now lowers eval → evolve →
    best-tracking into one `lax.scan` over generations with the
    (genomes, key) carry DONATED, history accumulated as device arrays,
    and exactly ONE `host_read` at the end — one dispatch, one sync, for
    any G.  The retired Python-loop driver survives as `run_ga_legacy`,
    the bit-exactness oracle the tests pin the scan against.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ai_crypto_trader_tpu.backtest.evolvable import evolvable_backtest
from ai_crypto_trader_tpu.backtest.metrics import compute_metrics
from ai_crypto_trader_tpu.backtest.strategy import (
    _HIGHS,
    _IS_INT,
    _LOWS,
    StrategyParams,
    stack_params,
    unstack_params,
)
from ai_crypto_trader_tpu.config import GAParams
from ai_crypto_trader_tpu.parallel.partitioner import (
    Partitioner,
    SingleDevicePartitioner,
)
from ai_crypto_trader_tpu.evolve.selection import tournament
from ai_crypto_trader_tpu.obs import tickpath
from ai_crypto_trader_tpu.utils import devprof, meshprof

# Shared by every run_ga call that doesn't name a partitioner, so the
# compiled-program cache below keys all of them onto one entry.
_SINGLE = SingleDevicePartitioner()


class GAState(NamedTuple):
    genomes: jnp.ndarray      # [pop, n_params]
    fitness: jnp.ndarray      # [pop]
    best_genome: jnp.ndarray  # [n_params]
    best_fitness: jnp.ndarray


def host_read(tree):
    """THE per-run device→host sync: GA outputs → numpy.

    Module-level seam (the ops/tick_engine.host_read pattern) so tests can
    wrap it with a counting double and assert ONE sync per run_ga.  Timed
    into the ``host_read`` SLO window when the observatory is on."""
    t0 = time.perf_counter()
    with meshprof.allow_transfers():   # THE sanctioned device→host sync
        out = jax.device_get(tree)
    devprof.observe_latency("host_read", time.perf_counter() - t0)
    return out


def population_diversity(genomes: jnp.ndarray) -> jnp.ndarray:
    """Mean normalized variance across parameter dims
    (`genetic_algorithm.py:293-348`)."""
    span = _HIGHS - _LOWS
    norm = (genomes - _LOWS) / span
    return jnp.mean(jnp.var(norm, axis=0))


def backtest_fitness(ohlcv: dict, *, min_sharpe_weight: float = 1.0,
                     drawdown_limit: float = 15.0,
                     win_rate_target: float = 52.0,
                     tables: bool = True) -> Callable:
    """Fitness = backtest Sharpe, penalized by the monitoring thresholds the
    reference's _needs_improvement checks (strategy_evolution_service.py:
    1571-1582): excess drawdown and win-rate shortfall subtract.

    ``tables=True`` (default) precomputes the integer-period indicator
    tables for this window ONCE (backtest/evolvable.py) so every genome's
    eval gathers its indicator rows instead of recomputing ~12 length-T
    kernels, and runs the signal rule fused into the replay scan
    (`evolvable_fused_backtest`) — the same values bit-for-bit, at a
    fraction of the per-generation wall time."""
    from ai_crypto_trader_tpu.backtest.evolvable import (
        build_indicator_tables,
        evolvable_fused_backtest,
    )

    arrays = {k: jnp.asarray(v) for k, v in ohlcv.items() if k != "regime"}
    tbl = build_indicator_tables(arrays) if tables else None

    def fitness(p: StrategyParams) -> jnp.ndarray:
        stats = (evolvable_fused_backtest(arrays, p, tbl) if tbl is not None
                 else evolvable_backtest(arrays, p))
        m = compute_metrics(stats)
        dd_pen = jnp.maximum(m["max_drawdown_pct"] - drawdown_limit, 0.0) * 0.05
        wr_pen = jnp.maximum(win_rate_target - m["win_rate"], 0.0) * 0.01
        no_trades = (stats.total_trades == 0).astype(jnp.float32)
        return (min_sharpe_weight * m["sharpe_ratio"] - dd_pen - wr_pen
                - no_trades * 5.0)

    return fitness


# Selection primitive shared with rl/population.py — moved to
# evolve/selection.py; the alias keeps the GA's internal name stable.
_tournament = tournament


def _evolve_core(key, state: GAState, cfg: GAParams) -> GAState:
    """One generation of selection → crossover → mutation → clamp (pure;
    traced both by the standalone `evolve_step` jit and INSIDE the scanned
    GA program).  Fitness of the new genomes is filled in by the
    evaluation pass — see run_ga."""
    genomes, fitness = state.genomes, state.fitness
    pop, n_params = genomes.shape
    k_sel, k_cross, k_mut, k_scale = jax.random.split(key, 4)

    # Elitism (genetic_algorithm.py:139-146)
    elite_idx = jnp.argsort(-fitness)[: cfg.elite_size]
    elites = genomes[elite_idx]

    n_children = pop - cfg.elite_size
    parents_a = genomes[_tournament(k_sel, fitness, cfg.tournament_size, n_children)]
    parents_b = genomes[
        _tournament(jax.random.fold_in(k_sel, 1), fitness, cfg.tournament_size, n_children)
    ]

    # Uniform crossover (genetic_algorithm.py:163-189)
    do_cross = jax.random.uniform(k_cross, (n_children, 1)) < cfg.crossover_rate
    mask = jax.random.bernoulli(jax.random.fold_in(k_cross, 1), 0.5,
                                (n_children, n_params))
    children = jnp.where(do_cross & mask, parents_b, parents_a)

    # Gaussian mutation scaled to range; ints re-rounded (:191-223)
    span = _HIGHS - _LOWS
    noise = jax.random.normal(k_scale, (n_children, n_params)) * span * cfg.mutation_scale
    do_mut = jax.random.bernoulli(k_mut, cfg.mutation_rate, (n_children, n_params))
    children = children + jnp.where(do_mut, noise, 0.0)
    children = jnp.clip(children, _LOWS, _HIGHS)
    children = jnp.where(_IS_INT, jnp.round(children), children)

    new_genomes = jnp.concatenate([elites, children], axis=0)
    return state._replace(genomes=new_genomes)


evolve_step = jax.jit(_evolve_core, static_argnames=("cfg",))


def _update_best(state: GAState) -> GAState:
    i = jnp.argmax(state.fitness)
    better = state.fitness[i] > state.best_fitness
    return state._replace(
        best_genome=jnp.where(better, state.genomes[i], state.best_genome),
        best_fitness=jnp.where(better, state.fitness[i], state.best_fitness),
    )


def _eval_impl(fitness_fn: Callable, partitioner: Partitioner):
    """Population fitness as one (optionally sharded) program: vmap the
    scalar fitness over genome rows, population axis split over the mesh
    data axis by the partitioner, fitness all-gathered."""
    return partitioner.population_eval(
        lambda g: jax.vmap(lambda row: fitness_fn(unstack_params(row)))(g),
        name="ga_scan")


@functools.lru_cache(maxsize=2)
def _ga_program(fitness_fn: Callable, cfg: GAParams,
                partitioner: Partitioner):
    """Build (and cache) THE compiled GA: initial eval + G scanned
    generations, genome buffer donated, history stacked on device.

    Cache key is (fitness closure, cfg, partitioner) identity — repeated
    runs with ONE fitness closure (the bench's median-of-3, a caller
    holding its backtest_fitness) reuse one program with zero re-trace,
    which the contract test pins.  A caller that rebuilds the fitness per
    run (the evolver cadence evolves a FRESH market window each time)
    re-traces by construction — that is the price of new data, and
    maxsize=2 keeps dead closures from pinning more than ~two windows'
    ohlcv + indicator tables on device."""
    eval_impl = _eval_impl(fitness_fn, partitioner)

    # Donate the genome buffer: the final population rides back out with
    # the same [pop, n_params] shape, so XLA aliases the input buffer onto
    # it (a donation with no shape-matched output would silently degrade
    # to a copy — exactly what the devprof verifier exists to catch).
    @functools.partial(jax.jit, donate_argnums=(0,))
    def program(genomes, key):
        fitness = eval_impl(genomes)
        state = GAState(genomes, fitness, genomes[jnp.argmax(fitness)],
                        jnp.max(fitness))
        state = _update_best(state)

        def gen_step(carry, _):
            state, key = carry
            key, k_gen = jax.random.split(key)
            state = _evolve_core(k_gen, state, cfg)
            state = state._replace(fitness=eval_impl(state.genomes))
            state = _update_best(state)
            record = (state.best_fitness,
                      jnp.mean(state.fitness),
                      population_diversity(state.genomes))
            return (state, key), record

        (state, _), history = lax.scan(gen_step, (state, key), None,
                                       length=cfg.generations)
        return state, history

    return program


def _init_genomes(key, cfg: GAParams,
                  seed_params: StrategyParams | None):
    """Shared by the scanned and legacy drivers so both consume the key
    stream identically (the bit-exactness contract)."""
    from ai_crypto_trader_tpu.backtest.strategy import sample_params

    k_init, key = jax.random.split(key)
    genomes = stack_params(sample_params(k_init, cfg.population_size))
    if seed_params is not None:
        # Seeded init: individual 0 is the incumbent strategy
        # (genetic_algorithm.py:92-99).
        genomes = genomes.at[0].set(stack_params(seed_params))
    return genomes, key


def run_ga(key, fitness_fn: Callable, cfg: GAParams,
           seed_params: StrategyParams | None = None,
           partitioner: Partitioner | None = None):
    """GA driver (`genetic_algorithm.py:254-291`): returns (best
    StrategyParams, history list of per-generation records).

    The whole run is ONE compiled program (see `_ga_program`) and ONE
    `host_read`; ``partitioner`` shards the population eval over a device
    mesh (default: single-device — pass
    ``parallel.get_partitioner()`` to use every visible chip).  Matches
    `run_ga_legacy` bit-for-bit on the same key."""
    partitioner = partitioner if partitioner is not None else _SINGLE
    genomes, key = _init_genomes(key, cfg, seed_params)
    genomes = partitioner.shard_population(genomes) \
        if cfg.population_size % partitioner.device_count == 0 else genomes

    # cold-run detection for the recompile sentinel: a program-cache MISS
    # means this (fitness, cfg, partitioner) triple compiles by design
    # (the evolver evolves a fresh market window each cadence) — an
    # expected re-trace must not count as a steady-state recompile
    misses_before = _ga_program.cache_info().misses
    program = _ga_program(fitness_fn, cfg, partitioner)
    cold = _ga_program.cache_info().misses > misses_before
    prof = devprof.active()
    if prof is not None and not devprof.has_card("ga_scan"):
        # FLOPs/bytes only: the scanned GA is among the largest programs
        # in the repo — skip the AOT re-compile memory_analysis costs
        # (the backtest_sweep precedent, utils/devprof.py).
        devprof.cost_card("ga_scan", program, genomes, key,
                          _memory_analysis=False)
    donated = genomes
    # meshprof watch (utils/meshprof.py): compile attribution + transfer
    # guard from dispatch through the one sanctioned host_read — the
    # zero-recompile/one-sync contract as a live production invariant
    with tickpath.coldstart("ga_scan", cold=cold), \
            meshprof.watch("ga_scan", cold=cold):
        out = program(genomes, key)
        if prof is not None:
            devprof.verify_donation("ga_scan", donated)

        state, (h_best, h_mean, h_div) = host_read(out)
    best_genome = state.best_genome
    history = [{
        "generation": gen,
        "best_fitness": float(h_best[gen]),
        "mean_fitness": float(h_mean[gen]),
        "diversity": float(h_div[gen]),
    } for gen in range(cfg.generations)]
    return unstack_params(best_genome), history


def run_ga_legacy(key, fitness_fn: Callable, cfg: GAParams,
                  seed_params: StrategyParams | None = None,
                  eval_fn: Callable | None = None):
    """The retired host-driven generation loop: one evaluator dispatch per
    generation plus three scalar syncs for the history record (3G+1 host
    round-trips).  Kept ONLY as the parity oracle `run_ga`'s scan is
    pinned against (tests/test_partitioner.py, tests/test_evolve.py) and
    as the bench's legacy-driver comparison — product code calls
    `run_ga`."""
    if eval_fn is None:
        eval_fn = jax.jit(
            lambda g: jax.vmap(lambda row: fitness_fn(unstack_params(row)))(g)
        )

    genomes, key = _init_genomes(key, cfg, seed_params)

    fitness = eval_fn(genomes)
    state = GAState(genomes, fitness, genomes[jnp.argmax(fitness)], jnp.max(fitness))
    state = _update_best(state)

    history = []
    for gen in range(cfg.generations):
        key, k_gen = jax.random.split(key)
        state = evolve_step(k_gen, state, cfg)
        state = state._replace(fitness=eval_fn(state.genomes))
        state = _update_best(state)
        history.append({
            "generation": gen,
            "best_fitness": float(state.best_fitness),
            "mean_fitness": float(jnp.mean(state.fitness)),
            "diversity": float(population_diversity(state.genomes)),
        })
    return unstack_params(state.best_genome), history
