"""Genetic strategy evolution with *real* backtest fitness, mesh-sharded.

Capability parity with `services/genetic_algorithm.py` (seeded init :83-117,
elitism + tournament-3 selection :135-161, uniform crossover :163-189,
int/float mutation :191-223, per-generation history + diversity :293-348) —
but the two structural flaws of the reference are fixed by design:

  * its fitness evaluation is a **sequential Python loop** over individuals
    (`genetic_algorithm.py:119-133`) — here the whole population evaluates
    as one vmapped program, sharded over the mesh data axis with fitness
    values all-gathered over ICI (replacing "publish fitness to Redis",
    SURVEY §2.7);
  * its production fitness is a **heuristic score**, not a backtest
    (`strategy_evolution_service.py:542-641`) — here fitness is the Sharpe
    (blended with drawdown/win-rate exactly where the reference's
    _needs_improvement thresholds look, strategy_evolution_service.py:
    1571-1582) of a full dynamic-period backtest (backtest/evolvable.py).

Every genetic operator is a pure jitted function of (key, genomes, fitness);
a generation is one device program.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ai_crypto_trader_tpu.backtest.evolvable import evolvable_backtest
from ai_crypto_trader_tpu.backtest.metrics import compute_metrics
from ai_crypto_trader_tpu.backtest.strategy import (
    _HIGHS,
    _IS_INT,
    _LOWS,
    StrategyParams,
    stack_params,
    unstack_params,
)
from ai_crypto_trader_tpu.config import GAParams


class GAState(NamedTuple):
    genomes: jnp.ndarray      # [pop, n_params]
    fitness: jnp.ndarray      # [pop]
    best_genome: jnp.ndarray  # [n_params]
    best_fitness: jnp.ndarray


def population_diversity(genomes: jnp.ndarray) -> jnp.ndarray:
    """Mean normalized variance across parameter dims
    (`genetic_algorithm.py:293-348`)."""
    span = _HIGHS - _LOWS
    norm = (genomes - _LOWS) / span
    return jnp.mean(jnp.var(norm, axis=0))


def backtest_fitness(ohlcv: dict, *, min_sharpe_weight: float = 1.0,
                     drawdown_limit: float = 15.0,
                     win_rate_target: float = 52.0) -> Callable:
    """Fitness = backtest Sharpe, penalized by the monitoring thresholds the
    reference's _needs_improvement checks (strategy_evolution_service.py:
    1571-1582): excess drawdown and win-rate shortfall subtract."""

    def fitness(p: StrategyParams) -> jnp.ndarray:
        stats = evolvable_backtest(ohlcv, p)
        m = compute_metrics(stats)
        dd_pen = jnp.maximum(m["max_drawdown_pct"] - drawdown_limit, 0.0) * 0.05
        wr_pen = jnp.maximum(win_rate_target - m["win_rate"], 0.0) * 0.01
        no_trades = (stats.total_trades == 0).astype(jnp.float32)
        return (min_sharpe_weight * m["sharpe_ratio"] - dd_pen - wr_pen
                - no_trades * 5.0)

    return fitness


def _tournament(key, fitness, k: int, n_picks: int):
    """[n_picks] winner indices of size-k tournaments
    (`genetic_algorithm.py:152-161`)."""
    pop = fitness.shape[0]
    cand = jax.random.randint(key, (n_picks, k), 0, pop)
    cand_fit = fitness[cand]
    return cand[jnp.arange(n_picks), jnp.argmax(cand_fit, axis=1)]


@functools.partial(jax.jit, static_argnames=("cfg",))
def evolve_step(key, state: GAState, cfg: GAParams) -> GAState:
    """One generation of selection → crossover → mutation → clamp.
    Fitness of the new genomes is filled in by the (separately jitted /
    sharded) evaluation pass — see run_ga."""
    genomes, fitness = state.genomes, state.fitness
    pop, n_params = genomes.shape
    k_sel, k_cross, k_mut, k_scale = jax.random.split(key, 4)

    # Elitism (genetic_algorithm.py:139-146)
    elite_idx = jnp.argsort(-fitness)[: cfg.elite_size]
    elites = genomes[elite_idx]

    n_children = pop - cfg.elite_size
    parents_a = genomes[_tournament(k_sel, fitness, cfg.tournament_size, n_children)]
    parents_b = genomes[
        _tournament(jax.random.fold_in(k_sel, 1), fitness, cfg.tournament_size, n_children)
    ]

    # Uniform crossover (genetic_algorithm.py:163-189)
    do_cross = jax.random.uniform(k_cross, (n_children, 1)) < cfg.crossover_rate
    mask = jax.random.bernoulli(jax.random.fold_in(k_cross, 1), 0.5,
                                (n_children, n_params))
    children = jnp.where(do_cross & mask, parents_b, parents_a)

    # Gaussian mutation scaled to range; ints re-rounded (:191-223)
    span = _HIGHS - _LOWS
    noise = jax.random.normal(k_scale, (n_children, n_params)) * span * cfg.mutation_scale
    do_mut = jax.random.bernoulli(k_mut, cfg.mutation_rate, (n_children, n_params))
    children = children + jnp.where(do_mut, noise, 0.0)
    children = jnp.clip(children, _LOWS, _HIGHS)
    children = jnp.where(_IS_INT, jnp.round(children), children)

    new_genomes = jnp.concatenate([elites, children], axis=0)
    return state._replace(genomes=new_genomes)


def _update_best(state: GAState) -> GAState:
    i = jnp.argmax(state.fitness)
    better = state.fitness[i] > state.best_fitness
    return state._replace(
        best_genome=jnp.where(better, state.genomes[i], state.best_genome),
        best_fitness=jnp.where(better, state.fitness[i], state.best_fitness),
    )


def run_ga(key, fitness_fn: Callable, cfg: GAParams,
           seed_params: StrategyParams | None = None,
           eval_fn: Callable | None = None):
    """GA driver (`genetic_algorithm.py:254-291`): returns (best
    StrategyParams, history list of per-generation records).

    `eval_fn(genomes) -> fitness` defaults to a vmap of fitness_fn; pass the
    sharded evaluator from run_ga_sharded for pod execution."""
    from ai_crypto_trader_tpu.backtest.strategy import sample_params

    if eval_fn is None:
        eval_fn = jax.jit(
            lambda g: jax.vmap(lambda row: fitness_fn(unstack_params(row)))(g)
        )

    k_init, key = jax.random.split(key)
    genomes = stack_params(sample_params(k_init, cfg.population_size))
    if seed_params is not None:
        # Seeded init: individual 0 is the incumbent strategy
        # (genetic_algorithm.py:92-99).
        genomes = genomes.at[0].set(stack_params(seed_params))

    fitness = eval_fn(genomes)
    state = GAState(genomes, fitness, genomes[jnp.argmax(fitness)], jnp.max(fitness))
    state = _update_best(state)

    history = []
    for gen in range(cfg.generations):
        key, k_gen = jax.random.split(key)
        state = evolve_step(k_gen, state, cfg)
        state = state._replace(fitness=eval_fn(state.genomes))
        state = _update_best(state)
        history.append({
            "generation": gen,
            "best_fitness": float(state.best_fitness),
            "mean_fitness": float(jnp.mean(state.fitness)),
            "diversity": float(population_diversity(state.genomes)),
        })
    return unstack_params(state.best_genome), history


def run_ga_sharded(key, mesh, ohlcv: dict, cfg: GAParams,
                   seed_params: StrategyParams | None = None,
                   fitness_fn: Callable | None = None):
    """GA with population evaluation sharded over the mesh data axis.

    Each device backtests its population shard; fitness is all-gathered over
    ICI by the out_spec (the collective that replaces the reference's
    sequential evaluate→publish loop). Population size must divide the data
    axis; GAParams.population_size is padded up if needed."""
    fitness_fn = fitness_fn or backtest_fitness(ohlcv)
    data_axis = mesh.axis_names[0]
    n_dev = mesh.shape[data_axis]
    pop = ((cfg.population_size + n_dev - 1) // n_dev) * n_dev
    if pop != cfg.population_size:
        import dataclasses
        cfg = dataclasses.replace(cfg, population_size=pop)

    def local_eval(g_shard):
        return jax.vmap(lambda row: fitness_fn(unstack_params(row)))(g_shard)

    sharded = jax.jit(jax.shard_map(
        local_eval, mesh=mesh,
        in_specs=(P(data_axis, None),), out_specs=P(data_axis),
        check_vma=False,
    ))

    def eval_fn(genomes):
        genomes = jax.device_put(genomes, NamedSharding(mesh, P(data_axis, None)))
        return sharded(genomes)

    return run_ga(key, fitness_fn, cfg, seed_params, eval_fn=eval_fn)
