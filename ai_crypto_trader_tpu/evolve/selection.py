"""Shared selection primitives for the evolutionary workloads.

The GA (`evolve/ga.py`) and the PBT population trainer
(`rl/population.py`) both rank a population by fitness and pick who
breeds / who copies whom.  The primitives live here so the two
workloads share one implementation — pure, shape-static, and traceable
inside either compiled program.

Everything operates on a [P] fitness vector and returns index arrays;
no genome/params gathering happens here (callers `tree_map` the gather
so the same code serves flat genome matrices and full DQN state trees).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tournament(key, fitness, k: int, n_picks: int):
    """[n_picks] winner indices of size-``k`` uniform tournaments
    (`genetic_algorithm.py:152-161`).  Moved verbatim from evolve/ga.py —
    the GA's key-stream consumption (ONE `randint` draw of shape
    [n_picks, k]) is part of its bit-exactness contract, so this must
    stay a single draw."""
    pop = fitness.shape[0]
    cand = jax.random.randint(key, (n_picks, k), 0, pop)
    cand_fit = fitness[cand]
    return cand[jnp.arange(n_picks), jnp.argmax(cand_fit, axis=1)]


def quantile_split(fitness, frac: float):
    """PBT exploit bracket: indices of the bottom-``frac`` and
    top-``frac`` quantiles by fitness (Fast PBT, arXiv 2206.08888 —
    truncation selection).

    ``n = floor(P * frac)`` is a Python int (``frac`` is static), so the
    returned index arrays are shape-static under jit: at P=1 (or any
    population too small for the bracket) ``n == 0`` and both brackets
    are empty — the exploit step becomes a structural no-op, which is
    exactly what the P=1 bit-parity oracle pins.

    Returns ``(bottom, top, n)`` — ``bottom[i]`` is the i-th worst
    member, ``top[i]`` the i-th best (both ascending in rank distance
    from the extreme)."""
    pop = fitness.shape[0]
    n = int(pop * frac)
    order = jnp.argsort(fitness)       # ascending: worst first
    bottom = order[:n]
    top = order[pop - n:][::-1]        # best first
    return bottom, top, n
