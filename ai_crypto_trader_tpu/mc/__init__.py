from ai_crypto_trader_tpu.mc.engine import (  # noqa: F401
    estimate_mu_sigma,
    path_statistics,
    portfolio_stats,
    run_simulation,
    simulate_bootstrap,
    simulate_gbm,
    simulate_portfolio_correlated,
)
