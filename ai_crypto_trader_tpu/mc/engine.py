"""Monte-Carlo risk engine — GBM & bootstrap path simulation on TPU.

Replaces the compute core of the reference MonteCarloService
(`services/monte_carlo_service.py:197-394`): its Python for-loop over
timesteps (GBM step at lines 269-273) and its per-simulation bootstrap loop
(275-298) become closed-form cumulative-sum programs — a GBM path is just
`exp(cumsum(log-increments))`, so the whole [paths × days] tensor is one
fused kernel with no sequential dependency at all.  10k × 30 paths is
microseconds; the same code scales to millions of paths sharded over the
mesh data axis.

Statistics (`:314-336`) — percentiles, VaR/CVaR on percent changes,
probability of profit, per-path max drawdown via running maximum — are all
computed on-device; drawdown's running max uses an associative cummax scan
(the reference uses `np.maximum.accumulate` per path in a Python loop).

Scenario handling mirrors config.json:97-103: drift/vol multipliers for
base / bull / bear / volatile / crab.

Portfolio aggregation ships both flavors:
  * `portfolio_stats` — the reference's correlation-ignoring weighted sums
    (`_calculate_portfolio_stats:577-659`), for parity;
  * `simulate_portfolio_correlated` — joint GBM with a Cholesky factor of
    the asset return covariance, which the reference explicitly lacks
    ("Simplified approach - ignores correlations").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

import numpy as _np

# NumPy, not jnp: a module-level device constant would initialize the JAX
# backend (and claim the TPU) at import time.
PERCENTILES = _np.asarray([1.0, 5.0, 10.0, 25.0, 50.0, 75.0, 90.0, 95.0, 99.0])
PERIODS_PER_YEAR = 252.0


def estimate_mu_sigma(returns: jnp.ndarray, periods_per_year: float = PERIODS_PER_YEAR):
    """Annualized drift / vol from a per-period return series
    (`monte_carlo_service.py:236-247`; pandas .std() is ddof=1)."""
    n = returns.shape[-1]
    mu = jnp.mean(returns, axis=-1) * periods_per_year
    sd = jnp.std(returns, axis=-1, ddof=1) if n > 1 else jnp.zeros_like(mu)
    return mu, sd * jnp.sqrt(periods_per_year)


@functools.partial(jax.jit, static_argnames=("days", "num_sims"))
def simulate_gbm(key, initial_price, mu, sigma, days: int, num_sims: int,
                 dt: float = 1.0 / PERIODS_PER_YEAR,
                 shock_shift=None, shock_vol=None):
    """GBM paths, shape [num_sims, days]; paths[:, 0] == initial_price.

    Same recursion as the reference timestep loop
    (`monte_carlo_service.py:266-273`) solved in closed form:
    S_t = S_0 · exp(Σ ((μ-σ²/2)dt + σ√dt·Z)).

    ``shock_shift`` / ``shock_vol`` ([num_sims, days-1], from
    `sim/scenarios.mc_schedule`) are the stress-mode channels: an additive
    log-return injection and a per-step vol multiplier.  None (the
    default) traces to exactly the unstressed program.
    """
    z = jax.random.normal(key, (num_sims, days - 1))
    if shock_vol is not None:
        z = z * shock_vol
    inc = (mu - 0.5 * sigma**2) * dt + sigma * jnp.sqrt(dt) * z
    if shock_shift is not None:
        inc = inc + shock_shift
    log_path = jnp.concatenate(
        [jnp.zeros((num_sims, 1)), jnp.cumsum(inc, axis=-1)], axis=-1
    )
    return initial_price * jnp.exp(log_path)


@functools.partial(jax.jit, static_argnames=("days", "num_sims", "log_returns"))
def simulate_bootstrap(key, initial_price, returns, days: int, num_sims: int,
                       log_returns: bool = True,
                       shock_shift=None, shock_vol=None):
    """Historical bootstrap: resample past returns with replacement
    (`monte_carlo_service.py:275-298`) — the per-simulation Python loop
    becomes one gather + cumsum.  Stress channels as in `simulate_gbm`."""
    idx = jax.random.randint(key, (num_sims, days - 1), 0, returns.shape[-1])
    sampled = returns[idx]
    if log_returns:
        log_inc = sampled
    else:
        log_inc = jnp.log1p(sampled)
    if shock_vol is not None:
        log_inc = log_inc * shock_vol
    if shock_shift is not None:
        log_inc = log_inc + shock_shift
    log_path = jnp.concatenate(
        [jnp.zeros((num_sims, 1)), jnp.cumsum(log_inc, axis=-1)], axis=-1
    )
    return initial_price * jnp.exp(log_path)


@jax.jit
def path_statistics(paths, initial_price, confidence: float = 0.95):
    """Reference result statistics (`monte_carlo_service.py:302-336`),
    vectorized: VaR/CVaR are on percent changes; |·| applied host-side as
    the reference does when reporting."""
    final = paths[:, -1]
    pct = (final / initial_price - 1.0) * 100.0

    pctl_prices = jnp.percentile(final, PERCENTILES)
    var_pctl = 100.0 * (1.0 - confidence)
    var = jnp.percentile(pct, var_pctl)
    tail = pct <= var
    cvar = jnp.sum(jnp.where(tail, pct, 0.0)) / jnp.maximum(jnp.sum(tail), 1)
    prob_profit = jnp.mean((final > initial_price).astype(jnp.float32))

    running_max = lax.associative_scan(jnp.maximum, paths, axis=-1)
    drawdown = (running_max - paths) / running_max
    max_dd = jnp.max(drawdown, axis=-1)

    return {
        "final_prices": final,
        "pct_changes": pct,
        "percentile_prices": pctl_prices,
        "expected_price": jnp.mean(final),
        "expected_pct_change": jnp.mean(pct),
        "var": var,
        "cvar": cvar,
        "prob_profit": prob_profit,
        "prob_loss": 1.0 - prob_profit,
        "max_drawdown_mean": jnp.mean(max_dd),
        "max_drawdown_median": jnp.median(max_dd),
        "max_drawdown_max": jnp.max(max_dd),
    }


def run_simulation(key, initial_price, returns, *, days: int = 30,
                   num_sims: int = 1_000, scenario: str = "base",
                   scenarios: dict | None = None, method: str = "gbm",
                   confidence: float = 0.95, stress: str | None = None,
                   stress_seed: int = 0) -> dict:
    """Full single-asset simulation: estimate params → apply scenario
    multipliers → simulate → statistics.  One fused device program.

    `scenarios` maps name → (drift_factor, volatility_factor); defaults to
    the reference's five (config.json:97-103 via config.MonteCarloParams).

    `stress` routes the paths through a `sim/scenarios.py` shock schedule
    (a preset name like "flash_crash" / "black_swan", or a ScenarioSpec):
    every simulated path gets its own randomized crash/vol-shock overlay
    on top of the estimated dynamics — tail risk from markets that never
    happened, surfaced as stress-VaR/CVaR via `risk/var.stress_var_cvar`.
    ``stress=None`` (default) runs the exact unstressed program.
    """
    from ai_crypto_trader_tpu.config import MonteCarloParams

    scenarios = scenarios or dict(MonteCarloParams().scenarios)
    drift_f, vol_f = scenarios[scenario]
    mu, sigma = estimate_mu_sigma(jnp.asarray(returns))
    mu, sigma = mu * drift_f, sigma * vol_f
    shift = vol_mult = None
    if stress is not None:
        from ai_crypto_trader_tpu.sim.scenarios import mc_schedule

        shift_np, vol_np = mc_schedule(stress, num_sims, days - 1,
                                       seed=stress_seed)
        shift, vol_mult = jnp.asarray(shift_np), jnp.asarray(vol_np)
    if method == "gbm":
        paths = simulate_gbm(key, initial_price, mu, sigma, days, num_sims,
                             shock_shift=shift, shock_vol=vol_mult)
    elif method in ("bootstrap", "historical"):
        paths = simulate_bootstrap(key, initial_price, jnp.asarray(returns),
                                   days, num_sims,
                                   shock_shift=shift, shock_vol=vol_mult)
    else:
        raise ValueError(f"unknown simulation method {method!r}")
    stats = path_statistics(paths, initial_price, confidence)
    stats.update({"mu": mu, "sigma": sigma, "scenario": scenario,
                  "drift_factor": drift_f, "volatility_factor": vol_f,
                  "stress": (stress if isinstance(stress, (str, type(None)))
                             else getattr(stress, "name", str(stress))),
                  "paths": paths})
    return stats


@jax.jit
def portfolio_stats(weights, expected_returns, vars_, cvars):
    """Reference portfolio aggregation — correlation-ignoring weighted sums
    (`monte_carlo_service.py:577-659`). All inputs [n_assets] decimals."""
    return {
        "expected_return": jnp.sum(weights * expected_returns),
        "var": jnp.sum(weights * vars_),
        "cvar": jnp.sum(weights * cvars),
    }


@functools.partial(jax.jit, static_argnames=("days", "num_sims"))
def simulate_portfolio_correlated(key, initial_prices, mus, cov, weights,
                                  days: int, num_sims: int,
                                  dt: float = 1.0 / PERIODS_PER_YEAR):
    """Correlation-aware joint GBM the reference lacks: draw correlated
    shocks via the Cholesky factor of the annualized return covariance and
    simulate all assets jointly; portfolio value per path = Σ wᵢ·Sᵢ/Sᵢ₀.

    Returns portfolio relative-value paths [num_sims, days]."""
    n_assets = initial_prices.shape[0]
    chol = jnp.linalg.cholesky(cov + 1e-12 * jnp.eye(n_assets))
    z = jax.random.normal(key, (num_sims, days - 1, n_assets))
    shocks = jnp.einsum("sdk,ak->sda", z, chol) * jnp.sqrt(dt)
    sig2 = jnp.diagonal(cov)
    inc = (mus - 0.5 * sig2) * dt + shocks
    log_paths = jnp.concatenate(
        [jnp.zeros((num_sims, 1, n_assets)), jnp.cumsum(inc, axis=1)], axis=1
    )
    rel = jnp.exp(log_paths)                      # S_t / S_0 per asset
    return jnp.einsum("sda,a->sd", rel, weights)  # portfolio relative value
