from ai_crypto_trader_tpu.models.zoo import (  # noqa: F401
    MODEL_REGISTRY,
    build_model,
)
from ai_crypto_trader_tpu.models.train import (  # noqa: F401
    Scaler,
    TrainResult,
    fit_scaler,
    make_windows,
    predict_prices,
    train_model,
)
from ai_crypto_trader_tpu.models.hpo import optimize_hyperparameters  # noqa: F401
from ai_crypto_trader_tpu.models.train_loop import (  # noqa: F401
    EpochTrainer,
    snapshot_params,
)
from ai_crypto_trader_tpu.models.long_context import (  # noqa: F401
    LongContextTransformer,
    long_context_loss,
)
from ai_crypto_trader_tpu.models.importance import feature_importance  # noqa: F401
