"""Fused LSTM layer: hoisted input GEMM + custom-VJP time scan.

The zoo's recurrent encoders originally ran `flax.linen.RNN` over
`OptimizedLSTMCell`.  Profiling the train step on CPU (the backend the
reference deploys on) showed the cost is NOT the matmuls — a
[32,64]@[64,256] recurrent GEMM takes ~11 µs — but the per-timestep op
soup around them: the cell's split/sigmoid/tanh gate block costs ~3× the
GEMM, and XLA's autodiff of the scan roughly doubles it again.  This
module restructures the layer the way cuDNN/oneDNN fused RNN kernels do:

  * the input projection for ALL timesteps is one big [T·B, F] @ [F, 4H]
    GEMM hoisted out of the scan (`wx`), so the scan body is a single
    recurrent GEMM plus one fused gate block;
  * all four gates go through ONE `tanh` over the contiguous [B, 4, H]
    gate tensor — sigmoid is evaluated through the exact identity
    σ(x) = ½·tanh(x/2) + ½, so the math (and the trained function) is
    identical to the textbook cell, while XLA emits one transcendental
    loop instead of four;
  * the backward pass is a hand-written `jax.custom_vjp`: gate
    derivatives that don't depend on the sequential chain are hoisted
    into big [T, ...] fusions, the reverse scan body is one GEMM plus a
    flat concatenate, and the weight gradients are TWO batched
    [H, T·B] @ [T·B, 4H] GEMMs instead of per-step accumulation.

Gate order is (i, f, g, o) and initializers match `flax.linen.LSTMCell`
(lecun-normal input kernel, orthogonal recurrent kernel, zero bias), so
training behavior is drop-in comparable; `tests/test_train_loop.py`
asserts forward AND gradient parity against the reference split/sigmoid
cell.  Everything here is time-major ([T, B, ...]) — callers transpose
once at the encoder boundary instead of per layer.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# Pre-tanh scale per gate block (i, f, g, o): sigmoid gates read
# tanh(x/2), the candidate gate reads tanh(x).
_GATE_SCALE = np.asarray([0.5, 0.5, 1.0, 0.5], np.float32)


def _fwd(zx, wh):
    """zx [T, B, 4H] (input projections + bias), wh [H, 4H] → hs [T, B, H].

    Residuals keep the post-tanh gate activations `a_s` (flattened to
    [T, B, 4H]) and the cell-state series — everything the backward pass
    needs that it cannot cheaply recompute in a bulk fusion."""
    Tt, Bb, H4 = zx.shape
    Hh = H4 // 4
    scale = jnp.asarray(_GATE_SCALE, zx.dtype)[None, :, None]

    def step(carry, z):
        c, h = carry
        g = (z + h @ wh).reshape(Bb, 4, Hh) * scale
        a = jnp.tanh(g)
        c2 = (0.5 * a[:, 1] + 0.5) * c + (0.5 * a[:, 0] + 0.5) * a[:, 2]
        tc = jnp.tanh(c2)
        h2 = (0.5 * a[:, 3] + 0.5) * tc
        return (c2, h2), (a.reshape(Bb, H4), c2, h2)

    init = (jnp.zeros((Bb, Hh), zx.dtype), jnp.zeros((Bb, Hh), zx.dtype))
    _, (a_s, c_s, hs) = jax.lax.scan(step, init, zx)
    return hs, (a_s, c_s, hs, wh)


@jax.custom_vjp
def lstm_scan(zx, wh):
    """Run the recurrent part of an LSTM layer over pre-projected inputs."""
    return _fwd(zx, wh)[0]


def _fwd_vjp(zx, wh):
    return _fwd(zx, wh)


def _bwd_vjp(res, dhs):
    a_s, c_s, hs, wh = res
    Tt, Bb, H4 = a_s.shape
    Hh = H4 // 4
    whT = wh.T
    # Bulk cofactors, one big fusion each (no per-step transcendentals:
    # tanh' and sigmoid' come from the stored activations).  The gate
    # gradient factors collapse into ONE [T, B, 4H] tensor:
    #   dg = concat(dc·g, dc·c_prev, dc·i, dh·tanh c) · (1-a²)·scale²
    #      = concat(dc, dc, dc, dh) · MQ
    # so the reverse-scan body is an add, two muls, one concat and the
    # recurrent GEMM — everything else is precomputed in bulk.
    i_s = 0.5 * a_s[..., :Hh] + 0.5
    f_s = 0.5 * a_s[..., Hh:2 * Hh] + 0.5
    gg_s = a_s[..., 2 * Hh:3 * Hh]
    tc_s = jnp.tanh(c_s)
    k1 = (0.5 * a_s[..., 3 * Hh:] + 0.5) * (1.0 - tc_s * tc_s)
    c_prev = jnp.concatenate(
        [jnp.zeros((1, Bb, Hh), c_s.dtype), c_s[:-1]], axis=0)
    # (1 - a²) · scale²: one factor of `scale` is tanh's argument scaling,
    # the other is dσ = ½·dtanh.
    mq = jnp.concatenate([gg_s, c_prev, i_s, tc_s], axis=-1) \
        * (1.0 - a_s * a_s) \
        * jnp.asarray(_GATE_SCALE * _GATE_SCALE, a_s.dtype).repeat(Hh)[None, None, :]

    def step(carry, inp):
        dc, dh_carry = carry
        mq_t, k1_t, f_t, dh_in = inp
        dh = dh_in + dh_carry
        dc = dc + dh * k1_t
        dg = jnp.concatenate([dc, dc, dc, dh], axis=-1) * mq_t
        return (dc * f_t, dg @ whT), dg

    init = (jnp.zeros((Bb, Hh), dhs.dtype), jnp.zeros((Bb, Hh), dhs.dtype))
    _, dgs = jax.lax.scan(step, init, (mq, k1, f_s, dhs), reverse=True)
    h_prev = jnp.concatenate(
        [jnp.zeros((1, Bb, Hh), hs.dtype), hs[:-1]], axis=0)
    # Weight gradient as ONE batched GEMM over all timesteps (the classic
    # cuDNN trick) — XLA's scan autodiff would emit 60 accumulating GEMMs.
    dwh = h_prev.reshape(-1, Hh).T @ dgs.reshape(-1, H4)
    return dgs, dwh


lstm_scan.defvjp(_fwd_vjp, _bwd_vjp)


class FusedLSTM(nn.Module):
    """One LSTM layer over a TIME-MAJOR sequence: [T, B, F] → [T, B, H].

    Parameters: `wx` (Dense, input projection for all four gates) and
    `wh` (recurrent kernel, orthogonal init — the flax cell default)."""

    units: int

    @nn.compact
    def __call__(self, x):
        T, B, F = x.shape
        # One [T·B, F] GEMM — a 3-d Dense would lower to a batched dot.
        zx = nn.Dense(4 * self.units, name="wx")(
            x.reshape(T * B, F)).reshape(T, B, 4 * self.units)
        wh = self.param("wh", nn.initializers.orthogonal(),
                        (self.units, 4 * self.units))
        return lstm_scan(zx, wh)
