"""Hyperparameter optimization: TPE sampling + successive halving.

Replaces the reference's Optuna Bayesian HPO
(`optimize_hyperparameters`, `services/neural_network_service.py:588-767`:
20 TPE trials over model_type/units/dropout/lr/batch) with a
dependency-free implementation of the same ideas:

  * sampler — Tree-structured Parzen Estimator (Optuna's default): after a
    random warm-up, split observed trials into the best γ fraction ("good")
    vs the rest, model each as a Parzen density (Gaussian KDE over the
    continuous dims, smoothed counts over the categorical dims), and pick
    the candidate maximizing the good/bad likelihood ratio l(x)/g(x)
    (Bergstra et al. 2011 — the algorithm, not the library);
  * scheduler — successive halving (ASHA-style): every trial gets a small
    epoch budget, the best fraction graduate to the full budget.

``sampler="random"`` recovers plain random search + halving.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np

from ai_crypto_trader_tpu.models.train import train_model
from ai_crypto_trader_tpu.utils import meshprof

SEARCH_SPACE = {
    # neural_network_service.py:604-640 (Optuna suggest_* calls)
    "model_type": ("lstm", "gru", "cnn_lstm", "attention", "transformer"),
    "units": (32, 64, 128),
    "dropout": (0.1, 0.5),
    "learning_rate": (1e-4, 1e-2),
    "batch_size": (16, 32, 64),
}


def _sample_trial(rng: np.random.Generator) -> dict:
    lo, hi = SEARCH_SPACE["dropout"]
    llo, lhi = np.log(SEARCH_SPACE["learning_rate"][0]), np.log(SEARCH_SPACE["learning_rate"][1])
    return {
        "model_type": rng.choice(SEARCH_SPACE["model_type"]),
        "units": int(rng.choice(SEARCH_SPACE["units"])),
        "dropout": float(rng.uniform(lo, hi)),
        "learning_rate": float(np.exp(rng.uniform(llo, lhi))),
        "batch_size": int(rng.choice(SEARCH_SPACE["batch_size"])),
    }


# --- TPE (Parzen-estimator) sampler ----------------------------------------

_CATEGORICAL = ("model_type", "units", "batch_size")
_CONTINUOUS = ("dropout", "learning_rate")       # learning_rate in log space


def _cont_value(trial: dict, dim: str) -> float:
    v = trial[dim]
    return float(np.log(v)) if dim == "learning_rate" else float(v)


def _parzen_logpdf(x: float, obs: np.ndarray, lo: float, hi: float) -> float:
    """Log density of a Gaussian Parzen mixture over the observations, with
    a uniform prior component (keeps unexplored regions reachable)."""
    span = hi - lo
    bw = max(float(np.std(obs)) if len(obs) > 1 else span, span * 0.1)
    comp = -0.5 * ((x - obs) / bw) ** 2 - np.log(bw * np.sqrt(2 * np.pi))
    comp = np.concatenate([comp, [-np.log(span)]])   # uniform prior member
    m = comp.max()
    return float(m + np.log(np.exp(comp - m).sum()) - np.log(len(comp)))


def _cat_logpmf(v, obs: list, choices: tuple) -> float:
    counts = {c: 1.0 for c in choices}               # add-one smoothing
    for o in obs:
        counts[o] += 1.0
    total = sum(counts.values())
    return float(np.log(counts[v] / total))


def suggest_tpe(history: list, rng: np.random.Generator, *,
                gamma: float = 0.25, n_candidates: int = 24) -> dict:
    """Propose the next trial by the TPE criterion.

    ``history``: [{"trial": dict, "val_loss": float}, …] from completed
    trials. Splits it into the best ceil(γ·n) ("good") and the rest
    ("bad"), draws candidates from the good distribution (perturbed good
    points / their categorical frequencies), and returns the candidate
    maximizing Σ_dims [log l(x) − log g(x)].

    An empty history has no good/bad split — fall back to a prior sample
    (optimize_hyperparameters never hits this via n_startup ≥ 1, but the
    public function must not assume its caller)."""
    if not history:
        return _sample_trial(rng)
    ranked = sorted(history, key=lambda r: r["val_loss"])
    n_good = max(int(np.ceil(len(ranked) * gamma)), 1)
    good = [r["trial"] for r in ranked[:n_good]]
    bad = [r["trial"] for r in ranked[n_good:]] or good

    bounds = {
        "dropout": SEARCH_SPACE["dropout"],
        "learning_rate": tuple(np.log(SEARCH_SPACE["learning_rate"])),
    }
    good_obs = {d: np.asarray([_cont_value(t, d) for t in good])
                for d in _CONTINUOUS}
    bad_obs = {d: np.asarray([_cont_value(t, d) for t in bad])
               for d in _CONTINUOUS}

    best_c, best_score = None, -np.inf
    for _ in range(n_candidates):
        cand = _sample_trial(rng)
        # bias candidate generation toward the good set: with p=0.75 draw
        # each dim from a good-point neighborhood instead of the prior
        for d in _CONTINUOUS:
            if rng.random() < 0.75:
                lo, hi = bounds[d]
                span = hi - lo
                bw = max(float(np.std(good_obs[d])) if len(good_obs[d]) > 1
                         else span * 0.25, span * 0.1)
                x = float(np.clip(rng.normal(rng.choice(good_obs[d]), bw),
                                  lo, hi))
                cand[d] = float(np.exp(x)) if d == "learning_rate" else x
        for d in _CATEGORICAL:
            if rng.random() < 0.75:
                cand[d] = good[int(rng.integers(len(good)))][d]

        score = 0.0
        for d in _CONTINUOUS:
            x = _cont_value(cand, d)
            lo, hi = bounds[d]
            score += (_parzen_logpdf(x, good_obs[d], lo, hi)
                      - _parzen_logpdf(x, bad_obs[d], lo, hi))
        for d in _CATEGORICAL:
            score += (_cat_logpmf(cand[d], [t[d] for t in good],
                                  SEARCH_SPACE[d])
                      - _cat_logpmf(cand[d], [t[d] for t in bad],
                                    SEARCH_SPACE[d]))
        if score > best_score:
            best_c, best_score = cand, score
    return best_c


def optimize_hyperparameters(
    key,
    features: np.ndarray,
    *,
    n_trials: int = 20,
    rung_epochs: Sequence[int] = (5, 20),
    survivor_fraction: float = 0.3,
    seq_len: int = 60,
    seed: int = 0,
    sampler: str = "tpe",
    n_startup: int = 5,
    target_col: int = 0,
    precision: str | None = None,
    partitioner=None,
) -> dict:
    """Returns {"best_params": ..., "best_val_loss": ..., "trials": [...]}.

    ``sampler="tpe"`` (default, the reference's Optuna behavior): the first
    ``n_startup`` rung-0 trials are random, the rest are proposed by the
    Parzen-estimator ratio over results so far. ``"random"`` disables the
    surrogate.

    Every trial runs through train_model's compiled-epoch path — one
    donated `lax.scan` program per epoch instead of re-entering the Python
    batch loop per trial — and ``precision`` ("f32"/"bf16") is forwarded
    to both rungs.

    ``partitioner`` (parallel/partitioner.py) farms trials over the mesh
    devices round-robin: trials can't fuse into one SPMD program (each
    architecture/width compiles to a different shape), but JAX dispatch is
    async, so pinning consecutive trials' programs to different devices
    via ``jax.default_device`` overlaps their device time — the host
    issues trial i+1's epochs while device i is still crunching trial i.
    None / single-device runs every trial on the default device."""
    rng = np.random.default_rng(seed)
    results = []
    devices = list(partitioner.trial_devices()) if partitioner is not None \
        else []

    def run_trial(i: int, t: dict, trial_key, epochs: int, patience: int):
        def go():
            return train_model(
                trial_key, features, t["model_type"], seq_len=seq_len,
                units=t["units"], dropout=t["dropout"],
                learning_rate=t["learning_rate"], batch_size=t["batch_size"],
                epochs=epochs, early_stopping_patience=patience,
                target_col=target_col, precision=precision)
        if devices:
            dev = devices[i % len(devices)]
            # per-device trial accounting (utils/meshprof.py): the
            # round-robin farm's assignment skew becomes a counted
            # mesh_trial_assignments_total{device=} series
            meshprof.record_trial(dev)
            with jax.default_device(dev):
                return go()
        return go()

    # Rung 0: short budget for everyone; TPE proposes from accumulated
    # rung-0 results once the warm-up is done.
    for i in range(n_trials):
        if sampler == "tpe" and i >= n_startup:
            t = suggest_tpe(results, rng)
        else:
            t = _sample_trial(rng)
        r = run_trial(i, t, jax.random.fold_in(key, i), rung_epochs[0],
                      patience=rung_epochs[0])
        results.append({"trial": t, "val_loss": r.best_val_loss, "rung": 0})

    # Survivors graduate to the full budget; the winner is chosen among
    # full-budget runs only (losses across unequal budgets and fresh inits
    # are not comparable).
    order = np.argsort([r["val_loss"] for r in results])
    n_sur = max(int(np.ceil(n_trials * survivor_fraction)), 1)
    finalists = []
    for rank, i in enumerate(order[:n_sur]):
        t = results[i]["trial"]
        r = run_trial(rank, t, jax.random.fold_in(key, 10_000 + rank),
                      rung_epochs[-1], patience=10)
        rec = {"trial": t, "val_loss": r.best_val_loss, "rung": 1}
        results[i] = rec
        finalists.append(rec)

    best = min(finalists, key=lambda r: r["val_loss"])
    return {"best_params": best["trial"], "best_val_loss": best["val_loss"],
            "trials": results}
