"""Hyperparameter optimization: random search + successive halving.

Replaces the reference's Optuna Bayesian HPO
(`optimize_hyperparameters`, `services/neural_network_service.py:588-767`:
20 trials over model_type/units/dropout/lr/batch) with a dependency-free
random-search + successive-halving (ASHA-style) scheme: all trials start
with a small epoch budget, the best fraction graduate to the full budget.
Same search space, same number of full-budget equivalents.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np

from ai_crypto_trader_tpu.models.train import train_model

SEARCH_SPACE = {
    # neural_network_service.py:604-640 (Optuna suggest_* calls)
    "model_type": ("lstm", "gru", "cnn_lstm", "attention", "transformer"),
    "units": (32, 64, 128),
    "dropout": (0.1, 0.5),
    "learning_rate": (1e-4, 1e-2),
    "batch_size": (16, 32, 64),
}


def _sample_trial(rng: np.random.Generator) -> dict:
    lo, hi = SEARCH_SPACE["dropout"]
    llo, lhi = np.log(SEARCH_SPACE["learning_rate"][0]), np.log(SEARCH_SPACE["learning_rate"][1])
    return {
        "model_type": rng.choice(SEARCH_SPACE["model_type"]),
        "units": int(rng.choice(SEARCH_SPACE["units"])),
        "dropout": float(rng.uniform(lo, hi)),
        "learning_rate": float(np.exp(rng.uniform(llo, lhi))),
        "batch_size": int(rng.choice(SEARCH_SPACE["batch_size"])),
    }


def optimize_hyperparameters(
    key,
    features: np.ndarray,
    *,
    n_trials: int = 20,
    rung_epochs: Sequence[int] = (5, 20),
    survivor_fraction: float = 0.3,
    seq_len: int = 60,
    seed: int = 0,
) -> dict:
    """Returns {"best_params": ..., "best_val_loss": ..., "trials": [...]}."""
    rng = np.random.default_rng(seed)
    trials = [_sample_trial(rng) for _ in range(n_trials)]
    results = []

    # Rung 0: short budget for everyone.
    for i, t in enumerate(trials):
        r = train_model(jax.random.fold_in(key, i), features, t["model_type"],
                        seq_len=seq_len, units=t["units"], dropout=t["dropout"],
                        learning_rate=t["learning_rate"], batch_size=t["batch_size"],
                        epochs=rung_epochs[0], early_stopping_patience=rung_epochs[0])
        results.append({"trial": t, "val_loss": r.best_val_loss, "rung": 0})

    # Survivors graduate to the full budget; the winner is chosen among
    # full-budget runs only (losses across unequal budgets and fresh inits
    # are not comparable).
    order = np.argsort([r["val_loss"] for r in results])
    n_sur = max(int(np.ceil(n_trials * survivor_fraction)), 1)
    finalists = []
    for rank, i in enumerate(order[:n_sur]):
        t = results[i]["trial"]
        r = train_model(jax.random.fold_in(key, 10_000 + rank), features,
                        t["model_type"], seq_len=seq_len, units=t["units"],
                        dropout=t["dropout"], learning_rate=t["learning_rate"],
                        batch_size=t["batch_size"], epochs=rung_epochs[-1])
        rec = {"trial": t, "val_loss": r.best_val_loss, "rung": 1}
        results[i] = rec
        finalists.append(rec)

    best = min(finalists, key=lambda r: r["val_loss"])
    return {"best_params": best["trial"], "best_val_loss": best["val_loss"],
            "trials": results}
