"""Feature importance for the NN zoo — gradient-based SHAP equivalent.

The reference attributes NN predictions with SHAP DeepExplainer
(`services/neural_network_service.py:957-1003`).  DeepExplainer's additive
attribution for smooth models is well-approximated by integrated gradients
(path integral from a baseline), which is exact on-device math — no
third-party dependency, fully jitted, and it vmaps over samples.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ai_crypto_trader_tpu.models.zoo import build_model


def feature_importance(params, model_type: str, X: jnp.ndarray,
                       baseline: jnp.ndarray | None = None,
                       steps: int = 32,
                       feature_names=None, model_kwargs: dict | None = None) -> dict:
    """Integrated gradients w.r.t. inputs, aggregated per feature.

    X: [N, T, F] windows.  Returns per-feature mean |attribution| normalized
    to sum 1 (the shape the reference publishes to Redis)."""
    model = build_model(model_type, **(model_kwargs or {}))
    if baseline is None:
        baseline = jnp.mean(X, axis=0, keepdims=True)

    def scalar_out(x):
        return jnp.sum(model.apply(params, x, False)["mean"])

    grad_fn = jax.grad(scalar_out)

    @jax.jit
    def ig(x):
        alphas = jnp.linspace(0.0, 1.0, steps)

        def one_alpha(a):
            return grad_fn(baseline + a * (x - baseline))

        grads = jax.vmap(one_alpha)(alphas)          # [steps, N, T, F]
        return (x - baseline) * jnp.mean(grads, axis=0)

    attr = ig(X)                                     # [N, T, F]
    per_feature = jnp.mean(jnp.abs(attr), axis=(0, 1))
    total = jnp.sum(per_feature)
    weights = np.asarray(per_feature / jnp.where(total == 0, 1.0, total))
    names = feature_names or [f"f{i}" for i in range(weights.shape[0])]
    order = np.argsort(-weights)
    return {
        "importances": {names[i]: float(weights[i]) for i in order},
        "ranked": [names[i] for i in order],
    }
