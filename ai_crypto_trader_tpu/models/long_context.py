"""Long-context transformer: a price model over the FULL candle history.

The reference's transformer sees exactly 60 candles
(`services/neural_network_service.py:247-306`, config sequence_length: 60);
anything older is invisible to it.  This model removes the window: it runs
causal self-attention over an arbitrarily long candle sequence, and when
given a mesh it shards the sequence axis across devices and computes the
attention as ring attention (parallel/ring_attention.py) — K/V blocks
rotating over ICI, activations never gathered.  Parameters (the Dense
projections) are tiny and stay replicated; memory per device is O(T/n).

Design notes (TPU-first, not a port):
  * input is one [T, F] series (seq-to-seq), not a [B, 60, F] window batch —
    the point of long context is that the batch axis IS the time axis;
  * every position emits a next-step return prediction, so one forward pass
    scores the whole history (the windowed zoo models need T passes);
  * `mesh=None` degenerates to the same math on one device (the parity
    tests hold the two paths equal).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ai_crypto_trader_tpu.models.zoo import sinusoidal_positions
from ai_crypto_trader_tpu.parallel.ring_attention import (
    reference_attention,
    ring_self_attention,
)


class RingSelfAttention(nn.Module):
    """Causal MHA whose score computation is ring-sharded when a mesh is
    supplied.  QKV/out projections are plain replicated Dense layers."""

    d_model: int
    num_heads: int
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x):                       # x: [T, d_model]
        T, _ = x.shape
        Dh = self.d_model // self.num_heads
        qkv = nn.Dense(3 * self.d_model, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (T, self.num_heads, Dh)
        q, k, v = (a.reshape(shape) for a in (q, k, v))
        if self.mesh is None:
            o = reference_attention(q, k, v, causal=True)
        else:
            o = ring_self_attention(q, k, v, self.mesh, causal=True)
        return nn.Dense(self.d_model, name="out")(o.reshape(T, self.d_model))


class LongContextBlock(nn.Module):
    d_model: int
    num_heads: int
    ff_dim: int
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x):
        a = RingSelfAttention(self.d_model, self.num_heads, self.mesh)(x)
        x = nn.LayerNorm()(x + a)
        f = nn.Dense(self.ff_dim)(x)
        f = nn.gelu(f)
        f = nn.Dense(self.d_model)(f)
        return nn.LayerNorm()(x + f)


class LongContextTransformer(nn.Module):
    """Causal seq-to-seq forecaster: [T, F] features → [T, 1] next-step
    return prediction at every position."""

    d_model: int = 64
    num_heads: int = 4
    num_blocks: int = 2
    ff_dim: int = 128
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x, train: bool = False):   # noqa: ARG002 (zoo API)
        T, _ = x.shape
        h = nn.Dense(self.d_model)(x)
        h = h + sinusoidal_positions(T, self.d_model)
        for _ in range(self.num_blocks):
            h = LongContextBlock(self.d_model, self.num_heads,
                                 self.ff_dim, self.mesh)(h)
        return {"mean": nn.Dense(1)(nn.gelu(nn.Dense(self.d_model // 2)(h)))}


def long_context_loss(model, params, x, y):
    """Per-position MSE against next-step targets ``y: [T, 1]``; positions
    with NaN targets (warmup / final step) are masked out.

    When the model is mesh-sharded, params are replicated onto the mesh
    first: the ring path commits activations to every mesh device, and
    eager-mode autodiff refuses to add cotangents whose placements differ
    (mesh vs single-device params).  Replicating here keeps `jax.grad`
    usable both eagerly and under jit."""
    mesh = getattr(model, "mesh", None)
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        params = jax.tree.map(lambda a: jax.device_put(a, rep), params)
    pred = model.apply(params, x)["mean"]
    ok = ~jnp.isnan(y)
    err = jnp.where(ok, pred - jnp.nan_to_num(y), 0.0)
    return (err ** 2).sum() / jnp.maximum(ok.sum(), 1)
