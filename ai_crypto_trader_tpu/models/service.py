"""NN prediction-service cadence — the loop that drives the model zoo.

Re-expression of `services/neural_network_service.py:1314-1480`
(`prediction_loop`): per (symbol × interval),

  * re-predict only when the stored prediction is older than HALF the
    interval (staleness gate, :1366-1387),
  * periodic retrain every ``retrain_interval_s`` (24 h default —
    ``model_checkpoint_interval``, :1406-1443),
  * on-request hyperparameter optimization via the bus key
    ``nn_optimization_request`` (:1327-1349), recording
    ``nn_last_optimization_{symbol}_{interval}``,
  * regime-tagged model snapshots when a market regime is known
    (:1445-1474), through the framework's single checkpoint story
    (utils/checkpoint.py) instead of scattered .h5 copies.

All wall-clock reads go through ``now_fn`` so tests drive the cadence with
a virtual clock (the reference's ``datetime.now()`` sprinkling is what made
its loop untestable — SURVEY §7.4).  Training is a compiled JAX program on
the device; this service is pure host-side orchestration.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Sequence

import jax
import numpy as np

from ai_crypto_trader_tpu.models.train import (
    TrainResult,
    predict_prices,
    predict_prices_batched,
    train_model,
)
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.utils import tracing
from ai_crypto_trader_tpu.utils.checkpoint import save_checkpoint

INTERVAL_SECONDS = {
    "1m": 60, "3m": 180, "5m": 300, "15m": 900, "30m": 1800,
    "1h": 3600, "2h": 7200, "4h": 14400, "12h": 43200, "1d": 86400,
    "3d": 259200, "1w": 604800,
}


def _features_from_klines(klines: list) -> np.ndarray | None:
    """Bus kline rows → [T, 5] OHLCV feature matrix (close is column 3,
    the prediction target column used throughout models/train.py)."""
    if not klines:
        return None
    arr = np.asarray([row[1:6] for row in klines], np.float32)
    return arr if arr.shape[0] > 0 else None


@dataclass
class PredictionService:
    """Launcher-attachable service; ``run_once`` advances the cadence."""

    bus: EventBus
    symbols: list[str]
    intervals: tuple = ("1m", "5m")
    now_fn: any = None
    model_type: str = "lstm"
    seq_len: int = 60
    epochs: int = 20
    units: int = 32
    retrain_interval_s: float = 86_400.0     # model_checkpoint_interval
    hpo_trials: int = 4
    precision: str | None = None             # f32 (default) | bf16 matmuls
    checkpoint_dir: str | None = None
    key: any = None
    name: str = "nn"
    # Live quality gate (obs/scorecard.py): when attached, an HPO winner
    # of a DIFFERENT architecture must not have a known-worse live score
    # than the incumbent it would replace — the registry/hot-swap quality
    # gate.  `registry` (strategy/registry.py ModelRegistry) versions each
    # HPO winner; blocked candidates are registered as "shadow".
    scorecard: any = None
    registry: any = None

    # When True, the synchronous JAX work (training / HPO / inference) runs
    # in a worker thread via asyncio.to_thread so a 24 h-retrain tick cannot
    # stall the trading event loop; bus reads/writes stay on the loop either
    # way. Default False keeps tests single-threaded and deterministic.
    offload: bool = False

    # All due (symbol × interval) pairs sharing a model architecture predict
    # as ONE stacked vmapped program (train.predict_prices_batched) instead
    # of a Python loop of per-pair dispatches — the serving-side twin of the
    # monitor's fused tick engine. False restores per-pair dispatches.
    batched_predict: bool = True

    models: dict = field(default_factory=dict)       # (sym, iv) -> TrainResult
    train_count: int = 0
    predict_count: int = 0
    _last_training: dict = field(default_factory=dict)   # (sym, iv) -> time

    def __post_init__(self):
        if self.now_fn is None:
            import time

            self.now_fn = time.time
        if self.key is None:
            self.key = jax.random.PRNGKey(0)

    # -- data ----------------------------------------------------------------
    def _features(self, symbol: str, interval: str) -> np.ndarray | None:
        feats = _features_from_klines(
            self.bus.get(f"historical_data_{symbol}_{interval}") or [])
        if feats is None or feats.shape[0] < self.seq_len + 8:
            return None
        return feats

    # -- tracing -------------------------------------------------------------
    def _traced_jax(self, name: str, attrs: dict, fn):
        """Span + compile-vs-execute breakdown around one JAX dispatch
        (tracing.traced_dispatch); a plain ``fn()`` when tracing is off."""
        return tracing.traced_dispatch(name, fn, service=self.name,
                                       attrs_fn=lambda: attrs)

    # -- training ------------------------------------------------------------
    def _train_one(self, symbol: str, interval: str) -> TrainResult | None:
        feats = self._features(symbol, interval)
        if feats is None:
            return None
        self.key, k = jax.random.split(self.key)
        result = self._traced_jax(
            "model.train",
            {"symbol": symbol, "interval": interval,
             "model_type": self.model_type},
            lambda: train_model(k, feats, self.model_type,
                                seq_len=self.seq_len, epochs=self.epochs,
                                units=self.units, target_col=3,
                                precision=self.precision))
        self.models[(symbol, interval)] = result
        self.train_count += 1
        self._snapshot(symbol, interval, result)
        return result

    def _snapshot(self, symbol: str, interval: str, result: TrainResult):
        """Regime-tagged checkpoint (`neural_network_service.py:1445-1474`):
        one atomic pytree per (model, interval, regime)."""
        if self.checkpoint_dir is None:
            return
        regime = (self.bus.get("market_regime") or {}).get("regime")
        tag = f"_{regime}" if regime else ""
        path = os.path.join(
            self.checkpoint_dir,
            f"nn_{self.model_type}_{symbol}_{interval}{tag}.ckpt")
        save_checkpoint(path, result.params, metadata={
            "symbol": symbol, "interval": interval,
            "model_type": self.model_type, "regime": regime or "unknown",
            "best_val_loss": float(result.best_val_loss),
            "trained_at": self.now_fn()})

    # -- cadence ---------------------------------------------------------------
    def _needs_prediction(self, symbol: str, interval: str, now: float) -> bool:
        prev = self.bus.get(f"nn_prediction_{symbol}_{interval}")
        if not prev:
            return True
        half = INTERVAL_SECONDS.get(interval, 3600) / 2.0
        return (now - prev.get("reference_time", -1e18)) >= half

    def _run_hpo(self, symbol: str, interval: str, feats, now: float):
        """HPO + scorecard-gated adoption of the winner; returns the
        optimization record (including the adoption verdict)."""
        from ai_crypto_trader_tpu.models.hpo import optimize_hyperparameters

        self.key, k = jax.random.split(self.key)
        # candidates must be RANKED on the same target the final model
        # trains on (close, col 3) — ranking on open while deploying close
        # selects hyperparameters for a different objective
        hpo = self._traced_jax(
            "model.hpo", {"symbol": symbol, "interval": interval,
                          "n_trials": self.hpo_trials},
            lambda: optimize_hyperparameters(
                k, feats, n_trials=self.hpo_trials,
                rung_epochs=(2, max(2, self.epochs // 2)),
                seq_len=self.seq_len, target_col=3,
                precision=self.precision))
        best = hpo["best_params"]
        # live quality gate: the candidate architecture must not be
        # measurably WORSE live than the incumbent it would replace —
        # val loss on the training window says nothing about whether the
        # incumbent's real predictions were coming true (obs/scorecard.py)
        incumbent = self.models.get((symbol, interval))
        adoption, gate_reason = "adopted", None
        if self.scorecard is not None and incumbent is not None:
            allowed, gate_reason = self.scorecard.adoption_gate(
                best["model_type"], incumbent.model_type, symbol, interval)
            if not allowed:
                adoption = "blocked_by_scorecard"
        version = None
        if self.registry is not None:
            version = self.registry.register(
                "nn_model", dict(best),
                metadata={"symbol": symbol, "interval": interval})
            self.registry.update_performance(
                version, {"val_loss": float(hpo["best_val_loss"])})
            self.registry.set_status(
                version, "active" if adoption == "adopted" else "shadow")
        if adoption == "adopted":
            self.key, k2 = jax.random.split(self.key)
            result = train_model(
                k2, feats, best["model_type"], seq_len=self.seq_len,
                units=best["units"], dropout=best["dropout"],
                learning_rate=best["learning_rate"],
                batch_size=best["batch_size"], epochs=self.epochs,
                target_col=3, precision=self.precision)
            self.models[(symbol, interval)] = result
            self.train_count += 1
            self._snapshot(symbol, interval, result)
        return {"at": now, "best": best,
                "val_loss": float(hpo["best_val_loss"]),
                "adoption": adoption, "adoption_reason": gate_reason,
                "version": version}

    def _compute(self, now: float, hpo_req: dict | None) -> dict:
        """ALL synchronous JAX work for one cadence step. Bus access is
        limited to plain key reads (GIL-safe dict lookups); async bus
        operations (publish, request clearing) stay on the event loop in
        run_once, so this can run in a worker thread (see ``offload``)."""
        out = {"predicted": 0, "trained": 0, "hpo": 0,
               "kv": [], "events": [], "hpo_consumed": False}

        if hpo_req and "symbol" in hpo_req and "interval" in hpo_req:
            symbol, interval = hpo_req["symbol"], hpo_req["interval"]
            feats = self._features(symbol, interval)
            if feats is None:
                # data not there yet: leave the request pending for retry
                # rather than dropping it silently
                pass
            else:
                rec = self._run_hpo(symbol, interval, feats, now)
                # this cycle IS the pair's training — adopted or blocked.
                # Without the refresh, a blocked adoption would leave the
                # cadence stale and the retrain loop below could clobber
                # the very incumbent the gate just protected, the same
                # tick, with a default-config model.
                self._last_training[(symbol, interval)] = now
                out["kv"].append(
                    (f"nn_last_optimization_{symbol}_{interval}", rec))
                out["hpo"] = 1
                out["hpo_consumed"] = True
        elif hpo_req:
            out["hpo_consumed"] = True       # malformed: drop it

        # periodic retrain, per (symbol × interval) so one pair's missing
        # data can't starve another's 24 h cadence (:1406-1443)
        for symbol in self.symbols:
            for interval in self.intervals:
                last = self._last_training.get((symbol, interval))
                if last is not None and now - last < self.retrain_interval_s:
                    continue
                # the regular retrain trains the service's DEFAULT
                # architecture — when that would REPLACE a different-arch
                # incumbent (an adopted HPO winner), it is an architecture
                # swap and must pass the same live quality gate as an HPO
                # candidate; blocked = the incumbent keeps serving and is
                # re-vetted next cadence
                incumbent = self.models.get((symbol, interval))
                if (self.scorecard is not None and incumbent is not None
                        and incumbent.model_type != self.model_type
                        and not self.scorecard.adoption_gate(
                            self.model_type, incumbent.model_type,
                            symbol, interval)[0]):
                    self._last_training[(symbol, interval)] = now
                    continue
                if self._train_one(symbol, interval) is not None:
                    self._last_training[(symbol, interval)] = now
                    out["trained"] += 1

        # staleness-gated predictions (:1366-1401); pairs sharing a model
        # architecture run as one stacked predict dispatch (_predict_jobs)
        jobs = []
        for symbol in self.symbols:
            for interval in self.intervals:
                if not self._needs_prediction(symbol, interval, now):
                    continue
                result = self.models.get((symbol, interval))
                if result is None:
                    continue
                feats = self._features(symbol, interval)
                if feats is None:
                    continue
                jobs.append((symbol, interval, result, feats))
        for (symbol, interval, result, feats), pred in zip(
                jobs, self._predict_jobs(jobs)):
            rows = self.bus.get(f"historical_data_{symbol}_{interval}") or []
            payload = {
                "symbol": symbol, "interval": interval,
                "predicted_price": float(np.ravel(pred["predicted_price"])[0]),
                "confidence": pred["confidence"],
                "reference_time": now,
                # explicit outcome-resolution provenance (obs/scorecard.py):
                # the snapshot used to keep only the value, which made
                # "did this prediction come true?" unanswerable — the
                # kline timestamp anchors resolution clock-independently
                "predicted_at": now,
                "horizon_s": float(INTERVAL_SECONDS.get(interval, 3600)),
                "reference_ts": float(rows[-1][0]) if rows else None,
                "reference_price": float(feats[-1, 3]),
                "model_type": result.model_type,
            }
            out["kv"].append((f"nn_prediction_{symbol}_{interval}", payload))
            out["events"].append({"type": "prediction", **payload})
            self.predict_count += 1
            out["predicted"] += 1
        return out

    def _predict_jobs(self, jobs: list) -> list:
        """Predictions for the due (symbol, interval, result, feats) jobs,
        in job order.  Architecture groups of ≥2 run as ONE stacked
        program; singletons keep the per-model cached jit.  The
        denormalization column comes from each TrainResult (the close
        column the service trains on)."""
        preds: list = [None] * len(jobs)
        groups: dict = {}
        for i, (_, _, result, _) in enumerate(jobs):
            key = (result.model_type,
                   tuple(sorted(result.model_kwargs.items())))
            groups.setdefault(key, []).append(i)
        for key, idxs in groups.items():
            if len(idxs) == 1 or not self.batched_predict:
                for i in idxs:
                    symbol, interval, result, feats = jobs[i]
                    preds[i] = self._traced_jax(
                        "model.predict",
                        {"symbol": symbol, "interval": interval,
                         "model_type": result.model_type},
                        lambda result=result, feats=feats: predict_prices(
                            result, feats, seq_len=self.seq_len))
            else:
                rs = [jobs[i][2] for i in idxs]
                fs = [jobs[i][3] for i in idxs]
                outs = self._traced_jax(
                    "model.predict_batch",
                    {"model_type": key[0], "lanes": len(idxs)},
                    lambda rs=rs, fs=fs: predict_prices_batched(
                        rs, fs, seq_len=self.seq_len))
                for i, o in zip(idxs, outs):
                    preds[i] = o
        return preds

    async def run_once(self) -> dict:
        now = self.now_fn()
        hpo_req = self.bus.get("nn_optimization_request")
        if self.offload:
            import asyncio

            computed = await asyncio.to_thread(self._compute, now, hpo_req)
        else:
            computed = self._compute(now, hpo_req)
        if computed.pop("hpo_consumed"):
            # compare-and-clear: a NEW request posted while the offloaded
            # compute ran must survive for the next cycle, not be dropped
            if self.bus.get("nn_optimization_request") == hpo_req:
                self.bus.set("nn_optimization_request", None)
        for key, value in computed.pop("kv"):
            self.bus.set(key, value)
        for event in computed.pop("events"):
            await self.bus.publish("neural_network_predictions", event)
        return computed
