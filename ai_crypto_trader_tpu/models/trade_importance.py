"""Trade-outcome feature importance: RF + permutation + pruned model.

Capability parity with FeatureImportanceAnalyzer / FeatureImportanceService
/ FeatureImportanceIntegrator (`services/feature_importance_analyzer.py`,
`services/feature_importance_service.py`, `services/model_integration.py`):
  * RandomForest (100 trees) trained on trade outcomes (win/loss) from
    per-trade feature snapshots;
  * permutation importance (n_repeats=30) — host-side loop over features ×
    repeats against the sklearn forest (offline, low-rate: the documented
    host boundary);
  * feature groups (price action / momentum / volatility / trend / volume /
    social) with per-group aggregation;
  * pruning features below a relative-importance threshold (25 %) into an
    "optimized model" retrained on the surviving features;
  * `predict_trade_outcome` with the pruned model;
  * strategy-weight adjustment hook (`model_integration.py:288`).

The forest itself is an offline, low-rate host-side component (SURVEY §7.4
"RandomForest/SHAP: keep on host") — sklearn is the documented boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

FEATURE_GROUPS = {
    "price_action": ("price_change_1m", "price_change_5m", "price_change_15m",
                     "bb_position"),
    "momentum": ("rsi", "stoch_k", "williams_r", "macd"),
    "volatility": ("volatility", "atr", "bb_width"),
    "trend": ("trend_strength", "ema_12", "sma_20"),
    "volume": ("avg_volume", "volume"),
    "social": ("social_sentiment", "social_volume", "social_engagement"),
}


@dataclass
class TradeOutcomeAnalyzer:
    n_trees: int = 100
    n_permutation_repeats: int = 30
    prune_threshold: float = 0.25     # relative to max importance
    seed: int = 0
    feature_names: list = field(default_factory=list)
    model: object = None
    pruned_model: object = None
    kept_features: list = field(default_factory=list)
    importances: dict = field(default_factory=dict)

    def _xy(self, trades: list[dict]):
        if not self.feature_names:
            numeric = set()
            for t in trades:
                numeric |= {k for k, v in t.get("features", {}).items()
                            if isinstance(v, (int, float))}
            self.feature_names = sorted(numeric)
        X = np.asarray([[float(t.get("features", {}).get(f, 0.0))
                         for f in self.feature_names] for t in trades])
        y = np.asarray([1 if t["pnl"] > 0 else 0 for t in trades])
        return X, y

    def fit(self, trades: list[dict]) -> dict:
        """`run_analysis` / `train_models`: RF fit → builtin + permutation
        importances → group aggregation → pruned model."""
        from sklearn.ensemble import RandomForestClassifier

        X, y = self._xy(trades)
        if len(np.unique(y)) < 2:
            raise ValueError("need both winning and losing trades to fit")
        rf = RandomForestClassifier(n_estimators=self.n_trees,
                                    random_state=self.seed)
        rf.fit(X, y)
        self.model = rf

        builtin = dict(zip(self.feature_names, rf.feature_importances_))
        perm = self._permutation_importance(rf, X, y)
        combined = {f: 0.5 * builtin[f] + 0.5 * perm[f]
                    for f in self.feature_names}
        top = max(combined.values()) or 1.0
        self.importances = {
            "builtin": builtin, "permutation": perm, "combined": combined,
            "groups": self._group_importance(combined),
        }

        self.kept_features = [f for f in self.feature_names
                              if combined[f] / top >= self.prune_threshold]
        if self.kept_features and len(self.kept_features) < len(self.feature_names):
            keep_idx = [self.feature_names.index(f) for f in self.kept_features]
            pruned = RandomForestClassifier(n_estimators=self.n_trees,
                                            random_state=self.seed)
            pruned.fit(X[:, keep_idx], y)
            self.pruned_model = pruned
        else:
            self.kept_features = list(self.feature_names)
            self.pruned_model = rf
        return self.importances

    def _permutation_importance(self, model, X, y) -> dict:
        """Permutation importance — accuracy drop averaged over
        n_permutation_repeats shuffles per feature."""
        rng = np.random.default_rng(self.seed)
        base = (model.predict(X) == y).mean()
        out = {}
        for j, f in enumerate(self.feature_names):
            drops = []
            for _ in range(self.n_permutation_repeats):
                Xp = X.copy()
                Xp[:, j] = rng.permutation(Xp[:, j])
                drops.append(base - (model.predict(Xp) == y).mean())
            out[f] = float(max(np.mean(drops), 0.0))
        return out

    def _group_importance(self, combined: dict) -> dict:
        groups = {}
        for group, members in FEATURE_GROUPS.items():
            vals = [combined[f] for f in members if f in combined]
            if vals:
                groups[group] = float(np.sum(vals))
        total = sum(groups.values()) or 1.0
        return {g: v / total for g, v in groups.items()}

    def predict_trade_outcome(self, features: dict) -> dict:
        """`model_integration.py:220`: win probability from the pruned
        model."""
        if self.pruned_model is None:
            raise RuntimeError("fit() first")
        x = np.asarray([[float(features.get(f, 0.0))
                         for f in self.kept_features]])
        p = self.pruned_model.predict_proba(x)[0]
        win_p = float(p[list(self.pruned_model.classes_).index(1)]) \
            if 1 in self.pruned_model.classes_ else 0.0
        return {"win_probability": win_p,
                "prediction": "win" if win_p >= 0.5 else "loss"}

    def adjust_strategy_weights(self, weights: dict) -> dict:
        """`model_integration.py:288`: scale strategy feature weights by
        group importance, renormalized."""
        groups = self.importances.get("groups", {})
        adjusted = {k: v * (0.5 + groups.get(k, 0.5)) for k, v in weights.items()}
        total = sum(adjusted.values()) or 1.0
        return {k: v / total for k, v in adjusted.items()}
