"""Trade-outcome feature importance: RF + permutation + pruned model.

Capability parity with FeatureImportanceAnalyzer / FeatureImportanceService
/ FeatureImportanceIntegrator (`services/feature_importance_analyzer.py`,
`services/feature_importance_service.py`, `services/model_integration.py`):
  * RandomForest (100 trees) trained on trade outcomes (win/loss) from
    per-trade feature snapshots;
  * permutation importance (n_repeats=30) — host-side loop over features ×
    repeats against the sklearn forest (offline, low-rate: the documented
    host boundary);
  * feature groups (price action / momentum / volatility / trend / volume /
    social) with per-group aggregation;
  * pruning features below a relative-importance threshold (25 %) into an
    "optimized model" retrained on the surviving features;
  * `predict_trade_outcome` with the pruned model.
The consumer side — strategy-weight adjustment from recommendations and
selection's feature-alignment feed (`model_integration.py:288`) — lives in
`strategy/integration.py` (FeatureImportanceIntegrator).

The forest itself is an offline, low-rate host-side component (SURVEY §7.4
"RandomForest/SHAP: keep on host") — sklearn is the documented boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# The reference integrator's no-model response (`model_integration.py:230`),
# shared with strategy.integration so the two paths cannot drift.
NO_MODEL_PREDICTION = {
    "success_probability": 0.5, "win_probability": 0.5,
    "confidence": 0.0, "status": "no_model", "prediction": "unknown",
}

FEATURE_GROUPS = {
    "price_action": ("price_change_1m", "price_change_5m", "price_change_15m",
                     "bb_position"),
    "momentum": ("rsi", "stoch_k", "williams_r", "macd"),
    "volatility": ("volatility", "atr", "bb_width"),
    "trend": ("trend_strength", "ema_12", "sma_20"),
    "volume": ("avg_volume", "volume"),
    "social": ("social_sentiment", "social_volume", "social_engagement"),
}


@dataclass
class TradeOutcomeAnalyzer:
    n_trees: int = 100
    n_permutation_repeats: int = 30
    prune_threshold: float = 0.25     # relative to max importance
    seed: int = 0
    feature_names: list = field(default_factory=list)
    model: object = None
    pruned_model: object = None
    kept_features: list = field(default_factory=list)
    importances: dict = field(default_factory=dict)

    def _xy(self, trades: list[dict]):
        if not self.feature_names:
            numeric = set()
            for t in trades:
                numeric |= {k for k, v in t.get("features", {}).items()
                            if isinstance(v, (int, float))}
            self.feature_names = sorted(numeric)
        X = np.asarray([[float(t.get("features", {}).get(f, 0.0))
                         for f in self.feature_names] for t in trades])
        y = np.asarray([1 if t["pnl"] > 0 else 0 for t in trades])
        return X, y

    def fit(self, trades: list[dict]) -> dict:
        """`run_analysis` / `train_models`: RF fit → builtin + permutation
        importances → group aggregation → pruned model."""
        from sklearn.ensemble import RandomForestClassifier

        X, y = self._xy(trades)
        if len(np.unique(y)) < 2:
            raise ValueError("need both winning and losing trades to fit")
        rf = RandomForestClassifier(n_estimators=self.n_trees,
                                    random_state=self.seed)
        rf.fit(X, y)
        self.model = rf

        builtin = dict(zip(self.feature_names, rf.feature_importances_))
        perm = self._permutation_importance(rf, X, y)
        combined = {f: 0.5 * builtin[f] + 0.5 * perm[f]
                    for f in self.feature_names}
        top = max(combined.values()) or 1.0
        groups = self._group_importance(combined)
        # recommendations (`feature_importance_analyzer.py` output consumed
        # by `model_integration.py:288`): groups well above/below a uniform
        # share are flagged to prioritize/reconsider
        uniform = 1.0 / max(len(groups), 1)
        self.importances = {
            "builtin": builtin, "permutation": perm, "combined": combined,
            "groups": groups,
            "recommendations": {
                "categories_to_prioritize":
                    [g for g, v in groups.items() if v >= 1.5 * uniform],
                "categories_to_reconsider":
                    [g for g, v in groups.items() if v <= 0.5 * uniform],
            },
        }

        self.kept_features = [f for f in self.feature_names
                              if combined[f] / top >= self.prune_threshold]
        if self.kept_features and len(self.kept_features) < len(self.feature_names):
            keep_idx = [self.feature_names.index(f) for f in self.kept_features]
            pruned = RandomForestClassifier(n_estimators=self.n_trees,
                                            random_state=self.seed)
            pruned.fit(X[:, keep_idx], y)
            self.pruned_model = pruned
        else:
            self.kept_features = list(self.feature_names)
            self.pruned_model = rf
        return self.importances

    def _permutation_importance(self, model, X, y) -> dict:
        """Permutation importance — accuracy drop averaged over
        n_permutation_repeats shuffles per feature."""
        rng = np.random.default_rng(self.seed)
        base = (model.predict(X) == y).mean()
        out = {}
        for j, f in enumerate(self.feature_names):
            drops = []
            for _ in range(self.n_permutation_repeats):
                Xp = X.copy()
                Xp[:, j] = rng.permutation(Xp[:, j])
                drops.append(base - (model.predict(Xp) == y).mean())
            out[f] = float(max(np.mean(drops), 0.0))
        return out

    def _group_importance(self, combined: dict) -> dict:
        groups = {}
        for group, members in FEATURE_GROUPS.items():
            vals = [combined[f] for f in members if f in combined]
            if vals:
                groups[group] = float(np.sum(vals))
        total = sum(groups.values()) or 1.0
        return {g: v / total for g, v in groups.items()}

    def predict_trade_outcome(self, features: dict) -> dict:
        """`model_integration.py:220-288`: win probability from the pruned
        model, confidence = distance from coin-flip scaled to [0,1], neutral
        defaults when nothing has been fit yet (the reference's no_model
        path rather than an exception)."""
        if self.pruned_model is None:
            return dict(NO_MODEL_PREDICTION)
        x = np.asarray([[float(features.get(f, 0.0))
                         for f in self.kept_features]])
        p = self.pruned_model.predict_proba(x)[0]
        win_p = float(p[list(self.pruned_model.classes_).index(1)]) \
            if 1 in self.pruned_model.classes_ else 0.0
        return {"success_probability": win_p,
                "win_probability": win_p,
                "confidence": abs(win_p - 0.5) * 2.0,
                "status": "success",
                "prediction": "win" if win_p >= 0.5 else "loss"}

