"""Training / prediction loop for the model zoo.

Replaces the reference's Keras fit pipeline
(`services/neural_network_service.py:530-1012`): MinMax scaling + sliding
windows (:530-586), EarlyStopping / ReduceLROnPlateau / checkpointing
callbacks (:805-912), and predict + denormalize + confidence (:1090-1219) —
as pure jitted train/eval steps under optax, with the LR-plateau logic
implemented via `optax.inject_hyperparams` so the schedule is host-driven
state, not a callback object.

The default loop is the COMPILED EPOCH (models/train_loop.py): one
`lax.scan` program per epoch over on-device batches with donated
params/opt_state, fused validation loss, and exactly ONE host readback per
epoch.  `compiled_epoch=False` keeps the legacy per-batch Python loop —
tests assert the two produce the same loss trajectory from the same key.

Multitask horizon losses are weighted 1.0/0.7/0.5
(`neural_network_service.py:335-344`); the probabilistic head trains on
Gaussian NLL (:381-391).
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ai_crypto_trader_tpu.models import train_loop
from ai_crypto_trader_tpu.models.train_loop import EpochTrainer, snapshot_params
from ai_crypto_trader_tpu.models.zoo import build_model
from ai_crypto_trader_tpu.utils import devprof, tracing

MULTITASK_WEIGHTS = (1.0, 0.7, 0.5)


class Scaler(NamedTuple):
    """MinMax scaler state (sklearn MinMaxScaler parity,
    `neural_network_service.py:541-549`)."""

    min: jnp.ndarray
    max: jnp.ndarray

    def transform(self, x):
        rng = self.max - self.min
        return (x - self.min) / jnp.where(rng == 0.0, 1.0, rng)

    def inverse(self, x, feature: int = 0):
        rng = self.max - self.min
        return x * jnp.where(rng[feature] == 0.0, 1.0, rng[feature]) + self.min[feature]


def fit_scaler(features: np.ndarray) -> Scaler:
    return Scaler(jnp.asarray(features.min(axis=0)), jnp.asarray(features.max(axis=0)))


def make_windows(features: np.ndarray, seq_len: int = 60,
                 horizons: Sequence[int] = (1,), target_col: int = 0):
    """[T, F] → (X [N, seq_len, F], y [N, H]).

    Target = scaled close at t+h (`prepare_training_data`,
    `neural_network_service.py:558-586`)."""
    T = features.shape[0]
    hmax = max(horizons)
    n = T - seq_len - hmax + 1
    if n <= 0:
        raise ValueError(f"series too short: T={T} seq_len={seq_len} hmax={hmax}")
    idx = np.arange(n)[:, None] + np.arange(seq_len)[None, :]
    X = features[idx]
    y = np.stack([features[np.arange(n) + seq_len + h - 1, target_col]
                  for h in horizons], axis=-1)
    return X.astype(np.float32), y.astype(np.float32)


@dataclass
class TrainResult:
    params: Any
    model_type: str
    scaler: Scaler
    model_kwargs: dict = field(default_factory=dict)
    history: list = field(default_factory=list)
    best_val_loss: float = float("inf")
    epochs_run: int = 0
    target_col: int = 0              # feature column the model predicts

    def model(self):
        return build_model(self.model_type, **self.model_kwargs)


def _loss_fn(out: dict, y: jnp.ndarray, model_type: str) -> jnp.ndarray:
    if model_type == "probabilistic":
        mu, log_sigma = out["mean"], out["log_sigma"]
        # Gaussian NLL — the 3-line replacement for the TFP head.
        nll = 0.5 * jnp.exp(-2 * log_sigma) * (y - mu) ** 2 + log_sigma
        return jnp.mean(nll)
    pred = out["mean"]
    if pred.shape[-1] > 1:  # multitask
        w = jnp.asarray(MULTITASK_WEIGHTS[: pred.shape[-1]])
        return jnp.mean(jnp.mean((pred - y) ** 2, axis=0) * w)
    return jnp.mean((pred - y) ** 2)


def train_model(
    key,
    features: np.ndarray,
    model_type: str = "lstm",
    *,
    seq_len: int = 60,
    horizons: Sequence[int] | None = None,
    units: int = 64,
    dropout: float = 0.2,
    epochs: int = 100,
    batch_size: int = 32,
    learning_rate: float = 1e-3,
    val_fraction: float = 0.2,
    early_stopping_patience: int = 10,
    reduce_lr_patience: int = 5,
    reduce_lr_factor: float = 0.5,
    min_lr: float = 1e-6,
    verbose: bool = False,
    target_col: int = 0,
    precision: str | None = None,
    compiled_epoch: bool = True,
) -> TrainResult:
    """Fit one model; returns params + history + scaler.

    Chronological train/val split (no shuffle across the boundary — the
    reference shuffles windows, which leaks future data into training; we
    split first, then shuffle within train).

    ``precision``: matmul precision for the training program ("f32"
    default, "bf16" for bf16-matmul).  ``compiled_epoch``: route through
    the donated whole-epoch `lax.scan` (default) or the legacy per-batch
    dispatch loop (kept for the loss-trajectory parity tests)."""
    if horizons is None:
        horizons = (1, 3, 5) if model_type == "multitask" else (1,)

    # Leak-free split: the scaler is fit ONLY on the training rows, and
    # validation windows are exactly those whose targets reach past the
    # training boundary (the reference fits MinMax on the whole series and
    # shuffles windows across the split, `neural_network_service.py:530-586`).
    T = features.shape[0]
    train_rows = max(T - int(T * val_fraction), seq_len + max(horizons) + 1)
    scaler = fit_scaler(features[:train_rows])
    scaled = np.asarray(scaler.transform(jnp.asarray(features)))
    X, y = make_windows(scaled, seq_len, horizons, target_col)
    hmax = max(horizons)
    target_row = np.arange(len(X)) + seq_len + hmax - 1
    is_train = target_row < train_rows
    X_tr, y_tr = X[is_train], y[is_train]
    X_val, y_val = X[~is_train], y[~is_train]
    if len(X_val) == 0:
        X_val, y_val = X_tr[-1:], y_tr[-1:]

    model_kwargs = dict(units=units, dropout=dropout, horizons=tuple(horizons))
    model = build_model(model_type, **model_kwargs)
    k_init, k_drop, key = jax.random.split(key, 3)
    params = model.init(k_init, jnp.asarray(X[:2]), False)

    tx = optax.inject_hyperparams(optax.adam)(learning_rate=learning_rate)
    opt_state = tx.init(params)

    def train_loss(p, xb, yb, rng):
        out = model.apply(p, xb, True, rngs={"dropout": rng})
        return _loss_fn(out, yb, model_type)

    def eval_loss(p, xb, yb):
        return _loss_fn(model.apply(p, xb, False), yb, model_type)

    X_val_j, y_val_j = jnp.asarray(X_val), jnp.asarray(y_val)
    n_batches = max(len(X_tr) // batch_size, 1)

    # Donation-safe snapshot: the raw `params` buffers are invalidated by
    # the first donated epoch call, and a NaN-from-epoch-0 run must still
    # return live best params.
    best = TrainResult(params=snapshot_params(params), model_type=model_type,
                       scaler=scaler, target_col=target_col,
                       model_kwargs=model_kwargs)
    patience = lr_patience = 0
    lr = learning_rate

    if compiled_epoch:
        trainer = EpochTrainer(train_loss, tx, eval_loss_fn=eval_loss,
                               precision=precision,
                               card=f"train_epoch.{model_type}")
        # One host→device transfer for the whole dataset, up front.
        X_tr_d, y_tr_d = jnp.asarray(X_tr), jnp.asarray(y_tr)
        run_epoch = lambda params, opt_state, k_shuf, k_ep: trainer.epoch(
            params, opt_state, X_tr_d, y_tr_d, k_shuf, k_ep,
            X_val_j, y_val_j, batch_size=batch_size)
    else:
        train_step = jax.jit(
            lambda params, opt_state, xb, yb, rng: _legacy_step(
                train_loss, tx, params, opt_state, xb, yb, rng))
        eval_loss_j = jax.jit(eval_loss)

        def run_epoch(params, opt_state, k_shuf, k_ep):
            # precision context must wrap the CALLS (tracing happens on
            # first dispatch, not at jit() construction)
            with train_loop.matmul_precision(precision):
                perm = np.asarray(jax.random.permutation(k_shuf, len(X_tr)))
                ep_loss = 0.0
                for b in range(n_batches):
                    sl = perm[b * batch_size: (b + 1) * batch_size]
                    params, opt_state, l = train_step(
                        params, opt_state, jnp.asarray(X_tr[sl]),
                        jnp.asarray(y_tr[sl]), jax.random.fold_in(k_ep, b))
                    ep_loss += float(l)
                val = eval_loss_j(params, X_val_j, y_val_j)
            return params, opt_state, jnp.stack(
                [jnp.asarray(ep_loss / n_batches), val])

    monitor = before = None
    if tracing.active() is not None:
        monitor = tracing.JitCompileMonitor.install()

    for epoch in range(epochs):
        key, k_shuf, k_ep = jax.random.split(key, 3)
        if monitor is not None:
            before = monitor.sample()
        t0 = time.perf_counter()
        with tracing.span("train.epoch",
                          attributes={"epoch": epoch,
                                      "model_type": model_type,
                                      "n_batches": n_batches}) as sp:
            params, opt_state, metrics = run_epoch(params, opt_state,
                                                   k_shuf, k_ep)
            # THE one host sync per epoch: [train_loss, val_loss] together.
            ep_loss, val_loss = (float(v) for v in train_loop.host_read(metrics))
            tracing.attribute_dispatch(sp, monitor, before,
                                       time.perf_counter() - t0)
        best.history.append({"epoch": epoch, "loss": ep_loss,
                             "val_loss": val_loss, "lr": lr})
        if verbose:
            print(f"epoch {epoch}: loss={ep_loss:.5f} val={val_loss:.5f}")

        if val_loss < best.best_val_loss - 1e-7:
            best.best_val_loss = val_loss
            # copy, not alias: the live params are donated next epoch
            best.params = snapshot_params(params)
            patience = lr_patience = 0
        else:
            patience += 1
            lr_patience += 1
            if lr_patience >= reduce_lr_patience and lr > min_lr:
                lr = max(lr * reduce_lr_factor, min_lr)
                opt_state.hyperparams["learning_rate"] = jnp.asarray(lr)
                lr_patience = 0
            if patience >= early_stopping_patience:
                break
    best.epochs_run = epoch + 1
    return best


def _legacy_step(train_loss, tx, params, opt_state, xb, yb, rng):
    """One per-batch update — the pre-compiled-epoch loop body, kept for
    the loss-trajectory parity tests."""
    l, grads = jax.value_and_grad(train_loss)(params, xb, yb, rng)
    updates, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, l


def predict_prices(result: TrainResult, features: np.ndarray,
                   seq_len: int = 60, target_col: int | None = None) -> dict:
    """Predict the next price from the trailing window + denormalize +
    confidence from validation loss (`predict_prices`,
    `neural_network_service.py:1090-1219`).

    ``target_col`` defaults to the column the model was TRAINED to predict
    (recorded on TrainResult) — denormalizing with a different column's
    min/max silently mis-scales the prediction (round-5 review)."""
    if target_col is None:
        target_col = result.target_col
    # One jitted predict program PER TRAINED MODEL, cached on the result:
    # building a fresh flax module per call makes its internal scan/pjit miss
    # the compile cache every time (new module constants in the key), and a
    # long-lived process accumulates one XLA compile per prediction — the
    # cumulative-compile segfault the 2000-tick soak exposed. The window is
    # also sliced BEFORE transforming (scaler is elementwise; identical
    # result) so the program sees a FIXED [seq_len, F] shape.
    fn = getattr(result, "_predict_fn", None)
    if fn is None:
        model = result.model()
        fn = jax.jit(lambda p, w: model.apply(p, w, False))
        result._predict_fn = fn
    window_feats = np.asarray(features)[-seq_len:]
    scaled = result.scaler.transform(jnp.asarray(window_feats))
    window = scaled[None]
    out = fn(result.params, window)
    mean_scaled = out["mean"][0]
    price = np.asarray(result.scaler.inverse(mean_scaled, target_col))
    confidence = float(1.0 / (1.0 + result.best_val_loss * 100.0))
    res = {"predicted_price": price, "confidence": confidence}
    if "log_sigma" in out:
        sigma_scaled = np.exp(np.asarray(out["log_sigma"][0]))
        rng = np.asarray(result.scaler.max[target_col] - result.scaler.min[target_col])
        res["predicted_std"] = sigma_scaled * rng
    return res


# One batched-predict program per ARCHITECTURE (model_type + kwargs), shared
# by every model instance of that architecture; jit retraces per lane count,
# so steady-state prediction cadences hit the cache every cycle.
_BATCHED_PREDICT_FNS: dict = {}


def _batched_predict_fn(model_type: str, kwargs_key: tuple):
    fn = _BATCHED_PREDICT_FNS.get((model_type, kwargs_key))
    if fn is None:
        model = build_model(model_type, **dict(kwargs_key))

        def one(params, smin, smax, window):
            rng = smax - smin
            scaled = (window - smin) / jnp.where(rng == 0.0, 1.0, rng)
            return model.apply(params, scaled[None], False)

        fn = jax.jit(jax.vmap(one))
        _BATCHED_PREDICT_FNS[(model_type, kwargs_key)] = fn
    return fn


def predict_prices_batched(results: Sequence[TrainResult], features_list,
                           seq_len: int = 60) -> list[dict]:
    """predict_prices for N models sharing ONE architecture, as ONE stacked
    dispatch: params/scalers stack into a leading lane axis, the per-lane
    MinMax transform runs in-program, and the host reads all lanes back in
    a single device_get.  Per-lane scaling/denormalization is the exact
    math of `predict_prices`, so the outputs are interchangeable — the
    parity tests pin them equal.  All ``results`` must share
    (model_type, model_kwargs); the caller groups by architecture."""
    r0 = results[0]
    kwargs_key = tuple(sorted(r0.model_kwargs.items()))
    windows = jnp.asarray(np.stack(
        [np.asarray(f, np.float32)[-seq_len:] for f in features_list]))
    params = jax.tree.map(lambda *xs: jnp.stack(xs),
                          *[r.params for r in results])
    smin = jnp.stack([r.scaler.min for r in results])
    smax = jnp.stack([r.scaler.max for r in results])
    fn = _batched_predict_fn(r0.model_type, kwargs_key)
    # one-shot devprof cost card per architecture (lane count varies per
    # call; the first-seen shape is the card — utils/devprof.py)
    devprof.cost_card(f"predict_batched.{r0.model_type}", fn,
                      params, smin, smax, windows)
    out = fn(params, smin, smax, windows)
    out, mins, maxs = jax.device_get((out, smin, smax))   # one pull, all lanes
    preds = []
    for lane, r in enumerate(results):
        tc = r.target_col
        rng_t = maxs[lane, tc] - mins[lane, tc]
        rng_t = rng_t if rng_t != 0.0 else np.float32(1.0)
        mean_scaled = out["mean"][lane, 0]
        res = {"predicted_price": np.asarray(mean_scaled * rng_t
                                             + mins[lane, tc]),
               "confidence": float(1.0 / (1.0 + r.best_val_loss * 100.0))}
        if "log_sigma" in out:
            res["predicted_std"] = np.exp(
                np.asarray(out["log_sigma"][lane, 0])) * rng_t
        preds.append(res)
    return preds
