"""Shared compiled-epoch trainer: one XLA program per epoch, one host sync.

Every training path in the repo (model zoo, HPO trials, chart-pattern
classifier; the DQN has its own scan in rl/dqn.py) used to run the same
Python minibatch loop: per step it paid a jit dispatch, a fresh
`jnp.asarray` host→device copy of the batch, and a `float(loss)` that
blocked the device — while params/opt_state round-tripped through XLA's
copy-on-call semantics.  Podracer's Anakin pattern (PAPERS: arxiv
2104.06272, 2206.08888) moves the whole epoch under `jit`:

  * the dataset lives on device as one [N, ...] tensor; each epoch is a
    `lax.scan` over `[n_batches, B, ...]` batches gathered on device via
    `jax.random.permutation` + `take`;
  * dropout keys are `fold_in`-ed per batch INSIDE the scan;
  * `(params, opt_state)` are donated (`donate_argnums`), so XLA updates
    them in place instead of copying;
  * the epoch train loss is accumulated on device, the validation loss is
    computed in the SAME program, and the host reads both back in ONE
    [2]-vector transfer per epoch (`host_read`) — the only device sync in
    the loop.  LR-plateau / early-stopping logic stays host-side.

A `precision` knob selects the matmul precision for the whole epoch
program ("f32" default; "bf16" routes matmuls through
`jax.default_matmul_precision("bfloat16")` — on TPU that is the MXU's
native mode, on CPU it maps to whatever the backend offers).

CAUTION (donation): the params/opt_state pytrees PASSED to `epoch()` are
invalidated by the call.  Hold `snapshot_params()` copies (not the donated
inputs) for best-params bookkeeping.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ai_crypto_trader_tpu.obs import tickpath
from ai_crypto_trader_tpu.utils import devprof, meshprof

_PRECISIONS = {
    # None = backend default (f32 on CPU; the MXU's default mode on TPU).
    # "f32" must force FULL float32 — mapping it to None would silently
    # leave TPU matmuls at the bf16-ish DEFAULT precision.
    None: None,
    "f32": "float32", "float32": "float32", "highest": "highest",
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "tf32": "tensorfloat32", "tensorfloat32": "tensorfloat32",
}


def canonical_precision(precision: str | None) -> str | None:
    """Map user-facing knob values to `jax.default_matmul_precision` names
    (None → backend default)."""
    try:
        return _PRECISIONS[precision]
    except KeyError:
        raise ValueError(
            f"unknown precision {precision!r}; one of {sorted(set(k for k in _PRECISIONS if k))}"
        ) from None


def matmul_precision(precision: str | None):
    """Context manager applying the canonical precision (no-op for f32)."""
    p = canonical_precision(precision)
    return jax.default_matmul_precision(p) if p else contextlib.nullcontext()


def host_read(x) -> np.ndarray:
    """THE per-epoch host sync: device metrics → numpy.

    Kept as a module-level seam so tests can wrap it with a counting
    double and assert the loop performs exactly one sync per epoch.
    Timed into the ``host_read`` SLO window (utils/devprof.py): this
    readback blocks on the whole epoch program, so its latency IS the
    device-side epoch time as seen from the host."""
    t0 = time.perf_counter()
    with meshprof.allow_transfers():   # THE sanctioned device→host sync
        out = np.asarray(x)
    devprof.observe_latency("host_read", time.perf_counter() - t0)
    return out


def snapshot_params(tree):
    """Device-side copy of a pytree — donation-safe best-params snapshot
    (the donated originals are invalidated by the next epoch call)."""
    return jax.tree.map(jnp.copy, tree)


class EpochTrainer:
    """Compiles `train_loss_fn` + `tx` into a donated whole-epoch program.

    train_loss_fn(params, xb, yb, rng) -> scalar loss   (rng: dropout key)
    eval_loss_fn(params, X_val, y_val) -> scalar loss   (optional; fused
        into the same program so validation costs no extra dispatch)

    `epoch(...)` returns (params, opt_state, metrics) where metrics is a
    device [2]-vector [mean_train_loss, val_loss] (val repeats the train
    loss when no eval_loss_fn was given).  Read it back with
    `host_read(metrics)` — once per epoch.
    """

    def __init__(self, train_loss_fn: Callable, tx, *,
                 eval_loss_fn: Callable | None = None,
                 precision: str | None = None,
                 card: str = "train_epoch"):
        self.train_loss_fn = train_loss_fn
        self.eval_loss_fn = eval_loss_fn
        self.tx = tx
        self.precision = canonical_precision(precision)
        # devprof cost-card name: cards are one-shot PER NAME, and every
        # architecture compiles a distinct epoch program — callers that
        # train multiple architectures pass e.g. "train_epoch.lstm" so a
        # later architecture's silent donation copy is still caught
        self.card = card
        self._with_val = eval_loss_fn is not None

        def body(carry, inp, k_drop):
            params, opt_state, loss_sum = carry
            i, xb, yb = inp
            rng = jax.random.fold_in(k_drop, i)
            loss, grads = jax.value_and_grad(self.train_loss_fn)(
                params, xb, yb, rng)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, loss_sum + loss), None

        def scan_epoch(params, opt_state, X, y, k_perm, k_drop, batch_size):
            n = X.shape[0]
            bs = min(batch_size, n)
            nb = max(n // bs, 1)
            perm = jax.random.permutation(k_perm, n)[: nb * bs]
            idx = perm.reshape(nb, bs)
            Xb = jnp.take(X, idx, axis=0)        # [nb, bs, ...] on device
            yb = jnp.take(y, idx, axis=0)
            (params, opt_state, loss_sum), _ = jax.lax.scan(
                lambda c, i: body(c, i, k_drop),
                (params, opt_state, jnp.zeros((), X.dtype)),
                (jnp.arange(nb), Xb, yb))
            return params, opt_state, loss_sum / nb

        if self._with_val:
            def _epoch(params, opt_state, X, y, k_perm, k_drop,
                       X_val, y_val, *, batch_size):
                params, opt_state, train_loss = scan_epoch(
                    params, opt_state, X, y, k_perm, k_drop, batch_size)
                val = self.eval_loss_fn(params, X_val, y_val)
                return params, opt_state, jnp.stack([train_loss, val])
        else:
            def _epoch(params, opt_state, X, y, k_perm, k_drop,
                       *, batch_size):
                params, opt_state, train_loss = scan_epoch(
                    params, opt_state, X, y, k_perm, k_drop, batch_size)
                return params, opt_state, jnp.stack([train_loss, train_loss])

        self._epoch = jax.jit(_epoch, static_argnames=("batch_size",),
                              donate_argnums=(0, 1))
        # (shapes, batch_size) combinations this trainer has dispatched —
        # the recompile sentinel's cold ledger (a fresh trainer/shape
        # compiles by design; a re-trace at a seen shape pages)
        self._watched_shapes: set = set()

    def epoch(self, params, opt_state, X, y, k_perm, k_drop,
              X_val=None, y_val=None, *, batch_size: int):
        """One compiled epoch.  DONATES params/opt_state (see module doc).

        With the devprof observatory active, the first epoch publishes a
        ``self.card`` cost card (default ``train_epoch``), verifies the params/opt_state donation
        actually freed the old buffers, and every epoch feeds the
        ``train_step`` SLO window (dispatch wall amortized per batch)."""
        args = (params, opt_state, X, y, k_perm, k_drop)
        if self._with_val:
            args = args + (X_val, y_val)
        dp = devprof.active()
        carding = dp is not None and not devprof.has_card(self.card)
        donated = jax.tree.leaves((params, opt_state)) if carding else None
        with matmul_precision(self.precision):
            if carding:        # lower under the same precision as the run
                devprof.cost_card(self.card, self._epoch, *args,
                                  batch_size=batch_size)
            # t0 AFTER carding: the card's duplicate AOT lowering/compile
            # must not pollute the train_step SLO window
            cold = True
            if meshprof.active() is not None:   # default-OFF discipline
                shape_key = (X.shape, y.shape,
                             (X_val.shape if X_val is not None else None),
                             batch_size)
                cold = shape_key not in self._watched_shapes
                self._watched_shapes.add(shape_key)
            t0 = time.perf_counter()
            with tickpath.coldstart(self.card, cold=cold), \
                    meshprof.watch(self.card, cold=cold):
                out = self._epoch(*args, batch_size=batch_size)
        if dp is not None:
            nb = max(X.shape[0] // min(batch_size, X.shape[0]), 1)
            dp.observe_latency("train_step",
                               (time.perf_counter() - t0) / nb)
            if donated is not None:
                devprof.verify_donation(self.card, donated)
        return out
