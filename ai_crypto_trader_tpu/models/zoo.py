"""The price-prediction model zoo — flax/linen, XLA:TPU-compiled.

Capability parity with the reference's 8 Keras architectures + ensemble
(`services/neural_network_service.py:164-485`):

  lstm(:191) gru(:202) bidirectional(:213) cnn_lstm(:224) attention(:236)
  transformer(:247-306, manual sinusoidal PE + 2 blocks)
  multitask(:308-353, 3 horizon heads, loss weights 1.0/0.7/0.5)
  probabilistic(:355-391, Normal head + NLL — TFP replaced by a 3-line
                log-prob in pure JAX)
  ensemble(:423-485, LSTM+GRU+CNN branches concatenated)

Design is TPU-first rather than a Keras translation: recurrent layers use
`flax.linen.RNN` over optimized cells (XLA fuses the scan body onto the
MXU), all dense/conv work is batched bf16-friendly, and every model exposes
the same functional signature

    apply(params, x[B, T, F], train=False, rngs=...) -> output

where output is `{"mean": [B,H]}` (H = #horizons, 1 for single-task) plus
`"log_sigma"` for the probabilistic head.  Losses live in models/train.py.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ai_crypto_trader_tpu.models.fused_lstm import FusedLSTM

Dtype = Any


def sinusoidal_positions(seq_len: int, d_model: int) -> jnp.ndarray:
    """Sinusoidal positional encoding (the reference builds the same table
    manually, `neural_network_service.py:252-270`)."""
    pos = np.arange(seq_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10_000.0, (2 * (i // 2)) / d_model)
    table = np.where(i % 2 == 0, np.sin(angle), np.cos(angle))
    return jnp.asarray(table, jnp.float32)


class RecurrentEncoder(nn.Module):
    """Stacked LSTM/GRU encoder with inter-layer dropout.

    The LSTM path runs the fused custom-VJP layer (models/fused_lstm.py)
    in time-major layout — one transpose at each encoder boundary instead
    of per layer; GRU keeps the flax RNN cell."""

    units: int = 64
    num_layers: int = 2
    dropout: float = 0.2
    cell: str = "lstm"          # lstm | gru
    bidirectional: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.cell == "lstm":
            h = x.swapaxes(0, 1)                       # [T, B, F]
            for layer in range(self.num_layers):
                fwd = FusedLSTM(self.units, name=f"rnn_{layer}")(h)
                if self.bidirectional:
                    bwd = jnp.flip(FusedLSTM(
                        self.units, name=f"rnn_b_{layer}")(
                            jnp.flip(h, axis=0)), axis=0)
                    h = jnp.concatenate([fwd, bwd], axis=-1)
                else:
                    h = fwd
                h = nn.Dropout(self.dropout, deterministic=not train)(h)
            return h.swapaxes(0, 1)
        for layer in range(self.num_layers):
            rnn = nn.RNN(nn.GRUCell(self.units), name=f"rnn_{layer}")
            if self.bidirectional:
                fwd = rnn(x)
                bwd = jnp.flip(nn.RNN(nn.GRUCell(self.units), name=f"rnn_b_{layer}")(
                    jnp.flip(x, axis=1)), axis=1)
                x = jnp.concatenate([fwd, bwd], axis=-1)
            else:
                x = rnn(x)
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x


class SingleHead(nn.Module):
    """encoder → last hidden state → Dense(1) regression head."""

    encoder: Callable
    units: int = 64
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = self.encoder(x, train)[:, -1, :]
        h = nn.Dense(self.units // 2)(h)
        h = nn.relu(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return {"mean": nn.Dense(1)(h)}


class CNNLSTM(nn.Module):
    """Conv1D feature extraction → max-pool → LSTM
    (`neural_network_service.py:224-234`)."""

    units: int = 64
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.units, kernel_size=(3,), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2,), strides=(2,))
        x = nn.Conv(self.units, kernel_size=(3,), padding="SAME")(x)
        x = nn.relu(x)
        h = FusedLSTM(self.units)(x.swapaxes(0, 1))[-1]   # last hidden state
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return {"mean": nn.Dense(1)(h)}


class AttentionModel(nn.Module):
    """LSTM encoder + multi-head self-attention pooling
    (`neural_network_service.py:236-245`)."""

    units: int = 64
    num_heads: int = 4
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = RecurrentEncoder(self.units, 1, self.dropout)(x, train)
        a = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, qkv_features=self.units,
            deterministic=not train, dropout_rate=self.dropout)(h, h)
        h = nn.LayerNorm()(h + a)
        h = jnp.mean(h, axis=1)
        return {"mean": nn.Dense(1)(nn.relu(nn.Dense(self.units // 2)(h)))}


class TransformerBlock(nn.Module):
    d_model: int
    num_heads: int
    ff_dim: int
    dropout: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        a = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, qkv_features=self.d_model,
            deterministic=not train, dropout_rate=self.dropout)(x, x)
        x = nn.LayerNorm()(x + a)
        f = nn.Dense(self.ff_dim)(x)
        f = nn.gelu(f)
        f = nn.Dense(self.d_model)(f)
        f = nn.Dropout(self.dropout, deterministic=not train)(f)
        return nn.LayerNorm()(x + f)


class TransformerModel(nn.Module):
    """Input proj + sinusoidal PE + 2 transformer blocks
    (`neural_network_service.py:247-306`)."""

    d_model: int = 64
    num_heads: int = 4
    num_blocks: int = 2
    ff_dim: int = 128
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, T, F = x.shape
        h = nn.Dense(self.d_model)(x)
        h = h + sinusoidal_positions(T, self.d_model)[None]
        for _ in range(self.num_blocks):
            h = TransformerBlock(self.d_model, self.num_heads,
                                 self.ff_dim, self.dropout)(h, train)
        h = jnp.mean(h, axis=1)
        return {"mean": nn.Dense(1)(nn.relu(nn.Dense(self.d_model // 2)(h)))}


class MultitaskModel(nn.Module):
    """Shared encoder + one head per prediction horizon; loss weights
    1.0/0.7/0.5 applied in train.py (`neural_network_service.py:308-353`)."""

    units: int = 64
    dropout: float = 0.2
    horizons: Sequence[int] = (1, 3, 5)

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = RecurrentEncoder(self.units, 2, self.dropout)(x, train)[:, -1, :]
        outs = [nn.Dense(1, name=f"head_h{hz}")(nn.relu(nn.Dense(32)(h)))
                for hz in self.horizons]
        return {"mean": jnp.concatenate(outs, axis=-1)}


class ProbabilisticModel(nn.Module):
    """Normal(μ, σ) head trained with NLL — replaces the TFP
    DistributionLambda (`neural_network_service.py:355-391`)."""

    units: int = 64
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = RecurrentEncoder(self.units, 2, self.dropout)(x, train)[:, -1, :]
        h = nn.relu(nn.Dense(self.units // 2)(h))
        mean = nn.Dense(1)(h)
        log_sigma = jnp.clip(nn.Dense(1)(h), -7.0, 3.0)
        return {"mean": mean, "log_sigma": log_sigma}


class EnsembleModel(nn.Module):
    """LSTM + GRU + CNN branches, concatenated
    (`create_ensemble_model`, `neural_network_service.py:423-485`)."""

    units: int = 64
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        lstm = RecurrentEncoder(self.units, 1, self.dropout, "lstm")(x, train)[:, -1]
        gru = RecurrentEncoder(self.units, 1, self.dropout, "gru")(x, train)[:, -1]
        c = nn.relu(nn.Conv(self.units, (3,), padding="SAME")(x))
        c = jnp.mean(c, axis=1)
        h = jnp.concatenate([lstm, gru, c], axis=-1)
        h = nn.relu(nn.Dense(self.units)(h))
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return {"mean": nn.Dense(1)(h)}


def build_model(model_type: str, units: int = 64, dropout: float = 0.2,
                num_layers: int = 2, horizons: Sequence[int] = (1, 3, 5)) -> nn.Module:
    """Factory mirroring `create_model`'s type dispatch
    (`neural_network_service.py:164-421`)."""
    mt = model_type.lower()
    if mt == "lstm":
        return SingleHead(RecurrentEncoder(units, num_layers, dropout, "lstm"),
                          units, dropout)
    if mt == "gru":
        return SingleHead(RecurrentEncoder(units, num_layers, dropout, "gru"),
                          units, dropout)
    if mt == "bidirectional":
        return SingleHead(
            RecurrentEncoder(units, num_layers, dropout, "lstm", bidirectional=True),
            units, dropout)
    if mt == "cnn_lstm":
        return CNNLSTM(units, dropout)
    if mt == "attention":
        return AttentionModel(units, dropout=dropout)
    if mt == "transformer":
        return TransformerModel(d_model=units, dropout=dropout)
    if mt == "multitask":
        return MultitaskModel(units, dropout, horizons)
    if mt == "probabilistic":
        return ProbabilisticModel(units, dropout)
    if mt == "ensemble":
        return EnsembleModel(units, dropout)
    raise ValueError(f"unknown model type {model_type!r}")


MODEL_REGISTRY = ("lstm", "gru", "bidirectional", "cnn_lstm", "attention",
                  "transformer", "multitask", "probabilistic", "ensemble")
