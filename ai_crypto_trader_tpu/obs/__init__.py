"""Decision provenance & model-quality observatory.

PR 6 instrumented the DEVICE runtime (cost cards, donation checks,
latency SLOs); this package instruments the TRADING axis — why a trade
fired or was vetoed, whether the zoo models' predictions are actually
correct once their horizon elapses, which signal family the realized
PnL comes from, and whether the live feature distribution has drifted
from its reference.  Four instruments:

  * flightrec    — signal→order→fill→PnL flight recorder: one compact
                   record per (symbol, tick) decision, bounded ring +
                   checksummed append-only JSONL (utils/journal format)
  * scorecard    — live prediction outcome scoring: hit-rate,
                   directional accuracy and Brier calibration per
                   (architecture, symbol, interval), resolved against
                   the realized candle when the horizon elapses
  * drift        — the per-feature PSI spec the fused tick dispatch
                   computes on-device (ops/tick_engine.py)
  * attribution  — realized-PnL / win-rate folding of journal closures
                   by entry signal family / strategy / model
  * fleetscope   — the fleet observatory: device-aggregated lane
                   telemetry for vmapped tenant fleets (gate histogram,
                   dispersion quantiles, top-k lane rank — computed
                   INSIDE the tenant engine's dispatch), bounded-
                   cardinality fleet_* export and crc32-sampled lane
                   provenance
"""

from ai_crypto_trader_tpu.obs.attribution import PnLAttribution
from ai_crypto_trader_tpu.obs.drift import (
    DRIFT_FEATURES,
    N_BINS,
    PSI_ALERT_THRESHOLD,
    reference_histogram,
)
from ai_crypto_trader_tpu.obs.fleetscope import FleetScope
from ai_crypto_trader_tpu.obs.flightrec import FlightRecorder, load_decisions
from ai_crypto_trader_tpu.obs.scorecard import Scorecard

__all__ = [
    "DRIFT_FEATURES", "N_BINS", "PSI_ALERT_THRESHOLD",
    "FleetScope", "FlightRecorder", "PnLAttribution", "Scorecard",
    "load_decisions", "reference_histogram",
]
