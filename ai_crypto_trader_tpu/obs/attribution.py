"""Realized-PnL attribution: which signal source actually makes money.

Folds the executor's journal-durable closure records by their entry
provenance — the dominant combination FAMILY at entry (one of the 15
`ops/combinations` families the monitor now stamps on every update), the
adopted STRATEGY structure version, and the analysis MODEL version —
into per-source realized PnL, win rate and trade counts, exported as
gauges and rendered as the dashboard's "PnL attribution" card.

"Which of the 15 combination families makes money" becomes a queryable
series instead of archaeology over trade logs.  Closure records carry
their ``source`` dict through the write-ahead journal, so attribution
survives restarts exactly as far as the books do.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# source kinds folded out of each closure record
KINDS = ("family", "structure", "model", "reason")


@dataclass
class PnLAttribution:
    metrics: object = None
    # (kind, source) -> {"pnl", "trades", "wins"}
    by_source: dict = field(default_factory=dict)
    folded: int = 0

    def _sources(self, rec: dict) -> list[tuple[str, str]]:
        src = rec.get("source") or {}
        return [
            ("family", str(src.get("family") or "unattributed")),
            ("structure", str(src.get("structure_version") or "none")),
            ("model", str(src.get("model_version") or "unknown")),
            ("reason", str(rec.get("reason") or "unknown")),
        ]

    def fold_record(self, rec: dict) -> None:
        pnl = float(rec.get("pnl") or 0.0)
        win = pnl > 0.0
        for kind, source in self._sources(rec):
            slot = self.by_source.setdefault(
                (kind, source), {"pnl": 0.0, "trades": 0, "wins": 0})
            slot["pnl"] += pnl
            slot["trades"] += 1
            slot["wins"] += int(win)
            if self.metrics is not None:
                self.metrics.inc("source_trades_total",
                                 kind=kind, source=source)
        self.folded += 1

    def fold_new(self, closed_trades: list, cursor: int) -> int:
        """Fold records from ``cursor`` onward; returns the new cursor.
        The caller owns the cursor so replayed journal closures (restart)
        and live closures ride the same path."""
        for rec in closed_trades[cursor:]:
            self.fold_record(rec)
        return len(closed_trades)

    def export(self) -> None:
        m = self.metrics
        if m is None:
            return
        for (kind, source), slot in self.by_source.items():
            m.set_gauge("source_realized_pnl", slot["pnl"],
                        kind=kind, source=source)
            m.set_gauge("source_win_rate",
                        slot["wins"] / slot["trades"] if slot["trades"] else 0.0,
                        kind=kind, source=source)

    def summary(self, kind: str | None = None) -> dict:
        """{kind: {source: {pnl, trades, win_rate}}} — the dashboard card
        / ``/state.json`` payload."""
        out: dict = {}
        for (k, source), slot in sorted(self.by_source.items()):
            if kind is not None and k != kind:
                continue
            out.setdefault(k, {})[source] = {
                "pnl": round(slot["pnl"], 6),
                "trades": slot["trades"],
                "win_rate": (round(slot["wins"] / slot["trades"], 4)
                             if slot["trades"] else 0.0),
            }
        return out
