"""Feature-drift spec: the per-feature PSI the fused tick dispatch computes.

The Population Stability Index compares the LIVE distribution of a signal
feature over the candle window against a REFERENCE distribution (training
time, or the first full window observed after warm-up):

    PSI = sum_bins (p_live - p_ref) * ln(p_live / p_ref)

with epsilon smoothing so empty bins don't blow up.  The classic reading:
< 0.1 stable, 0.1–0.25 moderate shift, > 0.25 significant drift — the
``SignalDrift`` alert threshold.

The histogramming itself runs INSIDE the fused tick program
(ops/tick_engine.py `_tick_program`): each feature's [S, F, T] window is
binned against the fixed edges below and the PSI lands in the same output
pytree as every other feature — zero additional dispatches, zero
additional host readbacks.  This module only owns the spec (which
features, what ranges, how many bins) and the host-side helpers, so the
engine, the monitor, the alert rules and the tests all read one source.

Bin ranges are fixed per feature (XLA static-shape discipline: data-
dependent edges would recompile); out-of-range values clamp into the
edge bins, which is exactly what you want drift-wise — a mass migration
past the range shows up as edge-bin inflation.
"""

from __future__ import annotations

import numpy as np

N_BINS = 16
PSI_EPS = 1e-4
PSI_ALERT_THRESHOLD = 0.25

# (name, lo, hi): the engine series each row bins.  `macd_norm` is
# macd / close (the raw MACD scales with price, so BTC would always
# "drift" against any fixed range); the rest are naturally bounded.
DRIFT_FEATURES = (
    ("rsi", 0.0, 100.0),
    ("stoch_k", 0.0, 100.0),
    ("bb_position", -0.5, 1.5),
    ("macd_norm", -0.02, 0.02),
    ("volatility", 0.0, 0.05),
)


def feature_names() -> tuple:
    return tuple(name for name, _, _ in DRIFT_FEATURES)


def reference_histogram(series: dict) -> np.ndarray:
    """[K, N_BINS] reference probabilities from host-side feature arrays
    (training-time stats: pass the same features the engine computes over
    the training window).  Missing features get a uniform row — PSI
    against uniform is meaningless but bounded, and the engine's
    first-window capture will overwrite it anyway."""
    out = np.full((len(DRIFT_FEATURES), N_BINS), 1.0 / N_BINS, np.float32)
    for k, (name, lo, hi) in enumerate(DRIFT_FEATURES):
        x = series.get(name)
        if x is None:
            continue
        x = np.asarray(x, np.float64).ravel()
        x = x[np.isfinite(x)]
        if x.size == 0:
            continue
        idx = np.clip(((x - lo) / (hi - lo) * N_BINS).astype(np.int64),
                      0, N_BINS - 1)
        counts = np.bincount(idx, minlength=N_BINS).astype(np.float32)
        out[k] = counts / counts.sum()
    return out


def psi(live: np.ndarray, ref: np.ndarray) -> np.ndarray:
    """Host-side PSI twin of the in-program computation (parity tests pin
    the two equal).  ``live``/``ref`` are [..., N_BINS] probabilities."""
    p = np.asarray(live, np.float64) + PSI_EPS
    q = np.asarray(ref, np.float64) + PSI_EPS
    return ((p - q) * np.log(p / q)).sum(axis=-1)
