"""Fleet observatory: device-aggregated lane telemetry for vmapped tenants.

PR 14 turned tenants into a batch axis — thousands of decision lanes per
host as ONE `ops/tenant_engine.py` dispatch — and in doing so made the
fleet a telemetry black hole: only executable decisions leave the device,
and per-lane host gauges are impossible BY DESIGN (`utils/metrics.py`
clips every family at 512 series).  FinRL-Podracer (arXiv:2111.05188) and
Fast Population-Based RL (arXiv:2206.08888) both rest on evaluating and
*ranking* an agent population — exactly the per-lane fitness/health
signals a naive export would drop.  This module is the SIXTH observatory
(tracing, devprof, flightrec/scorecard, saturation, meshprof, and now the
fleet), riding the drift-PSI precedent: the aggregation happens INSIDE
the compiled decision program, lands in the same output pytree, and rides
the same single ``host_read`` — zero extra dispatches, zero extra syncs.

What comes off the device every decide (``device_aggregates``):

  * a **gate histogram** over the full [N, S] gate-id table (one bin per
    flight-recorder gate plus `executable` / `no_decision`), padded and
    deactivated tenants excluded by the active mask;
  * **verdict counts** — decisions, executable, starved lanes (active
    tenants whose entire symbol row produced no decision);
  * per-tenant **rolling PnL** (mark-to-market equity minus the lane's
    seeded equity) and **max drawdown**, carried in the device-resident
    balance state and reduced to fleet **dispersion quantiles** (p5 /
    p50 / p95 of PnL and balance over the tenant axis, nearest-rank);
  * ``lax.top_k`` **best / worst-K lane ids** by rolling PnL — the rank
    table the population-evolution arc (ROADMAP items 1 and 5) selects
    from.

The host side (``FleetScope``) exports O(gates + quantiles + K) metric
series for ANY tenant count — never O(N) — plus the `fleet` block on
/state.json, the `cli fleet` operator view, and the alert inputs for
FleetGateDominance / FleetPnLDispersionHigh / FleetLaneStarved /
FleetBalanceDrift (in-process rules in utils/alerts.py; PromQL twins in
monitoring/alert_rules.yml).

Per-lane provenance is SAMPLED, not dropped: a crc32-stable subset of
lanes (stable across runs and processes — no RNG, no config drift) gets
full FlightRecorder records for every decision, so ``cli why --lane N``
answers for a vmapped lane the way it already does for object lanes.

Module-global activation follows the devprof/meshprof discipline: the
disabled hot path is ONE ``active() is None`` check.
"""

from __future__ import annotations

import contextlib
import zlib
from collections import deque

import numpy as np

#: best/worst lane count in the device rank table (clamped to the padded
#: tenant axis at trace time)
TOP_K = 8
#: fleet dispersion quantiles (percent) — nearest-rank, computed on device
QUANTILES = (5, 50, 95)
_QUANT_FRACS = tuple(q / 100.0 for q in QUANTILES)
QUANTILE_LABELS = tuple(f"p{q}" for q in QUANTILES)

#: default crc32 lane-sampling rate for full decision provenance
DEFAULT_SAMPLE_RATE = 0.05
#: veto-share past which one gate counts as dominating the fleet's mix
DEFAULT_GATE_DOMINANCE = 0.95
#: PnL p95−p5 spread (quote units) past which dispersion alerts
DEFAULT_PNL_SPREAD_BUDGET = 500.0
#: engine-mirror vs venue-truth relative balance divergence budget
DEFAULT_BALANCE_DRIFT_BUDGET = 0.01

_ACTIVE: "FleetScope | None" = None


def _gate_vocab():
    from ai_crypto_trader_tpu.obs.flightrec import GATES
    return GATES


def bin_names() -> tuple:
    """Histogram bin vocabulary, in bin order: ``no_decision`` (gate id
    −2), ``executable`` (−1), then the flight recorder's GATES (ids 0…)
    — the single gate vocabulary, extended with the two non-gate
    outcomes the [N, S] table can hold."""
    return ("no_decision", "executable") + tuple(_gate_vocab())


def device_aggregates(*, gate, pnl, balance, max_drawdown, active,
                      quarantined=None, k: int | None = None) -> dict:
    """The traced fleet reduction — called INSIDE the tenant engine's
    compiled decide program (the drift-PSI pattern: this module owns the
    math, the engine owns the dispatch).

    ``gate`` is the [N, S] i8 gate-id table; ``pnl`` / ``balance`` /
    ``max_drawdown`` / ``active`` / ``quarantined`` are [N] over the
    padded tenant axis.  Padded and deactivated tenants
    (``active=False``) are excluded from every aggregate.  Quarantined
    lanes stay in the gate histogram (their `lane_quarantined` verdicts
    ARE the fleet's containment signal) but are masked out of the value
    aggregates — their poisoned PnL/balance must not smear NaN over the
    healthy fleet's dispersion and rank table (blast radius = the
    faulted lane, in telemetry too).  Returns a pytree of
    O(gates + quantiles + K) scalars/small vectors that rides the
    engine's single host_read."""
    import jax.numpy as jnp
    from jax import lax

    n_gates = len(_gate_vocab())
    act = active.astype(bool)
    n_act = act.astype(jnp.int32).sum()
    if quarantined is None:
        healthy = act
        n_quar = jnp.int32(0)
    else:
        q = quarantined.astype(bool)
        healthy = act & ~q
        n_quar = (act & q).astype(jnp.int32).sum()
    n_healthy = healthy.astype(jnp.int32).sum()
    # histogram over gate ids −2 … n_gates−1, active tenants only
    ids = jnp.arange(-2, n_gates, dtype=gate.dtype)
    hist = ((gate[None, :, :] == ids[:, None, None])
            & act[None, :, None]).sum(axis=(1, 2)).astype(jnp.int32)
    decisions = hist[2:].sum() + hist[1]      # everything but no_decision
    executable = hist[1]
    # starved: active lanes whose whole symbol row produced no decision
    starved = (act & (gate == jnp.int8(-2)).all(axis=1)) \
        .astype(jnp.int32).sum()

    def quantiles(vals):
        # nearest-rank over the healthy rows: masked rows sort to +inf,
        # indices derive from the HEALTHY count (a traced scalar) — the
        # numpy twin in host_aggregates uses the identical formula
        v = jnp.sort(jnp.where(healthy, vals, jnp.inf))
        idx = jnp.clip(
            jnp.round(jnp.asarray(_QUANT_FRACS)
                      * jnp.maximum(n_healthy - 1, 0)).astype(jnp.int32),
            0, v.shape[0] - 1)
        return jnp.where(n_healthy > 0, v[idx], jnp.nan)

    k_eff = min(int(k if k is not None else TOP_K), int(pnl.shape[0]))
    best_pnl, best_lane = lax.top_k(jnp.where(healthy, pnl, -jnp.inf),
                                    k_eff)
    worst_neg, worst_lane = lax.top_k(jnp.where(healthy, -pnl, -jnp.inf),
                                      k_eff)
    dd = jnp.where(healthy, max_drawdown, -jnp.inf)
    return {
        "gate_hist": hist,
        "decisions": decisions.astype(jnp.int32),
        "executable": executable.astype(jnp.int32),
        "starved": starved,
        "active": n_act,
        "quarantined": n_quar,
        "pnl_q": quantiles(pnl),
        "balance_q": quantiles(balance),
        "max_drawdown_max": jnp.where(n_healthy > 0, dd.max(), jnp.nan),
        "best_pnl": best_pnl,
        "best_lane": best_lane.astype(jnp.int32),
        "worst_pnl": -worst_neg,
        "worst_lane": worst_lane.astype(jnp.int32),
    }


def host_aggregates(*, gate, pnl, balance, max_drawdown, active,
                    quarantined=None, k: int | None = None) -> dict:
    """NumPy twin of :func:`device_aggregates` — the parity oracle the
    tests recompute from the host-read decision table.  Bit-identical
    semantics (same nearest-rank formula, same masking — quarantined
    lanes counted in the histogram, excluded from values), independent
    implementation."""
    gate = np.asarray(gate)
    act = np.asarray(active, bool)
    n_gates = len(_gate_vocab())
    n_act = int(act.sum())
    if quarantined is None:
        healthy = act
        n_quar = 0
    else:
        q = np.asarray(quarantined, bool)
        healthy = act & ~q
        n_quar = int((act & q).sum())
    n_healthy = int(healthy.sum())
    ids = np.arange(-2, n_gates)
    hist = np.array([int(((gate == g) & act[:, None]).sum()) for g in ids],
                    np.int32)
    starved = int((act & (gate == -2).all(axis=1)).sum())

    def quantiles(vals):
        v = np.sort(np.where(healthy, np.asarray(vals, np.float64),
                             np.inf))
        idx = np.clip(np.round(np.asarray(_QUANT_FRACS)
                               * max(n_healthy - 1, 0)).astype(np.int64),
                      0, v.shape[0] - 1)
        return (v[idx] if n_healthy > 0
                else np.full(len(_QUANT_FRACS), np.nan))

    k_eff = min(int(k if k is not None else TOP_K), int(len(pnl)))
    pnl = np.asarray(pnl, np.float64)
    # ±inf masking mirrors the device exactly: tail ranks beyond the
    # healthy count read ∓inf, never a masked lane's stale real PnL
    best_vals = np.where(healthy, pnl, -np.inf)
    worst_vals = np.where(healthy, pnl, np.inf)
    best = np.argsort(-best_vals, kind="stable")[:k_eff]
    worst = np.argsort(worst_vals, kind="stable")[:k_eff]
    return {
        "gate_hist": hist,
        "decisions": int(hist[1:].sum()),
        "executable": int(hist[1]),
        "starved": starved,
        "active": n_act,
        "quarantined": n_quar,
        "pnl_q": quantiles(pnl),
        "balance_q": quantiles(balance),
        "max_drawdown_max": (float(np.max(np.asarray(max_drawdown)[healthy]))
                             if n_healthy else float("nan")),
        "best_pnl": best_vals[best],
        "best_lane": best.astype(np.int32),
        "worst_pnl": worst_vals[worst],
        "worst_lane": worst.astype(np.int32),
    }


def lane_sampled(lane: int, rate: float = DEFAULT_SAMPLE_RATE) -> bool:
    """crc32-stable lane sampling: deterministic across runs, processes
    and hosts (no RNG state, no seed to drift), uniform-ish over lane
    ids.  A lane keeps (or loses) its full provenance for life — the
    property that makes `cli why --lane N` answerable after a restart."""
    return zlib.crc32(b"fleet-lane-%d" % int(lane)) % 10_000 \
        < int(rate * 10_000)


class FleetScope:
    """Host half of the fleet observatory: bounded-cardinality export,
    rolling alert windows, the /state.json ``fleet`` block and the lane
    sample.

    Feed it once per decide with :meth:`observe_decide` (the tenant
    engine does this behind the module-global one-check); everything it
    publishes is O(gates + quantiles + K) series regardless of how many
    tenants the device evaluated."""

    def __init__(self, metrics=None, *, top_k: int = TOP_K,
                 sample_rate: float = DEFAULT_SAMPLE_RATE,
                 window: int = 64, min_decides: int = 8,
                 min_vetoes: int = 32,
                 gate_dominance_threshold: float = DEFAULT_GATE_DOMINANCE,
                 pnl_spread_budget: float = DEFAULT_PNL_SPREAD_BUDGET,
                 balance_drift_budget: float = DEFAULT_BALANCE_DRIFT_BUDGET):
        self.metrics = metrics
        self.top_k = int(top_k)
        self.sample_rate = float(sample_rate)
        self.window = int(window)
        self.min_decides = int(min_decides)
        self.min_vetoes = int(min_vetoes)
        self.gate_dominance_threshold = float(gate_dominance_threshold)
        self.pnl_spread_budget = float(pnl_spread_budget)
        self.balance_drift_budget = float(balance_drift_budget)
        self.decides = 0
        self.tenants = 0
        self.last: dict = {}                 # newest decide's summary
        self._hist_window: deque = deque(maxlen=self.window)
        self._starved_window: deque = deque(maxlen=self.window)
        self._drift_window: deque = deque(maxlen=self.window)
        self._sample_cache: tuple | None = None   # (n, lanes)
        self._rank_hwm: dict = {}            # extreme -> max rank exported

    # -- lane sampling -------------------------------------------------------
    def sampled(self, lane: int) -> bool:
        return lane_sampled(lane, self.sample_rate)

    def sample_lanes(self, n_tenants: int) -> list[int]:
        """The deterministic provenance sample for an N-tenant fleet."""
        if self._sample_cache and self._sample_cache[0] == n_tenants:
            return self._sample_cache[1]
        lanes = [i for i in range(int(n_tenants)) if self.sampled(i)]
        self._sample_cache = (int(n_tenants), lanes)
        return lanes

    # -- per-decide fold -----------------------------------------------------
    def veto_counts(self, fleet: dict) -> dict:
        """{gate_name: count} from the DEVICE gate histogram — the
        replacement for the host-side [N, S] table scan
        (`TenantEngine.veto_counts`): one dict of at most len(GATES)
        entries per tick, zero per-lane host work."""
        hist = np.asarray(fleet["gate_hist"], np.int64)
        names = bin_names()
        return {names[i]: int(hist[i]) for i in range(2, len(names))
                if hist[i] > 0}

    def observe_decide(self, fleet: dict, *, tenants: int,
                       balance_drift: float = 0.0,
                       balance_resyncs: int = 0,
                       quarantined: int | None = None,
                       heals: int = 0) -> None:
        """Fold one decide's device aggregates into the rolling windows
        and export the gauges.  ``balance_drift`` is the worst relative
        engine-mirror vs venue-truth divergence the rim re-anchored
        since the previous decide (0.0 = mirrors agreed);
        ``quarantined`` / ``heals`` are the engine's host-mirror
        containment counters (quarantined defaults to the device count
        when the caller doesn't override)."""
        hist = np.asarray(fleet["gate_hist"], np.int64)
        self.decides += 1
        self.tenants = int(tenants)
        self._hist_window.append(hist)
        decisions = int(fleet["decisions"])
        # a decide with no decisions at all (warming universe / outage)
        # must not mark every lane starved — the starvation signal is
        # "the fleet decided, this lane didn't"
        self._starved_window.append(int(fleet["starved"])
                                    if decisions > 0 else 0)
        self._drift_window.append(max(float(balance_drift), 0.0))
        n_act = int(fleet["active"])
        k = min(self.top_k, n_act)
        self.last = {
            "tenants": self.tenants,
            "active_lanes": n_act,
            "decisions": decisions,
            "executable": int(fleet["executable"]),
            # this decide's RAW count; the alerting value is the
            # windowed min (`starved_lanes()`) — distinct keys so the
            # status() merge can never shadow the gated signal
            "starved_last_decide": int(fleet["starved"]),
            "pnl": dict(zip(QUANTILE_LABELS,
                            [round(float(v), 6)
                             for v in np.asarray(fleet["pnl_q"])])),
            "balance": dict(zip(QUANTILE_LABELS,
                                [round(float(v), 6)
                                 for v in np.asarray(fleet["balance_q"])])),
            "max_drawdown_max": round(float(fleet["max_drawdown_max"]), 6),
            "best": [{"lane": int(l), "pnl": round(float(p), 6)}
                     for l, p in zip(np.asarray(fleet["best_lane"])[:k],
                                     np.asarray(fleet["best_pnl"])[:k])],
            "worst": [{"lane": int(l), "pnl": round(float(p), 6)}
                      for l, p in zip(np.asarray(fleet["worst_lane"])[:k],
                                      np.asarray(fleet["worst_pnl"])[:k])],
            "balance_resyncs": int(balance_resyncs),
            "quarantined_lanes": int(quarantined
                                     if quarantined is not None
                                     else fleet.get("quarantined", 0)),
            "heals_total": int(heals),
        }
        self.export()

    # -- rolling views -------------------------------------------------------
    def gate_mix(self) -> dict:
        """{bin_name: windowed count} over the histogram window —
        includes the `executable` / `no_decision` outcomes."""
        if not self._hist_window:
            return {}
        total = np.sum(np.stack(self._hist_window), axis=0)
        return {name: int(c) for name, c in zip(bin_names(), total) if c}

    def gate_dominance(self) -> tuple[str | None, float]:
        """(dominant veto gate, its share of the windowed VETO mix).
        Share is 0.0 until the window holds ``min_vetoes`` vetoes — one
        cold tick of nan_gate must never page (the burn-alert
        discipline)."""
        if not self._hist_window:
            return None, 0.0
        total = np.sum(np.stack(self._hist_window), axis=0)
        vetoes = total[2:]                    # gate bins only
        n_vetoes = int(vetoes.sum())
        if n_vetoes < self.min_vetoes:
            return None, 0.0
        top = int(np.argmax(vetoes))
        return bin_names()[2 + top], float(vetoes[top]) / n_vetoes

    def starved_lanes(self) -> int:
        """Windowed MIN of the per-decide starved-lane count (min-sample
        gated): a nonzero value means some lanes produced no decision in
        EVERY decide of the window — sustained starvation, not one
        throttled tick."""
        if len(self._starved_window) < self.min_decides:
            return 0
        return int(min(self._starved_window))

    def pnl_spread(self) -> float:
        pnl = self.last.get("pnl") or {}
        lo, hi = pnl.get(QUANTILE_LABELS[0]), pnl.get(QUANTILE_LABELS[-1])
        if lo is None or hi is None or not np.isfinite([lo, hi]).all():
            return 0.0
        return float(hi - lo)

    def balance_drift_max(self) -> float:
        return float(max(self._drift_window, default=0.0))

    # -- export surfaces -----------------------------------------------------
    def export(self) -> None:
        """Publish the fleet gauges: O(gates + quantiles + K) series for
        any N (the bounded-cardinality contract the tests pin at
        N=1000)."""
        m = self.metrics
        if m is None or not self.last:
            return
        last = self.last
        m.inc("fleet_decides_total")
        m.inc("fleet_decisions_total", last["decisions"])
        m.set_gauge("fleet_tenants", last["tenants"])
        m.set_gauge("fleet_active_lanes", last["active_lanes"])
        m.set_gauge("fleet_executable", last["executable"])
        m.set_gauge("fleet_starved_lanes", self.starved_lanes())
        m.set_gauge("fleet_quarantined_lanes",
                    last.get("quarantined_lanes", 0))
        m.set_gauge("fleet_heals_total", last.get("heals_total", 0))
        dom_gate, dom = self.gate_dominance()
        m.set_gauge("fleet_gate_dominance", dom)
        m.set_gauge("fleet_pnl_spread", self.pnl_spread())
        m.set_gauge("fleet_balance_drift_max", self.balance_drift_max())
        if np.isfinite(last["max_drawdown_max"]):
            m.set_gauge("fleet_max_drawdown", last["max_drawdown_max"])
        mix = self.gate_mix()
        total = sum(mix.values()) or 1
        for name in bin_names():
            # EVERY bin exported every time (0 when absent): a gate that
            # leaves the window must not freeze its last nonzero share
            # in Prometheus — the series set is bounded by the vocabulary
            m.set_gauge("fleet_gate_share", mix.get(name, 0) / total,
                        gate=name)
        for label in QUANTILE_LABELS:
            v = last["pnl"].get(label)
            if v is not None and np.isfinite(v):
                m.set_gauge("fleet_pnl_quantile", v, q=label)
            v = last["balance"].get(label)
            if v is not None and np.isfinite(v):
                m.set_gauge("fleet_balance_quantile", v, q=label)
        for extreme, rows in (("best", last["best"]),
                              ("worst", last["worst"])):
            for rank, row in enumerate(rows):
                m.set_gauge("fleet_lane_pnl", row["pnl"],
                            extreme=extreme, rank=rank)
                m.set_gauge("fleet_lane_id", row["lane"],
                            extreme=extreme, rank=rank)
            # a shrunk fleet must not leave the old fleet's tail ranks
            # frozen: ranks beyond the current table read as empty
            # (lane −1, pnl 0) up to the high-water rank ever exported
            hwm = self._rank_hwm.get(extreme, 0)
            for rank in range(len(rows), hwm):
                m.set_gauge("fleet_lane_pnl", 0.0,
                            extreme=extreme, rank=rank)
                m.set_gauge("fleet_lane_id", -1,
                            extreme=extreme, rank=rank)
            self._rank_hwm[extreme] = max(hwm, len(rows))

    def alert_state(self) -> dict:
        """Inputs for the in-process FleetGateDominance /
        FleetPnLDispersionHigh / FleetLaneStarved / FleetBalanceDrift
        rules (utils/alerts.py default_rules) — thresholds ride along so
        the rules evaluate THIS scope's configuration, not a second
        hardcoded constant (the saturation/loop-lag pattern)."""
        gate, dominance = self.gate_dominance()
        return {
            "fleet_gate_dominance": dominance,
            "fleet_dominant_gate": gate,
            "fleet_gate_dominance_threshold": self.gate_dominance_threshold,
            "fleet_pnl_spread": self.pnl_spread(),
            "fleet_pnl_spread_budget": self.pnl_spread_budget,
            "fleet_starved_lanes": self.starved_lanes(),
            "fleet_balance_drift": self.balance_drift_max(),
            "fleet_balance_drift_budget": self.balance_drift_budget,
            "fleet_quarantined_lanes": int(
                self.last.get("quarantined_lanes", 0)),
            "fleet_heals_total": int(self.last.get("heals_total", 0)),
        }

    def status(self) -> dict:
        """The `fleet` block on /state.json (and `cli fleet`'s source):
        rank tables, gate mix, dispersion, starvation and drift — all
        O(gates + quantiles + K) JSON.  The sampled-lane list is CAPPED
        (the full sample is O(rate × N) — embedding it would break the
        bound this block promises); `sampled_lane_count` carries the
        true size and `FleetScope.sample_lanes()` the full list."""
        gate, dominance = self.gate_dominance()
        sampled = self.sample_lanes(self.tenants)
        return {
            "decides": self.decides,
            "tenants": self.tenants,
            "sample_rate": self.sample_rate,
            "sampled_lanes": sampled[:32],
            "sampled_lane_count": len(sampled),
            "gate_mix": self.gate_mix(),
            "dominant_gate": gate,
            "gate_dominance": round(dominance, 4),
            "pnl_spread": round(self.pnl_spread(), 6),
            "starved_lanes": self.starved_lanes(),
            "balance_drift_max": round(self.balance_drift_max(), 8),
            **{k: v for k, v in self.last.items()},
        }


# -- module-level hot-path API (single-check disabled path) ------------------

def configure(fs: "FleetScope | None") -> "FleetScope | None":
    """Install ``fs`` as the process-wide active fleet observatory
    (``None`` disables — the tenant engine's next dispatch drops the
    fleet block, a declared-cold recompile)."""
    global _ACTIVE
    _ACTIVE = fs
    return fs


def disable() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> "FleetScope | None":
    return _ACTIVE


@contextlib.contextmanager
def use(fs: "FleetScope | None"):
    """Scoped activation (tests / load harness): restores the previous
    instance on exit."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = fs
    try:
        yield fs
    finally:
        _ACTIVE = prev
