"""Decision-provenance flight recorder: signal → order → fill → PnL.

One compact record per (symbol, tick) decision, keyed by the tick's
trace_id when tracing is on (a fresh id otherwise), capturing

  * the fused tick engine's feature/confluence slice for the symbol,
  * each architecture's live prediction (the ``nn_prediction_*`` bus
    snapshot at decision time),
  * the analyzer verdict plus the structured explanation
    (strategy/explain.py), and
  * the terminal outcome: either the REJECTING GATE (which check vetoed
    — confidence floor, strength floor, NaN gate, pending-intent park,
    quarantine, …) or the execution chain — the WAL client_order_id,
    the entry fill, and eventually the realized closure PnL.

Two sinks, mirroring utils/tracing.py: a bounded in-memory ring (the
dashboard's ``/decisions?symbol=&trace_id=`` endpoint and ``cli why``)
and an optional append-only JSONL in the utils/journal.py checksummed
record format — so a torn tail from a crash is detected, replay is
shared code, and the provenance chain survives restarts (the chaos soak
asserts it).  Execution/fill/closure records flush write-through (they
are rare and must survive a kill, like the executor's order intents);
veto records batch.

The recorder is DEFAULT-ON in the launcher.  The disabled path follows
the tracing/devprof discipline: services hold a ``flightrec`` attribute
and every hot-path call site is one ``None`` check.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque

from ai_crypto_trader_tpu.utils import journal as journal_mod
from ai_crypto_trader_tpu.utils import tracing

# Gate vocabulary (the bounded label set decision_vetoes_total uses).
GATES = (
    "analysis_interval",      # analyzer per-symbol cadence gate
    "outcome_probability",    # trade-outcome model downgraded BUY → HOLD
    "nan_gate",               # non-finite price/feature poisoned payload
    "confidence_floor",       # AI confidence below threshold
    "strength_floor",         # technical signal strength below floor
    "signal_disagreement",    # technical signal != AI decision
    "not_buy",                # agreed decision is HOLD/SELL
    "position_open",          # symbol already holds a position
    "pending_intent",         # unresolved ambiguous order parks entry
    "max_positions",          # position slots exhausted
    "risk_min_size",          # sized below min_trade_amount
    "entry_rejected",         # venue rejected the entry order
    "quarantine",             # executor stage quarantined mid-flight
    # appended (not inserted): gate ids are positional indices into this
    # tuple and live in journaled records — reordering would rewrite
    # history's meaning on replay
    "lane_quarantined",       # vmapped lane poisoned (NaN/Inf state or
    #                           params) — masked out of sizing/entry
    #                           until the host healer re-seeds it
)

# Executor gate evaluation ORDER — the priority in which
# `TradeExecutor.veto_reason` + its sizing gate test a signal, and the
# priority the vmapped tenant engine's traced predicates resolve in
# (ops/tenant_engine.py).  Both implementations derive from THIS tuple so
# the recorded gate can never depend on which path decided; the
# gate-for-gate parity sweep in tests/test_tenant_engine.py pins it.
VETO_ORDER = (
    # containment outranks every market gate: a quarantined lane's state
    # is not trustworthy enough to EVALUATE the other predicates, so its
    # decisions resolve here first (ops/tenant_engine.py traces this as
    # the lane-wide quarantine bit; object lanes never set it — a single
    # Python executor has no lane neighbors to be contained from)
    "lane_quarantined",
    "nan_gate",
    "confidence_floor",
    "strength_floor",
    "not_buy",
    "signal_disagreement",
    "position_open",
    "pending_intent",
    "max_positions",
    "risk_min_size",
)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class FlightRecorder:
    """Ring + journal-backed decision recorder.

    ``path=None`` keeps the ring only (tests, ad-hoc systems); with a
    path every terminal decision/fill/closure lands as a checksummed
    JSONL record replayable by :func:`load_decisions`.
    """

    def __init__(self, path: str | None = None, metrics=None,
                 now_fn=time.time, ring_size: int = 4096,
                 fsync_every: int = 64, id_fn=_new_id):
        self.metrics = metrics
        self.now_fn = now_fn
        self.ring_size = int(ring_size)
        self._id_fn = id_fn
        self.journal = (journal_mod.WriteAheadJournal(
            path, fsync_every=fsync_every, now_fn=now_fn)
            if path else None)
        self._ring: deque = deque()
        self._by_id: dict = {}              # open/ring records by id
        self._by_coid: dict = {}            # entry client_order_id -> id
        self._lock = threading.Lock()
        self.recorded = 0
        self.vetoed = 0
        self.executed = 0
        self.closed = 0
        # analysis-interval throttle hits, counted per symbol instead of
        # recorded: one fires per symbol per POLL — orders of magnitude
        # more often than real decisions, with no decision content.  Full
        # records would dominate the ring (evicting executed records
        # before their closures attach) and bloat the JSONL with ticks.
        self.throttled_counts: dict = {}    # symbol -> [n, last_t]

    # -- record lifecycle ----------------------------------------------------
    def _blank(self, symbol: str | None, decision_id: str | None = None,
               trace_fallback: bool = False,
               lane: int | None = None) -> dict:
        """One decision record in the canonical shape.  Synthetic records
        (veto/execution on an id the ring no longer holds — post-restart
        paths) leave trace_id None when no trace is active, so a journal
        re-append never clobbers the original record's trace on replay.
        ``lane`` tags a vmapped tenant lane's sampled decision
        (obs/fleetscope.py provenance sampling) so `cli why --lane N`
        can filter the fleet the way `--symbol` filters the universe."""
        sp = tracing.current()
        trace_id = sp.trace_id if sp is not None and sp.trace_id else None
        if trace_id is None and trace_fallback:
            trace_id = self._id_fn()
        return {
            "id": decision_id or self._id_fn(),
            "trace_id": trace_id,
            "symbol": symbol,
            "lane": lane,
            "t": self.now_fn(),
            "features": {},
            "predictions": {},
            "verdict": None,
            "explanation": None,
            "event_age_ms": None,
            "gate": None,
            "gate_detail": None,
            "status": "open",
            "exec": None,
            "fills": [],
            "closure": None,
        }

    def begin(self, symbol: str, features: dict | None = None,
              predictions: dict | None = None,
              verdict: dict | None = None,
              explanation: dict | None = None,
              lane: int | None = None,
              event_age_ms: float | None = None) -> str:
        """Open a decision record; returns its id (the analyzer stamps it
        onto the published signal as ``decision_id`` so the executor can
        finalize the same record).  ``event_age_ms`` is the venue-E →
        decision age the tickpath observatory clamped/folded
        (obs/tickpath.py) — None when that observatory is off."""
        rec = self._blank(symbol, trace_fallback=True, lane=lane)
        rec["features"] = features or {}
        rec["predictions"] = predictions or {}
        rec["verdict"] = verdict
        rec["explanation"] = explanation
        rec["event_age_ms"] = event_age_ms
        with self._lock:
            self._append(rec)
        self.recorded += 1
        if self.metrics is not None:
            self.metrics.inc("decisions_recorded_total", symbol=symbol)
        return rec["id"]

    def _append(self, rec: dict) -> None:
        self._ring.append(rec)
        self._by_id[rec["id"]] = rec
        if len(self._ring) > self.ring_size:
            old = self._ring.popleft()
            self._by_id.pop(old["id"], None)
            coid = (old.get("exec") or {}).get("client_order_id")
            if coid is not None:
                self._by_coid.pop(coid, None)

    def set_verdict(self, decision_id: str | None, verdict: dict,
                    explanation: dict | None = None) -> None:
        rec = self._by_id.get(decision_id)
        if rec is None:
            return
        rec["verdict"] = verdict
        if explanation is not None:
            # the structured explanation is large; keep the queryable core
            rec["explanation"] = {
                "supporting_factors": explanation.get("supporting_factors"),
                "narrative": explanation.get("narrative"),
            }

    def veto(self, decision_id: str | None, gate: str,
             detail: str | None = None, symbol: str | None = None) -> None:
        """Terminal: the decision was rejected by ``gate``."""
        rec = self._by_id.get(decision_id)
        if rec is None:
            if decision_id is None and symbol is None:
                return
            rec = self._blank(symbol, decision_id)
            with self._lock:
                self._append(rec)
        if rec["status"] == "vetoed":
            return                      # first gate wins (the informative
            #                             one — e.g. outcome_probability
            #                             before the executor's not_buy)
        rec["gate"] = gate
        rec["gate_detail"] = detail
        rec["status"] = "vetoed"
        self.vetoed += 1
        if self.metrics is not None:
            self.metrics.inc("decision_vetoes_total", gate=gate)
        if self.journal is not None:
            self.journal.append("decision", rec)

    def throttled(self, symbol: str) -> None:
        """The analyzer's per-poll cadence gate: counted (the
        ``decision_vetoes_total{gate="analysis_interval"}`` rate series
        and a per-symbol summary in ``why()``) but never recorded — see
        ``throttled_counts`` above."""
        slot = self.throttled_counts.setdefault(symbol, [0, 0.0])
        slot[0] += 1
        slot[1] = self.now_fn()
        if self.metrics is not None:
            self.metrics.inc("decision_vetoes_total",
                             gate="analysis_interval")

    def execution(self, decision_id: str | None, client_order_id: str,
                  symbol: str | None = None, **exec_info) -> None:
        """Terminal (for the decision): an entry order is about to reach
        the venue under ``client_order_id``.  Durable BEFORE placement
        (flush) so a kill in the placement window cannot orphan the
        venue-side fill from its provenance."""
        rec = self._by_id.get(decision_id)
        if rec is None:
            rec = self._blank(symbol, decision_id)
            with self._lock:
                self._append(rec)
        if rec["status"] == "vetoed":
            # a quarantine-parked decision drained after the stage came
            # back: the execution supersedes the provisional veto — an
            # executed record must not carry a gate
            self.vetoed -= 1
            rec["gate"] = None
            rec["gate_detail"] = None
        rec["exec"] = {"client_order_id": client_order_id, **exec_info}
        rec["status"] = "executed"
        with self._lock:
            self._by_coid[client_order_id] = rec["id"]
        self.executed += 1
        if self.metrics is not None:
            self.metrics.inc("decisions_executed_total",
                             symbol=rec.get("symbol") or symbol or "")
        if self.journal is not None:
            self.journal.append("decision", rec, flush=True)

    def fill(self, client_order_id: str, price: float, quantity: float,
             symbol: str | None = None) -> None:
        """Entry fill for a recorded client_order_id (live ack or the
        recovery path adopting a fill that landed while we were down)."""
        data = {"client_order_id": client_order_id, "price": float(price),
                "quantity": float(quantity), "symbol": symbol,
                "t": self.now_fn()}
        rid = self._by_coid.get(client_order_id)
        rec = self._by_id.get(rid)
        if rec is not None:
            rec["fills"].append(data)
        if self.journal is not None:
            self.journal.append("fill", data, flush=True)

    def closure(self, client_order_id: str | None, symbol: str,
                exit_price: float, pnl: float, reason: str) -> None:
        """Realized closure of the position opened by
        ``client_order_id`` — completes the provenance chain."""
        data = {"client_order_id": client_order_id, "symbol": symbol,
                "exit_price": float(exit_price), "pnl": float(pnl),
                "reason": reason, "t": self.now_fn()}
        rid = self._by_coid.get(client_order_id)
        rec = self._by_id.get(rid)
        if rec is not None:
            rec["closure"] = data
            rec["status"] = "closed"
        self.closed += 1
        if self.metrics is not None:
            self.metrics.inc("decision_closures_total", symbol=symbol)
        if self.journal is not None:
            self.journal.append("closure", data, flush=True)

    def mark_open(self, gate: str, detail: str | None = None) -> int:
        """Veto every still-open record (the executor-quarantine path:
        published signals that will not be drained while the stage is
        quarantined get their gate recorded instead of dangling)."""
        n = 0
        with self._lock:
            opens = [r for r in self._ring if r["status"] == "open"]
        for rec in opens:
            self.veto(rec["id"], gate, detail=detail)
            n += 1
        return n

    # -- queries -------------------------------------------------------------
    def query(self, symbol: str | None = None, trace_id: str | None = None,
              limit: int = 50, lane: int | None = None) -> list[dict]:
        """Newest-first decision records filtered by symbol / trace_id /
        sampled tenant lane."""
        with self._lock:
            records = list(self._ring)
        out = []
        for rec in reversed(records):
            if symbol is not None and rec.get("symbol") != symbol:
                continue
            if trace_id is not None and rec.get("trace_id") != trace_id:
                continue
            if lane is not None and rec.get("lane") != lane:
                continue
            out.append(rec)
            if limit and len(out) >= limit:
                break
        return out

    def why(self, symbol: str, n: int = 10) -> list[str]:
        lines = format_why(self.query(symbol=symbol, limit=n))
        thr = self.throttled_counts.get(symbol)
        if thr:
            stamp = time.strftime("%H:%M:%S", time.gmtime(thr[1]))
            lines.append(f"({thr[0]} polls throttled by analysis_interval, "
                         f"last at {stamp})")
        return lines

    def status(self) -> dict:
        with self._lock:
            ring = len(self._ring)
        return {"recorded": self.recorded, "vetoed": self.vetoed,
                "executed": self.executed, "closed": self.closed,
                "throttled": sum(v[0] for v in
                                 self.throttled_counts.values()),
                "ring": ring,
                "journal": self.journal.path if self.journal else None}

    def export(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("flightrec_ring_size", len(self._ring))

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()


def load_decisions(path: str) -> tuple[list[dict], dict]:
    """Replay a flight-recorder JSONL into joined decision records.

    Uses the journal's checksummed replay (torn tails dropped, mid-file
    corruption counted), then joins ``fill``/``closure`` records onto
    their decision via the entry client_order_id — the restart-surviving
    twin of the in-memory ring.  Returns (records, replay_stats)."""
    raw, stats = journal_mod.replay(path)
    records: list[dict] = []
    by_id: dict = {}
    by_coid: dict = {}
    for rec in raw:
        kind, d = rec.get("kind"), rec.get("data", {})
        if kind == "decision":
            prev = by_id.get(d.get("id"))
            if prev is not None:
                # veto→execution re-append updates.  The terminal triple
                # is taken verbatim (veto and execution write all three
                # consistently — an execution superseding a quarantine
                # veto must CLEAR the gate); other fields merge non-empty
                # only, so a post-restart SYNTHETIC veto (ring lost in the
                # crash: features/exec/trace empty) can't erase content.
                for k, v in d.items():
                    if k in ("status", "gate", "gate_detail") or v \
                            or k not in prev:
                        prev[k] = v
                d = prev
            else:
                records.append(d)
                by_id[d.get("id")] = d
            coid = (d.get("exec") or {}).get("client_order_id")
            if coid:
                by_coid[coid] = d
        elif kind == "fill":
            parent = by_coid.get(d.get("client_order_id"))
            if parent is not None:
                parent.setdefault("fills", []).append(d)
        elif kind == "closure":
            parent = by_coid.get(d.get("client_order_id"))
            if parent is not None:
                parent["closure"] = d
                parent["status"] = "closed"
            else:
                # closure whose decision predates the file (rotation) —
                # kept as a standalone record so PnL provenance is never
                # silently dropped
                records.append({"id": None, "symbol": d.get("symbol"),
                                "status": "closed", "gate": None,
                                "exec": {"client_order_id":
                                         d.get("client_order_id")},
                                "fills": [], "closure": d,
                                "orphan_closure": True})
    return records, stats


def format_why(records: list[dict]) -> list[str]:
    """Human lines for ``cli why`` / the recorder's ``why()``: one line
    per decision with its outcome, plus the explanation narrative."""
    lines = []
    for rec in records:
        t = rec.get("t")
        stamp = (time.strftime("%H:%M:%S", time.gmtime(t))
                 if isinstance(t, (int, float)) else "--:--:--")
        head = f"{stamp} {rec.get('symbol')} "
        if rec.get("lane") is not None:
            head += f"[lane {rec['lane']}] "
        verdict = rec.get("verdict") or {}
        if rec.get("status") == "vetoed":
            detail = f" ({rec['gate_detail']})" if rec.get("gate_detail") else ""
            head += f"VETO [{rec.get('gate')}]{detail}"
        elif rec.get("status") in ("executed", "closed"):
            ex = rec.get("exec") or {}
            head += f"EXECUTED {ex.get('client_order_id')}"
            fills = rec.get("fills") or []
            if fills:
                head += (f" filled {fills[0].get('quantity', 0):.6g}"
                         f" @ {fills[0].get('price', 0):,.2f}")
            closure = rec.get("closure")
            if closure:
                head += (f" → {closure.get('reason')} "
                         f"pnl {closure.get('pnl', 0):+,.2f}")
        else:
            head += "PENDING"
        if verdict:
            head += (f" | {verdict.get('decision', '?')}"
                     f" conf {verdict.get('confidence', 0):.2f}")
        lines.append(head)
        narrative = (rec.get("explanation") or {}).get("narrative")
        if narrative:
            lines.append(f"    {narrative}")
    return lines
