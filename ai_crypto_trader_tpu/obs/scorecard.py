"""Live prediction-outcome scoring: the model-quality scorecard.

The prediction service publishes a price forecast per (symbol, interval)
with an explicit horizon; nothing ever checked whether those forecasts
come true.  The scorecard closes the loop ON THE DATA ALREADY IN MEMORY:
when a prediction's horizon elapses, the realized candle is read from
the monitor's bus kline window (no extra venue I/O) and the outcome
feeds rolling windows per (architecture, symbol, interval):

  * directional accuracy — sign(predicted − reference) vs realized,
  * hit rate             — |predicted − realized| within ``hit_tolerance``,
  * Brier score          — mean (confidence − correct)², the calibration
                           error the ``ModelCalibrationBreach`` alert
                           watches (a model that says 0.9 and is right
                           half the time scores ~0.33).

Everything is keyed by the klines' own timestamps (milliseconds), so a
virtual paper clock and a real wall clock behave identically.

The scorecard is also the live half of the registry/hot-swap quality
gate: ``adoption_gate`` compares a candidate architecture's live score
against the incumbent's, and the prediction service refuses an HPO
winner that is measurably WORSE live than what it would replace
(models/service.py).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field


def _sign(x: float) -> int:
    return (x > 0) - (x < 0)


@dataclass
class Scorecard:
    bus: object = None
    metrics: object = None
    now_fn: object = time.time
    window: int = 256              # outcomes kept per (arch, symbol, interval)
    min_samples: int = 16          # below this, scores are not "live" yet
    hit_tolerance: float = 0.005   # |pred-realized|/realized for a "hit"
    # a prediction whose realized candle never shows up (symbol dropped,
    # venue gap) expires after this many horizons instead of leaking
    expire_horizons: float = 50.0

    _pending: dict = field(default_factory=dict)   # (s, iv, ref_ts) -> payload
    _last_ref: dict = field(default_factory=dict)  # (s, iv) -> newest ref_ts
    _stats: dict = field(default_factory=dict)     # (arch, s, iv) -> deque
    resolved_total: int = 0
    expired_total: int = 0
    # adoption-gate verdict trail (bounded): every candidate that passed
    # through `adoption_gate` on its way into the registry — the PBT
    # trainer records each generation's winner here so operators can see
    # WHY a policy went active or shadow without grepping a journal
    adoptions: deque = field(default_factory=lambda: deque(maxlen=64))

    # -- intake --------------------------------------------------------------
    def record_prediction(self, payload: dict) -> bool:
        """Register one prediction for future resolution.  Needs the
        explicit provenance fields the service now snapshots:
        ``reference_ts`` (ms), ``horizon_s``, ``reference_price``,
        ``predicted_price``, ``model_type``.  Returns True if queued."""
        s, iv = payload.get("symbol"), payload.get("interval")
        ref_ts = payload.get("reference_ts")
        if not s or not iv or ref_ts is None \
                or payload.get("horizon_s") is None \
                or payload.get("reference_price") is None:
            return False
        if self._last_ref.get((s, iv), -1) >= ref_ts:
            return False                   # already registered this forecast
        self._last_ref[(s, iv)] = ref_ts
        self._pending[(s, iv, ref_ts)] = dict(payload)
        return True

    def observe_bus(self) -> int:
        """Sweep every ``nn_prediction_*`` bus key (the launcher drives
        this each tick — polling KV state like every other consumer, no
        subscription plumbing; whatever (symbol, interval) pairs the
        prediction service serves are picked up automatically)."""
        if self.bus is None:
            return 0
        n = 0
        for key in self.bus.keys("nn_prediction_*"):
            p = self.bus.get(key)
            if isinstance(p, dict) and self.record_prediction(p):
                n += 1
        return n

    # -- resolution ----------------------------------------------------------
    def _klines(self, symbol: str, interval: str):
        if self.bus is None:
            return None
        return self.bus.get(f"historical_data_{symbol}_{interval}")

    def resolve_due(self, klines_fn=None) -> int:
        """Resolve every pending prediction whose horizon has elapsed in
        KLINE TIME: realized price = close of the first candle at/after
        reference_ts + horizon.  The window the monitor already holds is
        the only data source — zero additional I/O."""
        klines_fn = klines_fn or self._klines
        resolved = 0
        for key, p in list(self._pending.items()):
            s, iv, ref_ts = key
            horizon_ms = float(p["horizon_s"]) * 1000.0
            rows = klines_fn(s, iv)
            if not rows:
                continue
            due_ts = ref_ts + horizon_ms
            realized = None
            for j, row in enumerate(rows):
                if float(row[0]) >= due_ts:
                    # never score against the NEWEST row: live venues
                    # include the still-forming candle as the last kline,
                    # whose close is a transient mid-candle price.  A later
                    # row existing proves this one closed.
                    if j < len(rows) - 1:
                        realized = float(row[4])   # close column
                    break
            if realized is None:
                newest = float(rows[-1][0])
                if newest - ref_ts > horizon_ms * self.expire_horizons:
                    self._pending.pop(key, None)   # unresolvable: expire
                    self.expired_total += 1
                continue
            self._pending.pop(key, None)
            self._score(p, realized)
            resolved += 1
        return resolved

    def _score(self, p: dict, realized: float) -> None:
        arch = p.get("model_type") or "unknown"
        s, iv = p["symbol"], p["interval"]
        ref = float(p["reference_price"])
        pred = float(p["predicted_price"])
        conf = min(max(float(p.get("confidence") or 0.0), 0.0), 1.0)
        correct = _sign(pred - ref) == _sign(realized - ref)
        denom = max(abs(realized), 1e-9)
        hit = abs(pred - realized) / denom <= self.hit_tolerance
        brier = (conf - (1.0 if correct else 0.0)) ** 2
        q = self._stats.setdefault((arch, s, iv), deque(maxlen=self.window))
        q.append((bool(correct), bool(hit), float(brier)))
        self.resolved_total += 1
        if self.metrics is not None:
            self.metrics.inc("model_outcomes_resolved_total",
                             arch=arch, symbol=s, interval=iv)

    # -- scores --------------------------------------------------------------
    def scores(self) -> dict:
        out = {}
        for (arch, s, iv), q in self._stats.items():
            n = len(q)
            if n == 0:
                continue
            out[(arch, s, iv)] = {
                "n": n,
                "directional_accuracy": sum(c for c, _, _ in q) / n,
                "hit_rate": sum(h for _, h, _ in q) / n,
                "brier": sum(b for _, _, b in q) / n,
                "live": n >= self.min_samples,
            }
        return out

    def live_score(self, arch: str, symbol: str, interval: str) -> float | None:
        """The adoption-gate score: directional accuracy over the window,
        None until ``min_samples`` outcomes have resolved."""
        q = self._stats.get((arch, symbol, interval))
        if not q or len(q) < self.min_samples:
            return None
        return sum(c for c, _, _ in q) / len(q)

    def adoption_gate(self, candidate_arch: str, incumbent_arch: str,
                      symbol: str, interval: str,
                      candidate_score: float | None = None,
                      incumbent_score: float | None = None
                      ) -> tuple[bool, str]:
        """May ``candidate_arch`` replace ``incumbent_arch`` live?

        Blocks only a candidate with a KNOWN-WORSE score than a scored
        incumbent; an unscored candidate passes flagged (it has never
        served, so it has no live score to compare — the registry
        records the adoption as shadow-grade).

        Scores default to the live directional-accuracy windows; the
        ``candidate_score`` / ``incumbent_score`` overrides let OFFLINE
        champions gate on a shared offline metric instead — the PBT
        winner (rl/population.py) submits simulator fitness for both
        sides, so a freshly trained policy that never served live can
        still be refused when it is measurably worse than the incumbent
        policy on the same simulated markets."""
        if candidate_arch == incumbent_arch:
            return True, "same_architecture"
        inc = (incumbent_score if incumbent_score is not None
               else self.live_score(incumbent_arch, symbol, interval))
        cand = (candidate_score if candidate_score is not None
                else self.live_score(candidate_arch, symbol, interval))
        if inc is None:
            return True, "incumbent_unscored"
        if cand is None:
            return True, "candidate_unscored"
        if cand > inc:
            return True, "candidate_better"
        return False, (f"candidate {candidate_arch} live score {cand:.3f} "
                       f"<= incumbent {incumbent_arch} {inc:.3f}")

    def record_adoption(self, verdict: dict) -> dict:
        """Append one adoption-gate verdict (``{"version", "adopted",
        "reason", "fitness", ...}`` — the `rl/population.adopt_winner`
        return shape plus caller context) to the bounded trail and stamp
        it with the scorecard clock.  Returns the stored record."""
        rec = dict(verdict, at=self.now_fn())
        self.adoptions.append(rec)
        return rec

    # -- export --------------------------------------------------------------
    def export(self) -> None:
        m = self.metrics
        if m is None:
            return
        for (arch, s, iv), sc in self.scores().items():
            m.set_gauge("model_directional_accuracy",
                        sc["directional_accuracy"],
                        arch=arch, symbol=s, interval=iv)
            m.set_gauge("model_hit_rate", sc["hit_rate"],
                        arch=arch, symbol=s, interval=iv)
            m.set_gauge("model_brier_score", sc["brier"],
                        arch=arch, symbol=s, interval=iv)
        m.set_gauge("model_predictions_pending", len(self._pending))

    def alert_state(self) -> dict:
        """Worst-case inputs for the in-process alert rules, only from
        windows with ``min_samples`` outcomes (a 2-sample window must not
        page)."""
        live = [sc for sc in self.scores().values() if sc["live"]]
        out = {}
        if live:
            out["model_accuracy_worst"] = min(
                sc["directional_accuracy"] for sc in live)
            out["model_brier_worst"] = max(sc["brier"] for sc in live)
        return out

    def status(self) -> dict:
        out = {"pending": len(self._pending),
               "resolved": self.resolved_total,
               "expired": self.expired_total,
               "groups": {f"{a}:{s}:{iv}": sc for (a, s, iv), sc
                          in self.scores().items()}}
        if self.adoptions:
            out["adoptions"] = list(self.adoptions)[-8:]
        return out
