"""Decision critical-path observatory: tick-phase waterfall, overlap
headroom, and cold-start accounting.

ROADMAP item 4 wants event→decision latency at the hardware floor, but
the tick path's cost structure was only coarsely known: devprof names a
``host_readback_share``, the bench reports one end-to-end stream p50,
and cold compile was literally unmeasured downtime.  Following the PR 10
precedent (measure the capacity axis BEFORE the refactor that consumes
it), this module is the SEVENTH observatory (tracing → devprof →
flightrec → saturation → meshprof → fleetscope → tickpath) and the
measurement substrate for the coming double-buffering / async-readback
work (Podracer's Sebulba actor/learner overlap, arXiv:2104.06272).
Four instruments, one module:

  * **Phase waterfall** (`observe_phase` / the seams in
    ops/tick_engine.py, shell/stream.py, shell/monitor.py,
    shell/launcher.py): every tick decomposes into the serialized
    pipeline ``frame_wait`` (venue event time E → host receive, riding
    PR 9's dual timestamps) → ``parse`` (frame drain / kline fetch +
    ingest diffing) → ``scatter_build`` (scatter-list assembly +
    upload prep) → ``dispatch`` (jit-call return) → ``device_compute``
    (dispatch-return → outputs-ready, measured by a sentinel-leaf
    readiness wait SEPARATELY from the transfer) → ``host_read`` →
    ``publish`` (bus fan-out) → ``analyzer`` → ``executor``.  Sliding
    p50/p99 windows per phase export as
    ``tickpath_phase_seconds{phase=,q=}``; the largest p99 is the named
    **bottleneck** (``tickpath_bottleneck{phase=}``, a saturation-style
    0/1 indicator over the bounded phase set), drill-tested by
    injecting per-phase delays (`inject_delay`).
  * **Overlap headroom** (`observe_overlap`): the measured wait between
    dispatch-return and readback-start is host-idle time the item-4
    pipelining can fill with host work while the device computes —
    exported as ``tickpath_overlap_headroom_seconds`` and stamped into
    the bench ``stream_latency`` row, so the future pipelined tick has
    a before/after ledger.
  * **Cold-start ledger** (`coldstart`): a context manager at every
    named hot-program seam (the ``meshprof.watch`` call sites:
    tick_engine, tenant_engine, ga_scan, sim_sweep, lob_sweep,
    backtest sweeps, train_epoch.<arch>) samples the process-wide
    JitCompileMonitor around the FIRST (cold) dispatch, attributing
    first-compile wall time per program — the ``coldstart`` block on
    /state.json and the ``coldstart_*{program=}`` gauges behind the
    bench ``cold_start_ms`` row.
  * **Event-age SLO** (`observe_event_age`): venue event time E →
    decision publish, stamped onto every flight-recorder record as
    ``event_age_ms`` and exported as
    ``latency_p99_seconds{slo=event_to_decision}`` — the
    DecisionLatencyBudgetBreach input, whose payload names the current
    bottleneck phase.  Negative ages (host clock behind the venue) are
    clamped to 0 and counted on ``tickpath_clock_skew_total`` instead
    of poisoning the quantiles.

Unlike the first six observatories this one is ON by default in the
launcher (the flightrec precedent): the waterfall is the ledger every
latency decision reads, and its measured fused-tick overhead is budgeted
at ≤5% (stamped by the bench like fleetscope's).  The disabled path
keeps the tracing/devprof discipline — every hot-path helper checks one
module global and returns immediately.  Disable with
``TradingSystem(..., enable_tickpath=False)`` or ``tickpath.disable()``.
"""

from __future__ import annotations

import contextlib
import threading
import time

from ai_crypto_trader_tpu.utils.devprof import SlidingQuantiles, percentile

# The active observatory. None = disabled: the module-level helpers below
# check this one global and bail out immediately.
_ACTIVE: "TickPathScope | None" = None

#: The serialized tick pipeline, in critical-path order.  This tuple is
#: the bounded ``phase`` label set for every tickpath series — exports
#: iterate it so a phase that never observed still publishes flat zeros
#: (a missing series is a dashboard hole, a zero is a fact).
PHASES = (
    "frame_wait",       # venue event time E → host receive (stream seam)
    "parse",            # frame drain / kline fetch + ingest diffing
    "scatter_build",    # scatter-list assembly + upload prep
    "dispatch",         # jit-call issue → async return
    "device_compute",   # dispatch-return → outputs-ready (sentinel wait)
    "host_read",        # THE per-poll device→host transfer
    "publish",          # per-symbol feature extraction + bus fan-out
    "analyzer",         # signal analysis stage drain
    "executor",         # trade execution stage drain
)

#: Default event→decision latency budget (ms): the
#: DecisionLatencyBudgetBreach threshold.  One second of feed transit +
#: one budgeted tick (devprof's "tick" SLO target) of processing.
DEFAULT_EVENT_AGE_BUDGET_MS = 2000.0
#: Quantiles report 0 / the breach alert stays quiet below this window
#: fill — one cold compile-heavy tick is 100% of a 1-sample window
#: (the devprof min_samples discipline).
DEFAULT_MIN_SAMPLES = 8


class _NoopCtx:
    """Disabled-observatory stand-in (the meshprof _NoopCtx pattern)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP_CTX = _NoopCtx()


class _ColdStartCtx:
    """One cold-dispatch attribution window: JitCompileMonitor sampled
    before/after plus the wall clock — allocated only for a program's
    FIRST cold dispatch while the observatory is on."""

    __slots__ = ("tp", "name", "_mon", "_before", "_t0")

    def __init__(self, tp: "TickPathScope", name: str):
        self.tp = tp
        self.name = name

    def __enter__(self):
        from ai_crypto_trader_tpu.utils.tracing import JitCompileMonitor

        self._mon = JitCompileMonitor.install()
        self._before = self._mon.sample()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb):
        if ev is None:
            since = self._mon.since(self._before)
            self.tp.record_cold_start(
                self.name, wall_s=time.perf_counter() - self._t0,
                compile_s=since["compile_s"], compiles=since["compiles"],
                cache_hits=since["cache_hits"])
        return False                      # never swallow — callers recover


class TickPathScope:
    """The observatory instance: phase windows + bottleneck + overlap
    headroom + event-age SLO + cold-start ledger.

    ``metrics`` (a MetricsRegistry) receives every ``tickpath_*`` /
    ``coldstart_*`` series; ``event_age_budget_ms`` is the
    DecisionLatencyBudgetBreach threshold.  Thread-safe: dashboard
    handler threads read status() while the tick loop folds phases.
    """

    def __init__(self, metrics=None, *, window: int = 512,
                 event_age_budget_ms: float = DEFAULT_EVENT_AGE_BUDGET_MS,
                 min_samples: int = DEFAULT_MIN_SAMPLES):
        self.metrics = metrics
        self.window = int(window)
        self.event_age_budget_ms = float(event_age_budget_ms)
        self.min_samples = int(min_samples)
        self.phases: dict[str, SlidingQuantiles] = {}
        self.last: dict[str, float] = {}          # newest sample per phase
        self.overlap = SlidingQuantiles(window=self.window)
        # headroom actually FILLED by pipelining: host work that ran
        # between dispatch-return and the drain's readiness wait
        self.reclaimed = SlidingQuantiles(window=self.window)
        self.event_age = SlidingQuantiles(window=self.window)  # milliseconds
        self.clock_skew_total = 0
        self.cold_programs: dict[str, dict] = {}  # program -> ledger entry
        # injected per-phase delays (seconds) for the bottleneck drill:
        # added to every matching observation so tests can pin the named
        # bottleneck per injected stage without real sleeps
        self.drill_delays: dict[str, float] = {}
        self._lock = threading.Lock()

    # -- phase waterfall -----------------------------------------------------
    def observe_phase(self, name: str, seconds: float) -> None:
        """Fold one phase sample.  Negative durations (a skewed clock on
        the frame_wait seam) clamp to 0 and count as clock skew instead
        of corrupting the window quantiles."""
        seconds = float(seconds)
        if seconds < 0.0:
            self._count_skew()
            seconds = 0.0
        seconds += self.drill_delays.get(name, 0.0)
        with self._lock:
            q = self.phases.get(name)
            if q is None:
                q = self.phases[name] = SlidingQuantiles(window=self.window)
            q.observe(seconds)
            self.last[name] = seconds

    def inject_delay(self, phase: str, seconds: float) -> None:
        """Bottleneck drill: every subsequent ``phase`` observation reads
        ``seconds`` longer.  Test-only — the production path never sets
        one."""
        self.drill_delays[phase] = float(seconds)

    def _snapshots(self) -> dict:
        with self._lock:
            return {name: (q.count, list(q.buf))
                    for name, q in self.phases.items()}

    def bottleneck(self) -> str | None:
        """The phase with the largest window p99 — None until any phase
        has observed.  Bounded vocabulary: only PHASES members compete,
        so a typo'd seam can never mint a label."""
        snaps = self._snapshots()
        best, best_p99 = None, -1.0
        for name in PHASES:
            count, values = snaps.get(name, (0, []))
            if not values:
                continue
            p99 = percentile(values, 99)
            if p99 > best_p99:
                best, best_p99 = name, p99
        return best

    # -- overlap headroom ----------------------------------------------------
    def observe_overlap(self, seconds: float) -> None:
        """One tick's host-idle wait between dispatch-return and
        readback-start: the window item-4 pipelining can fill with host
        work while the device computes."""
        with self._lock:
            self.overlap.observe(max(float(seconds), 0.0))

    def observe_reclaimed(self, seconds: float) -> None:
        """One tick's overlap headroom actually FILLED by pipelining: the
        host work (publish/analyzer/executor/next-tick ingest) that ran
        between a dispatch returning and its drain starting to wait.
        Serial execution observes ~0 here; the pipelined tick path's
        reclaimed p50 is the before/after ledger for ROADMAP item 4."""
        with self._lock:
            self.reclaimed.observe(max(float(seconds), 0.0))

    # -- event-age SLO -------------------------------------------------------
    def observe_event_age(self, age_ms: float) -> float:
        """Fold one venue-E → decision-publish age (ms); returns the
        clamped value the caller stamps onto the flight-recorder record.
        Negative ages (host clock behind the venue) clamp to 0 and count
        on ``tickpath_clock_skew_total``."""
        age_ms = float(age_ms)
        if age_ms < 0.0:
            self._count_skew()
            age_ms = 0.0
        with self._lock:
            self.event_age.observe(age_ms)
        if self.metrics is not None:
            self.metrics.observe("slo_latency_seconds", age_ms / 1000.0,
                                 slo="event_to_decision")
        return age_ms

    def _count_skew(self) -> None:
        with self._lock:
            self.clock_skew_total += 1
        if self.metrics is not None:
            self.metrics.inc("tickpath_clock_skew_total")

    # -- cold-start ledger ---------------------------------------------------
    def coldstart(self, name: str, cold: bool = True):
        """Attribution window for ``name``'s first compile: wraps the
        cold dispatch at the program's ``meshprof.watch`` seam.  No-op
        for warm dispatches or already-ledgered programs, so the steady
        path pays one dict lookup."""
        if not cold or name in self.cold_programs:
            return _NOOP_CTX
        return _ColdStartCtx(self, name)

    def record_cold_start(self, name: str, *, wall_s: float,
                          compile_s: float, compiles: int,
                          cache_hits: int = 0) -> None:
        with self._lock:
            if name in self.cold_programs:
                return                     # first cold window wins
            self.cold_programs[name] = {
                "wall_ms": round(wall_s * 1000.0, 3),
                "compile_ms": round(compile_s * 1000.0, 3),
                "compiles": int(compiles),
                # persistent-compilation-cache hits during the cold window:
                # a warm restart REPLAYS the executable (cache_hits ≥ 1,
                # compile_ms collapses) instead of recompiling — the
                # utils/aotcache.py warm-restart evidence
                "cache_hits": int(cache_hits),
                "t": time.time(),
            }
        if self.metrics is not None:
            self.metrics.set_gauge("coldstart_wall_seconds", wall_s,
                                   program=name)
            self.metrics.set_gauge("coldstart_compile_seconds", compile_s,
                                   program=name)

    # -- views ---------------------------------------------------------------
    def export(self) -> None:
        """Publish the per-phase p50/p99, bottleneck indicator, overlap
        headroom, event-age SLO, and cold-start totals (one call per
        tick, from the launcher's health-gauge pass)."""
        m = self.metrics
        if m is None:
            return
        snaps = self._snapshots()
        bn = self.bottleneck()
        for name in PHASES:
            count, values = snaps.get(name, (0, []))
            m.set_gauge("tickpath_phase_seconds", percentile(values, 50),
                        phase=name, q="p50")
            m.set_gauge("tickpath_phase_seconds", percentile(values, 99),
                        phase=name, q="p99")
            m.set_gauge("tickpath_bottleneck",
                        1.0 if name == bn else 0.0, phase=name)
        with self._lock:
            overlap = list(self.overlap.buf)
            reclaimed = list(self.reclaimed.buf)
            ages = list(self.event_age.buf)
            total_wall = sum(e["wall_ms"] for e in
                             self.cold_programs.values())
        m.set_gauge("tickpath_overlap_headroom_seconds",
                    percentile(overlap, 50))
        m.set_gauge("tickpath_overlap_reclaimed_seconds",
                    percentile(reclaimed, 50))
        m.set_gauge("latency_p50_seconds", percentile(ages, 50) / 1000.0,
                    slo="event_to_decision")
        m.set_gauge("latency_p99_seconds", percentile(ages, 99) / 1000.0,
                    slo="event_to_decision")
        m.set_gauge("coldstart_total_seconds", total_wall / 1000.0)

    def alert_state(self) -> dict:
        """Inputs for the in-process rule engine (utils/alerts.py):
        DecisionLatencyBudgetBreach pages when the event→decision p99
        exceeds the budget, and its payload names the bottleneck phase —
        values AND thresholds, the fleetscope convention."""
        with self._lock:
            ages = list(self.event_age.buf)
        p99 = percentile(ages, 99) if len(ages) >= self.min_samples else 0.0
        return {
            "event_age_p99_ms": p99,
            "event_age_budget_ms": self.event_age_budget_ms,
            "event_age_samples": len(ages),
            "tickpath_bottleneck_phase": self.bottleneck() or "",
            "tickpath_clock_skew_total": self.clock_skew_total,
        }

    def status(self) -> dict:
        """JSON-able snapshot: the /state.json ``tickpath`` block and the
        ``cli latency`` waterfall table, in critical-path order."""
        snaps = self._snapshots()
        with self._lock:
            last = dict(self.last)
            overlap = list(self.overlap.buf)
            reclaimed = list(self.reclaimed.buf)
            ages = list(self.event_age.buf)
            skew = self.clock_skew_total
        phases = {}
        for name in PHASES:
            count, values = snaps.get(name, (0, []))
            phases[name] = {
                "count": count,
                "p50_ms": round(percentile(values, 50) * 1000.0, 3),
                "p99_ms": round(percentile(values, 99) * 1000.0, 3),
                "last_ms": round(last.get(name, 0.0) * 1000.0, 3),
            }
        return {
            "phases": phases,
            "bottleneck": self.bottleneck(),
            "overlap_headroom_ms": {
                "p50": round(percentile(overlap, 50) * 1000.0, 3),
                "p99": round(percentile(overlap, 99) * 1000.0, 3),
            },
            "overlap_reclaimed_ms": {
                "p50": round(percentile(reclaimed, 50) * 1000.0, 3),
                "p99": round(percentile(reclaimed, 99) * 1000.0, 3),
            },
            "event_age_ms": {
                "p50": round(percentile(ages, 50), 3),
                "p99": round(percentile(ages, 99), 3),
                "count": len(ages),
                "budget_ms": self.event_age_budget_ms,
            },
            "clock_skew_total": skew,
        }

    def coldstart_status(self) -> dict:
        """The /state.json ``coldstart`` block: per-program first-compile
        ledger plus totals — the 'unmeasured downtime' ROADMAP item 4
        names, measured."""
        with self._lock:
            programs = {n: dict(e) for n, e in self.cold_programs.items()}
        return {
            "programs": programs,
            "total_wall_ms": round(sum(e["wall_ms"]
                                       for e in programs.values()), 3),
            "total_compile_ms": round(sum(e["compile_ms"]
                                          for e in programs.values()), 3),
        }


# -- module-level hot-path API (single-check disabled path) ------------------

def configure(tp: TickPathScope) -> TickPathScope:
    """Install ``tp`` as the process-wide active observatory."""
    global _ACTIVE
    _ACTIVE = tp
    return tp


def disable() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> TickPathScope | None:
    return _ACTIVE


@contextlib.contextmanager
def use(tp: TickPathScope):
    """Scoped activation (tests, bench): restores the previous instance."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = tp
    try:
        yield tp
    finally:
        _ACTIVE = prev


def observe_phase(name: str, seconds: float) -> None:
    tp = _ACTIVE
    if tp is not None:
        tp.observe_phase(name, seconds)


def observe_overlap(seconds: float) -> None:
    tp = _ACTIVE
    if tp is not None:
        tp.observe_overlap(seconds)


def observe_reclaimed(seconds: float) -> None:
    tp = _ACTIVE
    if tp is not None:
        tp.observe_reclaimed(seconds)


def observe_event_age(age_ms: float) -> float | None:
    """Fold + clamp one event age; None when the observatory is off (the
    caller then leaves the flight-recorder field unset)."""
    tp = _ACTIVE
    if tp is None:
        return None
    return tp.observe_event_age(age_ms)


def coldstart(name: str, cold: bool = True):
    """First-compile attribution window around a named hot dispatch; the
    pre-allocated no-op when the observatory is off or the dispatch is
    warm."""
    tp = _ACTIVE
    if tp is None:
        return _NOOP_CTX
    return tp.coldstart(name, cold=cold)
