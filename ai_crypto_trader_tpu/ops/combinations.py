"""Combined technical indicators — confirmation scores across indicators.

Capability parity with IndicatorCombinations
(`services/utils/indicator_combinations.py`): the same 15 combination
families (trend confirmation, momentum/trend alignment, triple MA,
volatility-adjusted momentum, volatility trend score, oscillator consensus,
stoch-RSI, double RSI, volume-weighted price momentum, volume/price
confirmation, trend-strength index, market-regime indicator, reversal
probability, breakout confirmation, divergence detector) — but computed
per-candle over whole arrays in one jit (the reference scores one snapshot
dict at a time in Python).

Input: the `compute_indicators` output dict (plus derived per-candle price
changes). Every score is normalized to [-1, 1] (bearish → bullish) or
[0, 1] for probability-style outputs, matching the reference's conventions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu import ops


def _pct_change(close, n):
    """ops.roc with zero-filled warmup (NaN would poison the tanh blends)."""
    return jnp.nan_to_num(ops.roc(close, n))


@jax.jit
def combined_indicators(ind: dict) -> dict:
    """All 15 combination scores, [T] each."""
    close = ind["close"]
    rsi = ind["rsi"]
    macd_line = ind["macd"]
    macd_sig = ind["macd_signal"]
    stoch = ind["stoch_k"]
    willr = ind["williams_r"]
    bb_pos = ind["bb_position"]
    bb_width = ind["bb_width"]
    atr = ind["atr"]
    volume = ind["volume"]
    sma20, sma50, sma200 = ind["sma_20"], ind["sma_50"], ind["sma_200"]

    chg1 = _pct_change(close, 1)
    chg5 = _pct_change(close, 5)
    vol_ma = ops.nanfill(ops.rolling_mean(volume, 20))
    vol_ratio = volume / jnp.where(vol_ma == 0, 1.0, vol_ma)
    volatility = atr / close

    up_trend = ((close > sma20) & (sma20 > sma50)).astype(jnp.float32)
    dn_trend = ((close < sma20) & (sma20 < sma50)).astype(jnp.float32)
    trend_dir = up_trend - dn_trend                                # [-1, 1]

    # --- trend strength combinations ---------------------------------------
    macd_conf = jnp.tanh((macd_line - macd_sig) / close * 1e3)
    trend_confirmation = trend_dir * 0.5 + macd_conf * 0.5
    momentum_trend_alignment = trend_dir * jnp.tanh(chg5 / 2.0)
    triple_ma = (jnp.sign(close - sma20) + jnp.sign(sma20 - sma50)
                 + jnp.sign(sma50 - sma200)) / 3.0

    # --- volatility-adjusted -----------------------------------------------
    vol_safe = jnp.where(volatility == 0, 1e-6, volatility)
    volatility_adjusted_momentum = jnp.tanh(chg5 / (vol_safe * 100.0))
    volatility_trend_score = trend_dir * jnp.clip(1.0 - volatility / 0.05, 0.0, 1.0)

    # --- oscillators --------------------------------------------------------
    rsi_score = (50.0 - rsi) / 50.0            # oversold → +1
    stoch_score = (50.0 - stoch) / 50.0
    willr_score = (-50.0 - willr) / 50.0       # willr ∈ [-100, 0]
    oscillator_consensus = (rsi_score + stoch_score + willr_score) / 3.0
    stoch_rsi = (rsi_score + stoch_score) / 2.0
    rsi_fast = (50.0 - ops.nanfill(ops.rsi(close, 7))) / 50.0
    double_rsi = (rsi_score + rsi_fast) / 2.0

    # --- volume -------------------------------------------------------------
    volume_weighted_price_momentum = jnp.tanh(chg1 * jnp.minimum(vol_ratio, 3.0))
    volume_price_confirmation = jnp.sign(chg1) * jnp.clip(vol_ratio - 1.0, 0.0, 1.0)

    # --- compound -----------------------------------------------------------
    trend_strength_index = jnp.clip(
        jnp.abs(trend_confirmation) * 0.4 + jnp.abs(triple_ma) * 0.3
        + jnp.abs(momentum_trend_alignment) * 0.3, 0.0, 1.0)
    # regime: +1 trending-up, -1 trending-down, ~0 ranging; |x|>0.7 & high
    # bb_width → volatile flavor
    market_regime_indicator = trend_dir * trend_strength_index
    reversal_probability = jnp.clip(
        jnp.abs(oscillator_consensus) * 0.6
        + (jnp.abs(bb_pos - 0.5) * 2.0) * 0.4, 0.0, 1.0)
    bbw_ma = ops.nanfill(ops.rolling_mean(bb_width, 50))
    squeeze = bb_width < jnp.where(bbw_ma == 0, 1.0, bbw_ma) * 0.8
    breakout_confirmation = jnp.where(
        squeeze & (vol_ratio > 1.5), jnp.sign(chg1), 0.0)
    # divergence: price making new 14-bar highs while RSI is not (bearish),
    # and vice versa
    price_hh = close >= ops.nanfill(ops.rolling_max(close, 14))
    rsi_hh = rsi >= ops.nanfill(ops.rolling_max(rsi, 14))
    price_ll = close <= ops.nanfill(ops.rolling_min(close, 14))
    rsi_ll = rsi <= ops.nanfill(ops.rolling_min(rsi, 14))
    divergence_detector = (price_ll & ~rsi_ll).astype(jnp.float32) \
        - (price_hh & ~rsi_hh).astype(jnp.float32)

    return {
        "trend_confirmation": trend_confirmation,
        "momentum_trend_alignment": momentum_trend_alignment,
        "triple_moving_average": triple_ma,
        "volatility_adjusted_momentum": volatility_adjusted_momentum,
        "volatility_trend_score": volatility_trend_score,
        "oscillator_consensus": oscillator_consensus,
        "stoch_rsi": stoch_rsi,
        "double_rsi": double_rsi,
        "volume_weighted_price_momentum": volume_weighted_price_momentum,
        "volume_price_confirmation": volume_price_confirmation,
        "trend_strength_index": trend_strength_index,
        "market_regime_indicator": market_regime_indicator,
        "reversal_probability": reversal_probability,
        "breakout_confirmation": breakout_confirmation,
        "divergence_detector": divergence_detector,
    }


@jax.jit
def combination_signal(combos: dict, weights: dict | None = None):
    """Weighted confluence score ∈ [-1, 1] across the directional combos
    (the reference's combined-signal aggregation)."""
    directional = ("trend_confirmation", "momentum_trend_alignment",
                   "triple_moving_average", "oscillator_consensus",
                   "volume_weighted_price_momentum",
                   "market_regime_indicator")
    w = weights or {k: 1.0 for k in directional}
    total = sum(w.get(k, 0.0) for k in directional)
    acc = sum(combos[k] * w.get(k, 0.0) for k in directional)
    return acc / jnp.maximum(total, 1e-9)
