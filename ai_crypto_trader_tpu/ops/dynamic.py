"""Dynamic-window indicator kernels — periods as *traced* values.

The reference's evolution service mutates indicator periods
(`strategy_evolution_service.py:98-117`: rsi_period, macd_fast/slow,
bollinger_period, ema_short/long, atr_period, volume_ma_period) but never
backtests them.  Making periods ordinary traced scalars lets one compiled
program evaluate a whole GA population with *heterogeneous periods* via
vmap — no per-individual recompilation, no shape polymorphism.

Two machinery classes:
  * EMA-family (ema/rsi/atr/macd): the smoothing factor α is already a
    scalar multiplier in the first-order recurrence, so the associative-scan
    solver in ops.indicators works unchanged with traced α;
  * hard-window ops (mean/std/max/min): computed as a fori_loop over a
    static upper bound WMAX of lagged copies, masked to the traced window —
    O(T·WMAX) VPU work, which XLA keeps in registers/VMEM tiles; WMAX comes
    from the parameter ranges (≤100 for ema_long, ≤52 otherwise).

Warmup positions (t < window-1) are NaN like the static kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ai_crypto_trader_tpu.ops.indicators import _ewm, first_order_recursion, true_range


def _iota(x):
    return lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)


def _mask_warmup_dyn(y, window):
    return jnp.where(_iota(y) < window - 1, jnp.nan, y)


def _rolling_reduce_dyn(x, window, wmax: int, op, neutral):
    """Reduce over the trailing `window` (traced, ≤ wmax) positions."""
    t = _iota(x)

    def body(i, acc):
        lagged = jnp.roll(x, i, axis=-1)
        valid = (i < window) & (t >= i)
        return op(acc, jnp.where(valid, lagged, neutral))

    acc = lax.fori_loop(0, wmax, body, jnp.full_like(x, neutral))
    return _mask_warmup_dyn(acc, window)


def rolling_sum_dyn(x, window, wmax: int):
    return _rolling_reduce_dyn(jnp.nan_to_num(x), window, wmax, jnp.add, 0.0)


def rolling_mean_dyn(x, window, wmax: int):
    return rolling_sum_dyn(x, window, wmax) / window


def rolling_max_dyn(x, window, wmax: int):
    return _rolling_reduce_dyn(x, window, wmax, jnp.maximum, -jnp.inf)


def rolling_min_dyn(x, window, wmax: int):
    return _rolling_reduce_dyn(x, window, wmax, jnp.minimum, jnp.inf)


def rolling_std_dyn(x, window, wmax: int):
    c = jnp.nanmean(x, axis=-1, keepdims=True)
    xc = x - c
    m = rolling_mean_dyn(xc, window, wmax)
    m2 = rolling_mean_dyn(xc * xc, window, wmax)
    return jnp.sqrt(jnp.maximum(m2 - m * m, 0.0))


def ema_dyn(x, window):
    """EMA with traced span (pandas ewm(span=w, adjust=False) semantics)."""
    alpha = 2.0 / (window + 1.0)
    y = _ewm(x, alpha, start=0)
    return _mask_warmup_dyn(y, window)


def macd_dyn(close, fast, slow, signal):
    """MACD with traced periods. The signal line seeds where the slow EMA
    becomes valid, mirroring pandas NaN-skipping (ops.indicators.macd)."""
    line = ema_dyn(close, fast) - ema_dyn(close, slow)
    line_filled = jnp.where(jnp.isnan(line), 0.0, line)
    t = _iota(close)
    start = jnp.asarray(slow - 1, jnp.float32)
    alpha = 2.0 / (signal + 1.0)
    a = jnp.where(t <= start, 0.0, 1.0 - alpha)
    b = jnp.where(t == start, line_filled,
                  jnp.where(t < start, 0.0, alpha * line_filled))
    sig = first_order_recursion(a, b)
    sig = jnp.where(t < start + signal - 1, jnp.nan, sig)
    line = _mask_warmup_dyn(line, slow)
    return line, sig, line - sig


def rsi_dyn(close, window):
    """Wilder RSI with traced period (ops.indicators.rsi with α = 1/w)."""
    prev = jnp.roll(close, 1, axis=-1)
    diff = close - prev
    up = jnp.maximum(diff, 0.0)
    dn = jnp.maximum(-diff, 0.0)
    ag = _ewm(up, 1.0 / window, start=1)
    al = _ewm(dn, 1.0 / window, start=1)
    r = jnp.where(al == 0.0, jnp.where(ag == 0.0, 50.0, 100.0),
                  100.0 - 100.0 / (1.0 + ag / jnp.where(al == 0.0, 1.0, al)))
    return jnp.where(_iota(close) < window, jnp.nan, r)


def atr_dyn(high, low, close, window):
    tr = true_range(high, low, close)
    y = _ewm(tr, 1.0 / window, start=1)
    return jnp.where(_iota(close) < window, jnp.nan, y)


def bollinger_dyn(close, window, num_std, wmax: int):
    mid = rolling_mean_dyn(close, window, wmax)
    sd = rolling_std_dyn(close, window, wmax)
    hi, lo = mid + num_std * sd, mid - num_std * sd
    rng = hi - lo
    pos = (close - lo) / jnp.where(rng == 0.0, jnp.nan, rng)
    width = rng / mid
    return hi, mid, lo, width, pos
