"""Technical-indicator kernels as jit-compiled array programs.

TPU-native replacement for the reference's `ta`-library pipeline
(`binance_ml_strategy.py:14-249`, TechnicalAnalyzer).  Three building blocks,
all compiler-friendly (static shapes, no data-dependent control flow):

  * windowed reductions (`lax.reduce_window`) for rolling sum/mean/max/min —
    XLA lowers these to efficient vectorized loops on the VPU;
  * **parallel first-order recurrences** (`lax.associative_scan`) for every
    EMA-family indicator (EMA, MACD, Wilder RSI, Wilder ATR).  The reference
    computes these as sequential pandas `ewm` loops; here the recursion
    y[t] = a·y[t-1] + b[t] is evaluated in O(log T) depth by composing the
    affine maps associatively — this is what makes the 525 600-candle
    (1 y of 1 m) axis fast on TPU;
  * associative forward/backward NaN fill reproducing TechnicalAnalyzer's
    `_handle_nan_values` (ffill → bfill → 0, `binance_ml_strategy.py:28-38`).

Every kernel operates on the trailing time axis of a float32 array and is
vmap-safe, so the same code serves [T], [symbol, T], and
[device, symbol, T] layouts.

NaN semantics match pandas `min_periods=window`: positions before the first
full window are NaN until `nanfill` is applied — golden tests in
tests/test_indicators.py check parity against pandas formulas.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


def _nan_like(x):
    return jnp.full_like(x, jnp.nan)


def _mask_warmup(y, window):
    """NaN-out the first window-1 positions (pandas min_periods semantics)."""
    t = lax.broadcasted_iota(jnp.int32, y.shape, y.ndim - 1)
    return jnp.where(t < window - 1, jnp.nan, y)


# ---------------------------------------------------------------------------
# Windowed reductions
# ---------------------------------------------------------------------------

def _reduce_window_last(x, init, op, window):
    dims = [1] * x.ndim
    dims[-1] = window
    pads = [(0, 0)] * (x.ndim - 1) + [(window - 1, 0)]
    return lax.reduce_window(x, init, op, tuple(dims), (1,) * x.ndim, pads)


def rolling_sum(x, window: int):
    return _mask_warmup(_reduce_window_last(x, 0.0, lax.add, window), window)


def rolling_mean(x, window: int):
    return rolling_sum(x, window) / window


def rolling_max(x, window: int):
    return _mask_warmup(_reduce_window_last(x, -jnp.inf, lax.max, window), window)


def rolling_min(x, window: int):
    return _mask_warmup(_reduce_window_last(x, jnp.inf, lax.min, window), window)


def rolling_std(x, window: int, ddof: int = 0):
    """Rolling population std (ddof=0, matching `ta` BollingerBands).

    Numerically conditioned for long f32 price series by centering on the
    series mean before squaring (variance is shift-invariant)."""
    c = jnp.nanmean(x, axis=-1, keepdims=True)
    xc = x - c
    m = rolling_mean(xc, window)
    m2 = rolling_mean(xc * xc, window)
    var = jnp.maximum(m2 - m * m, 0.0) * (window / (window - ddof))
    return jnp.sqrt(var)


sma = rolling_mean


# ---------------------------------------------------------------------------
# Parallel first-order recurrences (the EMA family)
# ---------------------------------------------------------------------------

def first_order_recursion(a, b):
    """Solve y[t] = a[t]·y[t-1] + b[t] (y[-1]=0) in parallel.

    Composes affine maps (a, b) with the associative operator
    (a2, b2)∘(a1, b1) = (a1·a2, a2·b1 + b2) via `lax.associative_scan` —
    O(log T) depth on TPU instead of the reference's O(T) pandas loop.
    """
    def combine(l, r):
        al, bl = l
        ar, br = r
        return al * ar, ar * bl + br

    _, y = lax.associative_scan(combine, (a, b), axis=-1)
    return y


def _ewm(x, alpha: float, start: int):
    """pandas `ewm(alpha, adjust=False).mean()` beginning at index `start`
    (recursion seeded with x[start]; earlier positions NaN).

    `start` models pandas skipping leading NaNs (e.g. the diff/shift NaN at
    t=0 for RSI/ATR inputs) so parity with `ta` is exact."""
    t = lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    xs = jnp.where(t < start, 0.0, jnp.nan_to_num(x))
    a = jnp.where(t <= start, 0.0, 1.0 - alpha)          # reset at seed point
    b = jnp.where(t == start, xs, alpha * xs)
    b = jnp.where(t < start, 0.0, b)
    y = first_order_recursion(a, b)
    return jnp.where(t < start, jnp.nan, y)


def ema(x, window: int, start: int | None = None, min_periods: int | None = None):
    """`ta` EMAIndicator: ewm(span=window, adjust=False, min_periods=window).

    Reference: `binance_ml_strategy.py:79-83` (ema_12 / ema_26)."""
    alpha = 2.0 / (window + 1.0)
    start = 0 if start is None else start
    y = _ewm(x, alpha, start)
    mp = window if min_periods is None else min_periods
    return _mask_warmup(y, mp + start)


def macd(close, fast: int = 12, slow: int = 26, signal: int = 9):
    """MACD line / signal / histogram, `ta` defaults
    (reference `binance_ml_strategy.py:88-97`)."""
    line = ema(close, fast, min_periods=1) - ema(close, slow, min_periods=1)
    line = _mask_warmup(line, slow)
    # pandas ewm on the signal skips the slow-1 leading NaNs of the line.
    sig = ema(line, signal, start=slow - 1, min_periods=signal)
    hist = line - sig
    return line, sig, hist


def rsi(close, window: int = 14):
    """Wilder RSI, `ta` RSIIndicator semantics
    (reference `binance_ml_strategy.py:109-116`).

    gains/losses from diff(close); Wilder smoothing = ewm(alpha=1/window,
    adjust=False) seeded at t=1 (diff[0] is NaN); RSI = 100·g/(g+l)."""
    prev = jnp.roll(close, 1, axis=-1)
    diff = close - prev
    up = jnp.maximum(diff, 0.0)
    dn = jnp.maximum(-diff, 0.0)
    ag = _ewm(up, 1.0 / window, start=1)
    al = _ewm(dn, 1.0 / window, start=1)
    r = jnp.where(al == 0.0, jnp.where(ag == 0.0, 50.0, 100.0),
                  100.0 - 100.0 / (1.0 + ag / jnp.where(al == 0.0, 1.0, al)))
    return _mask_warmup(r, window + 1)


def true_range(high, low, close):
    prev_close = jnp.roll(close, 1, axis=-1)
    t = lax.broadcasted_iota(jnp.int32, close.shape, close.ndim - 1)
    prev_close = jnp.where(t == 0, jnp.nan, prev_close)
    tr = jnp.maximum(high - low,
                     jnp.maximum(jnp.abs(high - prev_close),
                                 jnp.abs(low - prev_close)))
    return jnp.where(t == 0, jnp.nan, tr)


def atr(high, low, close, window: int = 14):
    """Wilder ATR = ewm(alpha=1/window) of true range, `ta` AverageTrueRange
    semantics (reference `binance_ml_strategy.py:161-168`)."""
    tr = true_range(high, low, close)
    y = _ewm(tr, 1.0 / window, start=1)
    return _mask_warmup(y, window + 1)


# ---------------------------------------------------------------------------
# Oscillators / bands / volume
# ---------------------------------------------------------------------------

def stochastic(high, low, close, window: int = 14, smooth: int = 3):
    """Stochastic %K / %D (`ta` defaults; reference
    `binance_ml_strategy.py:118-130`)."""
    hh = rolling_max(high, window)
    ll = rolling_min(low, window)
    rng = hh - ll
    k = 100.0 * (close - ll) / jnp.where(rng == 0.0, jnp.nan, rng)
    # NaN propagates through the windowed sum, so any 3-window containing a
    # zero-range NaN %K yields NaN %D — exactly pandas rolling(3).mean().
    d = rolling_mean(k, smooth)
    return k, _mask_warmup(d, window + smooth - 1)


def williams_r(high, low, close, window: int = 14):
    """Williams %R (reference `binance_ml_strategy.py:132-143`)."""
    hh = rolling_max(high, window)
    ll = rolling_min(low, window)
    rng = hh - ll
    return -100.0 * (hh - close) / jnp.where(rng == 0.0, jnp.nan, rng)


class Bollinger(NamedTuple):
    high: jax.Array
    mid: jax.Array
    low: jax.Array
    width: jax.Array
    position: jax.Array


def bollinger(close, window: int = 20, num_std: float = 2.0) -> Bollinger:
    """Bollinger bands + width + %B (reference
    `binance_ml_strategy.py:145-159`; zero-range %B → NaN as at line 155)."""
    mid = rolling_mean(close, window)
    sd = rolling_std(close, window)
    hi = mid + num_std * sd
    lo = mid - num_std * sd
    width = (hi - lo) / mid
    rng = hi - lo
    pos = (close - lo) / jnp.where(rng == 0.0, jnp.nan, rng)
    return Bollinger(hi, mid, lo, width, pos)


def vwap(high, low, close, volume, window: int = 14):
    """Rolling VWAP over typical price (`ta` VolumeWeightedAveragePrice;
    reference `binance_ml_strategy.py:170-182`)."""
    tp = (high + low + close) / 3.0
    num = rolling_sum(tp * volume, window)
    den = rolling_sum(volume, window)
    return num / jnp.where(den == 0.0, jnp.nan, den)


def ichimoku(high, low, conv: int = 9, base: int = 26, span_b: int = 52):
    """Ichimoku senkou A/B, unshifted (`ta` visual=False; reference
    `binance_ml_strategy.py:99-107`)."""
    conv_line = (rolling_max(high, conv) + rolling_min(low, conv)) / 2.0
    base_line = (rolling_max(high, base) + rolling_min(low, base)) / 2.0
    a = (conv_line + base_line) / 2.0
    b = (rolling_max(high, span_b) + rolling_min(low, span_b)) / 2.0
    return a, b


def obv(close, volume):
    """On-balance volume (used by regime/feature components)."""
    prev = jnp.roll(close, 1, axis=-1)
    t = lax.broadcasted_iota(jnp.int32, close.shape, close.ndim - 1)
    sign = jnp.where(t == 0, 0.0, jnp.sign(close - prev))
    return jnp.cumsum(sign * volume, axis=-1)


def roc(close, window: int = 12):
    """Rate of change, percent."""
    prev = jnp.roll(close, window, axis=-1)
    t = lax.broadcasted_iota(jnp.int32, close.shape, close.ndim - 1)
    return jnp.where(t < window, jnp.nan, 100.0 * (close - prev) / prev)


# ---------------------------------------------------------------------------
# NaN fill (TechnicalAnalyzer._handle_nan_values parity)
# ---------------------------------------------------------------------------

def ffill(x):
    """Forward-fill NaNs: cummax over last-valid *indices* + one gather.

    Equivalent to the associative 'last valid value' scan but ~4x cheaper
    on CPU/TPU: a single int cumulative-max (one pass) and one
    take_along_axis replace two tuple-carrying associative scans whose
    O(T log T) slice/concat traffic dominated the fused tick program.
    Positions before the first valid value keep idx == -1 and stay NaN."""
    t = lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    valid = ~jnp.isnan(x)
    idx = lax.cummax(jnp.where(valid, t, -1), axis=x.ndim - 1)
    y = jnp.take_along_axis(jnp.nan_to_num(x), jnp.clip(idx, 0, None),
                            axis=-1)
    return jnp.where(idx < 0, jnp.nan, y)


def bfill(x):
    return jnp.flip(ffill(jnp.flip(x, axis=-1)), axis=-1)


def nanfill(x):
    """ffill → bfill → 0, exactly TechnicalAnalyzer._handle_nan_values
    (`binance_ml_strategy.py:28-38`)."""
    return jnp.nan_to_num(bfill(ffill(x)))


# ---------------------------------------------------------------------------
# The full per-candle indicator table
# ---------------------------------------------------------------------------

INDICATOR_NAMES = (
    "sma_20", "sma_50", "sma_200", "ema_12", "ema_26",
    "macd", "macd_signal", "macd_diff",
    "ichimoku_a", "ichimoku_b",
    "rsi", "stoch_k", "stoch_d", "williams_r",
    "bb_high", "bb_mid", "bb_low", "bb_width", "bb_position",
    "atr", "vwap",
)


@functools.partial(jax.jit, static_argnames=("fill",))
def compute_indicators(ohlcv: dict, fill: bool = True) -> dict:
    """Full TechnicalAnalyzer parity: every indicator column the reference
    computes (`binance_ml_strategy.py:40-182`), for **every candle** at once.

    (The reference's backtester actually evaluates indicators only on the
    final row and replays that single value for all candles,
    `backtesting/strategy_tester.py:63-125`; this framework computes true
    per-candle values — strictly more capable, and the per-candle path is
    what live mode uses anyway.)

    Input: dict with float32 arrays open/high/low/close/volume [..., T].
    Output: dict of the 21 indicator arrays plus passthrough OHLCV.
    """
    high, low, close, volume = (ohlcv[k] for k in ("high", "low", "close", "volume"))

    out = dict(ohlcv)
    out["sma_20"] = sma(close, 20)
    out["sma_50"] = sma(close, 50)
    out["sma_200"] = sma(close, 200)
    out["ema_12"] = ema(close, 12)
    out["ema_26"] = ema(close, 26)
    line, sig, hist = macd(close)
    out["macd"], out["macd_signal"], out["macd_diff"] = line, sig, hist
    a, b = ichimoku(high, low)
    out["ichimoku_a"], out["ichimoku_b"] = a, b
    out["rsi"] = rsi(close)
    k, d = stochastic(high, low, close)
    out["stoch_k"], out["stoch_d"] = k, d
    out["williams_r"] = williams_r(high, low, close)
    bb = bollinger(close)
    out["bb_high"], out["bb_mid"], out["bb_low"] = bb.high, bb.mid, bb.low
    out["bb_width"], out["bb_position"] = bb.width, bb.position
    out["atr"] = atr(high, low, close)
    out["vwap"] = vwap(high, low, close, volume)

    if fill:
        out = {k: (nanfill(v) if jnp.issubdtype(v.dtype, jnp.floating) else v)
               for k, v in out.items()}
    return out
