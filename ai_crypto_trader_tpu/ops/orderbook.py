"""Order-book analytics as array programs.

Capability parity with OrderBookAnalyzer
(`services/utils/order_book_analyzer.py`):
  * bid/ask imbalance and depth metrics (:127-180),
  * price impact of market orders for a ladder of trade sizes by walking
    the book (:181-244) — expressed as cumulative-sum searches, all sizes
    at once, no Python walk;
  * support/resistance walls (:245-292) — levels holding a multiple of the
    mean level size;
  * order clustering (:293-372) — k-means over (price, size) reusing the
    JAX clustering core;
  * pressure metrics (:373-472);
  * microstructure: Gini concentration + spoofing / iceberg heuristics
    (:473-606);
  * composite order-book trading signal (:667).

Input format: bids/asks as [N, 2] arrays of (price, size), bids sorted
descending, asks ascending (exchange convention).

`price_impact`, `find_walls` and `pressure_metrics` additionally accept
leading batch dims (`[..., N, 2]`) — the `ops.volume_profile` treatment:
the math runs per trailing book (vmapped internally where it reduces over
levels), which is what lets the depth-frame calibration
(`sim/calibrate.py`) and the LOB sweep analyze a whole capture window of
books in one program instead of a Python loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_TRADE_SIZES = (10_000.0, 50_000.0, 100_000.0, 500_000.0, 1_000_000.0)


@jax.jit
def imbalance(bids: jnp.ndarray, asks: jnp.ndarray) -> dict:
    """(:127-180)"""
    bid_vol = jnp.sum(bids[:, 1])
    ask_vol = jnp.sum(asks[:, 1])
    total = bid_vol + ask_vol
    mid = (bids[0, 0] + asks[0, 0]) / 2.0
    spread = asks[0, 0] - bids[0, 0]
    bid_value = jnp.sum(bids[:, 0] * bids[:, 1])
    ask_value = jnp.sum(asks[:, 0] * asks[:, 1])
    return {
        "imbalance": (bid_vol - ask_vol) / jnp.where(total == 0, 1.0, total),
        "bid_volume": bid_vol, "ask_volume": ask_vol,
        "bid_value": bid_value, "ask_value": ask_value,
        "mid_price": mid, "spread": spread,
        "spread_bps": spread / mid * 10_000.0,
    }


def _price_impact_1d(levels: jnp.ndarray,
                     trade_sizes: jnp.ndarray) -> jnp.ndarray:
    values = levels[:, 0] * levels[:, 1]                   # quote value per level
    cum = jnp.cumsum(values)

    def one(size):
        # fraction of each level consumed
        prev = jnp.concatenate([jnp.zeros(1), cum[:-1]])
        take = jnp.clip(size - prev, 0.0, values)   # quote value per level
        filled = jnp.sum(take)
        # quote-value-weighted average fill price: Σ take_i·p_i / Σ take_i
        avg_px = jnp.sum(take * levels[:, 0]) / jnp.where(filled == 0, 1.0, filled)
        return jnp.abs(avg_px - levels[0, 0]) / levels[0, 0]

    return jax.vmap(one)(trade_sizes)


@functools.partial(jax.jit, static_argnames=())
def price_impact(levels: jnp.ndarray, trade_sizes: jnp.ndarray) -> jnp.ndarray:
    """Impact (fraction of best price) of market orders of each quote-value
    size walking one side of the book (:181-244).

    For each size: find how deep the cumulative quote value reaches and
    average the filled price. Returns [n_sizes] relative impact (NaN-free:
    sizes exceeding total depth get the full-book impact).  Accepts
    leading batch dims: ``[..., N, 2]`` books → ``[..., n_sizes]``."""
    levels = jnp.asarray(levels)
    if levels.ndim == 2:
        return _price_impact_1d(levels, trade_sizes)
    batch = levels.shape[:-2]
    flat = levels.reshape((-1,) + levels.shape[-2:])
    out = jax.vmap(lambda lv: _price_impact_1d(lv, trade_sizes))(flat)
    return out.reshape(batch + out.shape[1:])


@functools.partial(jax.jit, static_argnames=())
def find_walls(levels: jnp.ndarray, multiple: float = 3.0):
    """Wall mask: levels holding ≥ multiple × mean size (:245-292).
    Batched over any leading dims (the mean is per trailing book)."""
    levels = jnp.asarray(levels)
    mean_size = jnp.mean(levels[..., 1], axis=-1, keepdims=True)
    return levels[..., 1] >= multiple * mean_size


@functools.partial(jax.jit, static_argnames=("near_levels",))
def pressure_metrics(bids: jnp.ndarray, asks: jnp.ndarray,
                     near_levels: int = 5) -> dict:
    """Near-book pressure (:373-472): top-of-book volume ratios and the
    weighted mid displacement.  Batched over any leading dims (every
    reduction is over the trailing level axis)."""
    bids, asks = jnp.asarray(bids), jnp.asarray(asks)
    nb = jnp.sum(bids[..., :near_levels, 1], axis=-1)
    na = jnp.sum(asks[..., :near_levels, 1], axis=-1)
    total = nb + na
    best_bid, best_ask = bids[..., 0, 0], asks[..., 0, 0]
    micro = (best_bid * na + best_ask * nb) / jnp.where(total == 0, 1.0,
                                                        total)
    mid = (best_bid + best_ask) / 2.0
    return {
        "near_pressure": (nb - na) / jnp.where(total == 0, 1.0, total),
        "microprice": micro,
        "microprice_tilt_bps": (micro - mid) / mid * 10_000.0,
    }


@jax.jit
def gini_concentration(levels: jnp.ndarray) -> jnp.ndarray:
    """Gini coefficient of size concentration across levels (:473-520)."""
    sizes = jnp.sort(levels[:, 1])
    n = sizes.shape[0]
    i = jnp.arange(1, n + 1)
    total = jnp.sum(sizes)
    return jnp.where(total > 0,
                     (2.0 * jnp.sum(i * sizes) / (n * total)) - (n + 1.0) / n,
                     0.0)


def microstructure_flags(levels: np.ndarray, mid: float,
                         far_threshold_pct: float = 1.0,
                         spoof_volume_frac: float = 0.4,
                         iceberg_uniform_tol: float = 0.02) -> dict:
    """Spoofing / iceberg heuristics (:521-606): spoofing — a large volume
    fraction parked far from mid; iceberg — suspiciously uniform level
    sizes (refill signature)."""
    levels = np.asarray(levels)
    dist_pct = np.abs(levels[:, 0] - mid) / mid * 100.0
    far = dist_pct > far_threshold_pct
    far_frac = levels[far, 1].sum() / max(levels[:, 1].sum(), 1e-12)
    sizes = levels[:, 1]
    cv = sizes.std() / max(sizes.mean(), 1e-12)
    return {
        "spoofing_suspected": bool(far_frac > spoof_volume_frac),
        "far_volume_fraction": float(far_frac),
        "iceberg_suspected": bool(cv < iceberg_uniform_tol and len(sizes) >= 5),
        "size_cv": float(cv),
    }


def cluster_orders(levels: np.ndarray, k: int = 3, seed: int = 0) -> dict:
    """k-means clusters over (price, size) (:293-372), reusing the JAX
    clustering core."""
    from ai_crypto_trader_tpu.regime.cluster import kmeans_fit, kmeans_predict, standardize_fit

    x = jnp.asarray(levels, jnp.float32)
    std = standardize_fit(x)
    z = std.transform(x)
    km = kmeans_fit(jax.random.PRNGKey(seed), z, k, iters=25)
    labels = np.asarray(kmeans_predict(km, z))
    out = []
    lv = np.asarray(levels)
    for c in range(k):
        m = labels == c
        if m.sum():
            out.append({"center_price": float(lv[m, 0].mean()),
                        "total_size": float(lv[m, 1].sum()),
                        "n_levels": int(m.sum())})
    return {"clusters": sorted(out, key=lambda c: -c["total_size"]),
            "labels": labels}


def orderbook_signal(bids: np.ndarray, asks: np.ndarray) -> dict:
    """Composite signal (:667): imbalance + pressure + wall asymmetry vote."""
    b, a = jnp.asarray(bids, jnp.float32), jnp.asarray(asks, jnp.float32)
    imb = {k: float(v) for k, v in imbalance(b, a).items()}
    pres = {k: float(v) for k, v in pressure_metrics(b, a).items()}
    bid_walls = int(np.asarray(find_walls(b)).sum())
    ask_walls = int(np.asarray(find_walls(a)).sum())
    score = (imb["imbalance"] * 0.5 + pres["near_pressure"] * 0.3
             + np.sign(bid_walls - ask_walls) * 0.2)
    return {
        "signal": "BUY" if score > 0.2 else "SELL" if score < -0.2 else "NEUTRAL",
        "score": float(score),
        "imbalance": imb, "pressure": pres,
        "bid_walls": bid_walls, "ask_walls": ask_walls,
        "gini_bids": float(gini_concentration(b)),
        "gini_asks": float(gini_concentration(a)),
    }
