"""Pallas TPU kernel for the replay-scan backtester.

The engine's `lax.scan` (backtest/engine.py) compiles to an XLA while-loop
whose per-step dispatch overhead dominates at T=525k candles × a [B]-wide
carry that never saturates the VPU.  This kernel re-expresses the whole
sweep as one Pallas program:

    grid = (B / BLOCK_B, T / CHUNK_T)        # population-block × time-chunk
    carry: (24, BLOCK_B) f32 VMEM scratch    # persists across time chunks
    inputs: per-candle scalars streamed through SMEM chunk by chunk
    params: per-strategy SL/TP rows in VMEM
    body:  fori_loop over the chunk — branch-free jnp.where arithmetic
           identical to engine.run_backtest's step (use_param_sl_tp mode)

so the candle loop runs entirely out of VMEM/SMEM with no per-step XLA
dispatch, and the population block rides the VPU lanes.  Semantics are
pinned against `engine.sweep` by tests/test_pallas_backtest.py (same
candles → same stats); the scan engine remains the reference path and the
fallback on non-TPU backends.

Reference lineage: the loop being accelerated is the TPU re-expression of
`backtesting/strategy_tester.py:190-300` — see engine.py's parity notes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ai_crypto_trader_tpu.backtest.engine import BacktestInputs, BacktestStats
from ai_crypto_trader_tpu.backtest.strategy import StrategyParams

BLOCK_B = 128          # population lanes per program (f32 lane width)
CHUNK_T = 1024         # candles streamed per grid step (9 × 4 KB of SMEM);
                       # must match XLA's {0:T(1024)} tiling of 1-D f32
                       # arrays or Mosaic rejects the operand layout

# carry rows in the VMEM scratch
(_BAL, _INPOS, _ENTRY, _QTY, _SL, _TP, _MAXEQ, _MAXDD, _MAXDDP, _TRADES,
 _WINS, _PROFIT, _LOSS, _SUMR, _SUMR2, _SUMNR2, _NR, _CW, _CL, _MWS,
 _MLS) = range(21)
_NCARRY = 24           # padded to a multiple of the 8-sublane f32 tile

_NSTAT = 16            # output rows (15 stats + padding row)


def _position_size(balance, vol, volume):
    """signals.position_size, inlined (binance_ml_strategy.py:251-291)."""
    hi = vol > 0.02
    mid = jnp.logical_and(jnp.logical_not(hi), vol > 0.01)
    position_pct = jnp.where(hi, 0.25, jnp.where(mid, 0.20, 0.15))
    sl = jnp.where(hi, 0.02, jnp.where(mid, 0.015, 0.01))
    volume_factor = jnp.minimum(volume / 50_000.0, 1.0)
    size = balance * position_pct * volume_factor
    size = jnp.minimum(size, balance * 0.15 / sl)
    size = jnp.minimum(size, balance * 0.20)
    size = jnp.maximum(size, balance * 0.10)
    size = jnp.maximum(size, 40.0)
    return size


def _book_close(c, price, do_close):
    """engine._book_close on the carry rows dict."""
    pnl = (price - c[_ENTRY]) * c[_QTY]
    win = pnl > 0.0
    closed = do_close.astype(jnp.float32)
    won = jnp.logical_and(do_close, win).astype(jnp.float32)
    c[_BAL] = c[_BAL] + jnp.where(do_close, pnl, 0.0)
    cw = jnp.where(do_close, jnp.where(win, c[_CW] + 1.0, 0.0), c[_CW])
    cl = jnp.where(do_close, jnp.where(win, 0.0, c[_CL] + 1.0), c[_CL])
    c[_INPOS] = jnp.where(do_close, 0.0, c[_INPOS])
    c[_TRADES] = c[_TRADES] + closed
    c[_WINS] = c[_WINS] + won
    c[_PROFIT] = c[_PROFIT] + jnp.where(jnp.logical_and(do_close, win), pnl, 0.0)
    c[_LOSS] = c[_LOSS] + jnp.where(
        jnp.logical_and(do_close, jnp.logical_not(win)), -pnl, 0.0)
    c[_CW], c[_CL] = cw, cl
    c[_MWS] = jnp.maximum(c[_MWS], cw)
    c[_MLS] = jnp.maximum(c[_MLS], cl)
    return c


def _make_kernel(T_true, warmup, initial_balance, conf_thr, min_strength,
                 n_tc):
    def kernel(close_ref, signal_ref, strength_ref, vol_ref, volume_ref,
               conf_ref, decision_ref, slov_ref, tpov_ref,
               psl_ref, ptp_ref, out_ref, carry):
        t_chunk = pl.program_id(1)

        @pl.when(t_chunk == 0)
        def _seed():
            carry[...] = jnp.zeros((_NCARRY, BLOCK_B), jnp.float32)
            carry[_BAL, :] = jnp.full((BLOCK_B,), initial_balance, jnp.float32)
            carry[_MAXEQ, :] = jnp.full((BLOCK_B,), initial_balance, jnp.float32)
            # n_r starts at 1 (engine._init_state: initial zero-return point)
            carry[_NR, :] = jnp.ones((BLOCK_B,), jnp.float32)

        psl = psl_ref[0, :]
        ptp = ptp_ref[0, :]

        def step(i, _):
            t = t_chunk * CHUNK_T + i
            c = {r: carry[r, :] for r in range(21)}
            close = close_ref[i]
            # pad candles (t >= T_true) are fully inert: no exits, no
            # entries, and — crucially — no equity-point booking (they
            # would inflate n_r and shift the Sharpe denominator)
            active = jnp.logical_and(t >= warmup, t < T_true)
            prev_balance = c[_BAL]
            in_pos = c[_INPOS] > 0.0

            # --- SL/TP scan on the open position ---
            entry_safe = jnp.where(c[_ENTRY] == 0.0, 1.0, c[_ENTRY])
            pnl_pct = (close - c[_ENTRY]) / entry_safe * 100.0
            hit_sl = jnp.logical_and(jnp.logical_and(active, in_pos),
                                     pnl_pct <= -c[_SL])
            hit_tp = jnp.logical_and(
                jnp.logical_and(jnp.logical_and(active, in_pos),
                                jnp.logical_not(hit_sl)),
                pnl_pct >= c[_TP])
            do_close = jnp.logical_or(hit_sl, hit_tp)
            survived = jnp.logical_and(in_pos, jnp.logical_not(do_close))
            c = _book_close(c, close, do_close)
            in_pos = c[_INPOS] > 0.0

            # --- entry gate ---
            gate = jnp.logical_and(
                jnp.logical_and(
                    jnp.logical_and(active, jnp.logical_not(in_pos)),
                    jnp.logical_and(conf_ref[i] >= conf_thr,
                                    strength_ref[i] >= min_strength)),
                jnp.logical_and(signal_ref[i] == decision_ref[i],
                                decision_ref[i] == 1.0))
            size = _position_size(c[_BAL], vol_ref[i], volume_ref[i])
            slov, tpov = slov_ref[i], tpov_ref[i]
            sl_new = jnp.where(jnp.isnan(slov), psl, slov)
            tp_new = jnp.where(jnp.isnan(tpov), ptp, tpov)
            c[_INPOS] = jnp.where(gate, 1.0, c[_INPOS])
            c[_ENTRY] = jnp.where(gate, close, c[_ENTRY])
            c[_QTY] = jnp.where(gate, size / close, c[_QTY])
            c[_SL] = jnp.where(gate, sl_new, c[_SL])
            c[_TP] = jnp.where(gate, tp_new, c[_TP])

            # --- equity point + drawdown ---
            book = jnp.logical_and(active, jnp.logical_not(survived))
            equity = c[_BAL]
            max_eq = jnp.where(book, jnp.maximum(c[_MAXEQ], equity), c[_MAXEQ])
            dd = max_eq - equity
            dd_pct = dd / max_eq * 100.0
            new_max = jnp.logical_and(book, dd > c[_MAXDD])
            r = jnp.where(book, (equity - prev_balance) / prev_balance, 0.0)
            c[_MAXEQ] = max_eq
            c[_MAXDD] = jnp.where(new_max, dd, c[_MAXDD])
            c[_MAXDDP] = jnp.where(new_max, dd_pct, c[_MAXDDP])
            c[_SUMR] = c[_SUMR] + r
            c[_SUMR2] = c[_SUMR2] + r * r
            c[_SUMNR2] = c[_SUMNR2] + jnp.where(r < 0.0, r * r, 0.0)
            c[_NR] = c[_NR] + book.astype(jnp.float32)

            for row in range(21):
                carry[row, :] = c[row]
            return 0

        jax.lax.fori_loop(0, CHUNK_T, step, 0)

        @pl.when(t_chunk == n_tc - 1)
        def _finish():
            # close any remaining position at the last price ("End of Test").
            # Stat rows are stored one ref-row at a time with static indices
            # (like the carry writeback in `step`) — building the block as a
            # jnp array via .at[].set() lowers as scatter, which the Mosaic
            # TPU pipeline rejects.
            c = {r: carry[r, :] for r in range(21)}
            c = _book_close(c, close_ref[CHUNK_T - 1], c[_INPOS] > 0.0)
            out_ref[0, :] = jnp.full((BLOCK_B,), initial_balance, jnp.float32)
            out_ref[1, :] = c[_BAL]
            out_ref[2, :] = c[_TRADES]
            out_ref[3, :] = c[_WINS]
            out_ref[4, :] = c[_TRADES] - c[_WINS]
            out_ref[5, :] = c[_PROFIT]
            out_ref[6, :] = c[_LOSS]
            out_ref[7, :] = c[_MAXDD]
            out_ref[8, :] = c[_MAXDDP]
            out_ref[9, :] = c[_SUMR]
            out_ref[10, :] = c[_SUMR2]
            out_ref[11, :] = c[_SUMNR2]
            out_ref[12, :] = c[_NR]
            out_ref[13, :] = c[_MWS]
            out_ref[14, :] = c[_MLS]
            out_ref[15, :] = jnp.zeros((BLOCK_B,), jnp.float32)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("initial_balance", "ai_confidence_threshold",
                     "min_signal_strength", "warmup", "interpret"),
)
def sweep_pallas(inputs: BacktestInputs, params: StrategyParams,
                 initial_balance: float = 10_000.0,
                 ai_confidence_threshold: float = 0.7,
                 min_signal_strength: float = 70.0,
                 warmup: int = 10,
                 interpret: bool = False) -> BacktestStats:
    """Drop-in for `engine.sweep(..., use_param_sl_tp=True)` stats.

    Pads T to a CHUNK_T multiple (neutral candles: zero signal, never
    traded, warmup-masked... the pad rides AFTER the data so the final
    close must use the true last candle — handled by padding with the last
    close and NEUTRAL signals, which cannot open or close positions and
    book no equity points (signal 0 ≠ decision requirement uses decision
    pad -2)). Pads B to a BLOCK_B multiple and slices back.
    """
    T = inputs.close.shape[-1]
    B = jax.tree.leaves(params)[0].shape[0]
    pad_t = (-T) % CHUNK_T
    pad_b = (-B) % BLOCK_B

    def pad_time(x, fill):
        return jnp.concatenate([x, jnp.full((pad_t,), fill, x.dtype)]) \
            if pad_t else x

    close = pad_time(inputs.close, inputs.close[-1])
    f32 = lambda x: x.astype(jnp.float32)
    arrs = dict(
        close=f32(close),
        signal=f32(pad_time(inputs.signal.astype(jnp.float32), 0.0)),
        strength=f32(pad_time(inputs.strength, 0.0)),
        vol=f32(pad_time(inputs.volatility, 0.0)),
        volume=f32(pad_time(inputs.volume, 0.0)),
        conf=f32(pad_time(inputs.confidence, 0.0)),
        # decision pad -2 can never equal signal pad 0 nor BUY=1
        decision=f32(pad_time(inputs.decision.astype(jnp.float32), -2.0)),
        slov=f32(pad_time(inputs.sl_pct, jnp.nan)),
        tpov=f32(pad_time(inputs.tp_pct, jnp.nan)),
    )
    psl = params.stop_loss.astype(jnp.float32)
    ptp = params.take_profit.astype(jnp.float32)
    if pad_b:
        psl = jnp.concatenate([psl, jnp.zeros((pad_b,), jnp.float32)])
        ptp = jnp.concatenate([ptp, jnp.zeros((pad_b,), jnp.float32)])
    psl = psl.reshape(1, -1)
    ptp = ptp.reshape(1, -1)

    Tp, Bp = T + pad_t, B + pad_b
    n_tc = Tp // CHUNK_T
    kernel = _make_kernel(T, warmup, float(initial_balance),
                          float(ai_confidence_threshold),
                          float(min_signal_strength), n_tc)

    t_spec = pl.BlockSpec((CHUNK_T,), lambda b, t: (t,),
                          memory_space=pltpu.SMEM)
    p_spec = pl.BlockSpec((1, BLOCK_B), lambda b, t: (0, b))
    out = pl.pallas_call(
        kernel,
        grid=(Bp // BLOCK_B, n_tc),
        in_specs=[t_spec] * 9 + [p_spec, p_spec],
        out_specs=pl.BlockSpec((_NSTAT, BLOCK_B), lambda b, t: (0, b)),
        out_shape=jax.ShapeDtypeStruct((_NSTAT, Bp), jnp.float32),
        scratch_shapes=[pltpu.VMEM((_NCARRY, BLOCK_B), jnp.float32)],
        interpret=interpret,
    )(arrs["close"], arrs["signal"], arrs["strength"], arrs["vol"],
      arrs["volume"], arrs["conf"], arrs["decision"], arrs["slov"],
      arrs["tpov"], psl, ptp)

    out = out[:, :B]
    i32 = lambda row: out[row].astype(jnp.int32)
    return BacktestStats(
        initial_balance=jnp.asarray(initial_balance, jnp.float32),
        final_balance=out[1],
        total_trades=i32(2), winning_trades=i32(3), losing_trades=i32(4),
        total_profit=out[5], total_loss=out[6],
        max_drawdown=out[7], max_drawdown_pct=out[8],
        sum_r=out[9], sum_r2=out[10], sum_neg_r2=out[11], n_r=i32(12),
        max_win_streak=i32(13), max_loss_streak=i32(14),
    )
