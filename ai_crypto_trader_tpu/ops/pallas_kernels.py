"""Pallas TPU kernels for the hot indicator ops.

`fused_ewma` — the whole EMA family (ema12/ema26/Wilder-RSI gains & losses/
Wilder ATR = any set of K smoothing factors) evaluated over a batch of
series in ONE pass over HBM.

Why a kernel: the XLA path runs one `associative_scan` per smoother — ~K
reads of the [B, T] series from HBM plus O(log T) intermediate tensors.
The recursion y[t] = (1-α)·y[t-1] + α·x[t] is trivially sequential per
step but only needs the carry in registers, so a Pallas kernel can stream
the series through VMEM once and produce all K outputs with O(1) on-chip
state:

  * layout [T, B]: the batch rides the 128-wide lane axis (each inner step
    is a K×[1, B] VPU fma), time rides sublanes;
  * grid over T tiles — TPU grid steps execute sequentially, so a VMEM
    scratch [K, 1, B] carries y across tiles (the standard sequential-grid
    carry pattern);
  * HBM traffic: read x once, write the K outputs once — vs ≥K reads plus
    scan temporaries for the XLA path.

Numerics match `ops.indicators._ewm(..., start=0)` (recursion seeded with
x[0]); warmup NaN masking stays the caller's concern, as in the jnp path.

`fused_ewma` falls back to the associative-scan implementation on
non-TPU backends (or under `interpret=True` for tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas import is safe everywhere; lowering needs a TPU
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

T_TILE = 256  # sublane-axis tile (multiple of 8 for f32)


def _ewma_kernel(alpha_ref, x_ref, out_ref, carry_ref):
    """One [T_TILE, B] block: sequential recursion over sublanes, K
    smoothers vectorized over the lane axis.

    alpha_ref: [K] SMEM; x_ref: [T_TILE, B] VMEM; out_ref: [K, T_TILE, B];
    carry_ref: [K, 1, B] VMEM scratch persisting across grid steps."""
    i = pl.program_id(0)
    k_count = out_ref.shape[0]

    @pl.when(i == 0)
    def _seed():
        first = x_ref[0:1, :]                       # [1, B]
        for k in range(k_count):
            carry_ref[k] = first

    def step(t, _):
        xt = x_ref[t, :][None, :]                   # [1, B]
        for k in range(k_count):
            a = alpha_ref[k]
            c = carry_ref[k]
            # seeded position: y[0] = x[0] exactly
            is_t0 = jnp.logical_and(i == 0, t == 0)
            new = jnp.where(is_t0, xt, (1.0 - a) * c + a * xt)
            carry_ref[k] = new
            out_ref[k, t, :] = new[0]
        return 0

    lax.fori_loop(0, x_ref.shape[0], step, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_ewma_pallas(x_tb: jnp.ndarray, alphas: jnp.ndarray,
                      interpret: bool = False) -> jnp.ndarray:
    """x_tb: [T, B] (T divisible by T_TILE), alphas: [K] → [K, T, B]."""
    T, B = x_tb.shape
    K = alphas.shape[0]
    if T % T_TILE != 0 or T == 0:
        raise ValueError(
            f"fused_ewma_pallas requires T divisible by {T_TILE}, got {T} "
            "(a floor-truncated grid would leave the tail unwritten)")
    grid = (T // T_TILE,)
    return pl.pallas_call(
        _ewma_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((T_TILE, B), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((K, T_TILE, B), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((K, T, B), x_tb.dtype),
        scratch_shapes=[pltpu.VMEM((K, 1, B), x_tb.dtype)],
        interpret=interpret,
    )(alphas, x_tb)


def fused_ewma(x: jnp.ndarray, alphas, *, force_pallas: bool | None = None,
               interpret: bool = False) -> jnp.ndarray:
    """Batch EMA family: x [B, T] (or [T]), alphas length-K → [K, B, T].

    Dispatches to the Pallas kernel on TPU (or when interpret=True for
    testing); otherwise computes the same recursion via K associative
    scans."""
    squeeze = x.ndim == 1
    if squeeze:
        x = x[None, :]
    alphas = jnp.asarray(alphas, x.dtype)
    B, T = x.shape

    use_pallas = force_pallas
    if use_pallas is None:
        use_pallas = (_HAVE_PALLAS and T % T_TILE == 0
                      and (interpret or jax.default_backend() == "tpu"))

    if use_pallas:
        out = fused_ewma_pallas(x.T, alphas, interpret=interpret)  # [K, T, B]
        out = jnp.transpose(out, (0, 2, 1))
    else:
        from ai_crypto_trader_tpu.ops.indicators import _ewm

        out = jnp.stack([_ewm(x, a, start=0) for a in alphas], axis=0)
    return out[:, 0, :] if squeeze else out
