"""Vmapped tenant decision engine: N tenants' gate/size decisions per tick
as ONE device dispatch.

PR 10's capacity bench proved the serving wall is the INTERPRETER, not the
device: each synthetic tenant lane was its own `SignalAnalyzer` +
`TradeExecutor` Python object, so a tick cost O(N·S) host work while the
fused tick engine — already computing the whole [S, F] feature universe in
one dispatch — sat idle.  Podracer/Anakin (arXiv:2104.06272) and Fast
Population-Based RL (arXiv:2206.08888) give the shape: stack per-agent
state into a leading axis and vmap ONE program over it.  Tenants become
*data*:

  * **strategy params** (confidence threshold, strength floor, position
    cap, min trade size, fee rate, live SL/TP overrides) as a `[N]`
    struct-of-arrays pytree;
  * **position state** (open/pending flags, entry, quantity, SL/TP,
    quote balance) as `[N, S]` / `[N]` arrays, device-resident and DONATED
    through every dispatch (the tick-engine ring-buffer discipline);
  * **the decision program**: the analyzer verdict (the deterministic
    `TechnicalPolicyBackend` rule: confidence = min(strength/100, 1) ·
    scale, decision = technical signal) and `TradeExecutor.veto_reason`'s
    gate vocabulary re-expressed as traced predicates that resolve — in
    `obs.flightrec.VETO_ORDER`, the shared priority — to ONE gate id (i8,
    an index into `obs.flightrec.GATES`) per (tenant, symbol), plus the
    `backtest.signals.position_size` sizing the executor would compute.
    Within-tick sequencing is honest: a `lax.scan` over the symbol axis
    threads (open-position count, balance) per tenant, so symbol k+1 sees
    symbol k's entry exactly like the Python executor's sequential drain.

The program is routed through `Partitioner.population_eval` (tenants =
the population axis; features replicate; results all-gather), carded by
devprof (`tenant_engine` cost card + donation verifier) and watched by the
meshprof recompile/transfer sentinels — the standard hot-program contract.
N tenants' decisions per tick are ONE dispatch + ONE `host_read` instead
of N Python object traversals; the thin Python rim (testing/loadgen.py)
stays per-tenant only where the venue forces it: fills/journaling keep the
per-tenant client-order-id namespace and the decision readback fans out on
the existing `trading_signals.<lane>` channels.

The tenant axis pads to a power of two (min 8, like the tick engine's
symbol axis) so a ramp's nearby tenant counts share one compiled program;
padded tenants are masked `active=False` and emit NO_DECISION.  Venue
truth stays authoritative: when a placement diverges from the engine's
optimistic entry (venue rejected, balance drift), `revert_entry` patches
the host mirror and the next dispatch re-seeds state — a transfer, never
a recompile.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ai_crypto_trader_tpu.backtest.signals import position_size
from ai_crypto_trader_tpu.obs import fleetscope, tickpath
from ai_crypto_trader_tpu.obs.flightrec import GATES, VETO_ORDER
from ai_crypto_trader_tpu.utils import devprof, meshprof

#: gate id for "no gate fired — the decision is executable"
EXECUTABLE = -1
#: gate id for "no decision existed" (warming/padded symbol lane, padded
#: or deactivated tenant) — never counted as a veto
NO_DECISION = -2

#: gate name -> i8 id (index into the flight recorder's GATES vocabulary —
#: the single source of truth; the traced program emits THESE ids)
GATE_ID = {name: int(GATES.index(name)) for name in VETO_ORDER}
GATE_NAME = {i: name for name, i in GATE_ID.items()}

#: feature columns the decision program consumes, in scan order
FEATURE_KEYS = ("price", "signal", "strength", "volatility", "avg_volume",
                "valid")


def host_read(tree):
    """THE per-decide device→host sync (the tick-engine seam pattern):
    tests wrap it with a counting double; the transfer rides the shared
    ``host_read`` SLO window and the meshprof sanctioned-transfer scope."""
    t0 = time.perf_counter()
    with meshprof.allow_transfers():   # THE sanctioned device→host sync
        out = jax.device_get(tree)
    devprof.observe_latency("host_read", time.perf_counter() - t0)
    return out


# the tick engine's pow2-min-8 pad — ONE definition, because
# feats_from_tick slices the tick engine's [S_pad, F] arrays with THIS
# module's S: the two pads must never drift apart
from ai_crypto_trader_tpu.ops.tick_engine import (  # noqa: E402
    _pad_symbols as _pad_pow2,
    _precision_ctx,
)


#: decides a poisoned lane stays quarantined before the host healer may
#: re-seed it from venue truth (per-lane param — array content, so a
#: different cooldown never recompiles)
DEFAULT_QUARANTINE_COOLDOWN = 8


def tenant_params(n: int, trading=None, *, confidence_scale: float = 0.9,
                  fee_rate: float = 0.001,
                  quarantine_cooldown: int = DEFAULT_QUARANTINE_COOLDOWN,
                  ) -> dict:
    """Struct-of-arrays tenant params ([N] numpy leaves) seeded from one
    `TradingParams` (every tenant identical — the load harness default);
    heterogeneous fleets overwrite individual rows.  ``confidence_scale``
    is the deterministic analyzer backend's strength→confidence factor
    (shell/llm.TechnicalPolicyBackend); ``fee_rate`` mirrors the venue's
    taker fee so the balance carry tracks venue truth."""
    from ai_crypto_trader_tpu.config import TradingParams

    t = trading or TradingParams()
    full = lambda v, dt=np.float32: np.full((n,), v, dt)   # noqa: E731
    return {
        "conf_threshold": full(t.ai_confidence_threshold),
        "min_strength": full(t.min_signal_strength),
        "max_positions": full(t.max_positions, np.int32),
        "min_trade": full(t.min_trade_amount),
        "conf_scale": full(confidence_scale),
        "fee_rate": full(fee_rate),
        # live strategy_params overrides (bus `strategy_params` hot-swap):
        # NaN = none — the sizer's volatility-ladder SL/TP applies
        "sl_override": full(np.nan),
        "tp_override": full(np.nan),
        "active": np.ones((n,), bool),
        # fault containment (lane_quarantined gate): decides a poisoned
        # lane sits quarantined before the healer may re-seed it
        "cooldown_ticks": full(quarantine_cooldown, np.int32),
    }


@functools.lru_cache(maxsize=8)
def _tenant_program(partitioner, containment: bool = True):
    """One cached decision program per Partitioner: the tenant axis splits
    over the mesh data axis (population_eval), features replicate, and
    every output all-gathers.  jit shape-keys on (N_pad, S) internally, so
    one builder serves every engine size.

    ``containment`` traces the per-lane poison detector: NaN/Inf anywhere
    in a lane's slice of the donated state or its strategy params sets the
    lane's quarantine bit (sticky — array content in the carry, never a
    recompile) and every decision on that lane resolves to the
    ``lane_quarantined`` gate, so a poisoned lane is masked out of
    sizing/entry while its neighbors' scan carries stay bit-identical
    (vmap gives lane independence; the gate keeps NaN sizes off the
    host rim).  Per-SYMBOL feature poison stays the nan_gate's job —
    features are fleet-shared, so they can never single out a lane.
    ``containment=False`` compiles the predicates out entirely (the
    bench's containment_overhead_pct probe)."""

    def fn(pop, feats):
        def one(st, pr):
            n_open0 = st["open"].astype(jnp.int32).sum()
            # -- lane poison detector (fault containment) ---------------
            # sl/tp_override are EXCLUDED: NaN there is the documented
            # "no override" sentinel, not poison
            isf = jnp.isfinite
            lane_ok = (isf(st["balance"]) & isf(st["equity0"])
                       & isf(st["peak_equity"]) & isf(st["max_drawdown"])
                       & isf(st["entry"]).all() & isf(st["qty"]).all()
                       & isf(st["sl"]).all() & isf(st["tp"]).all()
                       & isf(pr["conf_threshold"]) & isf(pr["min_strength"])
                       & isf(pr["min_trade"]) & isf(pr["conf_scale"])
                       & isf(pr["fee_rate"]))
            if containment:
                poisoned = ~lane_ok
                newly = poisoned & ~st["quarantined"]
                quarantined = st["quarantined"] | poisoned
                # the cooldown arms on the quarantine EDGE and counts
                # decides from there (the poison itself persists in state
                # until the healer re-seeds, so re-detection must not
                # re-arm it); the healer waits for 0 before re-seeding
                cooldown = jnp.where(
                    newly, pr["cooldown_ticks"],
                    jnp.maximum(st["cooldown"]
                                - quarantined.astype(jnp.int32), 0))
                q_pred = quarantined
            else:
                quarantined = st["quarantined"]
                cooldown = st["cooldown"]
                q_pred = jnp.bool_(False)

            def step(carry, xs):
                n_open, bal = carry
                price, sig, strength, vol, avol, valid, is_open, pending = xs
                # analyzer verdict (TechnicalPolicyBackend._trade):
                # confidence from strength, decision = technical signal.
                # The backend ROUNDS to 3 decimals on its JSON surface —
                # reproduced here (half-to-even both sides) so the
                # confidence_floor gate can never disagree at the edge
                conf = jnp.minimum(strength / 100.0, 1.0) * pr["conf_scale"]
                conf = jnp.round(conf * 1e3) / 1e3
                decision = jnp.sign(sig).astype(jnp.int8)
                # executor sizing (handle_signal): volatility-ladder plan
                # capped at 95% of the current balance carry
                plan = position_size(bal, vol, avol)
                size = jnp.minimum(plan.size, bal * 0.95)
                fin = jnp.isfinite
                # veto_reason's predicates, one per VETO_ORDER entry
                preds = (
                    q_pred,                             # lane_quarantined
                    (~(fin(price) & (price > 0.0))) | ~fin(conf)
                    | ~fin(strength) | ~fin(vol) | ~fin(avol),  # nan_gate
                    conf < pr["conf_threshold"],        # confidence_floor
                    strength < pr["min_strength"],      # strength_floor
                    decision != 1,                      # not_buy
                    sig.astype(jnp.int8) != decision,   # signal_disagreement
                    is_open,                            # position_open
                    pending,                            # pending_intent
                    n_open >= pr["max_positions"],      # max_positions
                    size < pr["min_trade"],             # risk_min_size
                )
                # first gate in VETO_ORDER wins (iterate back-to-front so
                # the earliest predicate overwrites last)
                gate = jnp.int8(EXECUTABLE)
                for p, name in zip(reversed(preds), reversed(VETO_ORDER)):
                    gate = jnp.where(p, jnp.int8(GATE_ID[name]), gate)
                gate = jnp.where(valid & pr["active"], gate,
                                 jnp.int8(NO_DECISION))
                ok = gate == jnp.int8(EXECUTABLE)
                sl = plan.stop_loss_pct * 100.0
                tp = plan.take_profit_pct * 100.0
                sl = jnp.where(jnp.isfinite(pr["sl_override"]),
                               pr["sl_override"], sl)
                tp = jnp.where(jnp.isfinite(pr["tp_override"]),
                               pr["tp_override"], tp)
                qty = jnp.where(ok, size / jnp.where(price > 0.0, price, 1.0),
                                0.0)
                carry = (n_open + ok.astype(jnp.int32),
                         bal - jnp.where(ok,
                                         size * (1.0 + pr["fee_rate"]), 0.0))
                out = {"gate": gate, "decision": decision,
                       "confidence": conf, "size": size, "qty": qty,
                       "sl_pct": sl, "tp_pct": tp, "exec": ok}
                return carry, out

            xs = (feats["price"], feats["signal"], feats["strength"],
                  feats["volatility"], feats["avg_volume"], feats["valid"],
                  st["open"], st["pending"])
            (_, bal), ys = lax.scan(step, (n_open0, st["balance"]), xs)
            ok = ys["exec"]
            new_state = {
                "open": st["open"] | ok,
                "pending": st["pending"],
                "entry": jnp.where(ok, feats["price"], st["entry"]),
                "qty": jnp.where(ok, ys["qty"], st["qty"]),
                "sl": jnp.where(ok, ys["sl_pct"], st["sl"]),
                "tp": jnp.where(ok, ys["tp_pct"], st["tp"]),
                "balance": bal,
            }
            # per-lane fitness carry (the fleet observatory's input, kept
            # current whether or not fleetscope is on so a toggle never
            # loses PnL history): mark-to-market equity — stale symbols
            # (invalid this tick) mark at their entry price — plus the
            # monotone peak/max-drawdown fold.  Equity itself rides the
            # OUT tree, not the carry: the program never reads the
            # previous tick's equity, and a donated-but-unread input
            # would be pruned by XLA and fail to alias (the donation
            # verifier caught exactly that).
            price_eff = jnp.where(feats["valid"] & (feats["price"] > 0.0),
                                  feats["price"], new_state["entry"])
            pos_val = jnp.where(new_state["open"],
                                new_state["qty"] * price_eff, 0.0).sum()
            equity = bal + pos_val
            peak = jnp.maximum(st["peak_equity"], equity)
            new_state.update({
                "equity0": st["equity0"],
                "peak_equity": peak,
                "max_drawdown": jnp.maximum(st["max_drawdown"],
                                            peak - equity),
                "quarantined": quarantined,
                "cooldown": cooldown,
            })
            return new_state, (ys, equity)

        new_state, (outs, equity) = jax.vmap(one)(pop["state"],
                                                  pop["params"])
        outs = {**outs, "equity": equity}    # [N] mark-to-market per lane
        # params ride through verbatim so the donated pop tree aliases
        # onto the carry 1:1 (the donation verifier proves it)
        return {"carry": {"state": new_state, "params": pop["params"]},
                "out": outs}

    return partitioner.population_eval(fn, name="tenant_engine",
                                       donate_pop=True)


@functools.lru_cache(maxsize=16)
def _fleet_program(partitioner, top_k: int, s_real: int,
                   containment: bool = True):
    """The tenant program with the fleet observatory's aggregation traced
    INTO it (obs/fleetscope.py, the drift-PSI precedent): gate histogram,
    dispersion quantiles and the top-k rank table come out of the SAME
    dispatch, in the same output pytree, through the same one host_read —
    zero extra dispatches.  The partitioned inner program inlines here
    (the population_eval contract: traceable inside a larger jit), so the
    tenant axis still shards over the mesh and the aggregation runs on
    the all-gathered lane state.  ``s_real`` slices the pow2-padded
    symbol axis back to the engine's REAL universe before aggregating:
    pad columns are structurally NO_DECISION and would otherwise dilute
    the gate mix with phantom cells that vary with the pad width."""
    inner = _tenant_program(partitioner, containment)

    def fn(pop, feats):
        res = inner(pop, feats)
        st = res["carry"]["state"]
        res["fleet"] = fleetscope.device_aggregates(
            gate=res["out"]["gate"][:, :s_real],
            pnl=res["out"]["equity"] - st["equity0"],
            balance=st["balance"],
            max_drawdown=st["max_drawdown"],
            active=res["carry"]["params"]["active"],
            # a poisoned lane's NaN PnL must not take the FLEET's
            # dispersion quantiles/rank table down with it: quarantined
            # lanes still land in the gate histogram (their
            # lane_quarantined bin is the telemetry) but are masked out
            # of every value aggregate — blast radius = the lane
            quarantined=st["quarantined"],
            k=top_k)
        return res

    return jax.jit(fn, donate_argnums=(0,))


class TenantEngine:
    """Host-side driver: tenant state mirrors, the one-dispatch/one-sync
    decide step, and venue-truth corrections.

    ``decide(feats)`` runs the whole [N_pad, S] decision table as one
    dispatch and one host_read; ``configure(n)`` resizes the tenant axis
    (a fresh compiled shape — declared cold to the recompile sentinel);
    ``revert_entry`` patches the mirror when the venue disagreed with the
    engine's optimistic entry (the next dispatch re-seeds: a transfer,
    never a compile).
    """

    def __init__(self, symbols, n_tenants: int, trading=None, *,
                 partitioner=None, quote_balance: float = 10_000.0,
                 confidence_scale: float = 0.9, fee_rate: float = 0.001,
                 pad_pow2: bool = True, containment: bool = True,
                 quarantine_cooldown: int = DEFAULT_QUARANTINE_COOLDOWN,
                 precision: str | None = None):
        from ai_crypto_trader_tpu.parallel import SingleDevicePartitioner
        from ai_crypto_trader_tpu.models.train_loop import canonical_precision

        # matmul precision for the fused decide (the PR 2 knob, same
        # plumbing as ops/tick_engine.py); None = full f32 default.  The
        # precision participates in the jit cache key, so an engine built
        # with a different setting traces its own program — configure()
        # declares the next dispatch cold either way.
        canonical_precision(precision)     # validate eagerly, fail loud
        self.precision = precision
        self.symbols = list(symbols)
        self.sym_index = {s: i for i, s in enumerate(self.symbols)}
        self.S = _pad_pow2(len(self.symbols))      # tick-engine symbol pad
        self.partitioner = (partitioner if partitioner is not None
                            else SingleDevicePartitioner())
        self.quote_balance = float(quote_balance)
        self.confidence_scale = float(confidence_scale)
        self.fee_rate = float(fee_rate)
        self.pad_pow2 = bool(pad_pow2)
        self.containment = bool(containment)
        self.quarantine_cooldown = int(quarantine_cooldown)
        self.trading = trading
        self.dispatch_count = 0
        self.full_seeds = 0
        self.last_stats: dict = {}
        self.last_out: dict | None = None
        # fleet observatory surfaces (obs/fleetscope.py): the newest
        # decide's device aggregates, plus the venue-truth re-anchor
        # accounting the FleetBalanceDrift alert reads
        self.last_fleet: dict | None = None
        self.balance_resyncs = 0
        self._drift_pending = 0.0
        # fault-containment accounting (lane_quarantined): lifetime
        # counters like balance_resyncs — a reconfigure resets lane
        # STATE, not the operator's history of the process
        self.quarantine_trips = 0
        self.heals_total = 0
        self.configure(n_tenants)

    # -- shape / state lifecycle ---------------------------------------------
    def configure(self, n_tenants: int, trading=None) -> None:
        """(Re)build the tenant axis: fresh params + flat position state.
        A changed pad width is a new compiled shape BY DESIGN — the next
        dispatch is declared cold to the recompile sentinel."""
        if trading is not None:
            self.trading = trading
        self.n_tenants = int(n_tenants)
        self.n_pad = (_pad_pow2(self.n_tenants) if self.pad_pow2
                      else self.n_tenants)
        N, S = self.n_pad, self.S
        self._params_np = tenant_params(
            N, self.trading, confidence_scale=self.confidence_scale,
            fee_rate=self.fee_rate,
            quarantine_cooldown=self.quarantine_cooldown)
        self._params_np["active"][self.n_tenants:] = False
        self._state_np = {
            "open": np.zeros((N, S), bool),
            "pending": np.zeros((N, S), bool),
            "entry": np.zeros((N, S), np.float32),
            "qty": np.zeros((N, S), np.float32),
            "sl": np.zeros((N, S), np.float32),
            "tp": np.zeros((N, S), np.float32),
            "balance": np.full((N,), self.quote_balance, np.float32),
            # per-lane fitness carry (obs/fleetscope.py): the lane's
            # seeded equity (rolling PnL = current equity − this) and the
            # monotone peak/max-drawdown fold; current equity itself
            # rides the out tree (see _tenant_program)
            "equity0": np.full((N,), self.quote_balance, np.float32),
            "peak_equity": np.full((N,), self.quote_balance, np.float32),
            "max_drawdown": np.zeros((N,), np.float32),
            # fault containment: the quarantine bit + heal cooldown ride
            # the donated carry as array CONTENT — a lane tripping (or
            # healing) never changes the compiled shape
            "quarantined": np.zeros((N,), bool),
            "cooldown": np.zeros((N,), np.int32),
        }
        self._pop = None
        self._need_seed = True
        self._cold = True                  # expected compile for this shape
        self._fleet_key = None             # (on, k) of the last dispatch

    def set_tenant(self, i: int, *, balance: float | None = None,
                   open_symbols=(), pending_symbols=(), **params) -> None:
        """Overwrite one tenant's params/state rows (heterogeneous fleets,
        the gate-parity sweep).  Param keys are `tenant_params` fields;
        the change is array CONTENT — the next dispatch re-seeds, never
        recompiles."""
        for k, v in params.items():
            self._params_np[k][i] = v
        if balance is not None:
            self._state_np["balance"][i] = balance
            # a provisioned balance re-bases the lane's PnL accounting:
            # rolling PnL measures THIS lane's life from here
            self._state_np["equity0"][i] = balance
            self._state_np["peak_equity"][i] = balance
            self._state_np["max_drawdown"][i] = 0.0
        for sym in open_symbols:
            self._state_np["open"][i, self.sym_index[sym]] = True
        for sym in pending_symbols:
            self._state_np["pending"][i, self.sym_index[sym]] = True
        self._need_seed = True

    def set_live_overrides(self, stop_loss=None, take_profit=None) -> None:
        """Mirror the bus `strategy_params` hot-swap: like the object-lane
        executors (which all read the same bus key at entry time) the
        override is FLEET-WIDE — every row is overwritten, including
        heterogeneous per-tenant values set via `set_tenant` (exactly what
        a hot-swap does to object lanes).  NaN/None clears.  The no-op
        check compares the FULL arrays, so a fleet with per-tenant rows is
        never mistaken for already-applied.  A change re-seeds — params
        are array CONTENT, so a hot-swap never recompiles."""
        p = self._params_np
        sl = np.full_like(p["sl_override"],
                          np.nan if stop_loss is None else stop_loss)
        tp = np.full_like(p["tp_override"],
                          np.nan if take_profit is None else take_profit)
        if (np.array_equal(p["sl_override"], sl, equal_nan=True)
                and np.array_equal(p["tp_override"], tp, equal_nan=True)):
            return
        p["sl_override"] = sl
        p["tp_override"] = tp
        self._need_seed = True

    def sync_positions(self, tenant: int, held_symbols) -> bool:
        """Venue truth for the position set: a protective SL/TP fill (or
        any executor-side closure) pops the trade from the executor's
        books, and the engine's open flag must follow — a stale True
        would veto every future re-entry via position_open AND consume a
        max_positions slot in the scan carry forever.  Clears engine
        rows whose symbol the executor no longer holds; the balance
        credit rides `sync_balance`."""
        held = np.zeros(self.S, bool)
        for sym in held_symbols:
            s = self.sym_index.get(sym)
            if s is not None:
                held[s] = True
        st = self._state_np
        stale = st["open"][tenant] & ~held
        if not stale.any():
            return False
        st["open"][tenant, stale] = False
        st["entry"][tenant, stale] = 0.0
        st["qty"][tenant, stale] = 0.0
        self._need_seed = True
        return True

    def sync_balance(self, tenant: int, venue_balance: float,
                     rel_tol: float = 1e-5, expected: bool = False) -> bool:
        """Venue truth for the quote balance: protective SL/TP orders fill
        venue-side on later candles (crediting quote the engine's entry
        model never sees), so the rim re-anchors each trading tenant's
        balance on its venue every tick.  Tolerance absorbs the f32 carry
        vs f64 venue rounding — only a REAL divergence re-seeds.

        ``expected=True`` marks a re-anchor the rim can EXPLAIN (it just
        learned a position closure via `sync_positions`, so a balance
        jump of the position's size is venue truth doing its job); an
        UNEXPLAINED divergence is the fleet observatory's
        FleetBalanceDrift input — fee-model error, a rejected order the
        engine still booked, or mirror corruption."""
        cur = float(self._state_np["balance"][tenant])
        ref = max(abs(cur), abs(float(venue_balance)), 1.0)
        drift = abs(cur - float(venue_balance)) / ref
        if drift <= rel_tol:
            return False
        self.balance_resyncs += 1
        if not expected:
            # folded into the next decide's fleetscope observe, reset there
            self._drift_pending = max(self._drift_pending, drift)
        self._state_np["balance"][tenant] = np.float32(venue_balance)
        self._need_seed = True
        return True

    def revert_entry(self, tenant: int, symbol: str | int) -> None:
        """Venue truth correction: the optimistic entry for (tenant,
        symbol) did not actually land (rejected order, balance drift).
        Refund the balance carry, clear the position row, and flag a state
        re-seed for the next dispatch."""
        s = (symbol if isinstance(symbol, (int, np.integer))
             else self.sym_index[symbol])
        st = self._state_np
        if not st["open"][tenant, s]:
            return
        spent = st["qty"][tenant, s] * st["entry"][tenant, s]
        st["balance"][tenant] += spent * (1.0 + self.fee_rate)
        st["open"][tenant, s] = False
        st["entry"][tenant, s] = 0.0
        st["qty"][tenant, s] = 0.0
        self._need_seed = True

    # -- feature assembly -----------------------------------------------------
    def feats_from_tick(self, tick_out: dict, tick_valid, frame: int = 0,
                        due_mask=None) -> dict:
        """[S] feature columns straight from the fused tick engine's host
        output pytree (TickEngine.last_out / last_valid) — zero per-symbol
        dict assembly between the two fused programs.  ``due_mask`` marks
        the symbols the monitor actually PUBLISHED this tick (throttled /
        warming symbols produce no decision, like the object lanes)."""
        S = self.S
        take = lambda a: np.asarray(a[:S, frame], np.float32)  # noqa: E731
        valid = np.asarray(tick_valid[:S, frame], bool)
        if due_mask is not None:
            valid = valid & np.asarray(due_mask[:S], bool)
        return {
            "price": take(tick_out["current_price"]),
            "signal": np.asarray(tick_out["signal"][:S, frame], np.int32),
            "strength": take(tick_out["signal_strength"]),
            "volatility": take(tick_out["volatility"]),
            "avg_volume": take(tick_out["avg_volume"]),
            "valid": valid,
        }

    def feats_from_updates(self, updates: dict) -> dict:
        """[S] feature columns from per-symbol market_update payloads (the
        per-symbol monitor path / hand-built test fixtures)."""
        S = self.S
        sig_id = {"BUY": 1, "SELL": -1}
        out = {"price": np.zeros(S, np.float32),
               "signal": np.zeros(S, np.int32),
               "strength": np.zeros(S, np.float32),
               "volatility": np.zeros(S, np.float32),
               "avg_volume": np.zeros(S, np.float32),
               "valid": np.zeros(S, bool)}
        for sym, u in updates.items():
            s = self.sym_index.get(sym)
            if s is None:
                continue
            out["price"][s] = u.get("current_price", 0.0)
            out["signal"][s] = sig_id.get(u.get("signal"), 0)
            out["strength"][s] = u.get("signal_strength", 0.0)
            out["volatility"][s] = u.get("volatility", 0.0)
            out["avg_volume"][s] = u.get("avg_volume", 0.0)
            out["valid"][s] = True
        return out

    # -- the decide step ------------------------------------------------------
    def _seed_pop(self):
        pop = {"state": {k: jnp.asarray(v)
                         for k, v in self._state_np.items()},
               "params": {k: jnp.asarray(v)
                          for k, v in self._params_np.items()}}
        n_dev = max(getattr(self.partitioner, "device_count", 1), 1)
        if self.n_pad % n_dev == 0:
            # donated carries must START on the mesh layout to alias
            # (the lob_sweep precedent); ragged pads inside population_eval
            pop = self.partitioner.shard_population(pop)
        self.full_seeds += 1
        return pop

    def decide(self, feats: dict) -> dict:
        """ONE dispatch over every (tenant, symbol) + ONE host readback.
        Returns the trimmed [N, S] output views (gate/decision/confidence/
        size/qty/sl/tp/exec); the device carry (state + params) stays
        resident and donated into the next decide.  When the fleet
        observatory is active (obs/fleetscope.py — ONE module-global
        check) the same dispatch also emits the device-side fleet
        aggregates and the same host_read carries them back."""
        t_step0 = time.perf_counter()
        fs = fleetscope.active()
        fleet_key = ((True, fs.top_k, self.containment) if fs is not None
                     else (False, 0, self.containment))
        if self._fleet_key is not None and fleet_key != self._fleet_key:
            # toggling the observatory (or containment) swaps in a
            # different compiled program — a DECLARED recompile, never a
            # sentinel page
            self._cold = True
        self._fleet_key = fleet_key
        program = (_fleet_program(self.partitioner, fs.top_k,
                                  len(self.symbols), self.containment)
                   if fs is not None
                   else _tenant_program(self.partitioner, self.containment))
        upload_bytes = 0
        seeded = self._pop is None or self._need_seed
        if seeded:
            self._pop = self._seed_pop()
            upload_bytes += sum(int(np.asarray(v).nbytes)
                                for v in (*self._state_np.values(),
                                          *self._params_np.values()))
        feats_dev = {k: jnp.asarray(feats[k]) for k in FEATURE_KEYS}
        upload_bytes += sum(int(np.asarray(v).nbytes)
                            for v in feats.values())
        n_dev = max(getattr(self.partitioner, "device_count", 1), 1)
        carding = (devprof.active() is not None
                   and not devprof.has_card("tenant_engine"))
        if carding:
            devprof.cost_card("tenant_engine", program, self._pop, feats_dev)
        # donation is only CLAIMED on the alias-able layout (divisible
        # populations); a ragged pop pads through a concatenate whose
        # buffers free without aliasing — must not page the verifier
        donated = (jax.tree.leaves(self._pop)
                   if carding and self.n_pad % n_dev == 0 else None)
        # tickpath seams (obs/tickpath.py): the dispatch /
        # device_compute split rides one sentinel-leaf readiness wait —
        # not a transfer, not a second host_read (the tick-engine
        # discipline); the cold-start ledger window wraps the cold
        # dispatch's first compile.
        tp = tickpath.active()
        try:
            with tickpath.coldstart("tenant_engine", cold=self._cold), \
                    meshprof.watch("tenant_engine", cold=self._cold), \
                    _precision_ctx(self.precision):
                t_d0 = time.perf_counter()
                res = program(self._pop, feats_dev)
                t_d1 = time.perf_counter()
                if donated is not None:
                    devprof.verify_donation("tenant_engine", donated)
                self._pop = res["carry"]
                self.dispatch_count += 1
                self._cold = False
                self._need_seed = False
                if tp is not None:
                    t_w0 = time.perf_counter()
                    jax.block_until_ready(
                        jax.tree_util.tree_leaves(res["out"])[0])
                    t_ready = time.perf_counter()
                t_hr = time.perf_counter()
                tree = {"out": res["out"], "state": res["carry"]["state"]}
                if fs is not None:
                    tree["fleet"] = res["fleet"]
                host = host_read(tree)
                host_read_s = time.perf_counter() - t_hr
                # readiness-mark the whole carry: host_read only syncs
                # the leaves it pulls, and donating a carry leaf PJRT
                # hasn't marked ready degrades the next decide's dispatch
                # to synchronous execution on the CPU thunk runtime
                jax.block_until_ready(self._pop)
        except Exception:
            # a mid-step abort leaves the donated carry in an unknown
            # state; the host mirror is authoritative → next decide
            # re-seeds (a transfer, never a compile)
            self._need_seed = True
            raise
        # np.array COPIES: device_get may hand back read-only views, and
        # the mirror must stay mutable for venue-truth corrections
        prev_q = self._state_np["quarantined"]
        self._state_np = {k: np.array(v) for k, v in host["state"].items()}
        # quarantine TRIP edges (host accounting for the healer + alert):
        # lanes whose bit rose in this dispatch
        self.quarantine_trips += int(
            (self._state_np["quarantined"] & ~prev_q).sum())
        if n_dev > 1 and self.n_pad % n_dev != 0:
            # ragged pop on a mesh: population_eval pads 100→104 and
            # SLICES the all-gathered outputs back, so the carry's
            # sharding differs from the seed layout — feeding it back
            # would retrace the program on EVERY dispatch (caught by the
            # recompile sentinel in the verify drive).  Re-seed from the
            # just-refreshed host mirror instead: one extra transfer per
            # tick on this corner layout, never a recompile.  (The
            # default pow2 tenant pad is divisible by any pow2 device
            # count, so the hot path never takes this branch.)
            self._need_seed = True
        n = self.n_tenants
        self.last_out = {k: np.asarray(v)[:n] for k, v in host["out"].items()}
        self.last_fleet = ({k: np.asarray(v) for k, v in
                            host["fleet"].items()}
                           if fs is not None else None)
        drift, self._drift_pending = self._drift_pending, 0.0
        if fs is not None:
            # drift drains every decide whether or not a scope consumes
            # it — enabling the observatory later must not replay a
            # long-corrected divergence as a fresh FleetBalanceDrift
            fs.observe_decide(self.last_fleet, tenants=n,
                              balance_drift=drift,
                              balance_resyncs=self.balance_resyncs,
                              quarantined=int(
                                  self._state_np["quarantined"][:n].sum()),
                              heals=self.heals_total)
        self.last_stats = {
            "dispatches": 1, "tenants": n, "tenant_pad": self.n_pad,
            "symbols": len(self.symbols), "symbol_pad": self.S,
            "lanes": n * len(self.symbols),
            "devices": n_dev, "full_seed": bool(seeded),
            "upload_bytes": int(upload_bytes),
            "host_read_s": host_read_s,
            "step_s": time.perf_counter() - t_step0,
        }
        if tp is not None:
            dispatch_s = t_d1 - t_d0
            device_compute_s = t_ready - t_d1
            overlap_headroom_s = t_ready - t_w0
            self.last_stats.update({
                "dispatch_s": dispatch_s,
                "device_compute_s": device_compute_s,
                "overlap_headroom_s": overlap_headroom_s,
            })
            tp.observe_phase("dispatch", dispatch_s)
            tp.observe_phase("device_compute", device_compute_s)
            tp.observe_phase("host_read", host_read_s)
            tp.observe_overlap(overlap_headroom_s)
        return self.last_out

    # -- views ---------------------------------------------------------------
    def veto_counts(self, out: dict | None = None) -> dict:
        """{gate_name: count} over the newest decide's [N, S] gate table —
        the vmapped feed for ``decision_vetoes_total{gate=}`` (aggregated
        across tenants: one counter inc per gate per tick, not N·S Python
        recorder calls)."""
        out = out or self.last_out
        if not out:
            return {}
        ids = np.asarray(out["gate"], np.int64)
        counts = {}
        for gid, name in GATE_NAME.items():
            c = int((ids == gid).sum())
            if c:
                counts[name] = c
        return counts

    def executable(self, out: dict | None = None) -> list[tuple[int, int]]:
        """(tenant, symbol_index) pairs the newest decide cleared for
        entry, in the executor drain order (tenant-major, symbol order =
        the scan's sequential-semantics order)."""
        out = out or self.last_out
        if not out:
            return []
        return [(int(n), int(s)) for n, s in np.argwhere(out["exec"])]

    def open_positions(self) -> int:
        return int(self._state_np["open"][:self.n_tenants].sum())

    def balances(self) -> np.ndarray:
        return self._state_np["balance"][:self.n_tenants].copy()

    def rolling_pnl(self) -> np.ndarray:
        """[N] mark-to-market PnL since each lane's seed (the fleet
        observatory's ranking axis): newest decide's equity out minus the
        seeded equity; zeros before the first decide."""
        n = self.n_tenants
        if not self.last_out or "equity" not in self.last_out:
            return np.zeros(n, np.float32)
        return (np.asarray(self.last_out["equity"][:n])
                - self._state_np["equity0"][:n])

    def max_drawdowns(self) -> np.ndarray:
        return self._state_np["max_drawdown"][:self.n_tenants].copy()

    # -- fault containment: quarantine views + the host healer ---------------
    def quarantined_lanes(self) -> list[dict]:
        """Per-lane quarantine ledger off the host mirror (refreshed by
        the last decide): lane id, the gate it will resolve to, decides
        of cooldown remaining before the healer may act.  O(quarantined
        lanes), empty for a healthy fleet — `cli fleet`'s quarantine
        column and the soak's assertions both read THIS."""
        st = self._state_np
        out = []
        for i in np.nonzero(st["quarantined"][:self.n_tenants])[0]:
            out.append({"lane": int(i), "gate": "lane_quarantined",
                        "cooldown": int(st["cooldown"][i])})
        return out

    def heal_ready(self) -> list[int]:
        """Lanes whose quarantine cooldown has expired — the set the rim
        should re-seed from venue truth via :meth:`heal_lane`."""
        st = self._state_np
        mask = st["quarantined"][:self.n_tenants] \
            & (st["cooldown"][:self.n_tenants] <= 0)
        return [int(i) for i in np.nonzero(mask)[0]]

    def heal_lane(self, i: int, *, balance: float,
                  positions: dict | None = None) -> None:
        """Re-seed one quarantined lane from VENUE TRUTH: the poisoned
        state rows are discarded wholesale and rebuilt from the venue's
        quote balance plus the executor's position book (``positions``
        maps symbol -> (entry_price, quantity) for trades the venue
        still holds).  The healed lane's PnL accounting re-bases here —
        exactly a fresh `set_tenant` seed, which is what the heal-parity
        test pins.  Array content only: a heal re-seeds the next
        dispatch via transfer, never a recompile."""
        st = self._state_np
        st["open"][i] = False
        st["pending"][i] = False
        st["entry"][i] = 0.0
        st["qty"][i] = 0.0
        st["sl"][i] = 0.0
        st["tp"][i] = 0.0
        pos_value = 0.0
        for sym, (entry, qty) in (positions or {}).items():
            s = self.sym_index.get(sym)
            if s is None:
                continue
            st["open"][i, s] = True
            st["entry"][i, s] = np.float32(entry)
            st["qty"][i, s] = np.float32(qty)
            pos_value += float(entry) * float(qty)
        st["balance"][i] = np.float32(balance)
        equity = np.float32(float(balance) + pos_value)
        st["equity0"][i] = equity
        st["peak_equity"][i] = equity
        st["max_drawdown"][i] = 0.0
        st["quarantined"][i] = False
        st["cooldown"][i] = 0
        # a poisoned PARAM row would re-trip on the next dispatch: any
        # non-finite strategy param rolls back to the fleet default
        fresh = tenant_params(
            1, self.trading, confidence_scale=self.confidence_scale,
            fee_rate=self.fee_rate,
            quarantine_cooldown=self.quarantine_cooldown)
        for k, v in self._params_np.items():
            if (np.issubdtype(v.dtype, np.floating)
                    and k not in ("sl_override", "tp_override")
                    and not np.isfinite(v[i])):
                v[i] = fresh[k][0]
        self.heals_total += 1
        self._need_seed = True

    # -- durable fleet state: snapshot + restore -----------------------------
    def snapshot(self) -> dict:
        """The [N] lane-state mirror (already refreshed by the last
        decide's one host_read — snapshotting costs ZERO extra syncs) as
        a WAL-able payload: every array packed with its own checksum
        (utils/journal.pack_array), plus the identity the restore path
        validates against."""
        from ai_crypto_trader_tpu.utils.journal import pack_array

        return {
            "version": 1,
            "n_tenants": self.n_tenants,
            "symbols": list(self.symbols),
            "dispatches": self.dispatch_count,
            "counters": {"balance_resyncs": self.balance_resyncs,
                         "quarantine_trips": self.quarantine_trips,
                         "heals_total": self.heals_total},
            "state": {k: pack_array(v) for k, v in self._state_np.items()},
            "params": {k: pack_array(v) for k, v in self._params_np.items()},
        }

    def restore(self, payload: dict) -> dict:
        """Rebuild the lane mirrors from a :meth:`snapshot` payload (the
        PR 5 `recover()` matrix extended to vmapped mode).  Validates the
        symbol universe, re-shapes the tenant axis if it drifted, and
        unpacks every checksummed array; the next dispatch re-seeds from
        the restored mirror (a transfer — and a declared-cold compile
        only if the axis width actually changed).  The caller then
        reconciles lane-by-lane against venue truth (`sync_positions` /
        `sync_balance` / the executor's per-lane `ld<i>-` journal
        namespaces) — restore is the state floor, the venue is the
        authority.  Returns restore stats for the recovery report."""
        from ai_crypto_trader_tpu.utils.journal import unpack_array

        if payload.get("version") != 1:
            raise ValueError(f"unknown fleet snapshot version: "
                             f"{payload.get('version')!r}")
        if list(payload.get("symbols") or []) != self.symbols:
            raise ValueError("fleet snapshot symbol universe does not "
                             "match this engine")
        n = int(payload["n_tenants"])
        if n != self.n_tenants:
            self.configure(n)
        state = {k: unpack_array(v) for k, v in payload["state"].items()}
        params = {k: unpack_array(v) for k, v in payload["params"].items()}
        for name, mirror, restored in (("state", self._state_np, state),
                                       ("params", self._params_np, params)):
            missing = set(mirror) - set(restored)
            if missing:
                raise ValueError(f"fleet snapshot {name} misses "
                                 f"{sorted(missing)}")
            for k, v in restored.items():
                if k in mirror and v.shape != mirror[k].shape:
                    raise ValueError(
                        f"fleet snapshot {name}[{k}] shape {v.shape} != "
                        f"engine {mirror[k].shape}")
        # known leaves restore verbatim; leaves a NEWER snapshot carries
        # that this engine doesn't know are dropped, not injected
        self._state_np.update({k: v for k, v in state.items()
                               if k in self._state_np})
        self._params_np.update({k: v for k, v in params.items()
                                if k in self._params_np})
        counters = payload.get("counters") or {}
        self.balance_resyncs = int(counters.get("balance_resyncs", 0))
        self.quarantine_trips = int(counters.get("quarantine_trips", 0))
        self.heals_total = int(counters.get("heals_total", 0))
        self._need_seed = True
        return {
            "lanes": n,
            "open_positions": self.open_positions(),
            "quarantined": int(
                self._state_np["quarantined"][:n].sum()),
            "snapshot_dispatches": int(payload.get("dispatches", 0)),
        }
