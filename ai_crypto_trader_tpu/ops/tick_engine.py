"""Fused live-tick engine: one device program per poll for the whole universe.

PR 2 compiled the training loop; this compiles the SERVING path.  The
per-symbol monitor ran one jitted indicator program per (symbol × frame) —
O(S·F) dispatches per poll — then ~40 scalar device→host pulls per symbol
and re-uploaded the full kline window on every tick.  Podracer
(arXiv:2104.06272) and JAX-LOB (arXiv:2308.13289) both land on the same
shape for hot loops: keep state resident on device, batch the step across
the population, cross the host boundary once per step.  Three pieces:

  * a **device-resident ring buffer** `[S, F, T, 5]` holding the candle
    windows of the whole universe, donated through every step so XLA
    updates it in place.  Per tick the host uploads only the new/changed
    candle rows (a fixed-capacity scatter list; position ``T`` = dropped
    write), never whole windows: window ORDER lives in a per-(s, f) ring
    base pointer, so a window that advanced by k candles costs k row
    writes instead of a T-row roll;
  * **one jitted program** (`_tick_program`): scatter the row updates,
    gather time-ordered windows, then indicators → signal features →
    reference signal → volume profile → the 15 combination families →
    confluence for every (symbol, frame) lane at once.  The kernels in
    ops.indicators / ops.combinations / backtest.signals are written
    against the trailing time axis, so the whole table batches with no
    explicit vmap; volume_profile vmaps internally.  Warm-up is a traced
    ``valid`` mask — cold frames NaN their outputs in-program instead of
    changing the program shape, so a symbol crossing warming→full (or a
    venue hiccup shrinking a window) triggers ZERO recompiles;
  * a single `host_read` (jax.device_get) of the last-candle feature
    pytree — the only device→host sync per poll, kept as a module seam so
    tests can count it (the models/train_loop.host_read pattern).

Symbol count is padded up to a power-of-two bucket (min 8) and frame
count up to 4 so monitors with nearby universe sizes share one compiled
program; dead lanes are masked invalid and cost only device FLOPs.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ai_crypto_trader_tpu import ops
from ai_crypto_trader_tpu.obs import tickpath
from ai_crypto_trader_tpu.utils import devprof, meshprof
from ai_crypto_trader_tpu.backtest import compute_signal_features, reference_signal
from ai_crypto_trader_tpu.obs.drift import DRIFT_FEATURES, N_BINS, PSI_EPS
from ai_crypto_trader_tpu.ops.combinations import (
    combination_signal,
    combined_indicators,
)
from ai_crypto_trader_tpu.ops.volume_profile import volume_profile


def host_read(tree):
    """THE per-poll device→host sync: output pytree → numpy pytree.

    Module-level seam (like models/train_loop.host_read) so tests can wrap
    it with a counting double and assert one sync per poll.  The transfer
    is timed into the ``host_read`` SLO window (utils/devprof.py) — sync
    time is where a device-queue stall first becomes visible."""
    t0 = time.perf_counter()
    with meshprof.allow_transfers():   # THE sanctioned device→host sync
        out = jax.device_get(tree)
    devprof.observe_latency("host_read", time.perf_counter() - t0)
    return out


def _pad_symbols(n: int) -> int:
    p = 8
    while p < n:
        p *= 2
    return p


def _pad_frames(n: int) -> int:
    return max(n, 4)


def _drift_hist(x, lo, hi):
    """[..., T] feature series → [..., N_BINS] window histogram
    probabilities against the fixed edges (obs/drift.py spec).  NaNs
    (warm-up lanes) land in bin 0; those lanes are masked invalid
    downstream anyway."""
    T = x.shape[-1]
    idx = jnp.clip((x - lo) / (hi - lo) * N_BINS, 0, N_BINS - 1)
    idx = jnp.nan_to_num(idx).astype(jnp.int32)
    onehot = idx[..., None] == jnp.arange(N_BINS, dtype=jnp.int32)
    return onehot.sum(axis=-2) / T


@functools.partial(jax.jit, donate_argnums=(0,))
def _tick_program(ring, base, rows, s_ix, f_ix, pos, valid, drift_ref):
    """Scatter row updates into the donated ring, then compute the whole
    last-candle feature table for every (symbol, frame) lane.

    ring  [S, F, T, 5]  donated candle ring buffer (OHLCV rows)
    base  [S, F]        ring base pointer: window index i lives at ring
                        position (base + i) % T
    rows  [W, 5]        new/changed candle rows (W = fixed capacity)
    s_ix, f_ix, pos [W] scatter coordinates; pos == T marks an unused
                        slot (dropped by mode="drop")
    valid [S, F]        warm frames; cold lanes get NaN outputs in-program
                        (int outputs 0) so warm-up never changes the shape
    drift_ref [S, F, K, B]  per-feature reference histograms (training-time
                        stats, or the first full window captured host-side);
                        PSI vs the live window rides the SAME output pytree —
                        zero extra dispatches, zero extra host readbacks
    """
    S, F, T, _ = ring.shape
    ring = ring.at[s_ix, f_ix, pos].set(rows, mode="drop")
    idx = (base[:, :, None] + jnp.arange(T, dtype=jnp.int32)) % T
    win = jnp.take_along_axis(ring, idx[..., None], axis=2)
    names = ("open", "high", "low", "close", "volume")
    ohlcv = {k: win[..., i] for i, k in enumerate(names)}

    ind = ops.compute_indicators(ohlcv)
    feats = compute_signal_features(ind)
    signal, strength = reference_signal(feats)
    vp = volume_profile(ohlcv["high"], ohlcv["low"], ohlcv["close"],
                        ohlcv["volume"])
    combos = combined_indicators(ind)
    confluence = combination_signal(combos)
    close = ohlcv["close"]

    # on-device drift: per-feature window histograms + PSI vs drift_ref
    drift_series = {
        "rsi": ind["rsi"],
        "stoch_k": ind["stoch_k"],
        "bb_position": ind["bb_position"],
        "macd_norm": jnp.where(close != 0.0, ind["macd"] / close, 0.0),
        "volatility": feats.volatility,
    }
    live_hist = jnp.stack(
        [_drift_hist(drift_series[name], lo, hi)
         for name, lo, hi in DRIFT_FEATURES], axis=-2)     # [S, F, K, B]
    p = live_hist + PSI_EPS
    q = drift_ref + PSI_EPS
    drift_psi = ((p - q) * jnp.log(p / q)).sum(-1)          # [S, F, K]

    def chg(n):
        # same guard as the host-side chg(): windows shorter than n → 0.0
        if T <= n:
            return jnp.zeros(close.shape[:-1], close.dtype)
        prev = close[..., -1 - n]
        return (close[..., -1] - prev) / prev * 100.0

    fm = lambda x: jnp.where(valid, x, jnp.nan)             # noqa: E731
    im = lambda x: jnp.where(valid, x, 0).astype(jnp.int32)  # noqa: E731
    out = {
        "current_price": fm(close[..., -1]),
        "rsi": fm(ind["rsi"][..., -1]),
        "stoch_k": fm(ind["stoch_k"][..., -1]),
        "macd": fm(ind["macd"][..., -1]),
        "williams_r": fm(ind["williams_r"][..., -1]),
        "bb_position": fm(ind["bb_position"][..., -1]),
        "atr": fm(ind["atr"][..., -1]),
        "volatility": fm(feats.volatility[..., -1]),
        "trend": im(feats.trend[..., -1]),
        "trend_strength": fm(feats.trend_strength[..., -1]),
        "avg_volume": fm(feats.volume[..., -1]),
        "signal": im(signal[..., -1]),
        "signal_strength": fm(strength[..., -1]),
        "chg_1": fm(chg(1)), "chg_3": fm(chg(3)),
        "chg_5": fm(chg(5)), "chg_15": fm(chg(15)),
        "poc_price": fm(vp["poc_price"]),
        "value_area_low": fm(vp["value_area_low"]),
        "value_area_high": fm(vp["value_area_high"]),
        "confluence": fm(confluence[..., -1]),
        "combo": {k: fm(v[..., -1]) for k, v in combos.items()},
        # popped by step() into last_drift — never part of the published
        # feature payload, so fused↔per-symbol parity is untouched
        "drift_psi": jnp.where(valid[..., None], drift_psi, jnp.nan),
        "drift_hist": live_hist,
    }
    return ring, out


class TickEngine:
    """Host-side driver of the fused program: kline diffing, the ring
    mirrors, and the one-dispatch/one-sync step.

    ``ingest(symbol, interval, klines)`` queues the delta between the new
    window and the device ring (typically 1-2 rows: the freshly closed
    candle plus the updated in-progress bar).  A slot whose delta exceeds
    ``max_new`` rows (cold start, reconnect gap, venue correction storm)
    is re-seeded: the whole buffer re-uploads once via device_put — a
    transfer, not a compile.  ``step()`` then runs ONE jitted dispatch for
    every (symbol, frame) lane and performs ONE host_read.
    """

    def __init__(self, symbols, intervals, window: int = 256,
                 max_new: int = 8):
        self.symbols = list(symbols)
        self.intervals = tuple(intervals)
        self.window = int(window)
        self.max_new = int(max_new)
        self.sym_index = {s: i for i, s in enumerate(self.symbols)}
        self.iv_index = {iv: i for i, iv in enumerate(self.intervals)}
        S = _pad_symbols(len(self.symbols))
        F = _pad_frames(len(self.intervals))
        T = self.window
        # time-ordered window mirror + timestamps (diffing) and the
        # ring-layout mirror (reseed source; always current)
        self._win = np.zeros((S, F, T, 5), np.float32)
        self._ts = np.zeros((S, F, T), np.int64)
        self._ring_np = np.zeros((S, F, T, 5), np.float32)
        self._base = np.zeros((S, F), np.int32)
        self._count = np.zeros((S, F), np.int32)
        self._ring = None                      # device buffer, donated
        # drift reference histograms (obs/drift.py): uniform until a
        # training-time reference is installed (set_drift_reference) or the
        # first full window is captured per lane; kept device-resident and
        # re-uploaded only when a reference changes — never per tick
        K, B = len(DRIFT_FEATURES), N_BINS
        self._drift_ref_np = np.full((S, F, K, B), 1.0 / B, np.float32)
        self._drift_ref_set = np.zeros((S, F), bool)
        self._drift_ref = None
        self.drift_ref_uploads = 0
        self.last_drift: dict = {}
        # queued writes this poll, keyed (s, f, pos) so a second ingest of
        # the same slot between steps overwrites rather than duplicates —
        # duplicate scatter indices pick an implementation-defined winner
        # in XLA, which could desync the device ring from the host mirror
        self._pending: dict = {}               # (s, f, pos) -> row
        self._need_seed = True
        self.dispatch_count = 0
        self.full_seeds = 0
        self.last_valid = np.zeros((S, F), bool)
        self.last_stats: dict = {}
        self.last_out: dict | None = None   # newest host output pytree
        # newest venue event time (ms) per symbol: candle open times from
        # the ingest paths, upgraded to the exchange's event-time E by the
        # stream (note_event_ms) — the event_age_ms source the monitor
        # stamps onto published updates (obs/tickpath.py)
        self.last_event_ms: dict[str, float] = {}

    # -- ingest ---------------------------------------------------------------
    def _seed_slot(self, s: int, f: int, ts: np.ndarray, arr: np.ndarray):
        self._win[s, f] = arr
        self._ts[s, f] = ts
        self._base[s, f] = 0
        self._ring_np[s, f] = arr
        self._count[s, f] = self.window
        self._need_seed = True
        self.full_seeds += 1
        # queued incremental writes for this slot are superseded
        self._pending = {k: v for k, v in self._pending.items()
                         if not (k[0] == s and k[1] == f)}

    def note_event_ms(self, symbol: str, event_ms: float) -> None:
        """Record a fresher venue event time for ``symbol`` (monotone max:
        candle open times are a lower bound, the stream's exchange E the
        true value)."""
        if event_ms > self.last_event_ms.get(symbol, 0.0):
            self.last_event_ms[symbol] = float(event_ms)

    # -- drift reference ------------------------------------------------------
    def set_drift_reference(self, symbol: str, interval: str,
                            probs: np.ndarray) -> None:
        """Install training-time reference stats ([K, N_BINS] probabilities,
        obs/drift.reference_histogram) for one (symbol, interval) lane.
        One device_put per change — a transfer, never a recompile."""
        s = self.sym_index[symbol]
        f = self.iv_index[interval]
        self._drift_ref_np[s, f] = np.asarray(probs, np.float32)
        self._drift_ref_set[s, f] = True
        self._drift_ref = jnp.asarray(self._drift_ref_np)
        self.drift_ref_uploads += 1

    def ingest_row(self, symbol: str, interval: str, row: list) -> bool:
        """Streamed-row upload seam: apply ONE candle row to a warm lane —
        O(1) scatter-list work instead of a full-window diff.

        Returns True when applied (in-progress-bar replacement, or an
        append that advances the ring by exactly one candle).  False means
        the caller must seed/backfill the lane through the full-window
        ``ingest`` path: lane still warming, timestamp gap, or an
        out-of-order row — a streamed row can NEVER tear the ring."""
        s = self.sym_index.get(symbol)
        f = self.iv_index.get(interval)
        if s is None or f is None:
            return False
        self.note_event_ms(symbol, float(row[0]))
        T = self.window
        if self._count[s, f] < T:
            return False                       # warming: needs a full seed
        ts = int(row[0])
        arr = np.asarray(row[1:6], np.float32)
        tail = self._ts[s, f]
        if ts == int(tail[-1]):                # in-progress bar update
            if np.array_equal(self._win[s, f, -1], arr):
                return True                    # exact duplicate: no write
            self._win[s, f, -1] = arr
            pos = (int(self._base[s, f]) + T - 1) % T
        elif ts > int(tail[-1]):
            step = int(tail[-1] - tail[-2]) if T >= 2 else 0
            if step <= 0 or ts != int(tail[-1]) + step:
                return False                   # gap/misalignment: re-seed
            self._ts[s, f] = np.roll(tail, -1)
            self._ts[s, f, -1] = ts
            self._win[s, f] = np.roll(self._win[s, f], -1, axis=0)
            self._win[s, f, -1] = arr
            base = (int(self._base[s, f]) + 1) % T
            self._base[s, f] = base
            pos = (base + T - 1) % T
        else:
            return False                       # older than the window tail
        self._ring_np[s, f, pos] = arr
        self._pending[(s, f, pos)] = arr       # latest write wins
        return True

    def ingest(self, symbol: str, interval: str, klines: list) -> None:
        """Diff one (symbol, frame) kline window against the device ring and
        queue only the new/changed rows for the next step()."""
        s = self.sym_index[symbol]
        f = self.iv_index[interval]
        if klines:
            self.note_event_ms(symbol, float(klines[-1][0]))
        T = self.window
        rows = klines[-T:]
        if len(rows) < T:
            self._count[s, f] = len(rows)      # warming: lane stays invalid
            return
        arr = np.asarray([r[1:6] for r in rows], np.float32)
        ts = np.asarray([int(r[0]) for r in rows], np.int64)
        if self._count[s, f] < T:
            self._seed_slot(s, f, ts, arr)     # warming → full transition
            return
        old_ts = self._ts[s, f]
        j = int(np.searchsorted(old_ts, ts[0]))
        if j >= T or old_ts[j] != ts[0] \
                or not np.array_equal(old_ts[j:], ts[:T - j]):
            self._seed_slot(s, f, ts, arr)     # gap/misalignment: re-seed
            return
        k = j                                  # window advanced by k candles
        changed = np.flatnonzero(
            (arr[:T - k] != self._win[s, f, k:]).any(axis=1))
        writes = list(changed) + list(range(T - k, T))
        if len(writes) > self.max_new:
            self._seed_slot(s, f, ts, arr)
            return
        base = (int(self._base[s, f]) + k) % T
        self._base[s, f] = base
        for i in writes:
            pos = (base + i) % T
            self._ring_np[s, f, pos] = arr[i]
            self._pending[(s, f, pos)] = arr[i]   # latest write wins
        self._win[s, f] = arr
        self._ts[s, f] = ts

    # -- step -----------------------------------------------------------------
    def step(self) -> dict:
        """ONE fused dispatch over every (symbol, frame) lane + ONE host
        readback.  Returns the numpy output pytree ([S, F] per feature);
        per-step transfer/dispatch accounting lands in ``last_stats``."""
        t_step0 = time.perf_counter()
        S, F, T = self._ring_np.shape[:3]
        W = S * F * self.max_new               # scatter capacity
        if len(self._pending) > W:             # paranoia: spilled capacity
            self._need_seed = True
        rows = np.zeros((W, 5), np.float32)
        s_ix = np.zeros((W,), np.int32)
        f_ix = np.zeros((W,), np.int32)
        pos = np.full((W,), T, np.int32)       # T = dropped write
        upload_bytes = 0
        seeded = self._ring is None or self._need_seed
        if seeded:
            self._ring = jnp.asarray(self._ring_np)   # transfer, no compile
            upload_bytes += self._ring_np.nbytes
            n_writes = 0
            self._pending.clear()              # already inside the seed
        else:
            n_writes = len(self._pending)
            for w, ((ps, pf, p), row) in enumerate(self._pending.items()):
                s_ix[w] = ps
                f_ix[w] = pf
                pos[w] = p
                rows[w] = row
            self._pending.clear()
            upload_bytes += (rows.nbytes + s_ix.nbytes + f_ix.nbytes
                             + pos.nbytes)
        valid = self._count >= T
        if self._drift_ref is None:
            self._drift_ref = jnp.asarray(self._drift_ref_np)
        # one-shot cost card + donation verification on the first carded
        # dispatch (utils/devprof.py; disabled = one attribute read)
        carding = (devprof.active() is not None
                   and not devprof.has_card("tick_engine"))
        if carding:
            devprof.cost_card("tick_engine", _tick_program, self._ring,
                              self._base, rows, s_ix, f_ix, pos, valid,
                              self._drift_ref)
        donated_ring = self._ring if carding else None
        # meshprof watch window (utils/meshprof.py; disabled = one module
        # check): attributes any compile during this dispatch to
        # "tick_engine" — a compile after warmup is a counted steady-state
        # recompile + SteadyStateRecompile alert — and arms the
        # device→host transfer guard from dispatch through the sanctioned
        # host_read, so a stray host pull on the fused path becomes a
        # counted gauge instead of invisible latency.  A fresh engine's
        # FIRST dispatch is cold: the monitor rebuilds the engine when the
        # universe/window changes (each is a compiled-shape input by
        # design), and the sentinel's window count is global across
        # instances — within one engine the array shapes are fixed, so any
        # later compile is genuinely unexpected.
        # tickpath phase seams (obs/tickpath.py; disabled = one module
        # check): the scatter-build / dispatch / device_compute /
        # host_read decomposition rides the existing perf_counter stamps
        # and ONE sentinel-leaf readiness wait — a wait, not a transfer
        # (the meshprof guard stays armed) and not a second host_read
        # (the one-sync contract test keeps counting 1).  The wait is
        # time host_read would have blocked anyway, re-attributed from
        # the transfer to the compute it actually was.
        tp = tickpath.active()
        try:
            with tickpath.coldstart("tick_engine",
                                    cold=self.dispatch_count == 0), \
                    meshprof.watch("tick_engine",
                                   cold=self.dispatch_count == 0):
                t_d0 = time.perf_counter()
                self._ring, out = _tick_program(self._ring, self._base,
                                                rows, s_ix, f_ix, pos,
                                                valid, self._drift_ref)
                t_d1 = time.perf_counter()
                if donated_ring is not None:
                    devprof.verify_donation("tick_engine", donated_ring)
                self.dispatch_count += 1
                self._need_seed = False
                self.last_valid = valid
                if tp is not None:
                    # host-idle window between dispatch-return and
                    # readback-start: the overlap headroom item-4
                    # pipelining can fill with host work
                    t_w0 = time.perf_counter()
                    jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])
                    t_ready = time.perf_counter()
                t_hr = time.perf_counter()
                host = host_read(out)
                host_read_s = time.perf_counter() - t_hr
        except Exception:
            # a mid-step abort (counted guard violation, XLA runtime
            # error) leaves the donated device ring in an unknown state;
            # the host mirror is authoritative, so the next step re-seeds
            # — a transfer, never a compile
            self._need_seed = True
            raise
        # drift outputs ride the same readback; pop them into last_drift so
        # the published feature payload (and the fused↔per-symbol parity
        # contract) is unchanged.  PSI is only meaningful where a reference
        # existed BEFORE this dispatch; lanes past warm-up with no reference
        # capture this window's histogram as their baseline (one device_put,
        # no recompile — pathology stays array content).
        drift_hist = host.pop("drift_hist")
        drift_psi = host.pop("drift_psi")
        ref_was_set = self._drift_ref_set.copy()
        newly = valid & ~self._drift_ref_set
        if newly.any():
            self._drift_ref_np[newly] = drift_hist[newly]
            self._drift_ref_set |= valid
            self._drift_ref = jnp.asarray(self._drift_ref_np)
            self.drift_ref_uploads += 1
        self.last_drift = {"psi": drift_psi, "hist": drift_hist,
                           "ref_set": ref_was_set}
        # newest host output pytree: the tenant engine's feed
        # (ops/tenant_engine.py reads its [S, F] feature columns directly —
        # no per-symbol dict assembly between the two fused programs)
        self.last_out = host
        self.last_stats = {
            "dispatches": 1, "upload_rows": int(n_writes),
            "upload_bytes": int(upload_bytes), "full_seed": bool(seeded),
            "lanes": int(S * F), "valid_lanes": int(valid.sum()),
            # saturation telemetry (utils/saturation.py): scatter-list
            # occupancy headroom and the host-readback share of tick time
            "scatter_capacity": int(W), "host_read_s": host_read_s,
            "step_s": time.perf_counter() - t_step0,
        }
        if tp is not None:
            scatter_build_s = t_d0 - t_step0
            dispatch_s = t_d1 - t_d0
            device_compute_s = t_ready - t_d1
            overlap_headroom_s = t_ready - t_w0
            self.last_stats.update({
                "scatter_build_s": scatter_build_s,
                "dispatch_s": dispatch_s,
                "device_compute_s": device_compute_s,
                "overlap_headroom_s": overlap_headroom_s,
            })
            tp.observe_phase("scatter_build", scatter_build_s)
            tp.observe_phase("dispatch", dispatch_s)
            tp.observe_phase("device_compute", device_compute_s)
            tp.observe_phase("host_read", host_read_s)
            tp.observe_overlap(overlap_headroom_s)
        return host
