"""Fused live-tick engine: one device program per poll for the whole universe.

PR 2 compiled the training loop; this compiles the SERVING path.  The
per-symbol monitor ran one jitted indicator program per (symbol × frame) —
O(S·F) dispatches per poll — then ~40 scalar device→host pulls per symbol
and re-uploaded the full kline window on every tick.  Podracer
(arXiv:2104.06272) and JAX-LOB (arXiv:2308.13289) both land on the same
shape for hot loops: keep state resident on device, batch the step across
the population, cross the host boundary once per step.  Three pieces:

  * a **device-resident ring buffer** `[S, F, T, 5]` holding the candle
    windows of the whole universe, donated through every step so XLA
    updates it in place.  Per tick the host uploads only the new/changed
    candle rows (a fixed-capacity scatter list; position ``T`` = dropped
    write), never whole windows: window ORDER lives in a per-(s, f) ring
    base pointer, so a window that advanced by k candles costs k row
    writes instead of a T-row roll;
  * **one jitted program** (`_tick_program`): scatter the row updates,
    gather time-ordered windows, then indicators → signal features →
    reference signal → volume profile → the 15 combination families →
    confluence for every (symbol, frame) lane at once.  The kernels in
    ops.indicators / ops.combinations / backtest.signals are written
    against the trailing time axis, so the whole table batches with no
    explicit vmap; volume_profile vmaps internally.  Warm-up is a traced
    ``valid`` mask — cold frames NaN their outputs in-program instead of
    changing the program shape, so a symbol crossing warming→full (or a
    venue hiccup shrinking a window) triggers ZERO recompiles;
  * a single `host_read` (jax.device_get) of the last-candle feature
    pytree — the only device→host sync per poll, kept as a module seam so
    tests can count it (the models/train_loop.host_read pattern).

Symbol count is padded up to a power-of-two bucket (min 8) and frame
count up to 4 so monitors with nearby universe sizes share one compiled
program; dead lanes are masked invalid and cost only device FLOPs.
"""

from __future__ import annotations

import contextlib
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ai_crypto_trader_tpu import ops
from ai_crypto_trader_tpu.obs import tickpath
from ai_crypto_trader_tpu.utils import devprof, meshprof
from ai_crypto_trader_tpu.backtest import compute_signal_features, reference_signal
from ai_crypto_trader_tpu.obs.drift import DRIFT_FEATURES, N_BINS, PSI_EPS
from ai_crypto_trader_tpu.ops.combinations import (
    combination_signal,
    combined_indicators,
)
from ai_crypto_trader_tpu.ops.volume_profile import volume_profile


def host_read(tree):
    """THE per-poll device→host sync: output pytree → numpy pytree.

    Module-level seam (like models/train_loop.host_read) so tests can wrap
    it with a counting double and assert one sync per poll.  The transfer
    is timed into the ``host_read`` SLO window (utils/devprof.py) — sync
    time is where a device-queue stall first becomes visible."""
    t0 = time.perf_counter()
    with meshprof.allow_transfers():   # THE sanctioned device→host sync
        out = jax.device_get(tree)
    devprof.observe_latency("host_read", time.perf_counter() - t0)
    return out


def _precision_ctx(precision: str | None):
    """Matmul-precision context for the fused dispatch: the PR 2 knob
    (models/train_loop.matmul_precision) threaded through the tick path.
    None = backend default (f32 on CPU) = a zero-cost nullcontext.  The
    precision participates in the jit cache key, so a bf16 engine traces
    its OWN compiled program — declared cold like any fresh engine."""
    if precision is None:
        return contextlib.nullcontext()
    from ai_crypto_trader_tpu.models.train_loop import matmul_precision

    return matmul_precision(precision)


def _pad_symbols(n: int) -> int:
    p = 8
    while p < n:
        p *= 2
    return p


def _pad_frames(n: int) -> int:
    return max(n, 4)


def _drift_hist(x, lo, hi):
    """[..., T] feature series → [..., N_BINS] window histogram
    probabilities against the fixed edges (obs/drift.py spec).  NaNs
    (warm-up lanes) land in bin 0; those lanes are masked invalid
    downstream anyway."""
    T = x.shape[-1]
    idx = jnp.clip((x - lo) / (hi - lo) * N_BINS, 0, N_BINS - 1)
    idx = jnp.nan_to_num(idx).astype(jnp.int32)
    onehot = idx[..., None] == jnp.arange(N_BINS, dtype=jnp.int32)
    return onehot.sum(axis=-2) / T


@functools.partial(jax.jit, donate_argnums=(0,))
def _tick_program(ring, base, rows, s_ix, f_ix, pos, valid, drift_ref):
    """Scatter row updates into the donated ring, then compute the whole
    last-candle feature table for every (symbol, frame) lane.

    ring  [S, F, T, 5]  donated candle ring buffer (OHLCV rows)
    base  [S, F]        ring base pointer: window index i lives at ring
                        position (base + i) % T
    rows  [W, 5]        new/changed candle rows (W = fixed capacity)
    s_ix, f_ix, pos [W] scatter coordinates; pos == T marks an unused
                        slot (dropped by mode="drop")
    valid [S, F]        warm frames; cold lanes get NaN outputs in-program
                        (int outputs 0) so warm-up never changes the shape
    drift_ref [S, F, K, B]  per-feature reference histograms (training-time
                        stats, or the first full window captured host-side);
                        PSI vs the live window rides the SAME output pytree —
                        zero extra dispatches, zero extra host readbacks
    """
    S, F, T, _ = ring.shape
    ring = ring.at[s_ix, f_ix, pos].set(rows, mode="drop")
    idx = (base[:, :, None] + jnp.arange(T, dtype=jnp.int32)) % T
    win = jnp.take_along_axis(ring, idx[..., None], axis=2)
    names = ("open", "high", "low", "close", "volume")
    ohlcv = {k: win[..., i] for i, k in enumerate(names)}

    ind = ops.compute_indicators(ohlcv)
    feats = compute_signal_features(ind)
    signal, strength = reference_signal(feats)
    vp = volume_profile(ohlcv["high"], ohlcv["low"], ohlcv["close"],
                        ohlcv["volume"])
    combos = combined_indicators(ind)
    confluence = combination_signal(combos)
    close = ohlcv["close"]

    # on-device drift: per-feature window histograms + PSI vs drift_ref
    drift_series = {
        "rsi": ind["rsi"],
        "stoch_k": ind["stoch_k"],
        "bb_position": ind["bb_position"],
        "macd_norm": jnp.where(close != 0.0, ind["macd"] / close, 0.0),
        "volatility": feats.volatility,
    }
    live_hist = jnp.stack(
        [_drift_hist(drift_series[name], lo, hi)
         for name, lo, hi in DRIFT_FEATURES], axis=-2)     # [S, F, K, B]
    p = live_hist + PSI_EPS
    q = drift_ref + PSI_EPS
    drift_psi = ((p - q) * jnp.log(p / q)).sum(-1)          # [S, F, K]

    def chg(n):
        # same guard as the host-side chg(): windows shorter than n → 0.0
        if T <= n:
            return jnp.zeros(close.shape[:-1], close.dtype)
        prev = close[..., -1 - n]
        return (close[..., -1] - prev) / prev * 100.0

    fm = lambda x: jnp.where(valid, x, jnp.nan)             # noqa: E731
    im = lambda x: jnp.where(valid, x, 0).astype(jnp.int32)  # noqa: E731
    out = {
        "current_price": fm(close[..., -1]),
        "rsi": fm(ind["rsi"][..., -1]),
        "stoch_k": fm(ind["stoch_k"][..., -1]),
        "macd": fm(ind["macd"][..., -1]),
        "williams_r": fm(ind["williams_r"][..., -1]),
        "bb_position": fm(ind["bb_position"][..., -1]),
        "atr": fm(ind["atr"][..., -1]),
        "volatility": fm(feats.volatility[..., -1]),
        "trend": im(feats.trend[..., -1]),
        "trend_strength": fm(feats.trend_strength[..., -1]),
        "avg_volume": fm(feats.volume[..., -1]),
        "signal": im(signal[..., -1]),
        "signal_strength": fm(strength[..., -1]),
        "chg_1": fm(chg(1)), "chg_3": fm(chg(3)),
        "chg_5": fm(chg(5)), "chg_15": fm(chg(15)),
        "poc_price": fm(vp["poc_price"]),
        "value_area_low": fm(vp["value_area_low"]),
        "value_area_high": fm(vp["value_area_high"]),
        "confluence": fm(confluence[..., -1]),
        "combo": {k: fm(v[..., -1]) for k, v in combos.items()},
        # popped by step() into last_drift — never part of the published
        # feature payload, so fused↔per-symbol parity is untouched
        "drift_psi": jnp.where(valid[..., None], drift_psi, jnp.nan),
        "drift_hist": live_hist,
    }
    return ring, out


class TickEngine:
    """Host-side driver of the fused program: kline diffing, the ring
    mirrors, and the one-dispatch/one-sync step.

    ``ingest(symbol, interval, klines)`` queues the delta between the new
    window and the device ring (typically 1-2 rows: the freshly closed
    candle plus the updated in-progress bar).  A slot whose delta exceeds
    ``max_new`` rows (cold start, reconnect gap, venue correction storm)
    is re-seeded: the whole buffer re-uploads once via device_put — a
    transfer, not a compile.  ``step()`` then runs ONE jitted dispatch for
    every (symbol, frame) lane and performs ONE host_read.

    ``pipelined=True`` switches to the DOUBLE-BUFFERED async tick path
    (ROADMAP item 4): two device rings alternate, each poll's row writes
    fan into both buffers' pending maps (a buffer dispatches every other
    tick, so it also applies the writes from the tick it sat out — dict
    assignment keeps latest-write-wins per ring slot), and ``step()``
    returns IMMEDIATELY after dispatching tick T — handing back tick
    T−1's drained host output (None on the very first tick).  The
    readback of T then overlaps the host's publish/analyzer/executor
    work and the next poll's fetch+ingest; ``flush()`` is the drain seam
    for teardown and parity tests.  Both buffers share ONE compiled
    program (identical shapes), the donation verifier runs against each
    buffer's first carded dispatch, and a failed dispatch OR drain drops
    everything in flight and re-seeds both buffers from the host mirror
    (a transfer, never a compile — the PR 17 containment discipline).

    ``precision`` threads the PR 2 matmul-precision knob ("bf16" for the
    reduced-precision decide path) through the fused program; None keeps
    the backend default (full f32 on CPU).
    """

    def __init__(self, symbols, intervals, window: int = 256,
                 max_new: int = 8, pipelined: bool = False,
                 precision: str | None = None):
        self.symbols = list(symbols)
        self.intervals = tuple(intervals)
        self.window = int(window)
        self.max_new = int(max_new)
        self.pipelined = bool(pipelined)
        from ai_crypto_trader_tpu.models.train_loop import canonical_precision
        canonical_precision(precision)     # validate eagerly, fail loud
        self.precision = precision
        self.sym_index = {s: i for i, s in enumerate(self.symbols)}
        self.iv_index = {iv: i for i, iv in enumerate(self.intervals)}
        S = _pad_symbols(len(self.symbols))
        F = _pad_frames(len(self.intervals))
        T = self.window
        # time-ordered window mirror + timestamps (diffing) and the
        # ring-layout mirror (reseed source; always current)
        self._win = np.zeros((S, F, T, 5), np.float32)
        self._ts = np.zeros((S, F, T), np.int64)
        self._ring_np = np.zeros((S, F, T, 5), np.float32)
        self._base = np.zeros((S, F), np.int32)
        self._count = np.zeros((S, F), np.int32)
        self._ring = None                      # device buffer, donated
        # drift reference histograms (obs/drift.py): uniform until a
        # training-time reference is installed (set_drift_reference) or the
        # first full window is captured per lane; kept device-resident and
        # re-uploaded only when a reference changes — never per tick
        K, B = len(DRIFT_FEATURES), N_BINS
        self._drift_ref_np = np.full((S, F, K, B), 1.0 / B, np.float32)
        self._drift_ref_set = np.zeros((S, F), bool)
        self._drift_ref = None
        self.drift_ref_uploads = 0
        self.last_drift: dict = {}
        # queued writes this poll, keyed (s, f, pos) so a second ingest of
        # the same slot between steps overwrites rather than duplicates —
        # duplicate scatter indices pick an implementation-defined winner
        # in XLA, which could desync the device ring from the host mirror
        self._pending: dict = {}               # (s, f, pos) -> row
        self._need_seed = True
        # per-lane stream-sync flag: True iff every row OFFERED to this
        # lane since its last full-window ingest() was applied (ingest_row
        # returned True).  While True, a full-window re-ingest of the same
        # source is provably a zero-change diff — the monitor skips it for
        # stream-served lanes (lane_synced), which removes the dominant
        # steady-state host cost (re-parsing window × lanes every tick).
        # Any refused row, warming lane, or re-seed clears it; only a
        # completed full ingest sets it.
        self._synced = np.zeros((S, F), bool)
        # pipelined double-buffer state: two donated device rings, each
        # with its own accumulated pending map and per-buffer donation
        # check; _inflight holds the not-yet-drained dispatch
        self._bufs: list = [None, None]
        self._buf_pending: list[dict] = [{}, {}]
        self._donation_checked = [False, False]
        self._cur = 0
        self._inflight: dict | None = None
        self.dispatch_count = 0
        self.full_seeds = 0
        self.last_valid = np.zeros((S, F), bool)
        self.last_stats: dict = {}
        self.last_out: dict | None = None   # newest host output pytree
        # newest venue event time (ms) per symbol: candle open times from
        # the ingest paths, upgraded to the exchange's event-time E by the
        # stream (note_event_ms) — the event_age_ms source the monitor
        # stamps onto published updates (obs/tickpath.py)
        self.last_event_ms: dict[str, float] = {}

    # -- ingest ---------------------------------------------------------------
    def _seed_slot(self, s: int, f: int, ts: np.ndarray, arr: np.ndarray):
        self._win[s, f] = arr
        self._ts[s, f] = ts
        self._base[s, f] = 0
        self._ring_np[s, f] = arr
        self._count[s, f] = self.window
        self._need_seed = True
        self.full_seeds += 1
        # queued incremental writes for this slot are superseded
        self._pending = {k: v for k, v in self._pending.items()
                         if not (k[0] == s and k[1] == f)}

    def note_event_ms(self, symbol: str, event_ms: float) -> None:
        """Record a fresher venue event time for ``symbol`` (monotone max:
        candle open times are a lower bound, the stream's exchange E the
        true value)."""
        if event_ms > self.last_event_ms.get(symbol, 0.0):
            self.last_event_ms[symbol] = float(event_ms)

    # -- drift reference ------------------------------------------------------
    def set_drift_reference(self, symbol: str, interval: str,
                            probs: np.ndarray) -> None:
        """Install training-time reference stats ([K, N_BINS] probabilities,
        obs/drift.reference_histogram) for one (symbol, interval) lane.
        One device_put per change — a transfer, never a recompile."""
        s = self.sym_index[symbol]
        f = self.iv_index[interval]
        self._drift_ref_np[s, f] = np.asarray(probs, np.float32)
        self._drift_ref_set[s, f] = True
        self._drift_ref = jnp.asarray(self._drift_ref_np)
        self.drift_ref_uploads += 1

    def ingest_row(self, symbol: str, interval: str, row: list) -> bool:
        """Streamed-row upload seam: apply ONE candle row to a warm lane —
        O(1) scatter-list work instead of a full-window diff.

        Returns True when applied (in-progress-bar replacement, or an
        append that advances the ring by exactly one candle).  False means
        the caller must seed/backfill the lane through the full-window
        ``ingest`` path: lane still warming, timestamp gap, or an
        out-of-order row — a streamed row can NEVER tear the ring."""
        s = self.sym_index.get(symbol)
        f = self.iv_index.get(interval)
        if s is None or f is None:
            return False
        self.note_event_ms(symbol, float(row[0]))
        T = self.window
        if self._count[s, f] < T:
            return False                       # warming: needs a full seed
        ts = int(row[0])
        arr = np.asarray(row[1:6], np.float32)
        tail = self._ts[s, f]
        if ts == int(tail[-1]):                # in-progress bar update
            if np.array_equal(self._win[s, f, -1], arr):
                return True                    # exact duplicate: no write
            self._win[s, f, -1] = arr
            pos = (int(self._base[s, f]) + T - 1) % T
        elif ts > int(tail[-1]):
            step = int(tail[-1] - tail[-2]) if T >= 2 else 0
            if step <= 0 or ts != int(tail[-1]) + step:
                self._synced[s, f] = False     # gap/misalignment: re-seed
                return False
            self._ts[s, f] = np.roll(tail, -1)
            self._ts[s, f, -1] = ts
            self._win[s, f] = np.roll(self._win[s, f], -1, axis=0)
            self._win[s, f, -1] = arr
            base = (int(self._base[s, f]) + 1) % T
            self._base[s, f] = base
            pos = (base + T - 1) % T
        else:
            self._synced[s, f] = False         # older than the window tail
            return False
        self._ring_np[s, f, pos] = arr
        self._pending[(s, f, pos)] = arr       # latest write wins
        return True

    def ingest(self, symbol: str, interval: str, klines: list) -> None:
        """Diff one (symbol, frame) kline window against the device ring and
        queue only the new/changed rows for the next step()."""
        s = self.sym_index[symbol]
        f = self.iv_index[interval]
        if klines:
            self.note_event_ms(symbol, float(klines[-1][0]))
        T = self.window
        rows = klines[-T:]
        if len(rows) < T:
            self._count[s, f] = len(rows)      # warming: lane stays invalid
            self._synced[s, f] = False
            return
        arr = np.asarray([r[1:6] for r in rows], np.float32)
        ts = np.asarray([int(r[0]) for r in rows], np.int64)
        if self._count[s, f] < T:
            self._seed_slot(s, f, ts, arr)     # warming → full transition
            self._synced[s, f] = True
            return
        old_ts = self._ts[s, f]
        j = int(np.searchsorted(old_ts, ts[0]))
        if j >= T or old_ts[j] != ts[0] \
                or not np.array_equal(old_ts[j:], ts[:T - j]):
            self._seed_slot(s, f, ts, arr)     # gap/misalignment: re-seed
            self._synced[s, f] = True
            return
        k = j                                  # window advanced by k candles
        changed = np.flatnonzero(
            (arr[:T - k] != self._win[s, f, k:]).any(axis=1))
        writes = list(changed) + list(range(T - k, T))
        if len(writes) > self.max_new:
            self._seed_slot(s, f, ts, arr)
            self._synced[s, f] = True
            return
        base = (int(self._base[s, f]) + k) % T
        self._base[s, f] = base
        for i in writes:
            pos = (base + i) % T
            self._ring_np[s, f, pos] = arr[i]
            self._pending[(s, f, pos)] = arr[i]   # latest write wins
        self._win[s, f] = arr
        self._ts[s, f] = ts
        self._synced[s, f] = True

    def lane_synced(self, symbol: str, interval: str) -> bool:
        """True iff this lane's window already reflects every row offered
        since its last full ingest — i.e. a full-window re-ingest of the
        same source would be a zero-change diff.  The stream attaches this
        as provenance on the windows it serves (`serve_klines`), letting
        the fused poll skip the redundant re-diff per lane."""
        s = self.sym_index.get(symbol)
        f = self.iv_index.get(interval)
        if s is None or f is None:
            return False
        return bool(self._synced[s, f]) \
            and int(self._count[s, f]) >= self.window

    # -- step -----------------------------------------------------------------
    def step(self) -> dict | None:
        """ONE fused dispatch over every (symbol, frame) lane.

        Serial mode (default): dispatch + ONE host readback, returning
        THIS tick's numpy output pytree.  Pipelined mode: dispatch tick T
        against the current ring buffer, flip buffers, then drain and
        return tick T−1's output — None on the first tick, when nothing
        is in flight yet.  Per-step transfer/dispatch accounting lands in
        ``last_stats`` either way."""
        if self.pipelined:
            return self._step_pipelined()
        return self._step_serial()

    def _step_serial(self) -> dict:
        t_step0 = time.perf_counter()
        S, F, T = self._ring_np.shape[:3]
        W = S * F * self.max_new               # scatter capacity
        if len(self._pending) > W:             # paranoia: spilled capacity
            self._need_seed = True
        rows = np.zeros((W, 5), np.float32)
        s_ix = np.zeros((W,), np.int32)
        f_ix = np.zeros((W,), np.int32)
        pos = np.full((W,), T, np.int32)       # T = dropped write
        upload_bytes = 0
        seeded = self._ring is None or self._need_seed
        if seeded:
            self._ring = jnp.asarray(self._ring_np)   # transfer, no compile
            upload_bytes += self._ring_np.nbytes
            n_writes = 0
            self._pending.clear()              # already inside the seed
        else:
            n_writes = len(self._pending)
            for w, ((ps, pf, p), row) in enumerate(self._pending.items()):
                s_ix[w] = ps
                f_ix[w] = pf
                pos[w] = p
                rows[w] = row
            self._pending.clear()
            upload_bytes += (rows.nbytes + s_ix.nbytes + f_ix.nbytes
                             + pos.nbytes)
        valid = self._count >= T
        if self._drift_ref is None:
            self._drift_ref = jnp.asarray(self._drift_ref_np)
        # one-shot cost card + donation verification on the first carded
        # dispatch (utils/devprof.py; disabled = one attribute read)
        carding = (devprof.active() is not None
                   and not devprof.has_card("tick_engine"))
        if carding:
            devprof.cost_card("tick_engine", _tick_program, self._ring,
                              self._base, rows, s_ix, f_ix, pos, valid,
                              self._drift_ref)
        donated_ring = self._ring if carding else None
        # meshprof watch window (utils/meshprof.py; disabled = one module
        # check): attributes any compile during this dispatch to
        # "tick_engine" — a compile after warmup is a counted steady-state
        # recompile + SteadyStateRecompile alert — and arms the
        # device→host transfer guard from dispatch through the sanctioned
        # host_read, so a stray host pull on the fused path becomes a
        # counted gauge instead of invisible latency.  A fresh engine's
        # FIRST dispatch is cold: the monitor rebuilds the engine when the
        # universe/window changes (each is a compiled-shape input by
        # design), and the sentinel's window count is global across
        # instances — within one engine the array shapes are fixed, so any
        # later compile is genuinely unexpected.
        # tickpath phase seams (obs/tickpath.py; disabled = one module
        # check): the scatter-build / dispatch / device_compute /
        # host_read decomposition rides the existing perf_counter stamps
        # and ONE sentinel-leaf readiness wait — a wait, not a transfer
        # (the meshprof guard stays armed) and not a second host_read
        # (the one-sync contract test keeps counting 1).  The wait is
        # time host_read would have blocked anyway, re-attributed from
        # the transfer to the compute it actually was.
        tp = tickpath.active()
        try:
            with tickpath.coldstart("tick_engine",
                                    cold=self.dispatch_count == 0), \
                    meshprof.watch("tick_engine",
                                   cold=self.dispatch_count == 0), \
                    _precision_ctx(self.precision):
                t_d0 = time.perf_counter()
                self._ring, out = _tick_program(self._ring, self._base,
                                                rows, s_ix, f_ix, pos,
                                                valid, self._drift_ref)
                t_d1 = time.perf_counter()
                if donated_ring is not None:
                    devprof.verify_donation("tick_engine", donated_ring)
                self.dispatch_count += 1
                self._need_seed = False
                self.last_valid = valid
                if tp is not None:
                    # host-idle window between dispatch-return and
                    # readback-start: the overlap headroom item-4
                    # pipelining can fill with host work
                    t_w0 = time.perf_counter()
                    jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])
                    t_ready = time.perf_counter()
                t_hr = time.perf_counter()
                host = host_read(out)
                host_read_s = time.perf_counter() - t_hr
                # readiness-mark the NEW ring too: on the XLA CPU thunk
                # runtime an output-leaf sync does not cover the aliased
                # ring output, and donating a buffer PJRT hasn't marked
                # ready silently degrades the next dispatch to synchronous
                # execution (the whole device compute lands inside the
                # dispatch call).  The compute is already finished here,
                # so this is event bookkeeping, not a wait.
                jax.block_until_ready(self._ring)
        except Exception:
            # a mid-step abort (counted guard violation, XLA runtime
            # error) leaves the donated device ring in an unknown state;
            # the host mirror is authoritative, so the next step re-seeds
            # — a transfer, never a compile
            self._need_seed = True
            raise
        # drift outputs ride the same readback; pop them into last_drift so
        # the published feature payload (and the fused↔per-symbol parity
        # contract) is unchanged.  PSI is only meaningful where a reference
        # existed BEFORE this dispatch; lanes past warm-up with no reference
        # capture this window's histogram as their baseline (one device_put,
        # no recompile — pathology stays array content).
        self._pop_drift(host, valid, self._drift_ref_set.copy())
        # newest host output pytree: the tenant engine's feed
        # (ops/tenant_engine.py reads its [S, F] feature columns directly —
        # no per-symbol dict assembly between the two fused programs)
        self.last_out = host
        self.last_stats = {
            "dispatches": 1, "upload_rows": int(n_writes),
            "upload_bytes": int(upload_bytes), "full_seed": bool(seeded),
            "lanes": int(S * F), "valid_lanes": int(valid.sum()),
            # saturation telemetry (utils/saturation.py): scatter-list
            # occupancy headroom and the host-readback share of tick time
            "scatter_capacity": int(W), "host_read_s": host_read_s,
            "step_s": time.perf_counter() - t_step0,
        }
        if tp is not None:
            scatter_build_s = t_d0 - t_step0
            dispatch_s = t_d1 - t_d0
            device_compute_s = t_ready - t_d1
            overlap_headroom_s = t_ready - t_w0
            self.last_stats.update({
                "scatter_build_s": scatter_build_s,
                "dispatch_s": dispatch_s,
                "device_compute_s": device_compute_s,
                "overlap_headroom_s": overlap_headroom_s,
            })
            tp.observe_phase("scatter_build", scatter_build_s)
            tp.observe_phase("dispatch", dispatch_s)
            tp.observe_phase("device_compute", device_compute_s)
            tp.observe_phase("host_read", host_read_s)
            tp.observe_overlap(overlap_headroom_s)
        return host

    def _pop_drift(self, host: dict, valid: np.ndarray,
                   ref_was_set: np.ndarray) -> None:
        """Pop the drift outputs off a drained readback into ``last_drift``
        and capture first-full-window references (one device_put, never a
        recompile).  ``ref_was_set`` is the reference state AS OF THE
        DISPATCH that produced ``host`` — in pipelined mode that dispatch
        happened one tick before this drain, so the snapshot rides the
        in-flight record instead of being read now."""
        drift_hist = host.pop("drift_hist")
        drift_psi = host.pop("drift_psi")
        newly = valid & ~self._drift_ref_set
        if newly.any():
            self._drift_ref_np[newly] = drift_hist[newly]
            self._drift_ref_set |= valid
            self._drift_ref = jnp.asarray(self._drift_ref_np)
            self.drift_ref_uploads += 1
        self.last_drift = {"psi": drift_psi, "hist": drift_hist,
                           "ref_set": ref_was_set}

    # -- pipelined step (double-buffered ring, async host_read) ---------------
    def _scatter_capacity(self) -> int:
        """Scatter-list capacity W.  A pipelined buffer dispatches every
        other tick, so it accumulates up to TWO polls of row writes —
        double the serial capacity (a different compiled shape; each
        engine is one program either way)."""
        S, F = self._ring_np.shape[:2]
        return S * F * self.max_new * (2 if self.pipelined else 1)

    def _build_scatter(self, pending: dict, W: int, T: int):
        rows = np.zeros((W, 5), np.float32)
        s_ix = np.zeros((W,), np.int32)
        f_ix = np.zeros((W,), np.int32)
        pos = np.full((W,), T, np.int32)       # T = dropped write
        for w, ((ps, pf, p), row) in enumerate(pending.items()):
            s_ix[w] = ps
            f_ix[w] = pf
            pos[w] = p
            rows[w] = row
        return rows, s_ix, f_ix, pos

    def _abort_pipeline(self) -> None:
        """A failed dispatch or drain leaves one or both donated device
        rings in an unknown state.  Drop everything in flight and re-seed
        BOTH buffers from the authoritative host mirror on the next step —
        a transfer, never a compile, and never a duplicate publish (the
        in-flight output is discarded, not re-drained)."""
        self._inflight = None
        self._bufs = [None, None]
        self._buf_pending[0].clear()
        self._buf_pending[1].clear()
        self._need_seed = True

    def _step_pipelined(self) -> dict | None:
        t_step0 = time.perf_counter()
        S, F, T = self._ring_np.shape[:3]
        W = self._scatter_capacity()
        # fan this poll's writes into BOTH buffers: the one dispatching
        # now and the one that sat this tick out (dict assignment keeps
        # latest-write-wins per absolute ring slot, so merging across
        # ticks is safe — a superseded row simply never lands)
        if self._pending:
            self._buf_pending[0].update(self._pending)
            self._buf_pending[1].update(self._pending)
            self._pending.clear()
        cur = self._cur
        if len(self._buf_pending[cur]) > W:    # paranoia: spilled capacity
            self._need_seed = True
        seeded = self._bufs[cur] is None or self._need_seed
        upload_bytes = 0
        if seeded:
            # re-seed BOTH buffers (two transfers, no compile): any
            # accumulated per-buffer deltas are inside the seed already
            self._bufs[0] = jnp.asarray(self._ring_np)
            self._bufs[1] = jnp.asarray(self._ring_np)
            upload_bytes += 2 * self._ring_np.nbytes
            self._buf_pending[0].clear()
            self._buf_pending[1].clear()
            n_writes = 0
            rows, s_ix, f_ix, pos = self._build_scatter({}, W, T)
        else:
            buf_pending = self._buf_pending[cur]
            n_writes = len(buf_pending)
            rows, s_ix, f_ix, pos = self._build_scatter(buf_pending, W, T)
            buf_pending.clear()                # consumed by this dispatch
            upload_bytes += (rows.nbytes + s_ix.nbytes + f_ix.nbytes
                             + pos.nbytes)
        valid = self._count >= T
        if self._drift_ref is None:
            self._drift_ref = jnp.asarray(self._drift_ref_np)
        # one-shot cost card (shapes identical for both buffers — one
        # card) + PER-BUFFER donation verification on each buffer's first
        # profiled dispatch
        carding = (devprof.active() is not None
                   and not devprof.has_card("tick_engine"))
        if carding:
            devprof.cost_card("tick_engine", _tick_program, self._bufs[cur],
                              self._base, rows, s_ix, f_ix, pos, valid,
                              self._drift_ref)
        verify = (devprof.active() is not None
                  and not self._donation_checked[cur])
        donated_ring = self._bufs[cur] if verify else None
        cold = self.dispatch_count == 0
        try:
            with tickpath.coldstart("tick_engine", cold=cold), \
                    meshprof.watch("tick_engine", cold=cold), \
                    _precision_ctx(self.precision):
                t_d0 = time.perf_counter()
                self._bufs[cur], out = _tick_program(
                    self._bufs[cur], self._base, rows, s_ix, f_ix, pos,
                    valid, self._drift_ref)
                t_d1 = time.perf_counter()
                if donated_ring is not None:
                    devprof.verify_donation("tick_engine", donated_ring)
                    self._donation_checked[cur] = True
        except Exception:
            self._abort_pipeline()
            raise
        self.dispatch_count += 1
        self._need_seed = False
        self._cur = 1 - cur
        scatter_build_s = t_d0 - t_step0
        dispatch_s = t_d1 - t_d0
        tp = tickpath.active()
        if tp is not None:
            tp.observe_phase("scatter_build", scatter_build_s)
            tp.observe_phase("dispatch", dispatch_s)
        prev, self._inflight = self._inflight, {
            "out": out, "buf": cur, "valid": valid, "seeded": bool(seeded),
            "n_writes": int(n_writes), "upload_bytes": int(upload_bytes),
            "lanes": int(S * F), "scatter_capacity": int(W),
            "scatter_build_s": scatter_build_s, "dispatch_s": dispatch_s,
            # reference state as of THIS dispatch (see _pop_drift)
            "ref_set": self._drift_ref_set.copy(),
            "t_step0": t_step0, "t_disp_ret": t_d1,
        }
        if prev is None:
            # pipeline fill: nothing to drain yet — the caller publishes
            # nothing this tick and collects T's output next poll (or via
            # flush() at teardown)
            self.last_stats = {
                "dispatches": 1, "upload_rows": int(n_writes),
                "upload_bytes": int(upload_bytes), "full_seed": bool(seeded),
                "lanes": int(S * F), "valid_lanes": int(valid.sum()),
                "scatter_capacity": int(W), "host_read_s": 0.0,
                "step_s": time.perf_counter() - t_step0, "inflight": True,
            }
            return None
        return self._drain(prev)

    def _drain(self, inflight: dict) -> dict:
        """Collect one in-flight dispatch's readback: the async half of
        the pipelined step.  The sentinel-leaf wait measures the RESIDUAL
        device_compute — everything the host did since that dispatch
        returned (publish, analyzer, executor, the next poll's fetch and
        ingest) already overlapped it, and is scored as reclaimed overlap
        (``tickpath_overlap_reclaimed_seconds``)."""
        t_drain0 = time.perf_counter()
        try:
            t_w0 = time.perf_counter()
            jax.block_until_ready(
                jax.tree_util.tree_leaves(inflight["out"])[0])
            # readiness-mark this dispatch's ring output as well: the
            # output-leaf sync above does not cover the aliased ring on
            # the CPU thunk runtime, and this buffer is the one the NEXT
            # dispatch on it will donate — donating a buffer PJRT hasn't
            # marked ready degrades that dispatch to synchronous
            # execution, which is exactly the overlap this pipeline
            # exists to reclaim
            ring_new = self._bufs[inflight["buf"]]
            if ring_new is not None:
                jax.block_until_ready(ring_new)
            t_ready = time.perf_counter()
            t_hr = time.perf_counter()
            host = host_read(inflight["out"])
            host_read_s = time.perf_counter() - t_hr
        except Exception:
            # a wedged/failed drain must not wedge the ring: drop the
            # in-flight outputs (this one AND the dispatch just issued)
            # and re-seed from the host mirror — the caller's stage
            # breaker handles the skipped tick
            self._abort_pipeline()
            raise
        valid = inflight["valid"]
        self._pop_drift(host, valid, inflight["ref_set"])
        self.last_valid = valid
        self.last_out = host
        device_compute_s = t_ready - t_w0      # residual blocked wait
        reclaimed_s = max(t_w0 - inflight["t_disp_ret"], 0.0)
        self.last_stats = {
            "dispatches": 1, "upload_rows": inflight["n_writes"],
            "upload_bytes": inflight["upload_bytes"],
            "full_seed": inflight["seeded"], "lanes": inflight["lanes"],
            "valid_lanes": int(valid.sum()),
            "scatter_capacity": inflight["scatter_capacity"],
            "scatter_build_s": inflight["scatter_build_s"],
            "dispatch_s": inflight["dispatch_s"],
            "device_compute_s": device_compute_s,
            "overlap_headroom_s": device_compute_s,
            "overlap_reclaimed_s": reclaimed_s,
            "host_read_s": host_read_s,
            "step_s": time.perf_counter() - t_drain0,
        }
        tp = tickpath.active()
        if tp is not None:
            tp.observe_phase("device_compute", device_compute_s)
            tp.observe_phase("host_read", host_read_s)
            tp.observe_overlap(device_compute_s)
            tp.observe_reclaimed(reclaimed_s)
        return host

    def flush(self) -> dict | None:
        """Drain the in-flight dispatch, if any: the pipeline teardown /
        parity seam (monitor.flush_pipeline, shutdown, tests).  Returns
        the drained host output, or None when nothing was in flight."""
        inflight, self._inflight = self._inflight, None
        if inflight is None:
            return None
        return self._drain(inflight)
