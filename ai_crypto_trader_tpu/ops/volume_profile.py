"""Volume-profile analytics.

Capability parity with `services/utils/volume_profile_analyzer.py` (used by
the market monitor at `market_monitor_service.py:303-372`): price-bucketed
volume histogram, point of control (POC), value area (the minimal
POC-centered band holding 70 % of volume), and high/low-volume nodes — all
as one jit over the candle arrays (the typical price of each candle books
its volume into a fixed price grid via a segment-sum).

Like the ops.indicators kernels, the public entry accepts leading batch
dims (`[..., T]`) — the profile is computed per trailing-axis series
(vmapped internally, since the histogram/value-area math reduces over the
whole series), which is what lets the fused tick engine profile every
(symbol × frame) lane in one program.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _volume_profile_1d(high, low, close, volume, n_bins: int,
                       value_area_frac: float) -> dict:
    tp = (high + low + close) / 3.0
    lo = jnp.min(tp)
    hi = jnp.max(tp)
    width = jnp.where(hi - lo == 0.0, 1.0, hi - lo)
    idx = jnp.clip(((tp - lo) / width * n_bins).astype(jnp.int32), 0, n_bins - 1)
    hist = jax.ops.segment_sum(volume, idx, num_segments=n_bins)
    centers = lo + (jnp.arange(n_bins) + 0.5) / n_bins * width

    poc = jnp.argmax(hist)
    total = jnp.sum(hist)

    # Value area: grow a window around the POC greedily (classic VA algo),
    # expressed as a fixed scan over n_bins expansion steps.
    def grow(carry, _):
        lo_i, hi_i, acc = carry
        can_lo = lo_i > 0
        can_hi = hi_i < n_bins - 1
        v_lo = jnp.where(can_lo, hist[jnp.maximum(lo_i - 1, 0)], -1.0)
        v_hi = jnp.where(can_hi, hist[jnp.minimum(hi_i + 1, n_bins - 1)], -1.0)
        take_lo = (v_lo >= v_hi) & can_lo
        done = acc >= value_area_frac * total
        lo_i = jnp.where(~done & take_lo, lo_i - 1, lo_i)
        hi_i = jnp.where(~done & ~take_lo & can_hi, hi_i + 1, hi_i)
        acc = acc + jnp.where(done, 0.0, jnp.where(take_lo, v_lo,
                                                   jnp.where(can_hi, v_hi, 0.0)))
        return (lo_i, hi_i, acc), None

    (va_lo, va_hi, _), _ = jax.lax.scan(grow, (poc, poc, hist[poc]),
                                        None, length=n_bins)

    mean_vol = total / n_bins
    return {
        "bin_centers": centers,
        "histogram": hist,
        "poc_price": centers[poc],
        "value_area_low": centers[va_lo],
        "value_area_high": centers[va_hi],
        "hvn_mask": hist > 1.5 * mean_vol,     # high-volume nodes
        "lvn_mask": hist < 0.5 * mean_vol,     # low-volume nodes
        "total_volume": total,
    }


@functools.partial(jax.jit, static_argnames=("n_bins",))
def volume_profile(high, low, close, volume, n_bins: int = 50,
                   value_area_frac: float = 0.70) -> dict:
    high, low, close, volume = (jnp.asarray(x)
                                for x in (high, low, close, volume))
    if high.ndim == 1:
        return _volume_profile_1d(high, low, close, volume, n_bins,
                                  value_area_frac)
    batch = high.shape[:-1]
    flat = [x.reshape((-1, x.shape[-1])) for x in (high, low, close, volume)]
    out = jax.vmap(lambda h, l, c, v: _volume_profile_1d(
        h, l, c, v, n_bins, value_area_frac))(*flat)
    return {k: v.reshape(batch + v.shape[1:]) for k, v in out.items()}
