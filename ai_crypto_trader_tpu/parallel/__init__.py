from ai_crypto_trader_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    default_mesh,
    data_sharding,
    initialize_distributed,
    pad_to_multiple,
    replicated,
    shard_leading_axis,
)
from ai_crypto_trader_tpu.parallel.ring_attention import (  # noqa: F401
    reference_attention,
    ring_self_attention,
)
from ai_crypto_trader_tpu.parallel.time_shard import (  # noqa: F401
    sharded_ema,
    sharded_first_order_recursion,
    sharded_rolling_mean,
)
