"""Public parallelism API: mesh construction + the Partitioner seam.

The population evaluators (GA, backtest sweep, structure pool, HPO
trials) all route through `get_partitioner()` — see
parallel/partitioner.py.  The sequence-parallel scan kernels
(parallel/time_shard.py) and ring attention (parallel/ring_attention.py)
are NOT re-exported here: they are exercised by the multichip dryrun and
the long-context model only — import them from their modules.
"""

from ai_crypto_trader_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    default_mesh,
    data_sharding,
    initialize_distributed,
    pad_to_multiple,
    replicated,
    shard_leading_axis,
)
from ai_crypto_trader_tpu.parallel.partitioner import (  # noqa: F401
    MeshPartitioner,
    Partitioner,
    SingleDevicePartitioner,
    get_partitioner,
    match_partition_rules,
)
