"""Device mesh & sharding helpers — the framework's communication backend.

The reference's entire distribution fabric is Redis pub/sub + key-value state
(`services/utils/redis_pool.py`; SURVEY §5.8): services publish fitness
values, market updates, and model outputs through a TCP bus.  Here the data
plane is the TPU interconnect: arrays are sharded over a
`jax.sharding.Mesh`, and XLA collectives (`psum` / `all_gather` /
`ppermute`) move numbers over ICI.  A host-side event bus (shell/bus.py)
survives only for control-plane signals.

Two mesh axes by convention:
  * ``data``  — batch / population / path / symbol parallelism
  * ``model`` — parameter sharding for large models (unused at reference
    model sizes, but first-class so pjit sharding is available; SURVEY §2.7)
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    data_parallel: int = -1,
    model_parallel: int = 1,
    *,
    axis_names: tuple[str, str] = ("data", "model"),
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a 2-D (data, model) mesh.

    ``data_parallel=-1`` consumes all remaining devices.  On a single chip
    this degenerates to a 1×1 mesh so every code path is mesh-shaped from the
    start — going from 1 chip to a v5e-8 (or multi-host pod) changes only
    this function's arguments.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if model_parallel <= 0:
        model_parallel = 1
    if data_parallel == -1:
        data_parallel = n // model_parallel
    if data_parallel < 1 or data_parallel * model_parallel > n:
        raise ValueError(
            f"mesh ({data_parallel} data x {model_parallel} model) does not fit "
            f"the {n} available device(s)"
        )
    grid = np.asarray(devices[: data_parallel * model_parallel]).reshape(
        data_parallel, model_parallel
    )
    return Mesh(grid, axis_names)


@functools.lru_cache(maxsize=1)
def default_mesh() -> Mesh:
    return make_mesh()


def compat_shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map across jax versions: `jax.shard_map` (check_vma kwarg)
    when present, else `jax.experimental.shard_map.shard_map` (check_rep).
    Replication checking is disabled either way — closed-over replicated
    arrays (candle windows, fold features) trip the checker."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading axis over the data axis, replicate the rest."""
    spec = P(mesh.axis_names[0], *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_leading_axis(mesh: Mesh, tree):
    """Device_put a pytree with every leaf sharded on its leading axis.

    Leading axes must divide the data-axis size; callers pad first (see
    ``pad_to_multiple``)."""
    def put(x):
        return jax.device_put(x, data_sharding(mesh, np.ndim(x)))
    return jax.tree.map(put, tree)


def pad_to_multiple(x, multiple: int, axis: int = 0, pad_value=0.0):
    """Pad ``x`` along ``axis`` so its size divides evenly over a mesh axis.

    Returns (padded, original_size) — callers slice results back.  Padding +
    masking is the standing answer to ragged shapes on TPU (SURVEY §7.4
    "Ragged reality")."""
    size = x.shape[axis]
    rem = (-size) % multiple
    if rem == 0:
        return x, size
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, rem)
    return np.pad(np.asarray(x), widths, constant_values=pad_value), size


def initialize_distributed(coordinator: str | None = None, num_processes: int | None = None, process_id: int | None = None):
    """Multi-host bring-up (DCN control plane + ICI data plane).

    Replaces the reference's "every service dials the same Redis host"
    topology (`services/utils/redis_pool.py:18-120`) for the compute tier:
    hosts join one JAX distributed system and all cross-host numeric traffic
    happens inside XLA collectives.
    """
    kwargs = {}
    if coordinator is not None:
        kwargs = dict(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    jax.distributed.initialize(**kwargs)
