"""The Partitioner seam: ONE object that decides how population-parallel
programs land on the hardware.

Every population evaluator in the repo — the GA fitness pass
(evolve/ga.py), the backtest sweep (backtest/engine.sweep), the
strategy-structure pool (strategy/generator.py), and HPO trials
(models/hpo.py) — maps a leading "population" axis over independent
members.  Before this seam each caller hand-rolled its own `shard_map`
plumbing (the dryrun-only `sweep_sharded` / `run_ga_sharded` helpers,
now absorbed here); after it, callers write the LOCAL computation and the
partitioner supplies mesh placement, padding to the device count, the
fitness all-gather (out_specs collective over ICI), and the
single-device fallback — the SNIPPETS [1]–[3] pattern
(`match_partition_rules`, `shard_map`, `SingleDevicePartitioner`).

Contracts:

  * ``population_eval(fn)`` — ``fn(pop_tree, *replicated)`` maps members
    independently (every leaf of ``pop_tree`` shares the leading
    population axis; every output leaf carries it back).  The returned
    callable is BOTH a standalone jitted program and traceable inside a
    larger jit (the scanned GA embeds it inside `lax.scan`).  Populations
    that don't divide the device count are padded by repeating the last
    member and the outputs sliced back — padding + masking is the
    standing answer to ragged shapes on TPU (SURVEY §7.4).
  * ``shard_population(tree)`` — device_put with the population sharding
    (leading axis split over the mesh data axis), so a donated carry
    starts life on the right devices.
  * ``trial_devices()`` — round-robin device list for HOST-level trial
    farming (HPO: each trial is its own compiled program; dispatch is
    async, so placing consecutive trials on different devices overlaps
    their device time without threads).

Results are mesh-size-invariant by construction: the sharded program
computes exactly the per-member values the single-device vmap computes
(the collective only all-gathers), so a 1-device mesh must be bit-equal
to the `SingleDevicePartitioner` — pinned by tests/test_partitioner.py.
"""

from __future__ import annotations

import functools
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ai_crypto_trader_tpu.parallel.mesh import (
    compat_shard_map as _shard_map,
    default_mesh,
)
from ai_crypto_trader_tpu.utils import meshprof


def _path_name(path) -> str:
    """'/'-joined tree path (SNIPPETS [1] named_tree_map, without flax)."""
    parts = []
    for k in path:
        for attr in ("key", "name", "idx"):
            if hasattr(k, attr):
                parts.append(str(getattr(k, attr)))
                break
        else:
            parts.append(str(k))
    return "/".join(parts)


def match_partition_rules(rules, tree):
    """Pytree of PartitionSpec chosen by the first regex matching each
    leaf's '/'-joined path (SNIPPETS [1] `match_partition_rules`).

    ``rules`` is a sequence of (pattern, PartitionSpec); scalar leaves
    (or one-element leaves) are never partitioned.  A leaf no rule covers
    raises — a silent replicate-by-default would hide a model-parallel
    sharding bug until the first OOM."""
    def spec_for(path, leaf):
        shape = np.shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        name = _path_name(path)
        for rule, ps in rules:
            if re.search(rule, name) is not None:
                return ps
        raise ValueError(f"no partition rule matches leaf {name!r}")

    return jax.tree_util.tree_map_with_path(spec_for, tree)


class Partitioner:
    """Abstract partitioning policy (SNIPPETS [3] `Partitioner`)."""

    mesh: Mesh | None = None
    axis: str | None = None

    @property
    def device_count(self) -> int:
        raise NotImplementedError

    def population_sharding(self, ndim: int = 1):
        """Sharding for a [pop, ...] array (None = single-device default)."""
        raise NotImplementedError

    def shard_population(self, tree):
        raise NotImplementedError

    def population_eval(self, fn, name: str | None = None,
                        donate_pop: bool = False):
        """``name`` registers the program with the mesh observatory
        (utils/meshprof.py): its pad/mask layout and all-gather byte
        volume are recorded at trace time under that program name.

        ``donate_pop=True`` donates the population tree (argument 0) to
        the compiled program — the LOB sweep's schedule buffers alias
        onto its [B, T] outputs instead of doubling HBM at 10k-scenario
        scale (the sim/engine.py donation contract, behind the seam)."""
        raise NotImplementedError

    def trial_devices(self) -> list:
        raise NotImplementedError

    def pad_for(self, population: int) -> int:
        """Members `population_eval` appends (by repeating the last one)
        to split ``population`` evenly over the devices — 0 on a single
        device or whenever the population divides the mesh axis.  The
        analytic twin of the layout card's ``pad``: bench/tests that
        need the pad fraction before (or without) a traced program
        compute it here instead of re-deriving the modulo inline."""
        return (-population) % max(self.device_count, 1)

    def _device_list(self) -> list:
        return [jax.devices()[0]]

    def describe(self) -> dict:
        """Operator-facing layout summary (/state.json `mesh` block,
        `cli mesh` / `cli status`): partitioner kind, device count and
        the concrete device kinds behind it."""
        out = {"kind": type(self).__name__, "devices": self.device_count}
        try:
            devs = self._device_list()
            out["device_kinds"] = sorted(
                {str(getattr(d, "device_kind", d.platform)) for d in devs})
            out["platform"] = devs[0].platform if devs else None
        except Exception:            # noqa: BLE001 — backend uninitialized
            pass                     # (gate/docs jobs): layout-only answer
        return out


class SingleDevicePartitioner(Partitioner):
    """The fallback: every program is a plain jit on the default device
    (SNIPPETS [3] `SingleDevicePartitioner`).  Semantically identical to
    the mesh path — the contract the tests pin.

    All instances compare equal: identity-keyed program caches (the GA's
    `_ga_program`, the sweep's `_sweep_partitioned`) must not compile the
    same program twice because one call site used `get_partitioner()` and
    another the module default."""

    def __eq__(self, other) -> bool:
        return type(other) is SingleDevicePartitioner

    def __hash__(self) -> int:
        return hash(SingleDevicePartitioner)

    @property
    def device_count(self) -> int:
        return 1

    def population_sharding(self, ndim: int = 1):
        return None

    def shard_population(self, tree):
        return tree

    def population_eval(self, fn, name: str | None = None,
                        donate_pop: bool = False):
        donate = (0,) if donate_pop else ()
        if name is None:
            return jax.jit(fn, donate_argnums=donate)

        def named(pop_tree, *repl):
            # trace-time layout card (once per compiled shape): pad 0,
            # one device — the 1-chip end of the same trajectory the
            # mesh rows stamp, so bench/state views never have holes
            out = fn(pop_tree, *repl)
            meshprof.record_population_layout(
                name, population=int(jax.tree.leaves(pop_tree)[0].shape[0]),
                pad=0, devices=1, out_tree=out)
            return out

        return jax.jit(named, donate_argnums=donate)

    def trial_devices(self) -> list:
        return []


class MeshPartitioner(Partitioner):
    """Population axis sharded over one mesh axis; outputs all-gathered.

    ``axis`` defaults to the mesh's first ("data") axis.  A 1-device mesh
    is legal and must match SingleDevicePartitioner exactly — the shape
    every code path is written in from the start (parallel/mesh.py)."""

    def __init__(self, mesh: Mesh | None = None, axis: str | None = None):
        self.mesh = mesh if mesh is not None else default_mesh()
        self.axis = axis if axis is not None else self.mesh.axis_names[0]

    @property
    def device_count(self) -> int:
        return int(self.mesh.shape[self.axis])

    def population_sharding(self, ndim: int = 1):
        return NamedSharding(self.mesh,
                             P(self.axis, *([None] * (ndim - 1))))

    def shard_population(self, tree):
        """device_put every leaf split on its leading axis (leading sizes
        must divide the axis — population_eval pads internally instead
        when handed an un-shardable population)."""
        def put(x):
            return jax.device_put(x, self.population_sharding(np.ndim(x)))
        return jax.tree.map(put, tree)

    def population_eval(self, fn, name: str | None = None,
                        donate_pop: bool = False):
        """``fn(pop_tree, *replicated) -> out_tree`` as a sharded program.

        The population axis splits over ``self.axis``; ``replicated``
        arguments are visible whole on every device; ``out_specs``
        all-gathers every output's population axis (the ICI collective
        that replaces the reference's "publish fitness to Redis",
        SURVEY §2.7).  Ragged populations pad by repeating the last
        member and slice back — the pad rows are masked out of every
        result the caller sees.  ``name`` publishes the layout (pad
        fraction, per-device members, all-gather bytes) to the mesh
        observatory at trace time — once per compiled shape."""
        mesh, axis, n_dev = self.mesh, self.axis, self.device_count
        dev_names = tuple(str(d) for d in np.ravel(mesh.devices))

        def padded(pop_tree, *repl):
            pop = int(jax.tree.leaves(pop_tree)[0].shape[0])
            pad = (-pop) % n_dev

            if pad:
                pop_tree = jax.tree.map(
                    lambda x: jnp.concatenate(
                        [x, jnp.repeat(x[-1:], pad, axis=0)]), pop_tree)
            sharded = _shard_map(
                fn, mesh,
                in_specs=(P(axis),) + (P(),) * len(repl),
                out_specs=P(axis),
            )
            out = sharded(pop_tree, *repl)
            if name is not None:
                # trace-time (once per compiled shape): out leaves are
                # tracers — only shapes/dtypes are read
                meshprof.record_population_layout(
                    name, population=pop, pad=pad, devices=n_dev,
                    out_tree=out, device_names=dev_names)
            if pad:
                out = jax.tree.map(
                    lambda x: x[:pop]
                    if getattr(x, "ndim", 0) >= 1 and x.shape[0] == pop + pad
                    else x, out)
            return out

        # jit at the seam: standalone callers get ONE compiled program per
        # shape; inside an enclosing jit (the scanned GA) this inlines.
        # (a padded population concatenates before the shard_map, so the
        # donated buffers free without aliasing; divisible populations
        # alias for real — same contract as the single-device fallback)
        return jax.jit(padded, donate_argnums=(0,) if donate_pop else ())

    def trial_devices(self) -> list:
        return list(np.ravel(self.mesh.devices))

    def _device_list(self) -> list:
        return list(np.ravel(self.mesh.devices))

    def describe(self) -> dict:
        out = super().describe()
        out["axis"] = self.axis
        out["mesh_shape"] = {str(a): int(self.mesh.shape[a])
                             for a in self.mesh.axis_names}
        out["device_names"] = [str(d) for d in np.ravel(self.mesh.devices)]
        return out


@functools.lru_cache(maxsize=8)
def _default_partitioner(n_devices: int) -> Partitioner:
    if n_devices <= 1:
        return SingleDevicePartitioner()
    return MeshPartitioner(default_mesh())


def get_partitioner(mesh: Mesh | None = None) -> Partitioner:
    """The default seam: MeshPartitioner over the default (all-devices)
    mesh when more than one device is visible, else the single-device
    fallback.  Pass an explicit mesh to pin topology (tests, dryruns)."""
    if mesh is not None:
        if mesh.shape[mesh.axis_names[0]] <= 1:
            return SingleDevicePartitioner()
        return MeshPartitioner(mesh)
    try:
        n = jax.device_count()
    except RuntimeError:       # backend not initializable (gate, docs jobs)
        n = 1
    return _default_partitioner(n)
