"""Ring attention: exact self-attention over a sequence sharded across the mesh.

The reference caps every attention model at 60-step windows
(`services/neural_network_service.py:530-586`, config sequence_length: 60) —
its transformer never sees a long context.  This module removes that ceiling
the TPU way: the sequence axis is sharded over the mesh, each device holds
one Q/K/V block, and K/V blocks rotate around the ring via `ppermute` while
an online-softmax accumulator (running max / normalizer, flash-attention
style) folds in one block per step.  After `n_devices` steps every Q block
has attended over the full sequence without any device ever materializing
the [T, T] score matrix — memory is O(T·d / n + Tb²) per device and the
block transfers ride ICI, overlapping with compute in XLA's pipeline.

This is the standard blockwise-ring formulation (Liu et al., "Ring
Attention with Blockwise Transformers", arXiv:2310.01889 — see PAPERS.md);
the implementation here is written against `shard_map` + collectives, not
ported from any reference code (the reference has no distributed attention
at all — SURVEY §5.7 "long-context: absent").

Numerics: accumulation runs in float32 regardless of input dtype; masked
positions are excluded by a hard zero on the post-exp weights (not a -1e30
additive mask), so fully-masked blocks contribute exactly nothing and a
causal first row stays finite.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ai_crypto_trader_tpu.parallel.mesh import compat_shard_map

NEG_BIG = -1e30   # finite stand-in for -inf: never produces NaN under exp/sub


def _block_update(o, m, l, q, k, v, kmask, *, scale):
    """Fold one K/V block into the (o, m, l) online-softmax accumulator.

    o: [Tq, H, D] f32 unnormalized output;  m, l: [H, Tq] running max and
    normalizer;  kmask: [Tq, Tk] bool, True where the key is attendable.
    """
    s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale          # [H, Tq, Tk]
    s = jnp.where(kmask[None, :, :], s, NEG_BIG)
    m_new = jnp.maximum(m, s.max(axis=-1))                 # [H, Tq]
    # exp of masked lanes may be exp(0)=1 when the whole row is masked —
    # the explicit mask multiply below zeroes them regardless.
    p = jnp.exp(s - m_new[..., None]) * kmask[None, :, :]  # [H, Tq, Tk]
    corr = jnp.exp(m - m_new)                              # [H, Tq]
    l = l * corr + p.sum(axis=-1)
    o = o * corr.T[..., None] + jnp.einsum(
        "hqk,khd->qhd", p, v.astype(jnp.float32))
    return o, m_new, l


def ring_self_attention(q, k, v, mesh: Mesh, *, axis: str = "data",
                        causal: bool = True):
    """Exact (optionally causal) multi-head self-attention on a
    sequence-sharded [T, H, D] q/k/v triple.

    ``T`` must divide evenly over ``mesh.shape[axis]``; outputs carry the
    same sequence sharding as the inputs.  One device degenerates to plain
    flash-style attention (same ops, same order), so the unsharded path and
    the ring path share numerics by construction.
    """
    T, H, D = q.shape
    n_dev = mesh.shape[axis]
    if T % n_dev:
        raise ValueError(f"sequence length {T} not divisible by the "
                         f"{n_dev}-way '{axis}' mesh axis")
    scale = 1.0 / (D ** 0.5)
    spec = P(axis, None, None)

    def local(q_blk, k_blk, v_blk):
        n = lax.psum(1, axis)
        me = lax.axis_index(axis)
        Tb = q_blk.shape[0]
        q_pos = me * Tb + jnp.arange(Tb)                   # global positions
        perm = [(i, (i + 1) % n) for i in range(n)]

        o = jnp.zeros((Tb, H, D), jnp.float32)
        m = jnp.full((H, Tb), NEG_BIG, jnp.float32)
        l = jnp.zeros((H, Tb), jnp.float32)

        def kv_mask(s):
            src = (me - s) % n                 # who originated this block
            k_pos = src * Tb + jnp.arange(Tb)
            if causal:
                return k_pos[None, :] <= q_pos[:, None]
            return jnp.ones((Tb, Tb), bool)

        def fold(o, m, l, k_c, v_c, s):
            """Fold block s in — skipping the score matmul entirely when the
            block is fully in the future (causal: src block strictly after
            this device's queries, i.e. src > me).  The predicate varies per
            device but the cond is purely local (the ppermute stays outside),
            so SPMD control flow is fine; on average this halves the causal
            FLOPs (the zigzag-scheduling observation from the ring-attention
            literature, applied as a skip rather than a re-layout)."""
            if not causal:
                return _block_update(o, m, l, q_blk, k_c, v_c, kv_mask(s),
                                     scale=scale)
            src = (me - s) % n
            return lax.cond(
                src > me,
                lambda: (o, m, l),
                lambda: _block_update(o, m, l, q_blk, k_c, v_c, kv_mask(s),
                                      scale=scale))

        def step(carry, s):
            k_c, v_c, o, m, l = carry
            o, m, l = fold(o, m, l, k_c, v_c, s)
            # hand the block to the right neighbour for the next step
            k_c = lax.ppermute(k_c, axis, perm)
            v_c = lax.ppermute(v_c, axis, perm)
            return (k_c, v_c, o, m, l), None

        # n-1 rotations, not n: the last block is folded outside the scan,
        # so no dead final ppermute returning K/V to their origin
        (k_c, v_c, o, m, l), _ = lax.scan(
            step, (k_blk, v_blk, o, m, l), jnp.arange(n - 1))
        o, m, l = fold(o, m, l, k_c, v_c, n - 1)
        out = o / jnp.maximum(l, 1e-30).T[..., None]
        return out.astype(q_blk.dtype)

    fn = compat_shard_map(local, mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    sharding = NamedSharding(mesh, spec)
    return fn(jax.device_put(q, sharding), jax.device_put(k, sharding),
              jax.device_put(v, sharding))


def reference_attention(q, k, v, *, causal: bool = True):
    """Dense single-device oracle (materializes [H, T, T]) for parity tests."""
    T, H, D = q.shape
    s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (D ** 0.5)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None], s, NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32)).astype(q.dtype)
