"""Sequence parallelism: the candle axis sharded across the mesh.

The only long axis in this domain is the backtest candle axis (a year of
1m candles ≈ 525,600 steps — SURVEY §5.7); its "context parallelism" is
not attention but the prefix-scan indicator family. This module shards
that axis across devices the way ring attention shards sequence blocks:

* `sharded_first_order_recursion` — the EMA-family recurrence
  ``y[t] = a[t]·y[t-1] + b[t]`` computed blockwise: each device runs the
  local associative scan, the per-block affine aggregates
  ``(A_i, B_i) = (∏a, local final)`` are all-gathered over ICI, the
  incoming carry for each block is the composition of its predecessors,
  and a rank-1 fix-up ``y += carry · cumprod(a)`` makes the result exact.
  One collective of 2·N scalars replaces any cross-device sequential
  dependency.
* `sharded_ema` — pandas-parity EMA (ops.indicators.ema semantics) on a
  time-sharded series.
* `sharded_rolling_mean` — windowed reductions via halo exchange: each
  device `ppermute`s its tail (window-1 candles) to its right neighbour,
  exactly the ring pattern of blockwise attention.

Everything runs under `shard_map` over the mesh's data axis; on one device
the math degenerates to the unsharded kernels (same ops, same order).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ai_crypto_trader_tpu.ops.indicators import first_order_recursion
from ai_crypto_trader_tpu.parallel.mesh import compat_shard_map


def _carry_for_my_block(A, B, axis: str):
    """Incoming carry for this device's block: the composition of all
    predecessor blocks' affine aggregates applied to y=0."""
    idx = lax.axis_index(axis)
    As = lax.all_gather(A, axis)            # [n]
    Bs = lax.all_gather(B, axis)

    def step(c, ab):
        a, b = ab
        return a * c + b, c

    # scan over blocks is O(n_devices) scalar work — negligible
    _, carries = lax.scan(step, 0.0, (As, Bs))
    return carries[idx]


def sharded_first_order_recursion(a, b, mesh, axis: str = "data"):
    """Exact ``y[t] = a[t]·y[t-1] + b[t]`` over a time-sharded pair.

    `a`/`b` are global [T] arrays (T divisible by the axis size); the
    result carries the same sharding.
    """
    spec = P(axis)

    def local(a_blk, b_blk):
        prefix = jnp.cumprod(a_blk)
        local_y = first_order_recursion(a_blk, b_blk)
        carry = _carry_for_my_block(prefix[-1], local_y[-1], axis)
        return local_y + carry * prefix

    fn = compat_shard_map(local, mesh, in_specs=(spec, spec),
                       out_specs=spec)
    sharding = NamedSharding(mesh, spec)
    return fn(jax.device_put(a, sharding), jax.device_put(b, sharding))


def sharded_ema(x, window: int, mesh, axis: str = "data"):
    """ops.indicators.ema (pandas ewm adjust=False, min_periods=window) on a
    time-sharded series — the global (a, b) recurrence coefficients feed
    `sharded_first_order_recursion` (one carry-fixup implementation)."""
    alpha = 2.0 / (window + 1.0)
    t = jnp.arange(x.shape[-1])
    xs = jnp.nan_to_num(x)
    a = jnp.where(t == 0, 0.0, 1.0 - alpha)
    b = jnp.where(t == 0, xs, alpha * xs)
    y = sharded_first_order_recursion(a, b, mesh, axis)
    # min_periods warmup: first window-1 positions NaN (_mask_warmup)
    return jnp.where(t < window - 1, jnp.nan, y)


def sharded_rolling_mean(x, window: int, mesh, axis: str = "data"):
    """ops.indicators.rolling_mean on a time-sharded series via halo
    exchange: each block receives the previous block's trailing window-1
    candles over ICI (`ppermute`), so every output is computed from the
    same window as the unsharded kernel.

    The halo is one block deep: requires ``2 <= window`` and
    ``window - 1 <= T // axis_size`` (enforced — a violated precondition
    would silently return wrong-length output through shard_map)."""
    if window == 1:
        return jax.device_put(x, NamedSharding(mesh, P(axis)))
    n_dev = mesh.shape[axis]
    block = x.shape[-1] // n_dev
    if window - 1 > block:
        raise ValueError(
            f"window {window} needs a halo of {window - 1} candles but the "
            f"per-device block is only {block}; use fewer shards or the "
            "unsharded kernel")
    spec = P(axis)

    def local(x_blk):
        n = lax.psum(1, axis)
        idx = lax.axis_index(axis)
        halo = lax.ppermute(x_blk[-(window - 1):], axis,
                            [(i, (i + 1) % n) for i in range(n)])
        # block 0 has no predecessor: NaN halo reproduces the warmup
        halo = jnp.where(idx == 0, jnp.nan, halo)
        ext = jnp.concatenate([halo, x_blk])
        kernel = jnp.ones((window,)) / window
        means = jnp.convolve(ext, kernel, mode="valid")
        return means

    fn = compat_shard_map(local, mesh, in_specs=(spec,), out_specs=spec)
    return fn(jax.device_put(x, NamedSharding(mesh, spec)))
