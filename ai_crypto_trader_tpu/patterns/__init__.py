from ai_crypto_trader_tpu.patterns.synthetic import (  # noqa: F401
    PATTERN_CLASSES,
    generate_dataset,
    generate_pattern,
)
from ai_crypto_trader_tpu.patterns.model import (  # noqa: F401
    PATTERN_IMPLICATIONS,
    PatternRecognizer,
    detect_patterns,
    pattern_completion,
    preprocess_window,
    train_pattern_model,
)
from ai_crypto_trader_tpu.patterns.service import (  # noqa: F401
    ChartPatternService,
    pattern_trading_signals,
)
