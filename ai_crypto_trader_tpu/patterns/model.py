"""Chart-pattern classifier: CNN / LSTM / CNN-LSTM over OHLCV windows.

Capability parity with PatternRecognitionModel + its service wrapper
(`services/utils/pattern_recognition.py`):
  * 15-class softmax classifiers (classes :59-66) in flax — CNN (:94-132),
    LSTM (:134-159), CNN-LSTM (:161-195);
  * preprocess = OHLC ÷ last close, volume ÷ max (:336-374) —
    `preprocess_window`;
  * overlapping windows (seq_len 60, stride 5, :376-401) scored in ONE
    batched forward pass (the reference loops windows in Python), softmax
    averaged, top-3 returned, primary requires prob > 0.5 (:403-474);
  * heuristic completion %, per-pattern trading implications /
    confirmation / invalidation rules (:476-529, :707-811);
  * training on the synthetic generators (patterns/synthetic.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ai_crypto_trader_tpu.models import train_loop
from ai_crypto_trader_tpu.models.fused_lstm import FusedLSTM
from ai_crypto_trader_tpu.models.train_loop import EpochTrainer
from ai_crypto_trader_tpu.patterns.synthetic import (
    N_CLASSES, PATTERN_CLASSES, generate_dataset,
)


def _center(x):
    """Per-window channel standardization. The ÷last-close preprocess leaves
    OHLC hovering near 1.0 (uncentered, tiny variance), which trains
    glacially; centering inside the model keeps the external preprocess
    reference-faithful while making the optimization well-conditioned."""
    mean = jnp.mean(x, axis=1, keepdims=True)
    std = jnp.std(x, axis=1, keepdims=True)
    return (x - mean) / (std + 1e-6)


class PatternCNN(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):     # [B, T, 5]
        x = _center(x)
        for feat in (32, 64):
            x = nn.Conv(feat, kernel_size=(5,), padding="SAME")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2,), strides=(2,))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.3, deterministic=not train)(x)
        return nn.Dense(N_CLASSES)(x)


class PatternLSTM(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        h = FusedLSTM(64)(_center(x).swapaxes(0, 1))[-1]
        h = nn.relu(nn.Dense(64)(h))
        h = nn.Dropout(0.3, deterministic=not train)(h)
        return nn.Dense(N_CLASSES)(h)


class PatternCNNLSTM(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(nn.Conv(32, (5,), padding="SAME")(_center(x)))
        x = nn.max_pool(x, (2,), strides=(2,))
        h = FusedLSTM(64)(x.swapaxes(0, 1))[-1]
        h = nn.Dropout(0.3, deterministic=not train)(h)
        return nn.Dense(N_CLASSES)(h)


def _build(model_type: str) -> nn.Module:
    return {"cnn": PatternCNN, "lstm": PatternLSTM,
            "cnn_lstm": PatternCNNLSTM}[model_type]()


# Per-pattern trading implications, confirmation & invalidation rules
# (`pattern_recognition.py:707-811`).
PATTERN_IMPLICATIONS = {
    "head_and_shoulders": {"bias": "bearish", "action": "consider_exit",
                           "confirmation": "neckline break on volume",
                           "invalidation": "close above right shoulder"},
    "inverse_head_and_shoulders": {"bias": "bullish", "action": "consider_entry",
                                   "confirmation": "neckline break on volume",
                                   "invalidation": "close below right shoulder"},
    "double_top": {"bias": "bearish", "action": "consider_exit",
                   "confirmation": "break below valley",
                   "invalidation": "close above tops"},
    "double_bottom": {"bias": "bullish", "action": "consider_entry",
                      "confirmation": "break above peak",
                      "invalidation": "close below bottoms"},
    "ascending_triangle": {"bias": "bullish", "action": "watch_breakout",
                           "confirmation": "break above resistance",
                           "invalidation": "break below rising support"},
    "descending_triangle": {"bias": "bearish", "action": "watch_breakdown",
                            "confirmation": "break below support",
                            "invalidation": "break above falling resistance"},
    "symmetric_triangle": {"bias": "neutral", "action": "watch_breakout",
                           "confirmation": "directional break on volume",
                           "invalidation": "failed break / chop"},
    "rectangle": {"bias": "neutral", "action": "range_trade",
                  "confirmation": "range boundary break",
                  "invalidation": "mid-range churn"},
    "flag_bull": {"bias": "bullish", "action": "consider_entry",
                  "confirmation": "break above flag channel",
                  "invalidation": "break below channel low"},
    "flag_bear": {"bias": "bearish", "action": "consider_exit",
                  "confirmation": "break below flag channel",
                  "invalidation": "break above channel high"},
    "pennant": {"bias": "continuation", "action": "watch_breakout",
                "confirmation": "break in pole direction",
                "invalidation": "break against pole"},
    "cup_and_handle": {"bias": "bullish", "action": "consider_entry",
                       "confirmation": "break above handle high",
                       "invalidation": "close below cup midpoint"},
    "rising_wedge": {"bias": "bearish", "action": "consider_exit",
                     "confirmation": "break below wedge support",
                     "invalidation": "break above wedge"},
    "falling_wedge": {"bias": "bullish", "action": "consider_entry",
                      "confirmation": "break above wedge resistance",
                      "invalidation": "break below wedge"},
    "no_pattern": {"bias": "neutral", "action": "none",
                   "confirmation": "", "invalidation": ""},
}


@jax.jit
def preprocess_window(ohlcv_window: jnp.ndarray) -> jnp.ndarray:
    """[T, 5] raw OHLCV → normalized (÷ last close; volume ÷ max),
    `pattern_recognition.py:336-374`."""
    ohlc = ohlcv_window[:, :4] / ohlcv_window[-1, 3]
    vmax = jnp.max(ohlcv_window[:, 4])
    vol = (ohlcv_window[:, 4] / jnp.where(vmax == 0, 1.0, vmax))[:, None]
    return jnp.concatenate([ohlc, vol], axis=-1)


@dataclass
class PatternRecognizer:
    model_type: str = "cnn"
    params: Any = None
    history: list = field(default_factory=list)
    # False marks a random-init recognizer (stack fallback): services tag
    # everything it publishes "untrained" so downstream consumers can gate
    trained: bool = True

    def logits(self, x, train=False, rngs=None):
        return _build(self.model_type).apply(self.params, x, train, rngs=rngs)


def train_pattern_model(key, model_type: str = "cnn", *, n_per_class: int = 64,
                        epochs: int = 10, batch_size: int = 64,
                        learning_rate: float = 1e-3, T: int = 60,
                        precision: str | None = None,
                        verbose: bool = False) -> PatternRecognizer:
    """Train on the synthetic generators (the reference's only data source,
    `pattern_recognition.py:813-1039`) — each epoch is one donated
    compiled `lax.scan` program (models/train_loop.py), with a single
    host readback per epoch."""
    k_data, k_init, key = jax.random.split(key, 3)
    X, y = generate_dataset(k_data, n_per_class, T)
    model = _build(model_type)
    params = model.init(k_init, X[:2], False)
    tx = optax.adam(learning_rate)
    opt_state = tx.init(params)

    def loss_fn(p, xb, yb, rng):
        logits = model.apply(p, xb, True, rngs={"dropout": rng})
        return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

    trainer = EpochTrainer(loss_fn, tx, precision=precision,
                           card="train_epoch.pattern_cnn")
    rec = PatternRecognizer(model_type=model_type)
    for epoch in range(epochs):
        key, k_perm, k_ep = jax.random.split(key, 3)
        params, opt_state, metrics = trainer.epoch(
            params, opt_state, X, y, k_perm, k_ep, batch_size=batch_size)
        ep_loss = float(train_loop.host_read(metrics)[0])   # one sync/epoch
        rec.history.append({"epoch": epoch, "loss": ep_loss})
        if verbose:
            print(f"pattern {model_type} epoch {epoch}: {ep_loss:.4f}")
    rec.params = params
    return rec


@functools.partial(jax.jit, static_argnames=("model_type", "seq_len", "stride"))
def _window_probs(params, model_type: str, ohlcv: jnp.ndarray,
                  seq_len: int, stride: int):
    """All overlapping windows scored in one batched forward pass."""
    T = ohlcv.shape[0]
    n_win = (T - seq_len) // stride + 1
    starts = jnp.arange(n_win) * stride
    windows = jax.vmap(
        lambda s: jax.lax.dynamic_slice(ohlcv, (s, 0), (seq_len, 5)))(starts)
    windows = jax.vmap(preprocess_window)(windows)
    logits = _build(model_type).apply(params, windows, False)
    return jax.nn.softmax(logits, axis=-1)


def pattern_completion(probs_per_window: np.ndarray, primary: int) -> float:
    """Heuristic completion %: how far through the window sequence the
    pattern's probability peaked (`pattern_recognition.py:476-529`)."""
    p = probs_per_window[:, primary]
    if p.size == 0 or p.max() <= 0:
        return 0.0
    return float((np.argmax(p) + 1) / p.size)


def detect_patterns(rec: PatternRecognizer, ohlcv: np.ndarray, *,
                    seq_len: int = 60, stride: int = 5,
                    confidence_threshold: float = 0.5) -> dict:
    """Averaged softmax over overlapping windows → top-3; primary requires
    prob > threshold (`detect_patterns`, `pattern_recognition.py:403-474`).

    ohlcv: [T, 5] raw (open, high, low, close, volume)."""
    ohlcv = jnp.asarray(ohlcv, jnp.float32)
    if ohlcv.shape[0] < seq_len:
        return {"detected": False, "reason": "insufficient_data"}
    probs = np.asarray(_window_probs(rec.params, rec.model_type, ohlcv,
                                     seq_len, stride))
    avg = probs.mean(axis=0)
    top3_idx = np.argsort(-avg)[:3]
    top3 = [{"pattern": PATTERN_CLASSES[i], "probability": float(avg[i])}
            for i in top3_idx]
    primary = int(top3_idx[0])
    detected = (avg[primary] > confidence_threshold
                and PATTERN_CLASSES[primary] != "no_pattern")
    out = {
        "detected": bool(detected),
        "top_patterns": top3,
        "all_probabilities": {PATTERN_CLASSES[i]: float(avg[i])
                              for i in range(len(avg))},
    }
    if detected:
        name = PATTERN_CLASSES[primary]
        out.update({
            "primary_pattern": name,
            "confidence": float(avg[primary]),
            "completion": pattern_completion(probs, primary),
            "implications": PATTERN_IMPLICATIONS[name],
        })
    return out
