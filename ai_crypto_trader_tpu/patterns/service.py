"""Chart-pattern recognition service: interval-gated detection, signal
publication, and the 5-minute combined report.

Capability parity with PatternRecognitionService
(`services/pattern_recognition_service.py`):
  * per-symbol update-interval gate (:150-156),
  * detection over the 5m timeframe when present, else 1m (:176-183),
  * signal derivation (`pattern_recognition.py:1147-1214`): completion %
    → strength label (≥90 very_strong 0.9 / ≥75 strong 0.7 / ≥50 moderate
    0.5 / else weak 0.3, :748-756), scaled by confidence and completion,
    bias → buy/sell with the 0.3 floor,
  * publishes `pattern_signals` when signal ≠ neutral and strength ≥ 0.3
    (:209-221) and stores per-symbol pattern state,
  * periodic combined report with bullish/bearish/neutral counts and the
    strongest signal (`generate_combined_analysis`, :298-343).

Detection itself is the compiled batched-window scorer in
patterns/model.py; this service is host-side cadence around it, clocked by
``now_fn`` for virtual-clock tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ai_crypto_trader_tpu.patterns.model import PatternRecognizer, detect_patterns
from ai_crypto_trader_tpu.shell.bus import EventBus

STRENGTH_LABELS = ((90.0, "very_strong", 0.9), (75.0, "strong", 0.7),
                   (50.0, "moderate", 0.5), (-1.0, "weak", 0.3))


def pattern_trading_signals(analysis: dict,
                            confidence_threshold: float = 0.5) -> dict:
    """`get_pattern_trading_signals` (`pattern_recognition.py:1147-1214`)."""
    if not analysis or not analysis.get("detected"):
        return {"signal": "neutral", "strength": 0.0}
    confidence = analysis.get("confidence", 0.0)
    if confidence < confidence_threshold:
        return {"signal": "neutral", "strength": 0.0}
    completion = float(analysis.get("completion", 0.0)) * 100.0 \
        if analysis.get("completion", 0.0) <= 1.0 else float(analysis["completion"])
    implications = analysis.get("implications", {})
    bias = implications.get("bias", "neutral")

    label, numeric = "weak", 0.3
    for floor, lab, num in STRENGTH_LABELS:
        if completion >= floor:
            label, numeric = lab, num
            break
    strength = round(numeric * confidence * (completion / 100.0), 2)
    if bias == "bullish" and strength > 0.3:
        signal = "buy"
    elif bias == "bearish" and strength > 0.3:
        signal = "sell"
    else:
        signal = "neutral"
    return {
        "signal": signal, "strength": strength,
        "pattern": analysis.get("primary_pattern", "no_pattern"),
        "bias": bias, "completion": completion,
        "signal_strength": label,
        "confirmation": implications.get("confirmation", ""),
        "invalidation": implications.get("invalidation", ""),
    }


@dataclass
class ChartPatternService:
    bus: EventBus
    recognizer: PatternRecognizer
    symbols: list[str]
    update_interval_s: float = 300.0
    report_interval_s: float = 300.0
    confidence_threshold: float = 0.5
    min_publish_strength: float = 0.3
    seq_len: int = 60
    stride: int = 5
    now_fn: any = None
    name: str = "patterns"

    pattern_data: dict = field(default_factory=dict)
    _last_update: dict = field(default_factory=dict)
    _last_report: float = field(default=-1e18)

    def __post_init__(self):
        if self.now_fn is None:
            import time

            self.now_fn = time.time

    def _ohlcv(self, symbol: str) -> np.ndarray | None:
        """5m timeframe preferred, 1m fallback (:176-183)."""
        for iv in ("5m", "1m"):
            klines = self.bus.get(f"historical_data_{symbol}_{iv}")
            if klines and len(klines) >= self.seq_len:
                return np.asarray([row[1:6] for row in klines], np.float32)
        return None

    async def analyze_symbol(self, symbol: str, now: float) -> dict | None:
        """Gate → detect → publish; returns the published signal or None."""
        if now - self._last_update.get(symbol, -1e18) < self.update_interval_s:
            return None
        ohlcv = self._ohlcv(symbol)
        if ohlcv is None:
            return None
        self._last_update[symbol] = now
        analysis = detect_patterns(
            self.recognizer, ohlcv, seq_len=self.seq_len, stride=self.stride,
            confidence_threshold=self.confidence_threshold)
        untrained = not getattr(self.recognizer, "trained", True)
        if untrained:
            # random-init fallback recognizer (shell/stack.py): keep the
            # cadence alive but mark every artifact so consumers can gate
            analysis["model_status"] = "untrained"
        self.pattern_data[symbol] = analysis
        self.bus.set(f"pattern_analysis_{symbol}", analysis)

        signals = pattern_trading_signals(analysis, self.confidence_threshold)
        if (signals["signal"] != "neutral"
                and signals["strength"] >= self.min_publish_strength):
            signals.update({"symbol": symbol, "timestamp": now,
                            "source": "pattern_recognition"})
            if untrained:
                signals["model_status"] = "untrained"
            await self.bus.publish("pattern_signals", signals)
            self.bus.set(f"pattern_signals_{symbol}", signals)
            return signals
        return None

    def combined_report(self, now: float) -> dict:
        """`generate_combined_analysis` (:298-343): non-neutral signals per
        symbol + summary counts + strongest."""
        per_symbol = {}
        for symbol, analysis in self.pattern_data.items():
            s = pattern_trading_signals(analysis, self.confidence_threshold)
            if s["signal"] != "neutral":
                per_symbol[symbol] = s
        count = lambda b: sum(1 for s in per_symbol.values() if s["bias"] == b)
        strongest = max(per_symbol.items(), key=lambda kv: kv[1]["strength"],
                        default=(None, {"strength": 0.0}))
        return {
            "timestamp": now,
            "signals": per_symbol,
            "summary": {
                "bullish_patterns": count("bullish"),
                "bearish_patterns": count("bearish"),
                # analyzed symbols whose pattern produced no actionable
                # signal (a non-neutral signal implies a directional bias,
                # so counting neutral inside per_symbol would be dead 0)
                "neutral_patterns": len(self.pattern_data) - len(per_symbol),
                "strongest_signal": {"symbol": strongest[0],
                                     **strongest[1]},
            },
        }

    async def run_once(self) -> dict:
        now = self.now_fn()
        published = 0
        for symbol in self.symbols:
            if await self.analyze_symbol(symbol, now) is not None:
                published += 1
        reported = False
        if (now - self._last_report >= self.report_interval_s
                and self.pattern_data):
            # the slot is only burned when a report is actually emitted —
            # otherwise the first real report would wait a full interval
            self._last_report = now
            self.bus.set("pattern_analysis_report", self.combined_report(now))
            reported = True
        return {"published": published, "reported": reported}
