"""Synthetic chart-pattern generators — the training-data source.

The reference's only in-repo training data for its pattern classifier is a
set of synthetic shape generators
(`services/utils/pattern_recognition.py:813-1039`: head & shoulders, double
top/bottom, triangles, rectangle, cup & handle).  This module regenerates
all **14 pattern families + no_pattern** (the reference draws only 9 of its
15 classes; the missing flags/pennant/wedges are added here so every class
is trainable), as pure jax.random functions that vmap into whole datasets
in one call.

Each generator returns a [T] close-price path; `to_ohlcv` dresses it into
the [T, 5] OHLCV windows the classifier consumes (normalized per the
reference's preprocess: OHLC ÷ last close, volume ÷ max —
`pattern_recognition.py:336-374`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

PATTERN_CLASSES = (
    "head_and_shoulders", "inverse_head_and_shoulders",
    "double_top", "double_bottom",
    "ascending_triangle", "descending_triangle", "symmetric_triangle",
    "rectangle", "flag_bull", "flag_bear",
    "pennant", "cup_and_handle", "rising_wedge", "falling_wedge",
    "no_pattern",
)
N_CLASSES = len(PATTERN_CLASSES)


def _bump(t, center, width, height):
    """Smooth gaussian bump."""
    return height * jnp.exp(-0.5 * ((t - center) / width) ** 2)


def _noise(key, T, level):
    return jax.random.normal(key, (T,)) * level


def _path(key, T, base, shape_fn):
    k_amp, k_noise, k_lvl = jax.random.split(key, 3)
    amp = 8.0 + 6.0 * jax.random.uniform(k_amp)
    noise = (0.3 + 0.7 * jax.random.uniform(k_lvl)) * 0.35
    t = jnp.linspace(0.0, 1.0, T)
    return base + amp * shape_fn(t) + _noise(k_noise, T, noise)


def _head_shoulders(t, sign):
    return sign * (_bump(t, 0.2, 0.07, 0.6) + _bump(t, 0.5, 0.08, 1.0)
                   + _bump(t, 0.8, 0.07, 0.6))


def _double(t, sign):
    return sign * (_bump(t, 0.3, 0.08, 1.0) + _bump(t, 0.7, 0.08, 1.0))


def _triangle(t, kind):
    osc = jnp.sin(t * 6 * jnp.pi)
    if kind == "ascending":
        env_hi, env_lo = 1.0, 1.0 - t        # flat top, rising lows
        return jnp.where(osc > 0, osc * 0.2, osc) * env_lo * 0.5 + t * 0.5
    if kind == "descending":
        return jnp.where(osc < 0, osc * 0.2, osc) * (1.0 - t) * 0.5 - t * 0.5
    return osc * (1.0 - t) * 0.5             # symmetric: shrinking envelope


def _rectangle(t):
    return 0.5 * jnp.sin(t * 8 * jnp.pi)


def _flag(t, sign):
    """Sharp pole then a counter-trend consolidation channel."""
    pole = jnp.clip(t / 0.3, 0.0, 1.0) * sign
    channel = jnp.where(t > 0.3, -sign * (t - 0.3) * 0.3
                        + 0.08 * jnp.sin((t - 0.3) * 20 * jnp.pi), 0.0)
    return pole + channel


def _pennant(t):
    pole = jnp.clip(t / 0.3, 0.0, 1.0)
    flagpart = jnp.where(t > 0.3, jnp.sin((t - 0.3) * 16 * jnp.pi)
                         * jnp.maximum(1.0 - (t - 0.3) / 0.7, 0.0) * 0.25, 0.0)
    return pole + flagpart


def _cup_handle(t):
    cup = -_bump(t, 0.4, 0.2, 1.0)
    handle = -_bump(t, 0.85, 0.05, 0.3)
    return cup + handle


def _wedge(t, rising):
    sign = 1.0 if rising else -1.0
    drift = sign * t * 0.8
    osc = jnp.sin(t * 8 * jnp.pi) * (0.5 - 0.4 * t)   # converging envelope
    return drift + osc


def _no_pattern(key, T, base):
    k1, k2 = jax.random.split(key)
    steps = jax.random.normal(k1, (T,)) * 0.5
    return base + jnp.cumsum(steps) + _noise(k2, T, 0.3)


@functools.partial(jax.jit, static_argnames=("label", "T"))
def generate_pattern(key, label: int, T: int = 60, base: float = 100.0):
    """One synthetic close path for class index `label`."""
    name = PATTERN_CLASSES[label]
    if name == "no_pattern":
        return _no_pattern(key, T, base)
    shape = {
        "head_and_shoulders": lambda t: _head_shoulders(t, 1.0),
        "inverse_head_and_shoulders": lambda t: _head_shoulders(t, -1.0),
        "double_top": lambda t: _double(t, 1.0),
        "double_bottom": lambda t: _double(t, -1.0),
        "ascending_triangle": lambda t: _triangle(t, "ascending"),
        "descending_triangle": lambda t: _triangle(t, "descending"),
        "symmetric_triangle": lambda t: _triangle(t, "symmetric"),
        "rectangle": _rectangle,
        "flag_bull": lambda t: _flag(t, 1.0),
        "flag_bear": lambda t: _flag(t, -1.0),
        "pennant": _pennant,
        "cup_and_handle": _cup_handle,
        "rising_wedge": lambda t: _wedge(t, True),
        "falling_wedge": lambda t: _wedge(t, False),
    }[name]
    return _path(key, T, base, shape)


def to_ohlcv(key, close):
    """Dress a close path into normalized OHLCV (preprocess parity:
    OHLC ÷ last close, volume ÷ max volume)."""
    T = close.shape[0]
    k_o, k_w, k_v = jax.random.split(key, 3)
    spread = jnp.abs(jax.random.normal(k_w, (2, T))) * 0.3
    open_ = jnp.concatenate([close[:1], close[:-1]]) + _noise(k_o, T, 0.1)
    high = jnp.maximum(open_, close) + spread[0]
    low = jnp.minimum(open_, close) - spread[1]
    volume = jnp.abs(jax.random.normal(k_v, (T,))) + 0.5
    last = close[-1]
    ohlc = jnp.stack([open_, high, low, close], axis=-1) / last
    vol = (volume / jnp.max(volume))[:, None]
    return jnp.concatenate([ohlc, vol], axis=-1)


def generate_dataset(key, n_per_class: int = 64, T: int = 60):
    """[(N·C), T, 5] windows + [N·C] labels, one vmapped call per class."""
    xs, ys = [], []
    for label in range(N_CLASSES):
        k = jax.random.fold_in(key, label)
        keys = jax.random.split(k, n_per_class)

        def one(kk):
            k1, k2 = jax.random.split(kk)
            return to_ohlcv(k2, generate_pattern(k1, label, T))

        xs.append(jax.vmap(one)(keys))
        ys.append(jnp.full((n_per_class,), label, jnp.int32))
    return jnp.concatenate(xs), jnp.concatenate(ys)
