"""PRNG discipline for the framework.

The reference sprinkles `np.random` / `random` and `datetime.now()` through
every code path, which is what makes it untestable (SURVEY §7.4).  Here all
randomness flows from explicit `jax.random` keys, split hierarchically.
"""

from __future__ import annotations

import jax


def root_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def split_tree(key: jax.Array, names: tuple[str, ...]) -> dict[str, jax.Array]:
    """Deterministically derive one named subkey per component."""
    keys = jax.random.split(key, len(names))
    return {name: k for name, k in zip(names, keys)}


def fold(key: jax.Array, step) -> jax.Array:
    """Derive a per-step key without carrying split state (safe inside scan)."""
    return jax.random.fold_in(key, step)
