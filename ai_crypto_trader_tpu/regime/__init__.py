from ai_crypto_trader_tpu.regime.detector import (  # noqa: F401
    REGIME_NAMES,
    RegimeDetector,
    regime_features,
    rules_regime,
)
from ai_crypto_trader_tpu.regime.cluster import (  # noqa: F401
    gmm_fit,
    gmm_predict_proba,
    kmeans_fit,
    kmeans_predict,
    pca_fit,
    standardize_fit,
)
from ai_crypto_trader_tpu.regime.hmm import (  # noqa: F401
    hmm_fit,
    hmm_posteriors,
    hmm_viterbi,
)
from ai_crypto_trader_tpu.regime.collector import RegimeDataCollector  # noqa: F401
from ai_crypto_trader_tpu.regime.service import MarketRegimeService  # noqa: F401
