"""Clustering primitives in pure JAX: standardize, PCA, k-means, GMM.

Replaces the sklearn pipeline of the reference MarketRegimeDetector
(`services/utils/market_regime_detector.py:138-224`: StandardScaler, PCA
when >5 features, KMeans, GaussianMixture).  EM and Lloyd iterations are
`lax.scan`s over fixed iteration counts — branch-free, jit-compiled, and
batched over the sample axis on the VPU/MXU (distance matrices are
matmuls).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class Standardizer(NamedTuple):
    mean: jnp.ndarray
    std: jnp.ndarray

    def transform(self, x):
        return (x - self.mean) / self.std


def standardize_fit(x) -> Standardizer:
    mean = jnp.mean(x, axis=0)
    std = jnp.std(x, axis=0)
    return Standardizer(mean, jnp.where(std == 0.0, 1.0, std))


class PCA(NamedTuple):
    components: jnp.ndarray   # [F, K]
    mean: jnp.ndarray

    def transform(self, x):
        return (x - self.mean) @ self.components


def pca_fit(x, n_components: int) -> PCA:
    mean = jnp.mean(x, axis=0)
    xc = x - mean
    _, _, vt = jnp.linalg.svd(xc, full_matrices=False)
    return PCA(components=vt[:n_components].T, mean=mean)


# ---------------------------------------------------------------------------
# k-means (Lloyd) with k-means++ style init
# ---------------------------------------------------------------------------

class KMeans(NamedTuple):
    centroids: jnp.ndarray    # [K, F]


def _sq_dists(x, c):
    """[N, K] squared distances as a matmul (MXU-friendly)."""
    return (jnp.sum(x * x, axis=1)[:, None] - 2.0 * x @ c.T
            + jnp.sum(c * c, axis=1)[None, :])


@functools.partial(jax.jit, static_argnames=("k", "iters"))
def kmeans_fit(key, x, k: int, iters: int = 100) -> KMeans:
    n = x.shape[0]

    # k-means++ seeding: greedy farthest-point with random first pick.
    def seed_step(carry, i):
        cents, key = carry
        d = jnp.min(_sq_dists(x, cents), axis=1)
        key, kk = jax.random.split(key)
        nxt = x[jnp.argmax(d)]
        cents = cents.at[i].set(nxt)
        return (cents, key), None

    key, k0 = jax.random.split(key)
    first = x[jax.random.randint(k0, (), 0, n)]
    cents0 = jnp.zeros((k, x.shape[1])).at[0].set(first)
    (cents, _), _ = lax.scan(seed_step, (cents0, key), jnp.arange(1, k))

    def lloyd(carry, _):
        cents = carry
        assign = jnp.argmin(_sq_dists(x, cents), axis=1)
        onehot = jax.nn.one_hot(assign, k)                       # [N, K]
        counts = jnp.sum(onehot, axis=0)
        sums = onehot.T @ x                                      # [K, F]
        new = jnp.where(counts[:, None] > 0,
                        sums / jnp.maximum(counts[:, None], 1.0), cents)
        return new, None

    cents, _ = lax.scan(lloyd, cents, None, length=iters)
    return KMeans(cents)


@jax.jit
def kmeans_predict(model: KMeans, x):
    return jnp.argmin(_sq_dists(x, model.centroids), axis=1)


# ---------------------------------------------------------------------------
# Diagonal-covariance GMM via EM
# ---------------------------------------------------------------------------

class GMM(NamedTuple):
    weights: jnp.ndarray   # [K]
    means: jnp.ndarray     # [K, F]
    vars: jnp.ndarray      # [K, F] diagonal


def _gmm_log_prob(gmm: GMM, x):
    """[N, K] per-component log densities + log weights."""
    diff = x[:, None, :] - gmm.means[None]                       # [N, K, F]
    lp = -0.5 * jnp.sum(diff * diff / gmm.vars[None] + jnp.log(2 * jnp.pi * gmm.vars[None]),
                        axis=-1)
    return lp + jnp.log(gmm.weights)[None]


@functools.partial(jax.jit, static_argnames=("k", "iters"))
def gmm_fit(key, x, k: int, iters: int = 50, var_floor: float = 1e-4) -> GMM:
    km = kmeans_fit(key, x, k, iters=20)
    assign = kmeans_predict(km, x)
    onehot = jax.nn.one_hot(assign, k)
    counts = jnp.maximum(jnp.sum(onehot, axis=0), 1.0)
    means0 = (onehot.T @ x) / counts[:, None]
    var0 = jnp.maximum(
        (onehot.T @ (x * x)) / counts[:, None] - means0**2, var_floor)
    gmm0 = GMM(weights=counts / x.shape[0], means=means0, vars=var0)

    def em(gmm, _):
        logp = _gmm_log_prob(gmm, x)                             # E-step
        resp = jax.nn.softmax(logp, axis=1)                      # [N, K]
        nk = jnp.maximum(jnp.sum(resp, axis=0), 1e-6)            # M-step
        means = (resp.T @ x) / nk[:, None]
        var = jnp.maximum((resp.T @ (x * x)) / nk[:, None] - means**2, var_floor)
        return GMM(weights=nk / x.shape[0], means=means, vars=var), None

    gmm, _ = lax.scan(em, gmm0, None, length=iters)
    return gmm


@jax.jit
def gmm_predict_proba(gmm: GMM, x):
    return jax.nn.softmax(_gmm_log_prob(gmm, x), axis=1)
