"""Regime-training data collection from live system state.

Capability parity with MarketRegimeDataCollector
(`services/utils/market_regime_data_collector.py`): assembles training
datasets from the bus's market data, signals, and trade outcomes (:44-284)
with the per-sample technical feature block (:285-395).  The produced
bundle ({'features': [N, 4], 'outcomes': [N]}) feeds the clustering
primitives in regime/cluster.py and the trade-outcome analyzer
(models/trade_importance.py); full-series regime *detection* runs on
candle arrays via RegimeDetector directly.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ai_crypto_trader_tpu.shell.bus import EventBus


@dataclass
class RegimeDataCollector:
    bus: EventBus
    max_samples: int = 5_000
    samples: deque = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.samples is None:
            self.samples = deque(maxlen=self.max_samples)

    def collect_snapshot(self, symbol: str) -> dict | None:
        """One (features, context) sample from current bus state
        (:44-140)."""
        md = self.bus.get(f"market_data_{symbol}")
        if not md:
            return None
        def num(key, default=0.0):
            v = md.get(key)
            return float(v) if isinstance(v, (int, float)) else default

        sample = {
            "symbol": symbol,
            "timestamp": num("timestamp"),
            "price": num("current_price"),
            "rsi": md.get("rsi") if isinstance(md.get("rsi"), (int, float)) else None,
            "volatility": md.get("volatility")
            if isinstance(md.get("volatility"), (int, float)) else None,
            "trend_strength": num("trend_strength"),
            "trend": md.get("trend"),
            "signal": md.get("signal"),
            "signal_strength": num("signal_strength"),
        }
        latest_signal = self.bus.get(f"latest_signal_{symbol}")
        if latest_signal:
            sample["decision"] = latest_signal.get("decision")
            sample["confidence"] = latest_signal.get("confidence")
        self.samples.append(sample)
        return sample

    def attach_outcomes(self, closed_trades: list[dict],
                        window_s: float = 3600.0) -> int:
        """Join trade outcomes onto collected snapshots by symbol + time
        proximity (:141-284). Returns #samples labeled."""
        n = 0
        for trade in closed_trades:
            t_close = trade.get("closed_at", 0.0)
            best, best_dt = None, window_s
            for s in self.samples:
                if s["symbol"] != trade["symbol"] or "outcome" in s:
                    continue
                dt = abs((s.get("timestamp") or 0.0) - t_close)
                if dt <= best_dt:
                    best, best_dt = s, dt
            if best is not None:
                best["outcome"] = "win" if trade["pnl"] > 0 else "loss"
                best["pnl"] = trade["pnl"]
                n += 1
        return n

    def training_arrays(self) -> dict | None:
        """Dense arrays for detector training / outcome modeling
        (:285-395)."""
        usable = [s for s in self.samples
                  if s.get("rsi") is not None and s.get("volatility") is not None]
        if len(usable) < 10:
            return None
        feats = np.asarray([[s["rsi"], s["volatility"],
                             s["trend_strength"], s["signal_strength"]]
                            for s in usable], np.float32)
        outcomes = np.asarray([1 if s.get("outcome") == "win" else
                               0 if s.get("outcome") == "loss" else -1
                               for s in usable], np.int32)
        return {"features": feats, "outcomes": outcomes,
                "feature_names": ["rsi", "volatility", "trend_strength",
                                  "signal_strength"],
                "n_labeled": int((outcomes >= 0).sum())}
