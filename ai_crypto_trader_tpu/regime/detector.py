"""Market-regime detection: rules + k-means / GMM / HMM, with the
cluster→regime-name mapping of the reference.

Capability parity with MarketRegimeDetector
(`services/utils/market_regime_detector.py`):
  * feature transformers return/volatility/trend-slope/RSI/MACD/BB-width
    (:64-110) — here computed from the indicator table in one jit;
  * StandardScaler + PCA when >5 features (:181-188);
  * kmeans / gmm / hmm backends (:138-224) — pure JAX (regime/cluster.py,
    regime/hmm.py) instead of sklearn/hmmlearn;
  * heuristic cluster→regime naming by mean return & volatility rank
    (:226-296): highest return → bull, lowest → bear, highest vol of the
    rest → volatile, remainder → ranging;
  * `detect_regime` → (regime, confidence, probabilities) (:298-455);
  * rules method (the reference's hybrid mode, config.json "market_regime")
    as a branch-free threshold classifier.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ai_crypto_trader_tpu import ops
from ai_crypto_trader_tpu.regime import cluster as cl
from ai_crypto_trader_tpu.regime import hmm as hmm_mod

REGIME_NAMES = ("bull", "bear", "ranging", "volatile")


@jax.jit
def regime_features(ohlcv: dict, window: int = 20) -> jnp.ndarray:
    """[T, 6] feature matrix: return, rolling vol, trend slope, RSI, MACD
    (price-normalized), BB width (`market_regime_detector.py:64-110`)."""
    close = ohlcv["close"]
    ret = jnp.diff(jnp.log(close), prepend=jnp.log(close[:1]))
    vol = ops.nanfill(ops.rolling_std(ret, window))
    # trend slope: per-candle OLS slope of close over the window, normalized
    # by price so it is scale-free
    slope = ops.nanfill(_rolling_slope(close, window)) / close
    rsi = ops.nanfill(ops.rsi(close)) / 100.0
    macd_line, _, _ = ops.macd(close)
    macd_n = ops.nanfill(macd_line) / close
    bb = ops.bollinger(close)
    bbw = ops.nanfill(bb.width)
    return jnp.stack([ret, vol, slope, rsi, macd_n, bbw], axis=-1)


def _rolling_slope(x, window: int):
    """OLS slope of x on t over a trailing window: slope_t =
    Σᵢ (i - t̄)(x_{t-w+1+i}) / Σᵢ (i - t̄)² — one small convolution with the
    centered time ramp."""
    t_mean = (window - 1) / 2.0
    ss_t = window * (window * window - 1) / 12.0      # Σ (i - t̄)²
    ramp = jnp.arange(window, dtype=jnp.float32) - t_mean
    tx = jnp.convolve(x, ramp[::-1], mode="full")[: x.shape[0]]
    tx = jnp.where(jnp.arange(x.shape[0]) < window - 1, jnp.nan, tx)
    return tx / ss_t


def rules_regime(features: jnp.ndarray, slope_thresh: float = 5e-5,
                 vol_quantile: float = 0.8) -> jnp.ndarray:
    """Branch-free threshold rules (the reference's hybrid 'rule' half):
    high vol → volatile; else slope sign picks bull/bear; flat → ranging.
    Returns [T] int labels indexing REGIME_NAMES."""
    vol = features[:, 1]
    slope = features[:, 2]
    vol_hi = vol > jnp.quantile(vol, vol_quantile)
    lbl = jnp.where(vol_hi, 3,
                    jnp.where(slope > slope_thresh, 0,
                              jnp.where(slope < -slope_thresh, 1, 2)))
    return lbl.astype(jnp.int32)


def _name_clusters(features: jnp.ndarray, labels: jnp.ndarray, k: int):
    """Cluster index → regime-name index by return/vol ranking
    (`market_regime_detector.py:226-296`)."""
    feats = np.asarray(features)
    labels = np.asarray(labels)
    counts = np.array([(labels == c).sum() for c in range(k)])
    rets = np.array([feats[labels == c, 0].mean() if counts[c] else np.nan
                     for c in range(k)])
    vols = np.array([feats[labels == c, 1].mean() if counts[c] else np.nan
                     for c in range(k)])
    mapping = np.full(k, 2, dtype=np.int32)          # default ranging
    occupied = np.where(counts > 0)[0]
    if len(occupied) == 0:
        return mapping
    # Rank only occupied clusters — an empty cluster must never be named
    # bull/bear or that regime becomes unreachable.
    bull = int(occupied[np.nanargmax(rets[occupied])])
    bear = int(occupied[np.nanargmin(rets[occupied])])
    mapping[bull] = 0
    if bear != bull:
        mapping[bear] = 1
    remaining = [c for c in occupied if c not in (bull, bear)]
    if remaining:
        mapping[max(remaining, key=lambda c: vols[c])] = 3   # volatile
    return mapping


@dataclass
class RegimeDetector:
    """fit/detect façade over the JAX backends."""

    method: str = "kmeans"      # kmeans | gmm | hmm | rules
    n_regimes: int = 4
    pca_components: int = 5
    seed: int = 0
    _state: dict = field(default_factory=dict)

    def fit(self, ohlcv: dict) -> "RegimeDetector":
        feats = regime_features(ohlcv)
        std = cl.standardize_fit(feats)
        z = std.transform(feats)
        if z.shape[1] > self.pca_components:
            pca = cl.pca_fit(z, self.pca_components)
            z = pca.transform(z)
        else:
            pca = None
        key = jax.random.PRNGKey(self.seed)
        if self.method == "kmeans":
            model = cl.kmeans_fit(key, z, self.n_regimes)
            labels = cl.kmeans_predict(model, z)
        elif self.method == "gmm":
            model = cl.gmm_fit(key, z, self.n_regimes)
            labels = jnp.argmax(cl.gmm_predict_proba(model, z), axis=1)
        elif self.method == "hmm":
            model = hmm_mod.hmm_fit(key, z, self.n_regimes)
            labels = hmm_mod.hmm_viterbi(model, z)
        elif self.method == "rules":
            model, labels = None, rules_regime(feats)
        else:
            raise ValueError(f"unknown regime method {self.method!r}")
        mapping = (np.arange(self.n_regimes, dtype=np.int32)
                   if self.method == "rules"
                   else _name_clusters(feats, labels, self.n_regimes))
        self._state = {"std": std, "pca": pca, "model": model,
                       "mapping": mapping}
        return self

    def _project(self, ohlcv: dict):
        feats = regime_features(ohlcv)
        z = self._state["std"].transform(feats)
        if self._state["pca"] is not None:
            z = self._state["pca"].transform(z)
        return feats, z

    def detect(self, ohlcv: dict) -> dict:
        """Regime of the final candle: name, confidence, full probability
        vector over REGIME_NAMES (`detect_regime`,
        `market_regime_detector.py:298-455`)."""
        feats, z = self._project(ohlcv)
        mapping = self._state["mapping"]
        probs4 = np.zeros(4, dtype=np.float64)
        if self.method == "kmeans":
            model = self._state["model"]
            d = np.asarray(cl._sq_dists(z[-1:], model.centroids))[0]
            sim = np.exp(-d / (d.mean() + 1e-9))
            p = sim / sim.sum()
            for c, pr in enumerate(p):
                probs4[mapping[c]] += pr
        elif self.method == "gmm":
            p = np.asarray(cl.gmm_predict_proba(self._state["model"], z[-1:]))[0]
            for c, pr in enumerate(p):
                probs4[mapping[c]] += pr
        elif self.method == "hmm":
            gamma, _ = hmm_mod.hmm_posteriors(self._state["model"], z)
            p = np.asarray(gamma[-1])
            for c, pr in enumerate(p):
                probs4[mapping[c]] += pr
        else:  # rules
            lbl = int(np.asarray(rules_regime(feats))[-1])
            probs4[lbl] = 1.0
        idx = int(np.argmax(probs4))
        return {"regime": REGIME_NAMES[idx],
                "confidence": float(probs4[idx]),
                "probabilities": {n: float(probs4[i])
                                  for i, n in enumerate(REGIME_NAMES)}}

    def label_series(self, ohlcv: dict) -> np.ndarray:
        """Per-candle regime labels (for per-regime strategy performance
        tracking, `services/market_regime_service.py:637-1062`)."""
        feats, z = self._project(ohlcv)
        mapping = self._state["mapping"]
        if self.method == "kmeans":
            lbl = np.asarray(cl.kmeans_predict(self._state["model"], z))
        elif self.method == "gmm":
            lbl = np.asarray(jnp.argmax(cl.gmm_predict_proba(self._state["model"], z), axis=1))
        elif self.method == "hmm":
            lbl = np.asarray(hmm_mod.hmm_viterbi(self._state["model"], z))
        else:
            return np.asarray(rules_regime(feats))
        return mapping[lbl]
