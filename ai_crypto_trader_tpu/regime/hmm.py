"""Gaussian HMM in log space: forward-backward + Baum-Welch as scans.

Replaces `hmmlearn.GaussianHMM` (reference
`services/utils/market_regime_detector.py:150-154`, C implementation) with
pure JAX: the forward and backward recursions are `lax.scan`s over time with
logsumexp accumulation (numerically-safe log space — SURVEY §7.4 flags this
as the touchy part), and Baum-Welch E/M is a fixed-iteration scan, all
jit-compiled.  Diagonal Gaussian emissions.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.special import logsumexp


class HMM(NamedTuple):
    log_pi: jnp.ndarray     # [K] initial log probs
    log_A: jnp.ndarray      # [K, K] transition log probs (row = from)
    means: jnp.ndarray      # [K, F]
    vars: jnp.ndarray       # [K, F]


def _emission_logp(hmm: HMM, x):
    """[T, K] log N(x_t | mean_k, var_k)."""
    diff = x[:, None, :] - hmm.means[None]
    return -0.5 * jnp.sum(diff * diff / hmm.vars[None]
                          + jnp.log(2 * jnp.pi * hmm.vars[None]), axis=-1)


def _forward(hmm: HMM, logb):
    """Returns (log_alpha [T, K], log-likelihood)."""
    def step(la, lb_t):
        la_next = lb_t + logsumexp(la[:, None] + hmm.log_A, axis=0)
        return la_next, la_next

    la0 = hmm.log_pi + logb[0]
    _, las = lax.scan(step, la0, logb[1:])
    log_alpha = jnp.concatenate([la0[None], las], axis=0)
    return log_alpha, logsumexp(log_alpha[-1])


def _backward(hmm: HMM, logb):
    def step(lb, lb_emit_next):
        lb_prev = logsumexp(hmm.log_A + (lb_emit_next + lb)[None, :], axis=1)
        return lb_prev, lb_prev

    lbT = jnp.zeros_like(logb[0])
    _, lbs = lax.scan(step, lbT, logb[1:][::-1])
    return jnp.concatenate([lbs[::-1], lbT[None]], axis=0)


@jax.jit
def hmm_posteriors(hmm: HMM, x):
    """γ_t(k) = P(z_t = k | x_1..T) and the sequence log-likelihood."""
    logb = _emission_logp(hmm, x)
    log_alpha, ll = _forward(hmm, logb)
    log_beta = _backward(hmm, logb)
    gamma = jax.nn.softmax(log_alpha + log_beta, axis=1)
    return gamma, ll


@functools.partial(jax.jit, static_argnames=("k", "iters"))
def hmm_fit(key, x, k: int, iters: int = 30, var_floor: float = 1e-4) -> HMM:
    """Baum-Welch with k-means initialization of emission params."""
    from ai_crypto_trader_tpu.regime.cluster import kmeans_fit, kmeans_predict

    km = kmeans_fit(key, x, k, iters=20)
    assign = kmeans_predict(km, x)
    onehot = jax.nn.one_hot(assign, k)
    counts = jnp.maximum(jnp.sum(onehot, axis=0), 1.0)
    means0 = (onehot.T @ x) / counts[:, None]
    vars0 = jnp.maximum((onehot.T @ (x * x)) / counts[:, None] - means0**2,
                        var_floor)
    hmm0 = HMM(
        log_pi=jnp.log(jnp.full((k,), 1.0 / k)),
        log_A=jnp.log((jnp.eye(k) * 0.9 + (1 - jnp.eye(k)) * (0.1 / (k - 1)))),
        means=means0, vars=vars0,
    )

    def bw(hmm, _):
        logb = _emission_logp(hmm, x)
        log_alpha, ll = _forward(hmm, logb)
        log_beta = _backward(hmm, logb)
        log_gamma = log_alpha + log_beta
        gamma = jax.nn.softmax(log_gamma, axis=1)                # [T, K]

        # ξ_t(i,j) ∝ α_t(i) A_ij b_j(t+1) β_{t+1}(j)
        lx = (log_alpha[:-1, :, None] + hmm.log_A[None]
              + (logb[1:] + log_beta[1:])[:, None, :])           # [T-1,K,K]
        xi = jax.nn.softmax(lx.reshape(lx.shape[0], -1), axis=1).reshape(lx.shape)

        new_pi = jnp.log(gamma[0] + 1e-12)
        trans = jnp.sum(xi, axis=0)
        new_A = jnp.log(trans / jnp.maximum(jnp.sum(trans, axis=1, keepdims=True), 1e-12) + 1e-12)
        nk = jnp.maximum(jnp.sum(gamma, axis=0), 1e-6)
        means = (gamma.T @ x) / nk[:, None]
        vars_ = jnp.maximum((gamma.T @ (x * x)) / nk[:, None] - means**2, var_floor)
        return HMM(new_pi, new_A, means, vars_), ll

    hmm, lls = lax.scan(bw, hmm0, None, length=iters)
    return hmm


@jax.jit
def hmm_viterbi(hmm: HMM, x):
    """Most-likely state path (argmax decoding)."""
    logb = _emission_logp(hmm, x)

    def step(delta, lb_t):
        scores = delta[:, None] + hmm.log_A                      # [K, K]
        best = jnp.max(scores, axis=0) + lb_t
        arg = jnp.argmax(scores, axis=0)
        return best, arg

    d0 = hmm.log_pi + logb[0]
    dT, args = lax.scan(step, d0, logb[1:])

    def backtrack(state, arg_t):
        prev = arg_t[state]
        return prev, prev

    last = jnp.argmax(dT)
    _, path = lax.scan(backtrack, last, args[::-1])
    return jnp.concatenate([path[::-1], last[None]])
