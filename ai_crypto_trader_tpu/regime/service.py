"""Market-regime service: periodic training, per-regime strategy
performance, and switching recommendations.

Capability parity with MarketRegimeService
(`services/market_regime_service.py`): hybrid rule+ML detection
(config.json "market_regime"), periodic re-training on recent history
(:231-283), per-regime strategy performance tracking and switch
recommendations (:637-1062) — wired to the bus the same way
(`market_regime` key + `regime_updates` channel).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ai_crypto_trader_tpu.regime.detector import REGIME_NAMES, RegimeDetector
from ai_crypto_trader_tpu.shell.bus import EventBus


@dataclass
class MarketRegimeService:
    bus: EventBus
    method: str = "kmeans"           # hybrid: rules fallback on thin history
    retrain_interval_s: float = 86_400.0
    min_candles: int = 300           # ML methods need this much history
    min_candles_rules: int = 60      # below min_candles, the rules detector runs
    now_fn: any = time.time
    # All per-symbol: one symbol's fitted clusters must never classify another
    detectors: dict = field(default_factory=dict)
    _last_train: dict = field(default_factory=dict)
    regimes: dict = field(default_factory=dict)   # symbol -> latest detection
    # regime -> strategy_id -> list of trade pnls (:637-720)
    regime_performance: dict = field(default_factory=dict)

    @property
    def current_regime(self) -> dict:
        """Most recent detection across symbols (legacy single-key view)."""
        if not self.regimes:
            return {"regime": "ranging", "confidence": 0.0}
        return max(self.regimes.values(), key=lambda r: r.get("timestamp", 0.0))

    def _history_arrays(self, symbol: str) -> dict | None:
        import jax.numpy as jnp
        klines = self.bus.get(f"historical_data_{symbol}_1m")
        if not klines or len(klines) < self.min_candles_rules:
            return None
        arr = np.asarray([row[1:6] for row in klines], np.float32)
        return {"open": jnp.asarray(arr[:, 0]), "high": jnp.asarray(arr[:, 1]),
                "low": jnp.asarray(arr[:, 2]), "close": jnp.asarray(arr[:, 3]),
                "volume": jnp.asarray(arr[:, 4])}

    async def update(self, symbol: str = "BTCUSDC") -> dict:
        """Detect (retraining on schedule); publish + store (:231-330)."""
        arrays = self._history_arrays(symbol)
        if arrays is None:
            return self.regimes.get(symbol,
                                    {"regime": "ranging", "confidence": 0.0})
        now = self.now_fn()
        thin = int(np.asarray(arrays["close"]).shape[0]) < self.min_candles
        method = "rules" if thin else self.method
        det = self.detectors.get(symbol)
        stale = now - self._last_train.get(symbol, -1e18) >= self.retrain_interval_s
        if det is None or stale or det.method != method:
            det = RegimeDetector(method=method).fit(arrays)
            self.detectors[symbol] = det
            self._last_train[symbol] = now
        out = det.detect(arrays)
        out["timestamp"] = now
        out["symbol"] = symbol
        self.regimes[symbol] = out
        self.bus.set(f"market_regime_{symbol}", out)
        self.bus.set("market_regime", out)   # legacy single-key consumers
        await self.bus.publish("regime_updates", out)
        return out

    # --- per-regime strategy performance (:637-1062) -----------------------
    def record_trade(self, strategy_id: str, pnl: float,
                     regime: str | None = None):
        regime = regime or self.current_regime.get("regime", "ranging")
        self.regime_performance.setdefault(regime, {}).setdefault(
            strategy_id, []).append(pnl)

    def regime_score(self, strategy_id: str, regime: str | None = None) -> float:
        """Win-rate-and-expectancy blend of a strategy within a regime
        (`_calculate_regime_score`)."""
        regime = regime or self.current_regime.get("regime", "ranging")
        pnls = self.regime_performance.get(regime, {}).get(strategy_id, [])
        if not pnls:
            return 0.5
        arr = np.asarray(pnls)
        win_rate = (arr > 0).mean()
        expectancy = arr.mean()
        return float(np.clip(0.5 * win_rate
                             + 0.5 * (0.5 + np.tanh(expectancy / 50.0) / 2.0),
                             0.0, 1.0))

    def best_strategy_for_regime(self, regime: str | None = None) -> str | None:
        regime = regime or self.current_regime.get("regime", "ranging")
        perf = self.regime_performance.get(regime, {})
        if not perf:
            return None
        return max(perf, key=lambda s: self.regime_score(s, regime))

    def switch_recommendation(self, current_strategy: str) -> dict:
        """Recommend a switch when another strategy clearly outperforms in
        the current regime (:900-1062)."""
        regime = self.current_regime.get("regime", "ranging")
        best = self.best_strategy_for_regime(regime)
        if best is None or best == current_strategy:
            return {"switch": False, "regime": regime}
        cur = self.regime_score(current_strategy, regime)
        cand = self.regime_score(best, regime)
        return {"switch": cand > cur + 0.1, "regime": regime,
                "candidate": best, "candidate_score": cand,
                "current_score": cur}
