from ai_crypto_trader_tpu.risk.var import (  # noqa: F401
    correlation_matrix,
    cvar,
    diversification_analysis,
    equal_risk_position_sizes,
    historical_var,
    parametric_var,
    portfolio_var,
    stress_var_cvar,
)
from ai_crypto_trader_tpu.risk.stops import (  # noqa: F401
    TrailingStopState,
    adaptive_stop_loss,
    trailing_stop_init,
    trailing_stop_update,
)
from ai_crypto_trader_tpu.risk.social import (  # noqa: F401
    SocialSnapshot,
    social_risk_adjustment,
    weighted_sentiment,
)
