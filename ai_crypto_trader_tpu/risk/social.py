"""Social-sentiment risk adjustment.

Capability parity with SocialRiskAdjuster (`services/social_risk_adjuster.py`):
  * source-weighted sentiment score (:150) over twitter/reddit/news/overall,
  * exponential time decay with a 6-hour half-life (:205),
  * sentiment → position-size / stop-loss / take-profit / correlation-limit
    adjustment factors (:229-298), each capped at ±max_adjustment_percent
    (config.json: 0.5),
  * data-quality gate (:323): below min_data_quality everything is neutral.

Pure functions over arrays of timestamped sentiment observations, so the
same code scores one live snapshot or a whole backtest's history (vmapped).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu.config import SocialRiskParams

DEFAULT_SOURCE_WEIGHTS = (0.35, 0.30, 0.25, 0.10)  # twitter/reddit/news/overall


class SocialSnapshot(NamedTuple):
    """Timestamped sentiment observations. sentiments[i, s] ∈ [0, 1] for
    observation i from source s; age_hours[i] = now - t_i."""

    sentiments: jnp.ndarray    # [N, n_sources]
    age_hours: jnp.ndarray     # [N]
    data_quality: jnp.ndarray  # scalar ∈ [0, 1]


@jax.jit
def weighted_sentiment(snap: SocialSnapshot,
                       source_weights=DEFAULT_SOURCE_WEIGHTS,
                       half_life_hours: float = 6.0):
    """Time-decayed, source-weighted sentiment ∈ [0, 1]
    (`social_risk_adjuster.py:150-228`)."""
    w_src = jnp.asarray(source_weights)
    w_src = w_src / jnp.sum(w_src)
    decay = jnp.exp2(-snap.age_hours / half_life_hours)        # [N]
    per_obs = snap.sentiments @ w_src                          # [N]
    denom = jnp.maximum(jnp.sum(decay), 1e-9)
    return jnp.sum(per_obs * decay) / denom


def social_risk_adjustment(snap: SocialSnapshot,
                           params: SocialRiskParams | None = None):
    """Sentiment → multiplicative adjustment factors
    (`social_risk_adjuster.py:229-323`).

    Bullish sentiment (≥ bullish_threshold) sizes up / widens TP; bearish
    (≤ bearish_threshold) sizes down / tightens stops; every factor is
    clamped to 1 ± max_adjustment_percent, and a failing data-quality gate
    returns exact neutrality."""
    p = params or SocialRiskParams()
    # Source order of SocialSnapshot columns: twitter, reddit, news, overall.
    w = tuple(p.sentiment_weights.get(k, d) for k, d in zip(
        ("twitter_sentiment", "reddit_sentiment", "news_sentiment",
         "overall_sentiment"), DEFAULT_SOURCE_WEIGHTS))
    s = weighted_sentiment(snap, source_weights=w,
                           half_life_hours=p.sentiment_half_life_hours)

    # signed intensity ∈ [-1, 1]: 0 at neutral band center, ±1 at extremes
    center = (p.bullish_threshold + p.bearish_threshold) / 2.0
    span = (p.bullish_threshold - p.bearish_threshold) / 2.0
    intensity = jnp.clip((s - center) / span, -1.0, 1.0)
    in_band = (s < p.bullish_threshold) & (s > p.bearish_threshold)
    intensity = jnp.where(in_band, 0.0, intensity)

    cap = p.max_adjustment_percent

    def factor(impact):
        return jnp.clip(1.0 + intensity * impact, 1.0 - cap, 1.0 + cap)

    quality_ok = snap.data_quality >= p.min_data_quality
    enabled = jnp.asarray(p.enabled) & quality_ok

    def gated(f):
        return jnp.where(enabled, f, 1.0)

    return {
        "sentiment": s,
        "intensity": jnp.where(enabled, intensity, 0.0),
        "position_size_factor": gated(factor(p.position_size_impact)),
        # bearish → tighter stop (smaller stop distance), bullish → roomier
        "stop_loss_factor": gated(factor(p.stop_loss_impact)),
        "take_profit_factor": gated(factor(p.take_profit_impact)),
        "correlation_limit_factor": gated(factor(-p.correlation_impact)),
        "data_quality_ok": quality_ok,
    }
