"""Stop-loss machinery: adaptive stops + the four trailing-stop strategies.

Capability parity with:
  * `PortfolioRiskService.calculate_adaptive_stop_loss`
    (`services/portfolio_risk_service.py:489-547`): volatility-scaled stop
    percentage, factor ∈ [min_factor, max_factor] via a 50 %-annual-vol
    normalization;
  * `TrailingStopManager` (`services/trade_executor_service.py:55-398`):
    percent_based / atr_based / volatility_based / fixed_amount trailing
    strategies, activation threshold, highest-price tracking, and the
    stop-only-moves-up invariant.

The trailing stop is a pure state machine `(state, price) → state'` — one
`jnp.where` chain instead of the reference's dict mutation — so it can run
per-candle *inside* the scan backtester (vmapped over positions) as well as
tick-by-tick in the live executor shell.  Time-based throttling
(`max_adjustment_frequency_seconds`) is a host-side concern and lives in
the shell executor, not here.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

STRATEGIES = ("percent_based", "atr_based", "volatility_based", "fixed_amount")


@jax.jit
def adaptive_stop_loss(entry_price, annual_volatility, base_stop_pct: float = 2.0,
                       min_factor: float = 0.5, max_factor: float = 2.0):
    """Volatility-adaptive stop (`portfolio_risk_service.py:489-547`):
    factor interpolates linearly as vol goes 0 → 50 % annualized."""
    vol_pct = jnp.clip(annual_volatility / 0.5, 0.0, 1.0)
    factor = min_factor + (max_factor - min_factor) * vol_pct
    stop_pct = base_stop_pct * factor
    return entry_price * (1.0 - stop_pct / 100.0), stop_pct


class TrailingStopState(NamedTuple):
    entry: jnp.ndarray
    highest: jnp.ndarray
    stop: jnp.ndarray
    activation_price: jnp.ndarray
    activated: jnp.ndarray       # bool
    n_adjustments: jnp.ndarray   # i32


def trailing_stop_init(entry_price, initial_stop,
                       activation_threshold_pct: float = 1.0) -> TrailingStopState:
    """register_trailing_stop (`trade_executor_service.py:104-140`)."""
    entry = jnp.asarray(entry_price, jnp.float32)
    return TrailingStopState(
        entry=entry,
        highest=entry,
        stop=jnp.asarray(initial_stop, jnp.float32),
        activation_price=entry * (1.0 + activation_threshold_pct / 100.0),
        activated=jnp.asarray(False),
        n_adjustments=jnp.asarray(0, jnp.int32),
    )


@functools.partial(jax.jit, static_argnames=("strategy",))
def trailing_stop_update(state: TrailingStopState, price, *,
                         strategy: str = "percent_based",
                         trail_percent: float = 0.8,
                         min_trail_distance_pct: float = 0.5,
                         atr=0.0, atr_multiplier: float = 2.0,
                         volatility=0.0, volatility_multiplier: float = 1.5,
                         fixed_trail_amount: float = 5.0,
                         min_adjustment_pct: float = 0.2):
    """One price update (`update_price` + `_adjust_trailing_stop`,
    `trade_executor_service.py:142-276`).

    Returns (state', triggered) where triggered = price fell to/below the
    active stop. The stop only ratchets up; adjustment happens only while
    activated and on new highs — exactly the reference's control flow,
    expressed branch-free."""
    price = jnp.asarray(price, jnp.float32)
    new_high = price > state.highest
    highest = jnp.maximum(state.highest, price)
    activated = state.activated | (price >= state.activation_price)

    if strategy == "percent_based":
        cand = highest * (1.0 - trail_percent / 100.0)
        min_stop = highest * (1.0 - min_trail_distance_pct / 100.0)
        cand = jnp.minimum(cand, min_stop)
    elif strategy == "atr_based":
        cand = highest - jnp.asarray(atr) * atr_multiplier
    elif strategy == "volatility_based":
        cand = highest - jnp.asarray(volatility) * volatility_multiplier
    elif strategy == "fixed_amount":
        trail = jnp.maximum(jnp.asarray(fixed_trail_amount),
                            highest * (min_adjustment_pct / 100.0))
        cand = highest - trail
    else:
        raise ValueError(f"unknown trailing strategy {strategy!r}")

    adjust = activated & new_high & (cand > state.stop)
    stop = jnp.where(adjust, cand, state.stop)
    triggered = activated & (price <= stop)

    return TrailingStopState(
        entry=state.entry, highest=highest, stop=stop,
        activation_price=state.activation_price, activated=activated,
        n_adjustments=state.n_adjustments + adjust.astype(jnp.int32),
    ), triggered
