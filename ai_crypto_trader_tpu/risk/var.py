"""Portfolio risk analytics: VaR / CVaR / correlation / sizing.

Capability parity with PortfolioRiskService
(`services/portfolio_risk_service.py`):
  * historical + parametric VaR and CVaR (:217-285),
  * asset correlation matrix (:286),
  * correlation-aware portfolio VaR (:328),
  * equal-risk ("risk parity light") optimal position sizes (:400),
  * diversification analysis (:718).

All functions are jitted array programs over return matrices
[n_assets, T]; the host shell feeds them live return windows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def historical_var(returns: jnp.ndarray, confidence: float = 0.95):
    """Empirical VaR: the (1-c) quantile of the return distribution,
    reported positive (loss). returns: [..., T]."""
    q = jnp.quantile(returns, 1.0 - confidence, axis=-1)
    return jnp.maximum(-q, 0.0)


@jax.jit
def parametric_var(returns: jnp.ndarray, confidence: float = 0.95):
    """Gaussian VaR: -(μ + z·σ). z hard-coded per reference's use of the
    normal quantile (z_{0.05} = -1.645, z_{0.01} = -2.326)."""
    mu = jnp.mean(returns, axis=-1)
    sd = jnp.std(returns, axis=-1)
    z = jnp.interp(jnp.asarray(confidence),
                   jnp.asarray([0.90, 0.95, 0.99]),
                   jnp.asarray([1.2816, 1.6449, 2.3263]))
    return jnp.maximum(-(mu - z * sd), 0.0)


@jax.jit
def cvar(returns: jnp.ndarray, confidence: float = 0.95):
    """Expected shortfall beyond the historical VaR."""
    var = historical_var(returns, confidence)
    tail = returns <= -var[..., None]
    tail_sum = jnp.sum(jnp.where(tail, returns, 0.0), axis=-1)
    tail_n = jnp.maximum(jnp.sum(tail, axis=-1), 1)
    return jnp.maximum(-(tail_sum / tail_n), 0.0)


@jax.jit
def correlation_matrix(returns: jnp.ndarray):
    """[n, n] Pearson correlations from [n, T] returns."""
    x = returns - jnp.mean(returns, axis=-1, keepdims=True)
    cov = x @ x.T / returns.shape[-1]
    sd = jnp.sqrt(jnp.diagonal(cov))
    denom = jnp.outer(sd, sd)
    return cov / jnp.where(denom == 0.0, 1.0, denom)


@jax.jit
def portfolio_var(weights: jnp.ndarray, returns: jnp.ndarray,
                  confidence: float = 0.95):
    """Correlation-aware portfolio VaR: σ_p = √(wᵀ Σ w), VaR = z·σ_p - μ_p
    (`portfolio_risk_service.py:328`)."""
    x = returns - jnp.mean(returns, axis=-1, keepdims=True)
    cov = x @ x.T / returns.shape[-1]
    mu_p = jnp.sum(weights * jnp.mean(returns, axis=-1))
    sigma_p = jnp.sqrt(jnp.maximum(weights @ cov @ weights, 0.0))
    z = jnp.interp(jnp.asarray(confidence),
                   jnp.asarray([0.90, 0.95, 0.99]),
                   jnp.asarray([1.2816, 1.6449, 2.3263]))
    return jnp.maximum(z * sigma_p - mu_p, 0.0)


@jax.jit
def equal_risk_position_sizes(volatilities: jnp.ndarray,
                              total_capital: float = 1.0,
                              max_allocation: float = 0.25):
    """Inverse-volatility sizing with a per-asset allocation cap
    (`calculate_optimal_position_sizes`, `portfolio_risk_service.py:400`).

    Caps are enforced iteratively by redistributing the excess — expressed
    as a fixed small number of projection steps (capped weights can free no
    more than n rounds of excess)."""
    inv = 1.0 / jnp.maximum(volatilities, 1e-8)
    w = inv / jnp.sum(inv)

    def project(w, _):
        over = jnp.maximum(w - max_allocation, 0.0)
        w = jnp.minimum(w, max_allocation)
        free = w < max_allocation
        freeable = jnp.where(free, w, 0.0)
        denom = jnp.sum(freeable)
        w = w + jnp.where(free, freeable / jnp.where(denom == 0, 1.0, denom), 0.0) * jnp.sum(over)
        return w, None

    w, _ = jax.lax.scan(project, w, None, length=4)
    w = jnp.minimum(w, max_allocation)
    return w * total_capital


def stress_var_cvar(key, initial_price, returns, *,
                    stress: str = "flash_crash", days: int = 30,
                    num_sims: int = 4096, confidence: float = 0.95,
                    method: str = "gbm", stress_seed: int = 0) -> dict:
    """Stress-VaR/CVaR: tail risk under an adversarial shock schedule, not
    just the estimated dynamics.

    Runs the Monte-Carlo engine twice at the same shapes — once plain,
    once with a `sim/scenarios.py` preset (flash crashes, vol regime
    shifts, black swans) overlaid per path — and reports both tails so the
    uplift is directly readable.  All ``*_pct`` values follow this
    module's positive-loss convention (percent of initial price)."""
    from ai_crypto_trader_tpu.mc import run_simulation

    kw = dict(days=days, num_sims=num_sims, confidence=confidence,
              method=method)
    base = run_simulation(key, initial_price, returns, **kw)
    stressed = run_simulation(key, initial_price, returns, stress=stress,
                              stress_seed=stress_seed, **kw)

    def loss(stats, k):
        return float(jnp.maximum(-stats[k], 0.0))

    # the uplift is computed on the SIGNED percentile shifts (how far the
    # stress moved the tail left), not on the clamped headline losses — a
    # bullish base tail clamped to 0 must not hide a real deterioration
    base_var, stress_var = float(base["var"]), float(stressed["var"])
    return {
        "stress": stressed["stress"],
        "confidence": confidence,
        "num_sims": num_sims,
        "days": days,
        "var_pct": loss(base, "var"),
        "cvar_pct": loss(base, "cvar"),
        "var_signed_pct": base_var,
        "stress_var_signed_pct": stress_var,
        "stress_var_pct": loss(stressed, "var"),
        "stress_cvar_pct": loss(stressed, "cvar"),
        "var_uplift_pct": base_var - stress_var,
        "stress_max_drawdown_mean": float(stressed["max_drawdown_mean"]),
        "stress_prob_loss": float(stressed["prob_loss"]),
    }


@jax.jit
def diversification_analysis(weights: jnp.ndarray, returns: jnp.ndarray):
    """Concentration + correlation diagnostics
    (`portfolio_risk_service.py:718`): Herfindahl index, effective number of
    assets, average pairwise correlation, diversification ratio."""
    corr = correlation_matrix(returns)
    n = weights.shape[0]
    hhi = jnp.sum(weights**2)
    off = corr - jnp.eye(n) * corr
    avg_corr = jnp.sum(off) / jnp.maximum(n * (n - 1), 1)
    sd = jnp.std(returns, axis=-1)
    x = returns - jnp.mean(returns, axis=-1, keepdims=True)
    cov = x @ x.T / returns.shape[-1]
    sigma_p = jnp.sqrt(jnp.maximum(weights @ cov @ weights, 1e-12))
    div_ratio = jnp.sum(weights * sd) / sigma_p
    return {
        "herfindahl": hhi,
        "effective_assets": 1.0 / jnp.maximum(hhi, 1e-9),
        "avg_pairwise_correlation": avg_corr,
        "diversification_ratio": div_ratio,
    }
