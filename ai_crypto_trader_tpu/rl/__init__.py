from ai_crypto_trader_tpu.rl.env import (  # noqa: F401
    EnvParams,
    EnvState,
    env_reset,
    env_step,
    make_env_params,
    obs_size,
)
from ai_crypto_trader_tpu.rl.dqn import (  # noqa: F401
    DQNConfig,
    DQNState,
    act,
    dqn_init,
    evaluate_policy,
    train_dqn,
    train_iteration,
    train_iterations,
)
