from ai_crypto_trader_tpu.rl.env import (  # noqa: F401
    EnvParams,
    EnvState,
    assert_transfer_compatible,
    env_reset,
    env_step,
    make_env_params,
    obs_size,
)
from ai_crypto_trader_tpu.rl.dqn import (  # noqa: F401
    DQNConfig,
    DQNState,
    Hypers,
    act,
    dqn_init,
    evaluate_policy,
    hypers_from_config,
    poisoned_members,
    train_dqn,
    train_iteration,
    train_iterations,
)
from ai_crypto_trader_tpu.rl.population import (  # noqa: F401
    PBTConfig,
    PBTResult,
    PopState,
    adopt_winner,
    best_params,
    pbt_env_params,
    pop_init,
    train_pbt,
)
from ai_crypto_trader_tpu.rl.trainer_service import (  # noqa: F401
    PBT_CHECKPOINT_KIND,
    PBTTrainerService,
    checkpoint_payload,
    load_checkpoint,
    restore_checkpoint,
)
