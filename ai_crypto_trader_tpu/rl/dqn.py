"""DQN on TPU: vmapped parallel envs, on-device replay, fully-jitted steps.

Capability parity with the reference TradingRLAgent
(`services/reinforcement_learning.py`): BUY/HOLD/SELL Q-network with hidden
(24, 24) (`_initialize_models:99-131`), ε-greedy `act` (:292-318), replay
buffer 10 000 + batch-64 Q-learning `replay` (:335-419), target sync every
100 learn steps (:397-401), save/load (utils/checkpoint.py handles state).

TPU-first differences:
  * the replay buffer is a preallocated device array ring, not a Python
    deque — sampling is one gather;
  * `num_envs` environments step in lock-step under vmap (Anakin/Podracer
    pattern; the reference steps one env in Python);
  * one `train_iteration` = [rollout scan over R steps × N envs] +
    [L learn steps] as a single compiled program; the host loop only
    orchestrates iterations and reads metrics.
  * the hand-written NumPy fallback net with manual backprop
    (`reinforcement_learning.py:132-241`) is obsolete — JAX *is* the
    autodiff fallback; nothing to hand-roll.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ai_crypto_trader_tpu.rl.env import EnvParams, EnvState, OBS_SIZE, env_reset, env_step
from ai_crypto_trader_tpu.utils import devprof


class QNetwork(nn.Module):
    """MLP Q(s,·) — Dense(24, 24, |A|) like the reference Keras model."""

    hidden: tuple = (24, 24)
    n_actions: int = 3

    @nn.compact
    def __call__(self, x):
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.n_actions)(x)


class DQNConfig(NamedTuple):
    state_size: int = OBS_SIZE
    n_actions: int = 3
    hidden: tuple = (24, 24)
    gamma: float = 0.95
    epsilon: float = 1.0
    epsilon_min: float = 0.01
    epsilon_decay: float = 0.995
    learning_rate: float = 1e-3
    replay_capacity: int = 10_000
    batch_size: int = 64
    target_sync_every: int = 100
    num_envs: int = 64
    rollout_len: int = 8
    learn_steps_per_iter: int = 4


class Replay(NamedTuple):
    obs: jnp.ndarray        # [cap, obs]
    actions: jnp.ndarray    # [cap]
    rewards: jnp.ndarray
    next_obs: jnp.ndarray
    dones: jnp.ndarray
    ptr: jnp.ndarray        # i32 write cursor
    size: jnp.ndarray       # i32 filled count


class DQNState(NamedTuple):
    params: dict
    target_params: dict
    opt_state: tuple
    replay: Replay
    env_states: EnvState    # batched [num_envs]
    obs: jnp.ndarray        # [num_envs, obs]
    epsilon: jnp.ndarray
    learn_steps: jnp.ndarray
    key: jnp.ndarray


class Hypers(NamedTuple):
    """The PBT-searchable hyperparameters as *array content*.

    Everything here is a traced scalar, not a Python constant baked into
    the compiled program: a population can then carry a [P] batch of
    these through ONE executable, and PBT explore steps rewrite them
    in place without triggering a recompile (rl/population.py).  The
    learning rate moves out of the optax chain for the same reason —
    `_learn` applies ``-learning_rate`` to `scale_by_adam` updates
    itself, which is bit-identical to `optax.adam` (adam ≡
    chain(scale_by_adam, scale(-lr)), and IEEE multiplication gives
    ``(-lr)·u == step_size·u`` exactly)."""

    learning_rate: jnp.ndarray   # f32
    gamma: jnp.ndarray           # f32 discount
    epsilon_decay: jnp.ndarray   # f32 per-env-step multiplier
    epsilon_min: jnp.ndarray     # f32 exploration floor
    target_sync_every: jnp.ndarray  # i32 learn-steps between target syncs


def hypers_from_config(cfg: DQNConfig) -> Hypers:
    return Hypers(
        learning_rate=jnp.asarray(cfg.learning_rate, jnp.float32),
        gamma=jnp.asarray(cfg.gamma, jnp.float32),
        epsilon_decay=jnp.asarray(cfg.epsilon_decay, jnp.float32),
        epsilon_min=jnp.asarray(cfg.epsilon_min, jnp.float32),
        target_sync_every=jnp.asarray(cfg.target_sync_every, jnp.int32),
    )


def _optimizer():
    # lr-free: `_learn` scales the updates by the traced Hypers lr
    return optax.scale_by_adam()


def dqn_init(key, env_params: EnvParams, cfg: DQNConfig) -> DQNState:
    k_net, k_env, key = jax.random.split(key, 3)
    net = QNetwork(cfg.hidden, cfg.n_actions)
    params = net.init(k_net, jnp.zeros((1, cfg.state_size)))
    cap = cfg.replay_capacity
    replay = Replay(
        obs=jnp.zeros((cap, cfg.state_size), jnp.float32),
        actions=jnp.zeros((cap,), jnp.int32),
        rewards=jnp.zeros((cap,), jnp.float32),
        next_obs=jnp.zeros((cap, cfg.state_size), jnp.float32),
        dones=jnp.zeros((cap,), jnp.bool_),
        ptr=jnp.asarray(0, jnp.int32),
        size=jnp.asarray(0, jnp.int32),
    )
    env_states, obs = jax.vmap(lambda k: env_reset(env_params, k))(
        jax.random.split(k_env, cfg.num_envs))
    # target_params must be a distinct buffer: train_iterations donates the
    # whole DQNState, and XLA rejects donating the same buffer twice
    return DQNState(params=params,
                    target_params=jax.tree.map(jnp.copy, params),
                    opt_state=_optimizer().init(params), replay=replay,
                    env_states=env_states, obs=obs,
                    epsilon=jnp.asarray(cfg.epsilon, jnp.float32),
                    learn_steps=jnp.asarray(0, jnp.int32), key=key)


def poisoned_members(state: DQNState, fitness=None) -> jnp.ndarray:
    """[P] poison mask over a population-batched DQNState: True where ANY
    float leaf of member *i*'s params or optimizer state — or its fitness,
    when given — carries a NaN/Inf.  The traced detector the population
    quarantine (rl/population.py) ORs into its sticky `quarantined` bit:
    pure reads over array content, so a trip never recompiles.  Works on
    any leading batch axis (the leaves' axis 0)."""
    leaves = jax.tree.leaves((state.params, state.opt_state))
    n = leaves[0].shape[0]
    bad = (jnp.zeros((n,), jnp.bool_) if fitness is None
           else ~jnp.isfinite(fitness))
    for leaf in leaves:
        if jnp.issubdtype(leaf.dtype, jnp.inexact):
            bad = bad | ~jnp.all(
                jnp.isfinite(leaf).reshape(leaf.shape[0], -1), axis=1)
    return bad


def act(key, params, obs, epsilon, cfg: DQNConfig):
    """ε-greedy batched action selection (`reinforcement_learning.py:292-318`)."""
    q = QNetwork(cfg.hidden, cfg.n_actions).apply(params, obs)
    greedy = jnp.argmax(q, axis=-1)
    k_eps, k_rand = jax.random.split(key)
    explore = jax.random.uniform(k_eps, greedy.shape) < epsilon
    random_a = jax.random.randint(k_rand, greedy.shape, 0, cfg.n_actions)
    return jnp.where(explore, random_a, greedy)


def _replay_add(rep: Replay, obs, actions, rewards, next_obs, dones) -> Replay:
    """Circular batched write of [n] transitions."""
    n = obs.shape[0]
    idx = (rep.ptr + jnp.arange(n)) % rep.obs.shape[0]
    return rep._replace(
        obs=rep.obs.at[idx].set(obs),
        actions=rep.actions.at[idx].set(actions),
        rewards=rep.rewards.at[idx].set(rewards),
        next_obs=rep.next_obs.at[idx].set(next_obs),
        dones=rep.dones.at[idx].set(dones),
        ptr=(rep.ptr + n) % rep.obs.shape[0],
        size=jnp.minimum(rep.size + n, rep.obs.shape[0]),
    )


def _learn(params, target_params, opt_state, rep: Replay, key,
           cfg: DQNConfig, hy: Hypers):
    """One Q-learning update on a sampled batch
    (`reinforcement_learning.py:335-419`)."""
    idx = jax.random.randint(key, (cfg.batch_size,), 0, jnp.maximum(rep.size, 1))
    net = QNetwork(cfg.hidden, cfg.n_actions)
    q_next = net.apply(target_params, rep.next_obs[idx])
    target = rep.rewards[idx] + hy.gamma * jnp.max(q_next, axis=-1) * (
        1.0 - rep.dones[idx].astype(jnp.float32))

    def loss_fn(p):
        q = net.apply(p, rep.obs[idx])
        q_sel = jnp.take_along_axis(q, rep.actions[idx][:, None], axis=-1)[:, 0]
        return jnp.mean((q_sel - target) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = _optimizer().update(grads, opt_state, params)
    updates = jax.tree.map(lambda u: -hy.learning_rate * u, updates)
    return optax.apply_updates(params, updates), opt_state, loss


def _iteration(env_params: EnvParams, state: DQNState, cfg: DQNConfig,
               hy: Hypers | None = None):
    """One iteration body: rollout_len vmapped env steps → replay writes
    → learn_steps_per_iter updates → target sync / ε decay.  Shared by the
    single-iteration jit, the multi-iteration scan below, and the vmapped
    population generation program (rl/population.py — which passes a
    per-member ``hy``; the single-agent paths use the config's values,
    traced from the same constants and therefore bit-identical)."""
    if hy is None:
        hy = hypers_from_config(cfg)

    def rollout_step(carry, _):
        env_states, obs, eps, key = carry
        key, k_act, k_step = jax.random.split(key, 3)
        actions = act(k_act, state.params, obs, eps, cfg)
        env_states2, obs2, rewards, dones = jax.vmap(
            lambda s, a: env_step(env_params, s, a))(env_states, actions)
        # auto-reset finished episodes
        reset_states, reset_obs = jax.vmap(lambda k: env_reset(env_params, k))(
            jax.random.split(k_step, cfg.num_envs))
        env_states3 = jax.tree.map(
            lambda a, b: jnp.where(
                dones.reshape(dones.shape + (1,) * (a.ndim - 1)), b, a),
            env_states2, reset_states)
        obs3 = jnp.where(dones[:, None], reset_obs, obs2)
        eps = jnp.maximum(eps * hy.epsilon_decay, hy.epsilon_min)
        return (env_states3, obs3, eps, key), (obs, actions, rewards, obs2, dones)

    key = state.key
    (env_states, obs, epsilon, key), traj = jax.lax.scan(
        rollout_step, (state.env_states, state.obs, state.epsilon, key),
        None, length=cfg.rollout_len)

    # [R, N, ...] → [R·N, ...]
    flat = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), traj)
    replay = _replay_add(state.replay, *flat)

    params, opt_state = state.params, state.opt_state
    losses = jnp.zeros((cfg.learn_steps_per_iter,))
    learn_steps = state.learn_steps
    target_params = state.target_params
    for i in range(cfg.learn_steps_per_iter):
        key, k_learn = jax.random.split(key)
        params, opt_state, loss = _learn(params, target_params, opt_state,
                                         replay, k_learn, cfg, hy)
        losses = losses.at[i].set(loss)
        learn_steps = learn_steps + 1
        sync = (learn_steps % hy.target_sync_every) == 0
        target_params = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t), target_params, params)

    new_state = DQNState(params=params, target_params=target_params,
                         opt_state=opt_state, replay=replay,
                         env_states=env_states, obs=obs, epsilon=epsilon,
                         learn_steps=learn_steps, key=key)
    metrics = {"loss": jnp.mean(losses), "epsilon": epsilon,
               "mean_reward": jnp.mean(flat[2]),
               "mean_balance": jnp.mean(env_states.balance)}
    return new_state, metrics


@functools.partial(jax.jit, static_argnames=("cfg",))
def train_iteration(env_params: EnvParams, state: DQNState, cfg: DQNConfig):
    """One compiled iteration (kept for callers that need per-iteration
    host control; the throughput path is `train_iterations`)."""
    return _iteration(env_params, state, cfg)


@functools.partial(jax.jit, static_argnames=("cfg", "n_iters"),
                   donate_argnums=(1,))
def _train_iterations_jit(env_params: EnvParams, state: DQNState,
                          cfg: DQNConfig, n_iters: int = 1):
    return jax.lax.scan(lambda st, _: _iteration(env_params, st, cfg),
                        state, None, length=n_iters)


def train_iterations(env_params: EnvParams, state: DQNState, cfg: DQNConfig,
                     n_iters: int = 1):
    """K iterations as ONE compiled `lax.scan` with the DQNState donated:
    params, replay ring, env states and opt state update in place, and the
    host reads metrics back once per K iterations instead of once per
    iteration — metrics readback no longer serializes the device queue.
    Returns (state, metrics) with each metric stacked to [n_iters].

    Host entry around the jitted scan: with the devprof observatory
    active (utils/devprof.py) the first call publishes a
    ``dqn_train_iterations`` cost card, verifies the DQNState donation
    actually freed the old buffers (replay ring + params — the largest
    donated tree in the repo), and every call feeds the ``train_step``
    SLO window (dispatch wall amortized per iteration)."""
    dp = devprof.active()
    if dp is None:
        return _train_iterations_jit(env_params, state, cfg, n_iters=n_iters)
    carding = not devprof.has_card("dqn_train_iterations")
    if carding:
        devprof.cost_card("dqn_train_iterations", _train_iterations_jit,
                          env_params, state, cfg, n_iters=n_iters)
    donated = jax.tree.leaves(state) if carding else None
    t0 = time.perf_counter()
    out = _train_iterations_jit(env_params, state, cfg, n_iters=n_iters)
    dp.observe_latency("train_step",
                       (time.perf_counter() - t0) / max(n_iters, 1))
    if donated is not None:
        devprof.verify_donation("dqn_train_iterations", donated)
    return out


def train_dqn(key, env_params: EnvParams, cfg: DQNConfig,
              iterations: int = 100, log_every: int = 0,
              iters_per_sync: int | None = None):
    """Host driver (`train`, `reinforcement_learning.py:421-503`): returns
    (final DQNState, history).

    Iterations run in chunks of ``iters_per_sync`` through the donated
    multi-iteration scan, with one metrics readback per chunk; history rows
    keep the old selection (every ``log_every``-th iteration plus the
    last).  The default chunk is the largest divisor of ``iterations`` not
    exceeding ``log_every`` (or 16): ``n_iters`` is a static argnum, so
    equal chunks mean the scan program compiles exactly once — a ragged
    remainder chunk would recompile the whole rollout+learn program just
    to run a few leftover iterations."""
    state = dqn_init(key, env_params, cfg)
    if iters_per_sync is None:
        cap = max(min(log_every if log_every else 16, iterations), 1)
        divisor = max(k for k in range(1, cap + 1) if iterations % k == 0)
        # a divisor-poor count (e.g. prime iterations) would degenerate to
        # per-iteration syncs — there, prefer full chunks plus one ragged
        # remainder (a second scan compile) over hundreds of host syncs
        iters_per_sync = divisor if divisor * 2 >= cap else cap
    history = []
    it0 = 0
    while it0 < iterations:
        k = min(max(iters_per_sync, 1), iterations - it0)
        state, m = train_iterations(env_params, state, cfg, n_iters=k)
        host = {name: np.asarray(v) for name, v in m.items()}  # one sync
        for j in range(k):
            it = it0 + j
            if it == iterations - 1 or (log_every and it % log_every == 0):
                history.append({name: float(v[j]) for name, v in host.items()}
                               | {"iter": it})
        it0 += k
    return state, history


@functools.partial(jax.jit, static_argnames=("cfg", "n_steps"))
def evaluate_policy(env_params: EnvParams, params, cfg: DQNConfig, key,
                    n_steps: int = 256):
    """Greedy-policy rollout (ε=0) over vmapped envs; returns mean final
    balance and reward trace."""
    states, obs = jax.vmap(lambda k: env_reset(env_params, k))(
        jax.random.split(key, cfg.num_envs))

    def step(carry, _):
        states, obs = carry
        actions = act(key, params, obs, jnp.asarray(0.0), cfg)
        states2, obs2, rewards, dones = jax.vmap(
            lambda s, a: env_step(env_params, s, a))(states, actions)
        return (states2, obs2), jnp.mean(rewards)

    (states, _), rewards = jax.lax.scan(step, (states, obs), None, length=n_steps)
    return {"mean_balance": jnp.mean(states.balance), "reward_trace": rewards}
