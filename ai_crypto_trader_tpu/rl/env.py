"""The vectorized trading environment — the env the reference never shipped.

`services/reinforcement_learning.py:421-503` trains a DQN against a
gym-style `env.reset()/env.step()` object, but **no environment class exists
anywhere in the reference repo** (SURVEY §2.3) — the env is implicit.  This
module supplies it as a pure functional environment over precomputed market
feature arrays, designed for massive vmap: thousands of independent episodes
(different start offsets) step in lock-step on one TPU core, Anakin/Podracer
style (PAPERS.md: "Podracer architectures for scalable RL").  The feature
tables may carry a leading scenario axis ([S, T], built by
`sim/engine.scenario_env_params` from adversarial generated markets): each
reset then draws a (scenario, offset) pair, so training data is scenario-
diverse, not one replayed history.

Action space mirrors the reference agent (BUY=0 / HOLD=1 / SELL=2,
`reinforcement_learning.py:292-318`); long-only single position; reward =
per-step change in mark-to-market equity (as a fraction of balance), which
sums to total return over an episode.

Observation (state_size=10, matching RLParams.state_size /
`reinforcement_learning.py:33-40`):
  [rsi/100, stoch_k/100, macd(clipped), williams_r/-100, bb_position,
   volatility, 1-step return, 5-step return, in_position, unrealized_pnl%]
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

BUY, HOLD, SELL = 0, 1, 2
OBS_SIZE = 10


class EnvParams(NamedTuple):
    close: jnp.ndarray       # [T], or [S, T] for a scenario-diverse env
    obs_table: jnp.ndarray   # [(S,) T, OBS_SIZE-2] market features (position
                             # features are appended dynamically)
    episode_len: int
    fee_rate: jnp.ndarray    # taker fee fraction per side
    # Per-candle execution cost fraction per side on top of the flat fee —
    # a scalar 0.0 (the frictionless default), or a [T] / [S, T] table.
    # The LOB path (`sim/engine.scenario_env_params(dynamics="lob")`)
    # threads the simulated half-spread here, so crossing a blown-out
    # book costs the agent exactly what the book says it should.
    trade_cost: jnp.ndarray = 0.0


class EnvState(NamedTuple):
    t: jnp.ndarray           # absolute candle index
    start: jnp.ndarray
    in_pos: jnp.ndarray      # bool
    entry: jnp.ndarray
    balance: jnp.ndarray     # equity in quote units (starts at 1.0)
    scen: jnp.ndarray        # scenario row (0 on a single-path env)


def obs_size(p: EnvParams) -> int:
    """This env's observation width: the market-feature table plus the
    two dynamic position features.  `OBS_SIZE` (10) is the default-table
    constant; envs carrying extra book-state features (the
    `sim/engine.scenario_env_params(dynamics="lob")` path) are wider —
    size DQN nets with this, not the constant."""
    return int(p.obs_table.shape[-1]) + 2


def assert_transfer_compatible(old: EnvParams, new: EnvParams) -> None:
    """Raise unless swapping ``old`` for ``new`` under a compiled program
    is a pure TRANSFER — identical pytree structure, leaf shapes, dtypes
    and static fields.  The rolling-recalibration contract
    (rl/trainer_service.py): re-fitted FlowParams regenerate the feature
    tables' VALUES, so a swap that would change a shape (and silently
    recompile every program the env threads through) is a bug upstream,
    not a recalibration."""
    if int(old.episode_len) != int(new.episode_len):
        raise ValueError(
            f"env episode_len changed {old.episode_len} -> "
            f"{new.episode_len}: a recalibrated env must be shape-stable")
    o_l, n_l = jax.tree.leaves(old), jax.tree.leaves(new)
    if len(o_l) != len(n_l):
        raise ValueError("env pytree structure changed under recalibration")
    for a, b in zip(o_l, n_l):
        a, b = jnp.asarray(a), jnp.asarray(b)
        if a.shape != b.shape or a.dtype != b.dtype:
            raise ValueError(
                f"env leaf changed {a.shape}/{a.dtype} -> "
                f"{b.shape}/{b.dtype}: a recalibration is a transfer, "
                f"never a recompile")


def make_env_params(ind: dict, episode_len: int = 256,
                    fee_rate: float = 0.0,
                    extra_features=None, trade_cost=None) -> EnvParams:
    """Build the feature table from a compute_indicators() dict.

    ``ind`` arrays may carry a leading scenario axis ([S, T] — the
    `sim/engine.scenario_env_params` path): the env then samples a
    scenario per episode on reset, so vmapped training sees S different
    adversarial markets instead of one replayed history.

    ``extra_features`` ([(S,) T, E]) appends E market columns to the
    table — the LOB's book-state features (spread, top-of-book depth)
    ride here; `_observe` concatenates whatever width the table has, so
    the program shape follows the table and nothing else changes.

    ``trade_cost`` (scalar or [(S,) T]) adds a per-candle execution-cost
    fraction per side on open/close — half-spread from the LOB path —
    on top of the flat ``fee_rate``.  None keeps the frictionless
    default (cost 0.0, a program-identical no-op)."""
    close = ind["close"]
    ret1 = jnp.diff(close, prepend=close[..., :1], axis=-1) / close
    prev5 = jnp.roll(close, 5, axis=-1)
    ret5 = (close - prev5) / prev5
    ret5 = ret5.at[..., :5].set(0.0)
    obs = jnp.stack([
        ind["rsi"] / 100.0,
        ind["stoch_k"] / 100.0,
        jnp.clip(ind["macd"] / close * 100.0, -1.0, 1.0),
        ind["williams_r"] / -100.0,
        ind["bb_position"],
        ind["atr"] / close,
        jnp.clip(ret1 * 100.0, -1.0, 1.0),
        jnp.clip(ret5 * 100.0, -1.0, 1.0),
    ], axis=-1)
    if extra_features is not None:
        obs = jnp.concatenate([obs, jnp.asarray(extra_features)], axis=-1)
    return EnvParams(close=close, obs_table=obs.astype(jnp.float32),
                     episode_len=episode_len,
                     fee_rate=jnp.asarray(fee_rate, jnp.float32),
                     trade_cost=(0.0 if trade_cost is None
                                 else jnp.asarray(trade_cost, jnp.float32)))


def _lane(p: EnvParams, s: EnvState):
    """This episode's [T] close / [T, F] obs slices — the scenario row when
    the params are batched, the whole table otherwise (ndim is static
    under jit, so single-path envs compile to exactly the old program)."""
    if p.close.ndim == 2:
        return p.close[s.scen], p.obs_table[s.scen]
    return p.close, p.obs_table


def _observe(p: EnvParams, s: EnvState) -> jnp.ndarray:
    close, obs_table = _lane(p, s)
    market = obs_table[s.t]
    unreal = jnp.where(s.in_pos, (close[s.t] - s.entry) / s.entry, 0.0)
    return jnp.concatenate([
        market,
        jnp.stack([s.in_pos.astype(jnp.float32), unreal * 100.0]),
    ])


@functools.partial(jax.jit, static_argnames=())
def env_reset(p: EnvParams, key) -> tuple[EnvState, jnp.ndarray]:
    """Random start offset so vmapped episodes decorrelate; on a
    scenario-batched env a random scenario row is drawn too."""
    T = p.close.shape[-1]
    if p.close.ndim == 2:
        k_scen, key = jax.random.split(key)
        scen = jax.random.randint(k_scen, (), 0, p.close.shape[0])
    else:
        scen = jnp.asarray(0, jnp.int32)
    start = jax.random.randint(key, (), 0, jnp.maximum(T - p.episode_len - 1, 1))
    s = EnvState(t=start, start=start, in_pos=jnp.asarray(False),
                 entry=jnp.asarray(0.0, jnp.float32),
                 balance=jnp.asarray(1.0, jnp.float32), scen=scen)
    return s, _observe(p, s)


@jax.jit
def env_step(p: EnvParams, s: EnvState, action) -> tuple[EnvState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(state, action) → (state', obs', reward, done). Pure; vmap over the
    leading axis of states for parallel envs."""
    close, _ = _lane(p, s)
    price = close[s.t]
    next_t = s.t + 1
    next_price = close[next_t]

    open_now = (action == BUY) & ~s.in_pos
    close_now = (action == SELL) & s.in_pos

    entry = jnp.where(open_now, price, s.entry)
    in_pos = (s.in_pos | open_now) & ~close_now

    # Mark-to-market equity delta over the candle t → t+1 (a SELL exits at
    # this candle's price, so no further exposure; per-step deltas already
    # sum to the trade's total return — no realized lump on close, or the
    # pnl would be double-counted). Fees charged on open/close.
    exposure = in_pos.astype(jnp.float32)
    price_ret = (next_price - price) / price
    # Per-side cost: flat fee plus this candle's execution cost (the LOB
    # half-spread when the table is populated; scalar 0.0 otherwise, which
    # compiles to the frictionless program — ndim is static under jit).
    cost = p.trade_cost
    if getattr(cost, "ndim", 0):
        cost = (cost[s.scen] if cost.ndim == 2 else cost)[s.t]
    fees = (open_now.astype(jnp.float32) + close_now.astype(jnp.float32)) * (
        p.fee_rate + cost)
    reward = exposure * price_ret - fees

    balance = s.balance * (1.0 + reward)
    # Terminal: episode budget exhausted OR end of data (without the latter,
    # an episode longer than the series would run forever on a clamped index).
    done = ((next_t - s.start) >= p.episode_len) | (next_t >= p.close.shape[-1] - 1)

    s2 = EnvState(t=next_t, start=s.start, in_pos=in_pos,
                  entry=entry, balance=balance, scen=s.scen)
    return s2, _observe(p, s2), reward, done
