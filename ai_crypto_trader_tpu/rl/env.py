"""The vectorized trading environment — the env the reference never shipped.

`services/reinforcement_learning.py:421-503` trains a DQN against a
gym-style `env.reset()/env.step()` object, but **no environment class exists
anywhere in the reference repo** (SURVEY §2.3) — the env is implicit.  This
module supplies it as a pure functional environment over precomputed market
feature arrays, designed for massive vmap: thousands of independent episodes
(different start offsets) step in lock-step on one TPU core, Anakin/Podracer
style (PAPERS.md: "Podracer architectures for scalable RL").

Action space mirrors the reference agent (BUY=0 / HOLD=1 / SELL=2,
`reinforcement_learning.py:292-318`); long-only single position; reward =
per-step change in mark-to-market equity (as a fraction of balance), which
sums to total return over an episode.

Observation (state_size=10, matching RLParams.state_size /
`reinforcement_learning.py:33-40`):
  [rsi/100, stoch_k/100, macd(clipped), williams_r/-100, bb_position,
   volatility, 1-step return, 5-step return, in_position, unrealized_pnl%]
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

BUY, HOLD, SELL = 0, 1, 2
OBS_SIZE = 10


class EnvParams(NamedTuple):
    close: jnp.ndarray       # [T]
    obs_table: jnp.ndarray   # [T, OBS_SIZE-2] market features (position
                             # features are appended dynamically)
    episode_len: int
    fee_rate: jnp.ndarray    # taker fee fraction per side


class EnvState(NamedTuple):
    t: jnp.ndarray           # absolute candle index
    start: jnp.ndarray
    in_pos: jnp.ndarray      # bool
    entry: jnp.ndarray
    balance: jnp.ndarray     # equity in quote units (starts at 1.0)


def make_env_params(ind: dict, episode_len: int = 256,
                    fee_rate: float = 0.0) -> EnvParams:
    """Build the feature table from a compute_indicators() dict."""
    close = ind["close"]
    ret1 = jnp.diff(close, prepend=close[:1]) / close
    ret5 = (close - jnp.roll(close, 5)) / jnp.roll(close, 5)
    ret5 = ret5.at[:5].set(0.0) if hasattr(ret5, "at") else ret5
    obs = jnp.stack([
        ind["rsi"] / 100.0,
        ind["stoch_k"] / 100.0,
        jnp.clip(ind["macd"] / close * 100.0, -1.0, 1.0),
        ind["williams_r"] / -100.0,
        ind["bb_position"],
        ind["atr"] / close,
        jnp.clip(ret1 * 100.0, -1.0, 1.0),
        jnp.clip(ret5 * 100.0, -1.0, 1.0),
    ], axis=-1)
    return EnvParams(close=close, obs_table=obs.astype(jnp.float32),
                     episode_len=episode_len,
                     fee_rate=jnp.asarray(fee_rate, jnp.float32))


def _observe(p: EnvParams, s: EnvState) -> jnp.ndarray:
    market = p.obs_table[s.t]
    unreal = jnp.where(s.in_pos, (p.close[s.t] - s.entry) / s.entry, 0.0)
    return jnp.concatenate([
        market,
        jnp.stack([s.in_pos.astype(jnp.float32), unreal * 100.0]),
    ])


@functools.partial(jax.jit, static_argnames=())
def env_reset(p: EnvParams, key) -> tuple[EnvState, jnp.ndarray]:
    """Random start offset so vmapped episodes decorrelate."""
    T = p.close.shape[0]
    start = jax.random.randint(key, (), 0, jnp.maximum(T - p.episode_len - 1, 1))
    s = EnvState(t=start, start=start, in_pos=jnp.asarray(False),
                 entry=jnp.asarray(0.0, jnp.float32),
                 balance=jnp.asarray(1.0, jnp.float32))
    return s, _observe(p, s)


@jax.jit
def env_step(p: EnvParams, s: EnvState, action) -> tuple[EnvState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(state, action) → (state', obs', reward, done). Pure; vmap over the
    leading axis of states for parallel envs."""
    price = p.close[s.t]
    next_t = s.t + 1
    next_price = p.close[next_t]

    open_now = (action == BUY) & ~s.in_pos
    close_now = (action == SELL) & s.in_pos

    entry = jnp.where(open_now, price, s.entry)
    in_pos = (s.in_pos | open_now) & ~close_now

    # Mark-to-market equity delta over the candle t → t+1 (a SELL exits at
    # this candle's price, so no further exposure; per-step deltas already
    # sum to the trade's total return — no realized lump on close, or the
    # pnl would be double-counted). Fees charged on open/close.
    exposure = in_pos.astype(jnp.float32)
    price_ret = (next_price - price) / price
    fees = (open_now.astype(jnp.float32) + close_now.astype(jnp.float32)) * p.fee_rate
    reward = exposure * price_ret - fees

    balance = s.balance * (1.0 + reward)
    # Terminal: episode budget exhausted OR end of data (without the latter,
    # an episode longer than the series would run forever on a clamped index).
    done = ((next_t - s.start) >= p.episode_len) | (next_t >= p.close.shape[0] - 1)

    s2 = EnvState(t=next_t, start=s.start, in_pos=in_pos,
                  entry=entry, balance=balance)
    return s2, _observe(p, s2), reward, done
