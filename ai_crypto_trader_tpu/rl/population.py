"""Population-based RL: a vmapped DQN fleet trained inside the LOB
simulator, with PBT exploit/explore between generations.

The single-agent trainer (rl/dqn.py) already scans K iterations per host
round-trip; this module lifts the WHOLE training state — params, target
params, optimizer state, replay ring, env states, ε, PRNG key — over a
leading [P] population axis (Anakin/Podracer, arXiv 2104.06272) and adds
the population-based-training loop of arXiv 2206.08888 (Fast PBT):

  * one **generation** = every member trains ``iters_per_generation``
    iterations and is then evaluated greedily, all as ONE compiled
    program routed through ``Partitioner.population_eval`` with the
    population tree donated — so the fleet shards over the mesh exactly
    like the GA population, pad/mask layout cards included;
  * between generations the **exchange** step (a second small donated
    program) truncation-selects: bottom-quantile members copy a random
    top-quantile member's params/opt-state/replay and perturb their
    hyperparameters — learning rate, γ, ε schedule, target-sync period —
    as *array content* (rl/dqn.py `Hypers`), never as a recompile;
  * the host reads back ONE pytree per generation (fitness + lineage +
    hypers), the same one-sync contract as `evolve/ga.run_ga`.

At P=1 the exploit bracket is empty (`evolve/selection.quantile_split`),
the exchange is a structural no-op, and G generations of
``iters_per_generation`` iterations are bit-identical to
``train_iterations(n_iters=G·iters)`` on the same PRNGKey — the parity
oracle tests/test_population.py pins.

The winning member closes the loop operationally: `adopt_winner`
registers it in the model registry and runs it through the scorecard
adoption gate (obs/scorecard.py, offline-score overrides) before it may
go active — a fresh policy that is measurably worse than the incumbent
on the same simulated markets lands as shadow, not live.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ai_crypto_trader_tpu.evolve.selection import quantile_split
from ai_crypto_trader_tpu.obs import tickpath
from ai_crypto_trader_tpu.parallel.partitioner import (
    Partitioner,
    SingleDevicePartitioner,
)
from ai_crypto_trader_tpu.rl.dqn import (
    DQNConfig,
    DQNState,
    Hypers,
    QNetwork,
    _iteration,
    dqn_init,
    hypers_from_config,
    poisoned_members,
)
from ai_crypto_trader_tpu.rl.env import EnvParams, env_reset, env_step
from ai_crypto_trader_tpu.utils import devprof, meshprof

# Shared default so every train_pbt call without a partitioner keys the
# program caches onto one entry (the evolve/ga.py pattern).
_SINGLE = SingleDevicePartitioner()

# fold_in salt deriving each member's greedy-eval key from its training
# key WITHOUT consuming it (consuming would break the P=1 parity oracle:
# the single-agent trainer never evaluates mid-run)
_EVAL_SALT = 0x5EED


@jax.jit
def _owned_copy(tree):
    """Re-home every leaf into an executable-owned device buffer.

    Inputs are NOT donated, so the runtime can never alias an output to
    a caller buffer — the outputs are fresh allocations the executable
    owns.  This is the safety valve between host-backed arrays (numpy
    views from checkpoint unpack, chaos-edited members) and the donating
    fleet programs downstream."""
    return jax.tree.map(jnp.copy, tree)


class PBTConfig(NamedTuple):
    """Static population/PBT knobs (hashable — program-cache key)."""

    population: int = 16
    generations: int = 4
    iters_per_generation: int = 8   # train iterations per member per gen
    eval_steps: int = 128           # greedy-eval rollout length
    exploit_frac: float = 0.25      # bottom/top truncation quantile
    perturb_scale: float = 1.2      # multiplicative hyperparam jitter
    lr_bounds: tuple = (1e-5, 1e-1)
    gamma_bounds: tuple = (0.90, 0.999)
    eps_decay_bounds: tuple = (0.9, 0.99999)
    eps_min_bounds: tuple = (1e-3, 0.2)
    sync_bounds: tuple = (2, 1000)  # target_sync_every clip (learn steps)
    # exchanges a tripped member stays frozen (masked out of ranking AND
    # selection) before the forced-exploit heal clones a survivor over it
    quarantine_cooldown: int = 1


class PopState(NamedTuple):
    """The device-resident fleet: every leaf leads with the [P] axis.

    ``quarantined``/``cooldown`` are the member-containment bits (the
    ops/tenant_engine.py lane pattern on the training axis): ARRAY
    CONTENT carried in the donated state, so a trip, a cooldown tick and
    a heal move values — never shapes — and the executable that trained
    a healthy fleet trains a poisoned one (the meshprof sentinel pins
    it).  A quarantined member keeps training (its NaNs stay its own —
    the vmap lanes are independent) but is masked out of fitness ranking
    and exchange selection until the forced-exploit heal replaces it."""

    members: DQNState   # each field stacked [P, ...]
    hypers: Hypers      # each field [P]
    quarantined: jnp.ndarray   # [P] bool — sticky poison bit
    cooldown: jnp.ndarray      # [P] i32 — exchanges left before heal


class PBTResult(NamedTuple):
    state: PopState          # final fleet (device arrays)
    fitness: np.ndarray      # [P] final-generation fitness (host)
    best_member: int         # argmax over HEALTHY members
    history: list            # one dict per generation
    cfg: DQNConfig
    pcfg: PBTConfig
    quarantined: np.ndarray | None = None  # [P] final quarantine bits


def host_read(tree):
    """THE per-generation device→host sync (the evolve/ga.py seam —
    module-level so tests wrap it with a counting double and assert ONE
    sync per generation)."""
    t0 = time.perf_counter()
    with meshprof.allow_transfers():
        out = jax.device_get(tree)
    devprof.observe_latency("host_read", time.perf_counter() - t0)
    return out


@functools.partial(jax.jit, static_argnames=("cfg", "n"))
def _pop_init_jit(key, env_params: EnvParams, cfg: DQNConfig, n: int):
    member_keys = jax.random.split(key, n)
    members = jax.vmap(lambda k: dqn_init(k, env_params, cfg))(member_keys)
    hypers = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n,) + x.shape),
        hypers_from_config(cfg))
    return PopState(members=members, hypers=hypers,
                    quarantined=jnp.zeros((n,), jnp.bool_),
                    cooldown=jnp.zeros((n,), jnp.int32))


def pop_init(key, env_params: EnvParams, cfg: DQNConfig,
             pcfg: PBTConfig) -> PopState:
    """Initialize the fleet: member *i*'s state is bit-identical to
    ``dqn_init(jax.random.split(key, P)[i], ...)`` — at P=1 that is the
    exact key stream the parity oracle's single agent consumes.  Hypers
    start at the config's values for every member; diversity comes from
    the explore step, not the init (Fast PBT §3 does the same)."""
    return _pop_init_jit(key, env_params, cfg, pcfg.population)


def _eval_member(env_params: EnvParams, params, cfg: DQNConfig, key,
                 n_steps: int):
    """Greedy-policy fitness: mean final equity over ``cfg.num_envs``
    fresh episodes after ``n_steps`` steps (the LOB env charges spread
    crossings, so blown-out books show up here, not just in the obs)."""
    states, obs = jax.vmap(lambda k: env_reset(env_params, k))(
        jax.random.split(key, cfg.num_envs))
    net = QNetwork(cfg.hidden, cfg.n_actions)

    def step(carry, _):
        states, obs = carry
        actions = jnp.argmax(net.apply(params, obs), axis=-1)
        states2, obs2, _, _ = jax.vmap(
            lambda s, a: env_step(env_params, s, a))(states, actions)
        return (states2, obs2), None

    (states, _), _ = lax.scan(step, (states, obs), None, length=n_steps)
    return jnp.mean(states.balance)


@functools.lru_cache(maxsize=2)
def _pbt_program(cfg: DQNConfig, pcfg: PBTConfig, partitioner: Partitioner):
    """THE per-generation compiled program: every member scans
    ``iters_per_generation`` training iterations (its own traced hypers)
    then evaluates greedily — vmapped over [P], sharded over the mesh by
    the partitioner, population tree donated.

    Cache key is (cfg, pcfg-sans-generations, partitioner) — see
    `_program_pcfg`; generation count is host-loop business, so runs
    that differ only in length reuse the same executable."""

    def member_generation(member: DQNState, hy: Hypers,
                          env_params: EnvParams):
        def it(st, _):
            st, metrics = _iteration(env_params, st, cfg, hy)
            return st, metrics

        member, metrics = lax.scan(it, member, None,
                                   length=pcfg.iters_per_generation)
        fitness = _eval_member(
            env_params, member.params, cfg,
            jax.random.fold_in(member.key, _EVAL_SALT), pcfg.eval_steps)
        return member, fitness, {
            "loss": jnp.mean(metrics["loss"]),
            "mean_reward": jnp.mean(metrics["mean_reward"]),
            "epsilon": member.epsilon,
        }

    def generation(pop: PopState, env_params: EnvParams):
        members, fitness, met = jax.vmap(
            member_generation, in_axes=(0, 0, None))(
                pop.members, pop.hypers, env_params)
        # in-program member containment (the tenant-engine lane pattern
        # on the [P] axis): a NaN/Inf anywhere in a member's params /
        # opt state / fitness ORs into its sticky quarantine bit, with
        # an edge-armed cooldown — all array content, zero recompiles
        poisoned = poisoned_members(members, fitness)
        newly = poisoned & ~pop.quarantined
        quarantined = pop.quarantined | poisoned
        cooldown = jnp.where(newly, pcfg.quarantine_cooldown, pop.cooldown)
        met = dict(met, tripped_new=newly)
        return PopState(members=members, hypers=pop.hypers,
                        quarantined=quarantined,
                        cooldown=cooldown), fitness, met

    return partitioner.population_eval(generation, name="pbt_generation",
                                       donate_pop=True)


@functools.lru_cache(maxsize=2)
def _exchange_program(cfg: DQNConfig, pcfg: PBTConfig):
    """The between-generations PBT step as ONE donated program:
    truncation-select (bottom ``exploit_frac`` copies a random top-
    ``exploit_frac`` member's full training state), then perturb the
    copies' hyperparameters in place.  Everything is array content —
    fitness values move, the executable never recompiles.

    Returns ``(members', hypers', quarantined', cooldown', lineage)``
    where ``lineage[i]`` is the member *i* copied from (its own index if
    it survived).  When the bracket is empty (P·frac < 1, notably P=1)
    the exchange is a structural no-op and the donated buffers pass
    straight through — the parity oracle's contract.

    Quarantine semantics (all array content — no recompiles):

      * a quarantined member's fitness is masked to ``-inf`` for DONOR
        ranking — a poisoned fleet member can never be cloned from;
      * while its cooldown runs it is also masked OUT of the exploit
        bracket (``+inf`` for bottom ranking): frozen, neither donor nor
        clone, so healthy members see exactly the exchange they would
        have seen had the slot been mid-pack;
      * once the cooldown expires it ranks ``-inf`` for the bottom
        bracket — the forced exploit — and the clone that overwrites it
        IS the heal (PBT's own repair path: survivor state + fold_in
        key fork + freshly perturbed hypers), clearing the bit."""
    n = int(pcfg.population * pcfg.exploit_frac)

    def _jitter(key, shape):
        """×s or ×1/s, coin-flipped per member (Fast PBT's explore)."""
        up = jax.random.bernoulli(key, 0.5, shape)
        return jnp.where(up, pcfg.perturb_scale, 1.0 / pcfg.perturb_scale)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def exchange(members: DQNState, hypers: Hypers, quarantined, cooldown,
                 fitness, key):
        P = fitness.shape[0]
        lineage = jnp.arange(P, dtype=jnp.int32)
        heal_ready = quarantined & (cooldown <= 0)
        frozen = quarantined & ~heal_ready
        cooldown = jnp.maximum(cooldown - frozen.astype(jnp.int32), 0)
        if n == 0:
            return members, hypers, quarantined, cooldown, lineage

        neg = jnp.asarray(-jnp.inf, fitness.dtype)
        # two ranking views of the same fitness (identical bitwise when
        # nothing is quarantined — the P=1/parity oracle's contract):
        # donors never poisoned, frozen slots never exploited, heal-ready
        # slots forced into the exploit bracket
        fit_top = jnp.where(quarantined, neg, fitness)
        fit_bottom = jnp.where(frozen, -neg,
                               jnp.where(heal_ready, neg, fitness))
        bottom, _, _ = quantile_split(fit_bottom, pcfg.exploit_frac)
        _, top, _ = quantile_split(fit_top, pcfg.exploit_frac)
        k_donor, k_jit = jax.random.split(key)
        donors = top[jax.random.randint(k_donor, (n,), 0, n)]
        lineage = lineage.at[bottom].set(donors)
        cloned = lineage != jnp.arange(P)
        healed = cloned & heal_ready
        quarantined = quarantined & ~healed
        cooldown = jnp.where(healed, 0, cooldown)

        # exploit: clones gather the donor's ENTIRE training state —
        # params, target, opt state, replay ring, env states, ε
        members = jax.tree.map(lambda x: x[lineage], members)
        # …except the PRNG key: a clone sharing its donor's stream would
        # explore in lock-step with it forever.  fold_in re-derives a
        # fresh per-slot stream for clones; survivors' keys are untouched
        # (bitwise — the parity contract again).
        forked = jax.vmap(jax.random.fold_in)(members.key, lineage)
        members = members._replace(
            key=jnp.where(cloned[:, None], forked, members.key))

        # explore: clones perturb the donor's hypers multiplicatively,
        # clipped to the search box; survivors keep theirs bitwise
        hy = jax.tree.map(lambda x: x[lineage], hypers)
        ks = jax.random.split(k_jit, 5)
        pert = Hypers(
            learning_rate=jnp.clip(
                hy.learning_rate * _jitter(ks[0], (P,)), *pcfg.lr_bounds),
            gamma=jnp.clip(
                hy.gamma * _jitter(ks[1], (P,)), *pcfg.gamma_bounds),
            # ε decay lives just under 1.0: perturb its distance to 1 so
            # the jitter changes the *half-life*, not the digit dust
            epsilon_decay=jnp.clip(
                1.0 - (1.0 - hy.epsilon_decay) * _jitter(ks[2], (P,)),
                *pcfg.eps_decay_bounds),
            epsilon_min=jnp.clip(
                hy.epsilon_min * _jitter(ks[3], (P,)), *pcfg.eps_min_bounds),
            target_sync_every=jnp.clip(
                jnp.round(hy.target_sync_every * _jitter(ks[4], (P,)))
                .astype(jnp.int32), *pcfg.sync_bounds),
        )
        hypers = jax.tree.map(
            lambda p, o: jnp.where(
                cloned.reshape((P,) + (1,) * (p.ndim - 1)), p, o), pert, hy)
        return members, hypers, quarantined, cooldown, lineage

    return exchange


def _program_pcfg(pcfg: PBTConfig) -> PBTConfig:
    """Program-cache key: the compiled programs don't depend on the
    generation count, so normalize it out — a 1-generation warmup run
    and a 20-generation timed run share one executable."""
    return pcfg._replace(generations=0)


def train_pbt(key, env_params: EnvParams, cfg: DQNConfig, pcfg: PBTConfig,
              partitioner: Partitioner | None = None, *,
              init_pop: PopState | None = None, start_generation: int = 0,
              on_generation=None) -> PBTResult:
    """Host driver: G generations of [train+eval → exchange], ONE
    host_read per generation.

    Per generation the device runs exactly two dispatches — the sharded
    generation program (population donated) and the small exchange
    program — inside a meshprof watch window, so a steady-state
    recompile or an unsanctioned device→host transfer pages exactly
    like the GA's would.  The first generation publishes the
    ``pbt_generation`` devprof cost card and verifies the donation
    actually freed the old fleet buffers.

    ``init_pop``/``start_generation`` are the RESUME seam (the trainer
    service + ``cli rl --resume``): hand back a restored fleet and the
    absolute generation counter it stopped at and the run continues on
    the exact key stream an uninterrupted run would have used — the
    exchange key is ``fold_in(key, g+1)`` with g ABSOLUTE, so a resumed
    run is bit-identical to one that never died.  ``on_generation(g,
    pop, row)`` fires after each generation's host_read (checkpoint
    cadences hook here; a host callback, never a recompile)."""
    partitioner = partitioner if partitioner is not None else _SINGLE
    if init_pop is not None:
        # A handed-in fleet may sit on HOST-backed buffers (checkpoint
        # unpack → numpy, chaos poisoning via numpy) that the CPU runtime
        # zero-copy aliases when alignment allows.  The generation program
        # DONATES the population; donating an aliased buffer lets XLA
        # scribble on — then free — memory it never owned, which surfaces
        # as glibc heap corruption ticks later, not as an exception.  One
        # non-donating jitted copy re-homes every leaf into
        # executable-owned device buffers before anything donates them.
        pop = _owned_copy(init_pop)
    else:
        pop = pop_init(key, env_params, cfg, pcfg)
    if pcfg.population % partitioner.device_count == 0:
        pop = partitioner.shard_population(pop)

    prog_pcfg = _program_pcfg(pcfg)
    misses_before = _pbt_program.cache_info().misses
    program = _pbt_program(cfg, prog_pcfg, partitioner)
    cold = _pbt_program.cache_info().misses > misses_before
    exchange = _exchange_program(cfg, prog_pcfg)

    prof = devprof.active()
    if prof is not None and not devprof.has_card("pbt_generation"):
        devprof.cost_card("pbt_generation", program, pop, env_params,
                          _memory_analysis=False)

    history = []
    host = None
    first = True
    for g in range(start_generation, start_generation + pcfg.generations):
        gcold = cold and first
        donated = jax.tree.leaves(pop) if (prof is not None and first) \
            else None
        first = False
        t0 = time.perf_counter()
        with tickpath.coldstart("pbt_generation", cold=gcold), \
                meshprof.watch("pbt_generation", cold=gcold):
            pop, fitness, met = program(pop, env_params)
            members, hypers, quarantined, cooldown, lineage = exchange(
                pop.members, pop.hypers, pop.quarantined, pop.cooldown,
                fitness, jax.random.fold_in(key, g + 1))
            if donated is not None:
                devprof.verify_donation("pbt_generation", donated)
            # tripped bits survive the exchange un-donated (args 2/3),
            # so the heal edge rides the SAME one host_read
            pre_q = pop.quarantined
            pop = PopState(members=members, hypers=hypers,
                           quarantined=quarantined, cooldown=cooldown)
            host = host_read({"fitness": fitness, "lineage": lineage,
                              "hypers": hypers._asdict(), "metrics": met,
                              "pre_quarantined": pre_q,
                              "quarantined": quarantined,
                              "cooldown": cooldown})
        if prof is not None:
            prof.observe_latency("pbt_generation", time.perf_counter() - t0)
        fin = np.asarray(host["fitness"])
        q = np.asarray(host["quarantined"])
        pre_q_h = np.asarray(host["pre_quarantined"])
        healthy = ~pre_q_h
        # a quarantined member's NaN fitness must never poison the
        # fleet-level stats — rank over healthy members only
        row = {
            "generation": g,
            "best_fitness": float(fin[healthy].max()) if healthy.any()
            else float("nan"),
            "mean_fitness": float(fin[healthy].mean()) if healthy.any()
            else float("nan"),
            "n_exploited": int(
                (host["lineage"] != np.arange(pcfg.population)).sum()),
            "fitness": fin.tolist(),
            "lineage": host["lineage"].tolist(),
            "hypers": {k: np.asarray(v).tolist()
                       for k, v in host["hypers"].items()},
            "loss": float(host["metrics"]["loss"].mean()),
            "mean_reward": float(host["metrics"]["mean_reward"].mean()),
            "quarantined": q.tolist(),
            "n_quarantined": int(q.sum()),
            "n_tripped": int(
                np.asarray(host["metrics"]["tripped_new"]).sum()),
            "n_healed": int((pre_q_h & ~q).sum()),
        }
        history.append(row)
        if on_generation is not None:
            on_generation(g, pop, row)

    fitness = np.asarray(host["fitness"])
    q = np.asarray(host["quarantined"])
    ranked = np.where(q, -np.inf, fitness)
    return PBTResult(state=pop, fitness=fitness,
                     best_member=int(np.argmax(ranked)),
                     history=history, cfg=cfg, pcfg=pcfg, quarantined=q)


def best_params(result: PBTResult):
    """The winning member's Q-network params (device tree)."""
    return jax.tree.map(lambda x: x[result.best_member],
                        result.state.members.params)


def adopt_winner(result: PBTResult, registry, scorecard=None, *,
                 kind: str = "rl_policy", symbol: str = "SIM",
                 interval: str = "pbt",
                 checkpoint_path: str | None = None) -> dict:
    """Close the loop: register the winning policy and run it through
    the scorecard adoption gate before it may go active.

    The gate compares SIMULATOR fitness (the score overrides added in
    obs/scorecard.py) between the candidate and the registry's best
    incumbent of the same ``kind`` — the models/service.py `_run_hpo`
    precedent: gate → register → performance → active/shadow.  A
    candidate worse than the incumbent on the same simulated markets is
    registered as shadow, never hot-swapped."""
    best = result.best_member
    hy = {k: float(np.asarray(v)[best])
          for k, v in result.state.hypers._asdict().items()}
    fitness = float(result.fitness[best])

    incumbent = registry.best(kind, metric="fitness")
    allowed, reason = True, "no_scorecard"
    if scorecard is not None:
        allowed, reason = scorecard.adoption_gate(
            "dqn_pbt:candidate",
            incumbent["version"] if incumbent else "dqn_pbt:none",
            symbol, interval,
            candidate_score=fitness,
            incumbent_score=(incumbent or {}).get(
                "performance", {}).get("fitness"))

    payload = dict(hy, arch="dqn_pbt", state_size=result.cfg.state_size,
                   hidden=str(result.cfg.hidden), fitness=fitness)
    if checkpoint_path is not None:
        from ai_crypto_trader_tpu.utils.checkpoint import save_checkpoint

        save_checkpoint(checkpoint_path, best_params(result),
                        metadata={"kind": kind, "fitness": fitness})
        payload["checkpoint"] = checkpoint_path
    # exact-dup-only threshold: a winner that cleared its gate must get
    # its OWN version (the structure-search precedent, registry.register)
    vid = registry.register(kind, payload, metadata={
        "arch": "dqn_pbt",
        "population": result.pcfg.population,
        "generations": result.pcfg.generations,
        "dynamics": "lob",
        "adoption": "adopted" if allowed else "blocked_by_scorecard",
        "adoption_reason": reason,
    }, similarity_threshold=1.0)
    registry.update_performance(vid, {
        "fitness": fitness,
        "mean_fitness": float(result.fitness.mean()),
    })
    registry.set_status(vid, "active" if allowed else "shadow")
    return {"version": vid, "adopted": allowed, "reason": reason,
            "fitness": fitness}


def pbt_env_params(key, scenario="mixed", num_scenarios: int = 32,
                   steps: int = 1024, episode_len: int = 256,
                   fee_rate: float = 0.0005, dynamics: str = "lob",
                   flow=None):
    """The fleet's training markets: `sim/engine.scenario_env_params`
    with LOB dynamics by default — book-state observation columns AND
    the half-spread trade cost, so queue position, spread blowouts and
    liquidity holes shape the reward.  Returns (EnvParams, labels);
    size networks with ``rl.env.obs_size(params)``."""
    from ai_crypto_trader_tpu.sim.engine import scenario_env_params

    return scenario_env_params(key, scenario=scenario,
                               num_scenarios=num_scenarios, steps=steps,
                               episode_len=episode_len, fee_rate=fee_rate,
                               dynamics=dynamics, flow=flow)
