"""Continuous PBT training service: the launcher-managed loop that turns
one-shot `cli rl` sessions into a fleet that never stops training.

ROADMAP item 1's remaining half.  `rl/population.py` (PR 19) made the
population a single compiled program; this module makes it a SERVICE —
the evolver-service cadence pattern (`shell/stack.EvolverService`) run
under launcher supervision (StageBreaker + heartbeat via
`TradingSystem.attach_trainer`), with the durability and containment rim
a days-long run actually needs:

  * **one generation per cadence tick** — `train_pbt(generations=1)`
    with the ABSOLUTE generation counter threaded through, so the key
    stream is identical to an uninterrupted `train_pbt` call;
  * **crash-safe lineage** — every ``checkpoint_every`` generations the
    FULL vmapped training state (params, targets, opt state, replay
    rings, env states, PRNG keys, Hypers, quarantine bits, fitness
    history, adoption trail) lands in a `utils/journal.SnapshotJournal`
    as `pack_array` records: per-array CRCs catch bit rot, the WAL line
    CRC catches torn tails, compaction bounds the file.  A run killed
    mid-generation resumes from the newest intact checkpoint and
    produces BIT-identical history — the resume-parity pin;
  * **winner flow** — each generation's best healthy member goes through
    the existing `adopt_winner` scorecard gate (active when it beats the
    incumbent's simulator fitness, shadow otherwise), the verdict
    journaled beside the checkpoints AND recorded on the scorecard's
    adoption trail;
  * **rolling recalibration with last-good fallback** — every
    ``recalibrate_every`` generations the LOB FlowParams are re-fit from
    fresh DepthCapture snapshots (`sim/calibrate.fit_flow_params`) so
    the training distribution tracks the venue; an empty, NaN-poisoned
    or CRC-corrupted window degrades to the last-good params with
    ``pbt_recalibration_failures_total`` counted — and the swap is
    shape-guarded (`rl/env.assert_transfer_compatible`): a transfer,
    never a recompile.

Alert inputs (`alert_state()`, merged into the launcher's rule-engine
state) and gauges pair with the `TrainingFleetStalled` /
`MemberQuarantined` rules in utils/alerts.py and their PromQL twins in
monitoring/alert_rules.yml.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ai_crypto_trader_tpu.rl.dqn import DQNConfig
from ai_crypto_trader_tpu.rl.population import (
    PBTConfig,
    PopState,
    adopt_winner,
    host_read,
    pbt_env_params,
    pop_init,
    train_pbt,
)
from ai_crypto_trader_tpu.utils.journal import (
    SnapshotJournal,
    load_snapshot,
    pack_array,
    unpack_array,
)

#: WAL record kind for trainer checkpoints (distinct from the tenant
#: fleet's `fleet_state` stream — `load_snapshot(kind=...)` selects it)
PBT_CHECKPOINT_KIND = "pbt_lineage"

#: checkpoint payload format version — bump on layout changes so a
#: restore can refuse cleanly instead of mis-unpacking
CHECKPOINT_FORMAT = 1


def _cfg_identity(cfg: DQNConfig) -> dict:
    """The DQNConfig fields that shape the training-state arrays — the
    drift detector's comparison key (hypers are state, not identity)."""
    return {"state_size": int(cfg.state_size),
            "num_envs": int(cfg.num_envs),
            "rollout_len": int(cfg.rollout_len),
            "hidden": [int(h) for h in cfg.hidden],
            "n_actions": int(cfg.n_actions),
            "replay_capacity": int(cfg.replay_capacity),
            "batch_size": int(cfg.batch_size)}


def checkpoint_payload(pop: PopState, *, generation: int, cfg: DQNConfig,
                       pcfg: PBTConfig, seed: int | None = None,
                       history: list | None = None,
                       adoptions: list | None = None,
                       recalibration: dict | None = None,
                       flow=None) -> dict:
    """Serialize the FULL fleet as one journal-ready snapshot payload.

    Every leaf of the PopState pytree (params, target params, opt state,
    replay rings, env states, obs, ε, learn counters, PRNG keys, Hypers,
    quarantine bits, cooldowns) rides as a `pack_array` record — raw
    bytes + dtype + shape + CRC per array, so the restore is BIT-exact
    and bit rot raises instead of silently training a corrupted fleet."""
    leaves = host_read(jax.tree.leaves(pop))
    payload = {
        "format": CHECKPOINT_FORMAT,
        "generation": int(generation),
        "population": int(pcfg.population),
        "seed": None if seed is None else int(seed),
        "cfg": _cfg_identity(cfg),
        "arrays": [pack_array(np.asarray(leaf)) for leaf in leaves],
        "history": list(history or []),
        "adoptions": list(adoptions or []),
        "recalibration": recalibration,
    }
    if flow is not None:
        payload["flow"] = {k: float(v) for k, v in flow._asdict().items()}
    return payload


def restore_checkpoint(payload: dict, cfg: DQNConfig, pcfg: PBTConfig,
                       env_params) -> PopState:
    """Rebuild the device-resident fleet from a checkpoint payload.

    Refuses loudly on every drift axis instead of mis-shaping state into
    a recompile (or worse, silently training the wrong fleet):
    population-size drift, network/replay-shape drift, leaf-count drift,
    and per-leaf shape/dtype drift; per-array CRC mismatches raise from
    `unpack_array` before any of that."""
    if int(payload.get("format", -1)) != CHECKPOINT_FORMAT:
        raise ValueError(
            f"checkpoint format {payload.get('format')!r} != "
            f"{CHECKPOINT_FORMAT} — refusing to guess a layout")
    saved_p = int(payload.get("population", -1))
    if saved_p != int(pcfg.population):
        raise ValueError(
            f"checkpoint population {saved_p} != configured population "
            f"{pcfg.population}: refusing to load a drifted fleet "
            f"(resume with population={saved_p} or start fresh)")
    saved_cfg = payload.get("cfg") or {}
    want_cfg = _cfg_identity(cfg)
    if saved_cfg != want_cfg:
        drift = {k: (saved_cfg.get(k), want_cfg[k]) for k in want_cfg
                 if saved_cfg.get(k) != want_cfg[k]}
        raise ValueError(
            f"checkpoint training-config drift {drift} (saved, "
            f"configured): the snapshot arrays would not fit this fleet")
    leaves = [unpack_array(a) for a in payload["arrays"]]  # CRC per array
    template = pop_init(jax.random.PRNGKey(0), env_params, cfg, pcfg)
    t_leaves, treedef = jax.tree.flatten(template)
    if len(leaves) != len(t_leaves):
        raise ValueError(
            f"checkpoint carries {len(leaves)} arrays, fleet needs "
            f"{len(t_leaves)}: state-layout drift")
    for i, (got, want) in enumerate(zip(leaves, t_leaves)):
        if tuple(got.shape) != tuple(want.shape) \
                or got.dtype != np.asarray(want).dtype:
            raise ValueError(
                f"checkpoint array {i} is {got.shape}/{got.dtype}, fleet "
                f"needs {tuple(want.shape)}/{np.asarray(want).dtype}: "
                f"state-shape drift")
    return jax.tree.unflatten(treedef, [jnp.asarray(a) for a in leaves])


def load_checkpoint(path: str) -> tuple[dict | None, dict]:
    """Newest intact trainer checkpoint from ``path`` (torn-tail
    tolerant — a kill mid-append falls back to the previous generation's
    record).  Returns ``(payload | None, replay stats)``."""
    return load_snapshot(path, kind=PBT_CHECKPOINT_KIND)


@dataclass
class PBTTrainerService:
    """The continuously-training fleet as a launcher cadence service.

    Register via `TradingSystem.attach_trainer` (StageBreaker +
    heartbeat supervision) or append to ``extra_services`` directly;
    each eligible tick runs ONE PBT generation, then the durability /
    adoption / recalibration rim around it.  All state mutation happens
    on the host between compiled dispatches — the device programs are
    exactly the ones `train_pbt` compiles, shared through the same
    lru-caches, so a service fleet and a one-shot session are
    bit-interchangeable."""

    cfg: DQNConfig
    pcfg: PBTConfig
    env_params: object = None          # EnvParams; built lazily when None
    seed: int = 0
    partitioner: object = None
    interval_s: float = 0.0            # 0 = one generation per tick
    max_generations: int | None = None
    # durability
    checkpoint_path: str | None = None
    checkpoint_every: int = 1
    compact_every: int = 8
    # adoption
    registry: object = None
    scorecard: object = None
    adopt_every: int = 1
    # recalibration
    depth_source: object = None        # DepthCapture | callable | path
    recalibrate_every: int = 0         # generations between re-fits; 0=off
    calibration_symbol: str | None = None
    env_builder: object = None         # callable(flow) -> EnvParams
    env_kwargs: dict = field(default_factory=dict)
    # plumbing
    now_fn: object = time.time
    metrics: object = None
    name: str = "trainer"
    stall_after_s: float | None = None  # default: max(3·interval, 60 s)

    # -- mutable service state ----------------------------------------------
    generation: int = 0
    history: list = field(default_factory=list)
    adoptions: list = field(default_factory=list)
    flow: object = None                # last-good FlowParams
    last_recalibration: dict | None = None
    recalibration_failures: int = 0
    quarantine_trips: int = 0
    member_heals: int = 0
    resumed_at: int | None = None      # provenance: generation resumed from
    last_generation_at: float | None = None
    last_checkpoint_at: float | None = None
    last_checkpoint_generation: int | None = None
    _pop: object = None
    _journal: object = None
    _last: float = -1e18

    # -- lifecycle -----------------------------------------------------------
    def _build_env(self, flow):
        if self.env_builder is not None:
            out = self.env_builder(flow)
        else:
            kw = dict(self.env_kwargs)
            if self.env_params is not None:
                # a re-fit must regenerate VALUES into the live env's
                # SHAPES (assert_transfer_compatible's contract) — derive
                # the scenario geometry from the env itself so a caller
                # who handed us env_params never has to restate it
                shape = self.env_params.close.shape
                if len(shape) == 2:
                    kw.setdefault("num_scenarios", int(shape[0]))
                kw.setdefault("steps", int(shape[-1]))
                kw.setdefault("episode_len", int(self.env_params.episode_len))
            out = pbt_env_params(jax.random.PRNGKey(self.seed), flow=flow,
                                 **kw)
        return out[0] if isinstance(out, tuple) else out

    def _ensure_journal(self):
        if self._journal is None and self.checkpoint_path is not None:
            self._journal = SnapshotJournal(self.checkpoint_path,
                                            compact_every=self.compact_every,
                                            now_fn=self.now_fn,
                                            kind=PBT_CHECKPOINT_KIND)
        return self._journal

    def _bootstrap(self) -> dict:
        """First run: resume from the newest intact checkpoint when one
        exists, else init a fresh fleet.  Either way the env comes up
        from the SAME builder — on resume with a persisted last-good
        flow, from that flow, so the training distribution survives the
        restart too."""
        resumed = False
        payload = None
        if self.checkpoint_path is not None \
                and os.path.exists(self.checkpoint_path):
            payload, _stats = load_checkpoint(self.checkpoint_path)
        if payload is not None:
            from ai_crypto_trader_tpu.sim.lob import flow_params

            if payload.get("flow"):
                self.flow = flow_params(**payload["flow"])
            if self.env_params is None:
                self.env_params = self._build_env(self.flow)
            self._pop = restore_checkpoint(payload, self.cfg, self.pcfg,
                                           self.env_params)
            self.generation = int(payload["generation"])
            self.history = list(payload.get("history") or [])
            self.adoptions = list(payload.get("adoptions") or [])
            self.last_recalibration = payload.get("recalibration")
            self.resumed_at = self.generation
            self.last_checkpoint_generation = self.generation
            resumed = True
        else:
            if self.env_params is None:
                self.env_params = self._build_env(self.flow)
            self._pop = pop_init(jax.random.PRNGKey(self.seed),
                                 self.env_params, self.cfg, self.pcfg)
        return {"resumed": resumed, "generation": self.generation}

    # -- the rim -------------------------------------------------------------
    def _depth_records(self) -> list:
        src = self.depth_source
        if src is None:
            raise ValueError("no depth source configured")
        if callable(src):
            return list(src())
        if isinstance(src, str):
            from ai_crypto_trader_tpu.shell.stream import (
                depth_records_from_journal,
            )

            records, _stats = depth_records_from_journal(src)
            return records
        window = getattr(src, "calibration_window", None)
        if window is not None:
            return window(symbol=self.calibration_symbol)
        return list(src.records())

    def _recalibrate(self) -> dict:
        """Re-fit FlowParams from the freshest capture window; ANY
        failure (empty window, poisoned records, fit error, shape drift)
        keeps the last-good params and counts — the fleet trains on,
        never on a poisoned distribution."""
        from ai_crypto_trader_tpu.rl.env import assert_transfer_compatible
        from ai_crypto_trader_tpu.sim import calibrate

        now = self.now_fn()
        try:
            records = self._depth_records()
            calibrate.validate_depth_records(
                records, symbol=self.calibration_symbol)
            flow, report = calibrate.fit_flow_params(
                records, symbol=self.calibration_symbol)
            new_env = self._build_env(flow)
            assert_transfer_compatible(self.env_params, new_env)
        except Exception as exc:        # noqa: BLE001 — last-good fallback
            self.recalibration_failures += 1
            if self.metrics is not None:
                self.metrics.inc("pbt_recalibration_failures_total")
            self.last_recalibration = {
                "at": now, "generation": self.generation, "ok": False,
                "reason": f"{type(exc).__name__}: {exc}"}
            return self.last_recalibration
        self.flow = flow
        self.env_params = new_env
        if self.metrics is not None:
            self.metrics.set_gauge("pbt_last_recalibration_timestamp", now)
        self.last_recalibration = {
            "at": now, "generation": self.generation, "ok": True,
            "records": int(np.asarray(report.get("frames", 0)).item())
            if isinstance(report, dict) else None}
        return self.last_recalibration

    def checkpoint(self) -> int | None:
        """Durably snapshot the fleet NOW (also the `checkpoint_every`
        cadence target).  Returns the WAL sequence number."""
        journal = self._ensure_journal()
        if journal is None or self._pop is None:
            return None
        seq = journal.write(checkpoint_payload(
            self._pop, generation=self.generation, cfg=self.cfg,
            pcfg=self.pcfg, seed=self.seed, history=self.history,
            adoptions=self.adoptions,
            recalibration=self.last_recalibration, flow=self.flow))
        self.last_checkpoint_at = self.now_fn()
        self.last_checkpoint_generation = self.generation
        return seq

    def _adopt(self, result) -> dict | None:
        if self.registry is None:
            return None
        verdict = adopt_winner(result, self.registry, self.scorecard)
        rec = dict(verdict, generation=self.generation)
        self.adoptions.append(rec)
        if self.scorecard is not None:
            self.scorecard.record_adoption(rec)
        journal = self._ensure_journal()
        if journal is not None:
            # the verdict rides the SAME WAL as the checkpoints (and the
            # checkpoint payload's adoption trail survives compaction)
            journal.journal.append("pbt_adoption", rec, flush=True)
        return rec

    # -- the service tick ----------------------------------------------------
    async def run_once(self) -> dict:
        now = self.now_fn()
        if now - self._last < self.interval_s:
            return {"ran": False}
        if self.max_generations is not None \
                and self.generation >= self.max_generations:
            return {"ran": False, "reason": "complete"}
        self._last = now
        out: dict = {"ran": True}
        if self._pop is None:
            out["bootstrap"] = self._bootstrap()
        if self.recalibrate_every and self.depth_source is not None \
                and self.generation > 0 \
                and self.generation % self.recalibrate_every == 0:
            out["recalibration"] = self._recalibrate()

        prev_trips, prev_heals = self.quarantine_trips, self.member_heals
        res = train_pbt(
            jax.random.PRNGKey(self.seed), self.env_params, self.cfg,
            self.pcfg._replace(generations=1),
            partitioner=self.partitioner, init_pop=self._pop,
            start_generation=self.generation)
        self._pop = res.state
        row = res.history[0]
        self.history.append(row)
        self.generation += 1
        self.last_generation_at = self.now_fn()
        self.quarantine_trips += row["n_tripped"]
        self.member_heals += row["n_healed"]
        out["generation"] = row["generation"]
        out["best_fitness"] = row["best_fitness"]
        out["n_quarantined"] = row["n_quarantined"]

        if self.checkpoint_every \
                and self.generation % self.checkpoint_every == 0:
            # adopt BEFORE the checkpoint so the verdict trail the
            # snapshot carries includes this generation's winner
            verdict = self._adopt(res)
            if verdict is not None:
                out["adoption"] = verdict
            out["checkpoint_seq"] = self.checkpoint()
        elif self.adopt_every \
                and self.generation % self.adopt_every == 0:
            verdict = self._adopt(res)
            if verdict is not None:
                out["adoption"] = verdict
        self._export_gauges(row,
                            trips=self.quarantine_trips - prev_trips,
                            heals=self.member_heals - prev_heals)
        return out

    # -- observability -------------------------------------------------------
    def _export_gauges(self, row: dict, trips: int = 0, heals: int = 0):
        m = self.metrics
        if m is None:
            return
        now = self.now_fn()
        m.set_gauge("pbt_generation", float(self.generation))
        m.set_gauge("pbt_generation_interval_seconds",
                    float(max(self.interval_s, 1e-9)))
        m.set_gauge("pbt_last_generation_timestamp", float(now))
        m.set_gauge("pbt_quarantined_members", float(row["n_quarantined"]))
        if np.isfinite(row["best_fitness"]):
            m.set_gauge("pbt_best_fitness", float(row["best_fitness"]))
            m.set_gauge("pbt_mean_fitness", float(row["mean_fitness"]))
        if self.last_checkpoint_at is not None:
            m.set_gauge("pbt_checkpoint_age_s",
                        float(now - self.last_checkpoint_at))
        m.inc("pbt_generations_total")
        if trips:
            m.inc("pbt_quarantine_trips_total", trips)
        if heals:
            m.inc("pbt_member_heals_total", heals)

    def _stall_threshold(self) -> float:
        if self.stall_after_s is not None:
            return float(self.stall_after_s)
        return max(3.0 * float(self.interval_s), 60.0)

    def alert_state(self) -> dict:
        """Inputs for the in-process rule engine (merged into
        `TradingSystem._alert_state`): the `TrainingFleetStalled` /
        `MemberQuarantined` predicates read exactly these keys."""
        out = {"pbt_quarantined_members": int(
            self.history[-1]["n_quarantined"]) if self.history else 0,
            "pbt_stall_after_s": self._stall_threshold()}
        if self.last_generation_at is not None:
            out["pbt_generation_age_s"] = \
                self.now_fn() - self.last_generation_at
        return out

    def status(self) -> dict:
        """The /state.json ``training`` block (`cli status` renders it):
        where the fleet is, who is quarantined, how stale the newest
        checkpoint and calibration are."""
        now = self.now_fn()
        last = self.history[-1] if self.history else None
        out = {
            "generation": self.generation,
            "population": int(self.pcfg.population),
            "best_fitness": last["best_fitness"] if last else None,
            "mean_fitness": last["mean_fitness"] if last else None,
            "quarantined_members": last["n_quarantined"] if last else 0,
            "quarantine_trips": self.quarantine_trips,
            "member_heals": self.member_heals,
            "recalibration_failures": self.recalibration_failures,
            "last_recalibration": self.last_recalibration,
            "resumed_at": self.resumed_at,
            "adoptions": self.adoptions[-4:],
        }
        if self.last_generation_at is not None:
            out["generation_age_s"] = round(now - self.last_generation_at, 3)
        if self.last_checkpoint_at is not None:
            out["checkpoint_age_s"] = round(now - self.last_checkpoint_at, 3)
            out["checkpoint_generation"] = self.last_checkpoint_generation
            out["checkpoint_path"] = self.checkpoint_path
        return out

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()
            self._journal = None
