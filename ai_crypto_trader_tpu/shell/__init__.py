from ai_crypto_trader_tpu.shell.bus import EventBus  # noqa: F401
from ai_crypto_trader_tpu.shell.exchange import (  # noqa: F401
    ExchangeInterface,
    ExchangeUnavailable,
    FakeExchange,
    ResilientExchange,
    make_exchange,
)
from ai_crypto_trader_tpu.shell.llm import (  # noqa: F401
    LLMTrader,
    OpenAIBackend,
    TechnicalPolicyBackend,
    UrllibPostTransport,
)
from ai_crypto_trader_tpu.shell.monitor import MarketMonitor  # noqa: F401
from ai_crypto_trader_tpu.shell.analyzer import SignalAnalyzer  # noqa: F401
from ai_crypto_trader_tpu.shell.executor import TradeExecutor  # noqa: F401
