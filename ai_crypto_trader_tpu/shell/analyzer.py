"""Signal analyzer service: market_updates → AI gate → trading_signals.

Capability parity with AIAnalyzerService (`services/ai_analyzer_service.py`):
per-symbol analysis-interval gate (60 s, :382), market-context assembly from
technical + social + news inputs (:153-380), LLM analysis via the adapter,
and publication of `trading_signals` carrying decision/confidence plus the
technical signal (the executor cross-checks both, as in the reference).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.llm import LLMTrader
from ai_crypto_trader_tpu.utils import tracing


def _flat_features(ctx: dict) -> dict:
    """Flatten one level of nested context dicts (social/news/pattern) into
    the flat numeric feature namespace the pruned outcome model was fitted
    on (FEATURE_GROUPS names like social_sentiment) — nested dicts would
    otherwise silently read as 0.0 in the gate."""
    flat = {k: v for k, v in ctx.items() if isinstance(v, (int, float))}
    for v in ctx.values():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                if isinstance(v2, (int, float)) and k2 not in flat:
                    flat[k2] = v2
    return flat


@dataclass
class SignalAnalyzer:
    bus: EventBus
    trader: LLMTrader = field(default_factory=LLMTrader)
    analysis_interval_s: float = 60.0
    now_fn: any = time.time
    # Optional trade-outcome gate (strategy.integration
    # FeatureImportanceIntegrator or models.trade_importance analyzer): BUY
    # decisions whose pruned-model success probability falls below the
    # threshold are downgraded to HOLD (the integrator consumption path,
    # `services/model_integration.py:220-288`).
    outcome_model: any = None
    min_success_probability: float = 0.45
    _last_analysis: dict = field(default_factory=dict)

    def _build_context(self, update: dict) -> dict:
        """Market context string/dict (`ai_analyzer_service.py:153-380`) —
        technical core plus whatever social/news state services posted."""
        ctx = dict(update)
        symbol = update["symbol"]
        social = self.bus.get(f"social_metrics_{symbol}")
        if social:
            ctx["social"] = social
        news = self.bus.get(f"news_analysis_{symbol}")
        if news:
            ctx["news"] = news
        pattern = self.bus.get(f"pattern_signals_{symbol}")
        if pattern:
            ctx["chart_pattern"] = pattern
        return ctx

    async def handle_update(self, update: dict) -> dict | None:
        """Process one market update; returns the published signal or None
        when gated."""
        symbol = update["symbol"]
        now = self.now_fn()
        if now - self._last_analysis.get(symbol, -1e18) < self.analysis_interval_s:
            return None
        self._last_analysis[symbol] = now

        ctx = self._build_context(update)
        analysis = await self.trader.analyze_trade_opportunity(ctx)
        signal = {
            "symbol": symbol,
            "timestamp": now,
            "current_price": update["current_price"],
            "signal": update.get("signal", "NEUTRAL"),
            "signal_strength": update.get("signal_strength", 0.0),
            "volatility": update.get("volatility", 0.0),
            "avg_volume": update.get("avg_volume", 0.0),
            "decision": analysis.get("decision", "HOLD"),
            "confidence": float(analysis.get("confidence", 0.0)),
            "reasoning": analysis.get("reasoning", ""),
            "model_version": analysis.get("model_version"),
        }
        if self.outcome_model is not None and signal["decision"] == "BUY":
            outcome = self.outcome_model.predict_trade_outcome(
                _flat_features(ctx))
            signal["success_probability"] = outcome["success_probability"]
            if (outcome["status"] == "success"
                    and outcome["success_probability"]
                    < self.min_success_probability):
                signal["decision"] = "HOLD"
                signal["reasoning"] = (
                    f"{signal['reasoning']} [outcome gate: win probability "
                    f"{outcome['success_probability']:.2f} < "
                    f"{self.min_success_probability:.2f}]").strip()
        await self.bus.publish("trading_signals", signal)
        self.bus.set(f"latest_signal_{symbol}", signal)
        # structured explanation per signal (AIExplainabilityService consumes
        # trading_signals, `services/ai_explainability_service.py:138-354`;
        # the dashboard's drill-down panel renders this bounded history)
        from ai_crypto_trader_tpu.strategy.explain import explain_signal

        explanation = explain_signal(signal)
        self.bus.set(f"explanation_{symbol}", explanation)
        history = self.bus.get("explanations") or []
        history.append(explanation)
        self.bus.set("explanations", history[-50:])
        return signal

    def _queue(self):
        # Persistent subscription — a fresh queue per drain would miss every
        # message published before the drain started.
        if not hasattr(self, "_q"):
            self._q = self.bus.subscribe("market_updates")
        return self._q

    async def run_once(self) -> int:
        """Drain pending market_updates (used by tests / the launcher tick)."""
        n = 0
        q = self._queue()
        while not q.empty():
            env = q.get_nowait()
            # span parents to the publish that produced this envelope (the
            # carried trace context), so one trace_id follows the tick
            # across the service boundary
            with tracing.consumer_span(
                    env, "analyzer.handle_update", service="analyzer",
                    attributes={"symbol": env["data"].get("symbol")}) as sp:
                signal = await self.handle_update(env["data"])
                if signal:
                    sp.set_attribute("decision", signal.get("decision"))
                    n += 1
                else:
                    sp.set_attribute("gated", True)
        return n
