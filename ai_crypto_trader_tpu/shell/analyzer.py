"""Signal analyzer service: market_updates → AI gate → trading_signals.

Capability parity with AIAnalyzerService (`services/ai_analyzer_service.py`):
per-symbol analysis-interval gate (60 s, :382), market-context assembly from
technical + social + news inputs (:153-380), LLM analysis via the adapter,
and publication of `trading_signals` carrying decision/confidence plus the
technical signal (the executor cross-checks both, as in the reference).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ai_crypto_trader_tpu.obs import tickpath
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.llm import LLMTrader
from ai_crypto_trader_tpu.utils import tracing


def _flat_features(ctx: dict) -> dict:
    """Flatten one level of nested context dicts (social/news/pattern) into
    the flat numeric feature namespace the pruned outcome model was fitted
    on (FEATURE_GROUPS names like social_sentiment) — nested dicts would
    otherwise silently read as 0.0 in the gate."""
    flat = {k: v for k, v in ctx.items() if isinstance(v, (int, float))}
    for v in ctx.values():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                if isinstance(v2, (int, float)) and k2 not in flat:
                    flat[k2] = v2
    return flat


@dataclass
class SignalAnalyzer:
    bus: EventBus
    trader: LLMTrader = field(default_factory=LLMTrader)
    analysis_interval_s: float = 60.0
    now_fn: any = time.time
    # Optional trade-outcome gate (strategy.integration
    # FeatureImportanceIntegrator or models.trade_importance analyzer): BUY
    # decisions whose pruned-model success probability falls below the
    # threshold are downgraded to HOLD (the integrator consumption path,
    # `services/model_integration.py:220-288`).
    outcome_model: any = None
    min_success_probability: float = 0.45
    # Decision-provenance flight recorder (obs/flightrec.py), wired by the
    # launcher (default-on there).  None = disabled: every call site below
    # is a single attribute check, the tracing/devprof discipline.
    flightrec: any = None
    # Tenant-lane tag (ROADMAP item 4 / testing/loadgen.py): when set,
    # signals carry `lane` AND publish on the per-lane channel
    # `trading_signals.<lane>`, so each tenant's executor subscribes to
    # exactly its own lane — N tenants cost O(N) bus fanout, not N² with
    # consumer-side filtering.  None (the default, the one-tenant
    # launcher) keeps the shared `trading_signals` channel untagged.
    lane: str | None = None
    _last_analysis: dict = field(default_factory=dict)

    def _decision_features(self, update: dict) -> dict:
        """The compact feature/confluence slice the flight recorder keeps
        per decision — enough to answer `cli why` without replaying the
        whole market payload."""
        keys = ("current_price", "signal", "signal_strength", "confluence",
                "rsi", "macd", "volatility", "trend", "trend_strength",
                "top_family", "top_family_score", "structure_version",
                "structure_blend")
        return {k: update[k] for k in keys if k in update}

    def _prediction_snapshot(self, symbol: str) -> dict:
        """Each architecture's live prediction for this symbol (the
        nn_prediction_* bus keys the prediction service maintains)."""
        out = {}
        for key in self.bus.keys(f"nn_prediction_{symbol}_*"):
            p = self.bus.get(key)
            if not isinstance(p, dict):
                continue
            tag = f"{p.get('model_type', 'nn')}:{p.get('interval', '?')}"
            out[tag] = {k: p[k] for k in ("predicted_price", "confidence",
                                          "reference_price", "horizon_s")
                        if k in p}
        return out

    def _build_context(self, update: dict) -> dict:
        """Market context string/dict (`ai_analyzer_service.py:153-380`) —
        technical core plus whatever social/news state services posted."""
        ctx = dict(update)
        symbol = update["symbol"]
        social = self.bus.get(f"social_metrics_{symbol}")
        if social:
            ctx["social"] = social
        news = self.bus.get(f"news_analysis_{symbol}")
        if news:
            ctx["news"] = news
        pattern = self.bus.get(f"pattern_signals_{symbol}")
        if pattern:
            ctx["chart_pattern"] = pattern
        return ctx

    async def handle_update(self, update: dict) -> dict | None:
        """Process one market update; returns the published signal or None
        when gated."""
        symbol = update["symbol"]
        now = self.now_fn()
        fr = self.flightrec
        rec_id = None
        if now - self._last_analysis.get(symbol, -1e18) < self.analysis_interval_s:
            # throttle hit — the COMMON path (every poll between analysis
            # cadences).  Counted, not recorded: no feature slice, no
            # bus-wide prediction-snapshot scan, no ring slot — the hot
            # path stays O(1) and real decisions own the ring.
            if fr is not None:
                fr.throttled(symbol)
            return None
        self._last_analysis[symbol] = now
        # event→decision age (obs/tickpath.py): venue event time E (the
        # monitor stamps `event_ms` onto the update) → this decision.
        # The scope clamps a negative age (host clock behind the venue)
        # to 0 and counts tickpath_clock_skew_total; None when the
        # observatory is off (the field then stays unset on the record).
        event_age_ms = None
        ev_ms = update.get("event_ms")
        if ev_ms:
            event_age_ms = tickpath.observe_event_age(
                now * 1000.0 - float(ev_ms))
        if fr is not None:
            rec_id = fr.begin(symbol,
                              features=self._decision_features(update),
                              predictions=self._prediction_snapshot(symbol),
                              event_age_ms=event_age_ms)

        ctx = self._build_context(update)
        analysis = await self.trader.analyze_trade_opportunity(ctx)
        signal = {
            "symbol": symbol,
            "timestamp": now,
            "current_price": update["current_price"],
            "signal": update.get("signal", "NEUTRAL"),
            "signal_strength": update.get("signal_strength", 0.0),
            "volatility": update.get("volatility", 0.0),
            "avg_volume": update.get("avg_volume", 0.0),
            "decision": analysis.get("decision", "HOLD"),
            "confidence": float(analysis.get("confidence", 0.0)),
            "reasoning": analysis.get("reasoning", ""),
            "model_version": analysis.get("model_version"),
            # entry-signal provenance riding to the executor and, for
            # executed trades, into the journal closure records the PnL
            # attribution folds (obs/attribution.py)
            "top_family": update.get("top_family"),
            "structure_version": update.get("structure_version"),
        }
        channel = "trading_signals"
        if self.lane is not None:
            signal["lane"] = self.lane
            channel = f"trading_signals.{self.lane}"
        if rec_id is not None:
            signal["decision_id"] = rec_id
        outcome_veto = None
        if self.outcome_model is not None and signal["decision"] == "BUY":
            outcome = self.outcome_model.predict_trade_outcome(
                _flat_features(ctx))
            signal["success_probability"] = outcome["success_probability"]
            if (outcome["status"] == "success"
                    and outcome["success_probability"]
                    < self.min_success_probability):
                signal["decision"] = "HOLD"
                signal["reasoning"] = (
                    f"{signal['reasoning']} [outcome gate: win probability "
                    f"{outcome['success_probability']:.2f} < "
                    f"{self.min_success_probability:.2f}]").strip()
                # the veto is TERMINAL (journals the record) — deferred
                # until after set_verdict below so the durable copy carries
                # the verdict + explanation, not just the gate
                outcome_veto = f"p={outcome['success_probability']:.2f}"
        await self.bus.publish(channel, signal)
        self.bus.set(f"latest_signal_{symbol}", signal)
        # structured explanation per signal (AIExplainabilityService consumes
        # trading_signals, `services/ai_explainability_service.py:138-354`;
        # the dashboard's drill-down panel renders this bounded history)
        from ai_crypto_trader_tpu.strategy.explain import explain_signal

        explanation = explain_signal({**update, **signal})
        self.bus.set(f"explanation_{symbol}", explanation)
        history = self.bus.get("explanations") or []
        history.append(explanation)
        self.bus.set("explanations", history[-50:])
        if fr is not None:
            # the verdict + structured explanation land on the decision
            # record BEFORE the executor finalizes it (veto/execution)
            fr.set_verdict(rec_id, {
                "decision": signal["decision"],
                "confidence": signal["confidence"],
                "model_version": signal.get("model_version"),
            }, explanation=explanation)
            if outcome_veto is not None:
                fr.veto(rec_id, "outcome_probability", detail=outcome_veto)
        return signal

    def _queue(self):
        # Persistent subscription — a fresh queue per drain would miss every
        # message published before the drain started.
        if not hasattr(self, "_q"):
            self._q = self.bus.subscribe("market_updates")
        return self._q

    async def run_once(self) -> int:
        """Drain pending market_updates (used by tests / the launcher tick)."""
        n = 0
        q = self._queue()
        while not q.empty():
            env = q.get_nowait()
            # span parents to the publish that produced this envelope (the
            # carried trace context), so one trace_id follows the tick
            # across the service boundary
            with tracing.consumer_span(
                    env, "analyzer.handle_update", service="analyzer",
                    attributes={"symbol": env["data"].get("symbol")}) as sp:
                signal = await self.handle_update(env["data"])
                if signal:
                    sp.set_attribute("decision", signal.get("decision"))
                    n += 1
                else:
                    sp.set_attribute("gated", True)
        return n
