"""In-process async event bus — the control plane that replaces Redis.

The reference's entire inter-service fabric is Redis pub/sub + key-value
state over TCP (`services/utils/redis_pool.py`; SURVEY §1 L1, §5.8): every
numeric result crosses a network bus.  In the TPU-native design, numbers
move over ICI inside XLA collectives; what remains is *control*: signal
fan-out, hot-swapped strategy params, dashboard feeds.  This bus serves that
role in-process (one asyncio loop per host) with the same surface the
reference's services use — publish/subscribe channels + a key-value store —
so every reference channel (`market_updates`, `trading_signals`,
`pattern_signals`, `strategy_update`, …, `dashboard.py:91-99`) has a direct
equivalent.  A multi-host deployment can swap in any transport behind the
same interface without touching services.

Observability: when tracing is active (utils/tracing.py), every publish
stamps the envelope with the current trace context so subscribers can
parent their handling spans to the publish — causal tracing across service
boundaries with unchanged service signatures.  With a MetricsRegistry
attached the bus reports `bus_fanout_latency_seconds{channel=...}` and
`bus_queue_depth{channel=...}`; a StructuredLogger turns queue overflow
(slow subscriber dropping oldest) into a warning carrying the trace_id, so
logs ↔ traces ↔ metrics correlate on one id.
"""

from __future__ import annotations

import asyncio
import fnmatch
import time
from collections import defaultdict
from typing import Any, AsyncIterator

from ai_crypto_trader_tpu.utils import tracing
from ai_crypto_trader_tpu.utils.metrics import channel_family


#: Channels where silently losing a message is NOT acceptable telemetry
#: behavior: a dropped alert hides an incident, a dropped trading signal
#: silently skips a trade.  Default policy "grow": their queues are
#: unbounded (backlog is surfaced as a warning past the soft limit instead
#: of discarded).  The other policy is "alert_on_drop": bounded, but every
#: overflow publishes a MessageLoss alert naming the channel.  The
#: trading-signals entry is a PATTERN: per-tenant decision lanes publish
#: on `trading_signals.<lane>` (shell/analyzer.py `lane`), and a lane's
#: signals are exactly as loss-critical as the shared channel's.
CRITICAL_CHANNELS = {"alerts": "grow", "trading_signals*": "grow"}


class EventBus:
    """Channels + KV store. Subscribers get bounded asyncio queues; slow
    consumers drop oldest (the reference's fire-and-forget pub/sub has no
    delivery guarantee either — parity, but explicit).  Critical channels
    carry a per-channel overflow policy instead (see CRITICAL_CHANNELS /
    the ``overflow`` ctor arg): "grow" or "alert_on_drop"."""

    def __init__(self, max_queue: int = 1024, now_fn=time.time,
                 metrics=None, log=None, overflow: dict | None = None,
                 warn_interval_s: float = 30.0):
        self._subs: dict[str, list[asyncio.Queue]] = defaultdict(list)
        # subscription patterns containing fnmatch wildcards — the ONLY
        # ones a publish must scan.  Exact-name subscriptions resolve by
        # dict lookup, so N tenant lanes subscribed to their own
        # `trading_signals.<lane>` cost a publish O(1), not O(N) fnmatch
        # calls per message (the vmapped-tenant scale contract).
        self._wild: set[str] = set()
        self._kv: dict[str, Any] = {}
        self._max_queue = max_queue
        self._now = now_fn
        self.metrics = metrics            # MetricsRegistry | None
        self.log = log                    # StructuredLogger | None
        self.overflow = {**CRITICAL_CHANNELS, **(overflow or {})}
        self.published_counts: dict[str, int] = defaultdict(int)
        self.dropped_counts: dict[str, int] = defaultdict(int)
        # Log rate limiting (edge-trigger + periodic summary): a channel
        # saturated at thousands of publishes/second must not turn the
        # structured log into its own denial of service.  The FIRST drop
        # of an episode logs immediately; further drops within
        # `warn_interval_s` are counted and folded into the next summary
        # line (`suppressed_warnings`).  The drop COUNTERS (and metrics)
        # stay exact — only the log lines are limited.  Wall clock on
        # purpose: `now_fn` may be a frozen/virtual test clock, which
        # would either suppress forever or spam per publish.
        self.warn_interval_s = warn_interval_s
        self._drop_warn: dict[str, tuple[float, int]] = {}
        self._grow_warn: dict[str, tuple[float, int]] = {}
        # per-channel max observed fanout queue depth (the saturation
        # monitor's bus_queue_high_watermark input)
        self.depth_watermarks: dict[str, int] = defaultdict(int)
        # rolled-up metric families' held depth (see publish/
        # sync_family_depth_gauges): {family: (max depth, established at)}
        self._fam_depth_hold: dict[str, tuple[float, float]] = {}

    @property
    def max_queue(self) -> int:
        """Bounded-channel queue capacity (the soft limit for "grow"
        channels) — the denominator of bus_queue_utilization."""
        return self._max_queue

    def _policy(self, channel: str) -> str:
        pol = self.overflow.get(channel)
        if pol is None:
            for pattern, p in self.overflow.items():
                if fnmatch.fnmatch(channel, pattern):
                    return p
            return "drop_oldest"
        return pol

    # --- pub/sub -----------------------------------------------------------
    def subscribe(self, channel: str) -> asyncio.Queue:
        # "grow" channels get an unbounded queue: a slow subscriber backlog
        # on alerts/trading_signals must never silently discard
        maxsize = 0 if self._policy(channel) == "grow" else self._max_queue
        q: asyncio.Queue = asyncio.Queue(maxsize)
        self._subs[channel].append(q)
        if any(c in channel for c in "*?["):
            self._wild.add(channel)
        return q

    def unsubscribe(self, channel: str, q: asyncio.Queue) -> None:
        if q in self._subs.get(channel, []):
            self._subs[channel].remove(q)
            if not self._subs[channel]:
                del self._subs[channel]
                self._wild.discard(channel)

    async def publish(self, channel: str, message: Any) -> int:
        self.published_counts[channel] += 1
        delivered = 0
        dropped = 0
        envelope = {"channel": channel, "ts": self._now(), "data": message}
        # Trace propagation: stamp the originating span's context onto the
        # envelope (one module-global check when tracing is off).
        ctx = tracing.inject()
        if ctx is not None:
            envelope["trace"] = ctx
        fanout_t0 = time.perf_counter() if self.metrics is not None else 0.0
        depth = 0
        # exact-match fast path + wildcard patterns only: fanout cost is
        # O(subscribers of THIS channel + wildcard patterns), independent
        # of how many tenant lanes subscribed to their own channels
        targets = list(self._subs.get(channel, ()))
        for pattern in self._wild:
            if pattern != channel and fnmatch.fnmatch(channel, pattern):
                targets.extend(self._subs.get(pattern, ()))
        for q in targets:
            if q.full():
                try:
                    q.get_nowait()          # drop oldest
                    dropped += 1
                except asyncio.QueueEmpty:
                    pass
            q.put_nowait(envelope)
            delivered += 1
            if q.qsize() > depth:
                depth = q.qsize()
        # capture fanout latency BEFORE the drop-logging below: the flushed
        # log write would otherwise inflate exactly the incidents this
        # metric exists to diagnose
        fanout_s = (time.perf_counter() - fanout_t0
                    if self.metrics is not None else 0.0)
        if depth > self.depth_watermarks[channel]:
            self.depth_watermarks[channel] = depth
        if dropped:
            self.dropped_counts[channel] += dropped
            if self.log is not None:
                # slow-subscriber detection: a full queue means a consumer
                # is not keeping up with the publish rate; the trace_id ties
                # this line to the span and metric views of the same moment.
                # Edge-trigger + periodic summary: the first drop of an
                # episode warns immediately, then at most one summary line
                # per warn_interval_s carrying the suppressed count.
                mono = time.monotonic()
                last, suppressed = self._drop_warn.get(channel, (None, 0))
                if last is None or mono - last >= self.warn_interval_s:
                    self.log.warning(
                        "slow subscriber: dropped oldest message(s)",
                        channel=channel, dropped=dropped,
                        suppressed_warnings=suppressed,
                        total_dropped=self.dropped_counts[channel],
                        queue_depth=depth,
                        trace_id=ctx.get("trace_id") if ctx else None)
                    self._drop_warn[channel] = (mono, 0)
                else:
                    self._drop_warn[channel] = (last, suppressed + 1)
            if (self._policy(channel) == "alert_on_drop"
                    and channel != "alerts"):
                # loss on a critical bounded channel is an INCIDENT, not
                # telemetry: surface it on the alerts channel (itself
                # "grow", so this publish cannot recurse into a drop)
                await self.publish("alerts", {
                    "name": "MessageLoss", "severity": "warning",
                    "channel": channel, "dropped": dropped,
                    "at": self._now()})
        else:
            pending = self._drop_warn.get(channel)
            if (pending is not None and pending[1]
                    and self.log is not None
                    and time.monotonic() - pending[0]
                    >= self.warn_interval_s):
                # a drop episode ENDED without its summary landing (the
                # interval never elapsed while drops kept coming): flush
                # the suppressed count on the next healthy publish, so
                # the log — not just the counters — records the loss
                self.log.warning(
                    "slow subscriber: drop episode ended",
                    channel=channel, suppressed_warnings=pending[1],
                    total_dropped=self.dropped_counts[channel])
                self._drop_warn[channel] = (time.monotonic(), 0)
            if (self._policy(channel) == "grow"
                    and depth > self._max_queue and self.log is not None):
                # unbounded critical channel growing past the soft limit:
                # warn on the episode edge and on doublings, then at most
                # one periodic summary per warn_interval_s while it lasts
                mono = time.monotonic()
                last, warned_depth = self._grow_warn.get(channel, (None, 0))
                if (last is None or depth >= 2 * warned_depth
                        or mono - last >= self.warn_interval_s):
                    self._grow_warn[channel] = (mono, depth)
                    self.log.warning("critical channel backlog growing",
                                     channel=channel, queue_depth=depth,
                                     soft_limit=self._max_queue)
        if self.metrics is not None:
            # per-lane channels (`trading_signals.<lane>`) roll up to one
            # `trading_signals.*` series per family: a 1020-lane fleet
            # would otherwise eat the registry's 512-series cap and clip
            # UNRELATED channels (utils/metrics.channel_family)
            fam = channel_family(channel)
            if fam != channel:
                # last-write-wins across lanes would let an idle lane's
                # depth-0 publish overwrite a backlogged lane's 900
                # between scrapes, hiding backpressure from the
                # bus_queue_depth alert — hold the family MAX here.
                # sync_family_depth_gauges() (the saturation monitor's
                # per-tick close-out) re-anchors it to the true current
                # max; the TTL bounds the hold when NO saturation
                # monitor runs (enable_saturation=False), so a drained
                # transient backlog cannot latch the gauge forever
                mono = time.monotonic()
                held, t_held = self._fam_depth_hold.get(fam, (0, mono))
                if mono - t_held > self.warn_interval_s:
                    held, t_held = 0, mono     # hold expired: re-anchor
                if depth >= held:
                    # the timestamp tracks when the max was ESTABLISHED
                    # (an idle lane's publish must not refresh a stale
                    # hold it didn't set)
                    held, t_held = depth, mono
                self._fam_depth_hold[fam] = (held, t_held)
                depth = held
            self.metrics.observe("bus_fanout_latency_seconds", fanout_s,
                                 channel=fam)
            self.metrics.set_gauge("bus_queue_depth", depth, channel=fam)
            if dropped:
                self.metrics.inc("bus_dropped_messages_total", dropped,
                                 channel=fam)
        return delivered

    def sync_family_depth_gauges(self) -> None:
        """Re-anchor each rolled-up family's held `bus_queue_depth` gauge
        on the TRUE current max over its member channels (the per-publish
        path only max-holds — cheap but monotone until corrected).  One
        O(channels) pass, called once per tick by
        `SaturationMonitor.observe_bus`."""
        if self.metrics is None or not self._fam_depth_hold:
            return
        true_max: dict[str, int] = {}
        for channel, depth in self.queue_depths().items():
            fam = channel_family(channel)
            if fam in self._fam_depth_hold:
                true_max[fam] = max(true_max.get(fam, 0), int(depth))
        mono = time.monotonic()
        for fam in self._fam_depth_hold:
            depth = true_max.get(fam, 0)
            self._fam_depth_hold[fam] = (depth, mono)
            self.metrics.set_gauge("bus_queue_depth", depth, channel=fam)

    def queue_depths(self) -> dict[str, int]:
        """Max pending depth per subscription pattern (telemetry view)."""
        return {pattern: max((q.qsize() for q in queues), default=0)
                for pattern, queues in self._subs.items()}

    async def listen(self, channel: str) -> AsyncIterator[dict]:
        q = self.subscribe(channel)
        try:
            while True:
                yield await q.get()
        finally:
            self.unsubscribe(channel, q)

    # --- key-value state (Redis get/set/hget parity) -----------------------
    def set(self, key: str, value: Any) -> None:
        self._kv[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self._kv.get(key, default)

    def keys(self, pattern: str = "*") -> list[str]:
        return [k for k in self._kv if fnmatch.fnmatch(k, pattern)]

    def delete(self, key: str) -> None:
        self._kv.pop(key, None)
