"""In-process async event bus — the control plane that replaces Redis.

The reference's entire inter-service fabric is Redis pub/sub + key-value
state over TCP (`services/utils/redis_pool.py`; SURVEY §1 L1, §5.8): every
numeric result crosses a network bus.  In the TPU-native design, numbers
move over ICI inside XLA collectives; what remains is *control*: signal
fan-out, hot-swapped strategy params, dashboard feeds.  This bus serves that
role in-process (one asyncio loop per host) with the same surface the
reference's services use — publish/subscribe channels + a key-value store —
so every reference channel (`market_updates`, `trading_signals`,
`pattern_signals`, `strategy_update`, …, `dashboard.py:91-99`) has a direct
equivalent.  A multi-host deployment can swap in any transport behind the
same interface without touching services.
"""

from __future__ import annotations

import asyncio
import fnmatch
import time
from collections import defaultdict
from typing import Any, AsyncIterator


class EventBus:
    """Channels + KV store. Subscribers get bounded asyncio queues; slow
    consumers drop oldest (the reference's fire-and-forget pub/sub has no
    delivery guarantee either — parity, but explicit)."""

    def __init__(self, max_queue: int = 1024, now_fn=time.time):
        self._subs: dict[str, list[asyncio.Queue]] = defaultdict(list)
        self._kv: dict[str, Any] = {}
        self._max_queue = max_queue
        self._now = now_fn
        self.published_counts: dict[str, int] = defaultdict(int)

    # --- pub/sub -----------------------------------------------------------
    def subscribe(self, channel: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(self._max_queue)
        self._subs[channel].append(q)
        return q

    def unsubscribe(self, channel: str, q: asyncio.Queue) -> None:
        if q in self._subs.get(channel, []):
            self._subs[channel].remove(q)

    async def publish(self, channel: str, message: Any) -> int:
        self.published_counts[channel] += 1
        delivered = 0
        envelope = {"channel": channel, "ts": self._now(), "data": message}
        for pattern, queues in list(self._subs.items()):
            if pattern == channel or fnmatch.fnmatch(channel, pattern):
                for q in queues:
                    if q.full():
                        try:
                            q.get_nowait()          # drop oldest
                        except asyncio.QueueEmpty:
                            pass
                    q.put_nowait(envelope)
                    delivered += 1
        return delivered

    async def listen(self, channel: str) -> AsyncIterator[dict]:
        q = self.subscribe(channel)
        try:
            while True:
                yield await q.get()
        finally:
            self.unsubscribe(channel, q)

    # --- key-value state (Redis get/set/hget parity) -----------------------
    def set(self, key: str, value: Any) -> None:
        self._kv[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self._kv.get(key, default)

    def keys(self, pattern: str = "*") -> list[str]:
        return [k for k in self._kv if fnmatch.fnmatch(k, pattern)]

    def delete(self, key: str) -> None:
        self._kv.pop(key, None)
