"""Dashboard: self-contained HTML snapshot of the whole system state.

Capability parity with `dashboard.py` (2 315 LoC Plotly Dash UI: price
chart, portfolio, signals feed, VaR, risk metrics, strategy state,
explanations) re-designed as a dependency-free static artifact: one call
renders bus state + backtest/MC results into a single HTML file with inline
SVG charts — servable by anything, regeneratable on a timer by the
launcher, and diffable in tests.  The live data plane is the bus KV, same
as the reference's Redis keys.
"""

from __future__ import annotations

import html
import json
import time

import numpy as np


def _svg_line(values, width=640, height=160, color="#2a7", label=""):
    v = np.asarray(values, dtype=float)
    if v.size < 2 or not np.isfinite(v).any():
        return "<svg/>"
    v = np.nan_to_num(v, nan=float(np.nanmean(v)))
    lo, hi = float(v.min()), float(v.max())
    rng = hi - lo or 1.0
    xs = np.linspace(4, width - 4, v.size)
    ys = height - 4 - (v - lo) / rng * (height - 8)
    pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in zip(xs, ys))
    return (f'<svg width="{width}" height="{height}" '
            f'style="background:#111;border-radius:6px">'
            f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
            f'points="{pts}"/>'
            f'<text x="8" y="16" fill="#999" font-size="11">{html.escape(label)}'
            f' [{lo:.2f} … {hi:.2f}]</text></svg>')


def _svg_heatmap(matrix, labels, cell=34, pad=70):
    """Correlation heatmap (the reference's dashboard.py:1712 panel):
    blue −1 … dark 0 … green +1, labels on both axes."""
    m = np.asarray(matrix, dtype=float)
    n = m.shape[0]
    if n == 0 or m.shape != (n, n):
        return "<svg/>"
    w = h = pad + n * cell + 4
    cells = []
    for i in range(n):
        for j in range(n):
            v = float(np.clip(np.nan_to_num(m[i, j]), -1.0, 1.0))
            if v >= 0:
                color = f"rgb({int(20 + 20 * v)},{int(40 + 150 * v)},{int(40 + 60 * v)})"
            else:
                color = f"rgb({int(40 - 20 * v)},{int(60 + 20 * v)},{int(60 - 150 * v)})"
            x, y = pad + j * cell, pad + i * cell
            cells.append(
                f'<rect x="{x}" y="{y}" width="{cell - 1}" height="{cell - 1}"'
                f' fill="{color}"><title>{html.escape(str(labels[i]))} / '
                f'{html.escape(str(labels[j]))}: {v:+.2f}</title></rect>'
                f'<text x="{x + cell / 2:.0f}" y="{y + cell / 2 + 3:.0f}" '
                f'fill="#ddd" font-size="9" text-anchor="middle">{v:+.2f}</text>')
    texts = []
    for i, lab in enumerate(labels):
        lab = html.escape(str(lab).replace("USDC", ""))
        texts.append(f'<text x="{pad - 6}" y="{pad + i * cell + cell / 2 + 3:.0f}"'
                     f' fill="#999" font-size="10" text-anchor="end">{lab}</text>')
        texts.append(f'<text x="{pad + i * cell + cell / 2:.0f}" y="{pad - 8}"'
                     f' fill="#999" font-size="10" text-anchor="middle" '
                     f'transform="rotate(-45 {pad + i * cell + cell / 2:.0f} '
                     f'{pad - 8})">{lab}</text>')
    return (f'<svg width="{w}" height="{h}" '
            f'style="background:#111;border-radius:6px">'
            + "".join(texts) + "".join(cells) + "</svg>")


def _explanations_html(explanations: list) -> str:
    """Explanation drill-down (the reference's AI-explanation modal,
    dashboard.py:1937): a <details> disclosure per signal with the factor
    table inside — click to drill in."""
    items = []
    for e in explanations[-8:][::-1]:
        head = (f"{e.get('symbol', '?')} {e.get('decision', '?')} "
                f"(conf {float(e.get('confidence') or 0.0):.2f})")
        factors = e.get("factors") or e.get("factor_weights") or {}

        def cell(v):
            if isinstance(v, dict):               # explain_signal factor row
                return (f"{v.get('value', 0):,.2f} ({v.get('reading', '')}) "
                        f"× {v.get('weight', 0):.2f}")
            return _fmt(v)

        rows = "".join(
            f"<tr><td>{html.escape(str(k))}</td>"
            f"<td style='text-align:right'>{html.escape(cell(v))}</td></tr>"
            for k, v in (factors.items() if isinstance(factors, dict)
                         else enumerate(factors)))
        summary = html.escape(str(e.get("narrative", ""))[:300])
        items.append(
            f"<details><summary>{html.escape(head)}</summary>"
            f"<p style='color:#999;font-size:12px'>{summary}</p>"
            f"<table>{rows}</table></details>")
    if not items:
        return ""
    return ("<div class='card'><h3>AI explanations</h3>"
            + "".join(items) + "</div>")


def _table(rows: dict, title: str) -> str:
    body = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td style='text-align:right'>{html.escape(_fmt(v))}</td></tr>"
        for k, v in rows.items())
    return (f"<div class='card'><h3>{html.escape(title)}</h3>"
            f"<table>{body}</table></div>")


def _fmt(v):
    if isinstance(v, float):
        return f"{v:,.4f}" if abs(v) < 100 else f"{v:,.2f}"
    return str(v)


def render_dashboard(bus=None, *, price_series=None, equity_curve=None,
                     metrics: dict | None = None, mc_stats: dict | None = None,
                     signals: list | None = None, alerts: list | None = None,
                     regime: dict | None = None, refresh_s: float | None = None,
                     now_fn=time.time) -> str:
    """Return the dashboard HTML. Every section is optional — sections
    render from whatever state exists (like the reference's per-callback
    panels tolerating missing Redis keys). `refresh_s` adds a meta-refresh
    so a served page polls like the reference's 5 s Dash interval."""
    sections = []
    if price_series is not None:
        sections.append(_svg_line(price_series, label="price", color="#4af"))
    if equity_curve is not None:
        sections.append(_svg_line(equity_curve, label="equity", color="#2a7"))
    if metrics:
        sections.append(_table(metrics, "Backtest / portfolio metrics"))
    if mc_stats:
        sections.append(_table(mc_stats, "Monte-Carlo risk"))
    if regime:
        sections.append(_table(regime, "Market regime"))
    if bus is not None:
        params = bus.get("strategy_params")
        if params:
            sections.append(_table(params, "Live strategy parameters"))
        trades = bus.get("active_trades")
        if trades:
            sections.append(_table({s: f"entry {t.get('entry_price', 0):,.2f}"
                                    for s, t in trades.items()}, "Active trades"))
        # --- reference dashboard.py parity panels ---
        pv = bus.get("portfolio_value_history")
        if pv and len(pv) >= 2:                   # portfolio value chart
            sections.append(_svg_line([p["value"] for p in pv],
                                      label="portfolio value", color="#fa4"))
        live_regime = bus.get("market_regime")
        if (not regime and live_regime            # regime panel (skip when a
                and isinstance(live_regime, dict)):  # snapshot was passed in)
            sections.append(_table(
                {k: v for k, v in live_regime.items()
                 if isinstance(v, (int, float, str))}, "Market regime"))
        risk = bus.get("risk_metrics")
        if risk:
            sections.append(_table(risk, "Portfolio risk"))
        var_hist = bus.get("var_history")
        if var_hist and len(var_hist) >= 2:       # dashboard.py:1485
            sections.append(_svg_line([p["var_95"] for p in var_hist],
                                      label="VaR 95% history", color="#e66"))
        corr = bus.get("correlation_matrix")
        if corr and corr.get("symbols"):          # dashboard.py:1712
            sections.append(
                "<div class='card'><h3>Asset correlation</h3>"
                + _svg_heatmap(corr["matrix"], corr["symbols"]) + "</div>")
        expl = bus.get("explanations")
        if expl:                                  # dashboard.py:1937
            sections.append(_explanations_html(expl))
    if signals:
        rows = {f"{s.get('symbol')} @ {s.get('timestamp', 0):.0f}":
                f"{s.get('decision')} ({s.get('confidence', 0):.2f})"
                for s in signals[-10:]}
        sections.append(_table(rows, "Recent signals"))
    if alerts:
        rows = {a["name"]: f"{a['severity']} — {a['description']}" for a in alerts}
        sections.append(_table(rows, "Active alerts"))

    body = "\n".join(sections) or "<p>no data yet</p>"
    refresh = (f'<meta http-equiv="refresh" content="{refresh_s:g}">'
               if refresh_s else "")
    return f"""<!doctype html><html><head><meta charset="utf-8">{refresh}
<title>ai_crypto_trader_tpu</title><style>
body{{background:#0a0a0a;color:#ddd;font-family:system-ui;margin:24px}}
.card{{background:#161616;border-radius:6px;padding:12px;margin:10px 0;
display:inline-block;vertical-align:top;min-width:280px;margin-right:10px}}
table{{border-collapse:collapse;font-size:13px}}
td{{padding:2px 10px;border-bottom:1px solid #222}}
h3{{margin:0 0 8px 0;font-size:14px;color:#8ac}}
</style></head><body>
<h2>ai_crypto_trader_tpu dashboard</h2>
<p style="color:#777">generated {time.strftime('%Y-%m-%d %H:%M:%S', time.gmtime(now_fn()))} UTC</p>
{body}
</body></html>"""


def write_dashboard(path: str, **kw) -> str:
    html_text = render_dashboard(**kw)
    with open(path, "w") as f:
        f.write(html_text)
    return path


def dump_state_json(bus, path: str) -> str:
    """Machine-readable state dump (the Redis-keys equivalent surface)."""
    state = {k: bus.get(k) for k in bus.keys("*")
             if isinstance(bus.get(k), (int, float, str, list, dict))}
    with open(path, "w") as f:
        json.dump(state, f, indent=2, default=str)
    return path
