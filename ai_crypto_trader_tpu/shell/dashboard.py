"""Dashboard: self-contained HTML snapshot of the whole system state.

Capability parity with `dashboard.py` (2 315 LoC Plotly Dash UI: price
chart, portfolio, signals feed, VaR, risk metrics, strategy state,
explanations) re-designed as a dependency-free static artifact: one call
renders bus state + backtest/MC results into a single HTML file with inline
SVG charts — servable by anything, regeneratable on a timer by the
launcher, and diffable in tests.  The live data plane is the bus KV, same
as the reference's Redis keys.
"""

from __future__ import annotations

import html
import json
import time

import numpy as np


def _svg_line(values, width=640, height=160, color="#2a7", label=""):
    v = np.asarray(values, dtype=float)
    if v.size < 2 or not np.isfinite(v).any():
        return "<svg/>"
    v = np.nan_to_num(v, nan=float(np.nanmean(v)))
    lo, hi = float(v.min()), float(v.max())
    rng = hi - lo or 1.0
    xs = np.linspace(4, width - 4, v.size)
    ys = height - 4 - (v - lo) / rng * (height - 8)
    pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in zip(xs, ys))
    return (f'<svg width="{width}" height="{height}" '
            f'style="background:#111;border-radius:6px">'
            f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
            f'points="{pts}"/>'
            f'<text x="8" y="16" fill="#999" font-size="11">{html.escape(label)}'
            f' [{lo:.2f} … {hi:.2f}]</text></svg>')


def _svg_heatmap(matrix, labels, cell=34, pad=70):
    """Correlation heatmap (the reference's dashboard.py:1712 panel):
    blue −1 … dark 0 … green +1, labels on both axes."""
    m = np.asarray(matrix, dtype=float)
    n = m.shape[0]
    if n == 0 or m.shape != (n, n):
        return "<svg/>"
    w = h = pad + n * cell + 4
    cells = []
    for i in range(n):
        for j in range(n):
            v = float(np.clip(np.nan_to_num(m[i, j]), -1.0, 1.0))
            if v >= 0:
                color = f"rgb({int(20 + 20 * v)},{int(40 + 150 * v)},{int(40 + 60 * v)})"
            else:
                color = f"rgb({int(40 - 20 * v)},{int(60 + 20 * v)},{int(60 - 150 * v)})"
            x, y = pad + j * cell, pad + i * cell
            cells.append(
                f'<rect x="{x}" y="{y}" width="{cell - 1}" height="{cell - 1}"'
                f' fill="{color}"><title>{html.escape(str(labels[i]))} / '
                f'{html.escape(str(labels[j]))}: {v:+.2f}</title></rect>'
                f'<text x="{x + cell / 2:.0f}" y="{y + cell / 2 + 3:.0f}" '
                f'fill="#ddd" font-size="9" text-anchor="middle">{v:+.2f}</text>')
    texts = []
    for i, lab in enumerate(labels):
        lab = html.escape(str(lab).replace("USDC", ""))
        texts.append(f'<text x="{pad - 6}" y="{pad + i * cell + cell / 2 + 3:.0f}"'
                     f' fill="#999" font-size="10" text-anchor="end">{lab}</text>')
        texts.append(f'<text x="{pad + i * cell + cell / 2:.0f}" y="{pad - 8}"'
                     f' fill="#999" font-size="10" text-anchor="middle" '
                     f'transform="rotate(-45 {pad + i * cell + cell / 2:.0f} '
                     f'{pad - 8})">{lab}</text>')
    return (f'<svg width="{w}" height="{h}" '
            f'style="background:#111;border-radius:6px">'
            + "".join(texts) + "".join(cells) + "</svg>")


def _np_ewm(v: np.ndarray, alpha: float, start: int = 0) -> np.ndarray:
    """Host-numpy ewm(alpha, adjust=False) seeded at `start` — the same
    recurrence as ops/indicators._ewm, so overlays agree with the published
    columns (no jit round-trip from a serving thread)."""
    out = np.empty_like(v)
    out[:start] = v[:start]
    acc = v[start] if start < v.size else 0.0
    for i in range(start, v.size):
        acc = alpha * v[i] + (1 - alpha) * acc
        out[i] = acc
    return out


def chart_overlays(closes) -> dict:
    """Display-only indicator overlays for the candlestick panel (the
    reference pulls bb_upper/middle/lower + RSI/MACD per candle from Redis,
    `dashboard.py:536-640`; here they're derived from the close series at
    render time — tiny numpy, no jit round-trip from a serving thread).

    RSI uses Wilder smoothing (alpha=1/14 seeded at t=1), matching
    ops/indicators.rsi — an EMA-smoothed display RSI visibly disagreed
    with the same page's published `rsi` columns (VERDICT r4 weak#7)."""
    c = np.asarray(closes, dtype=float)
    if c.size < 3:
        return {}
    n = min(20, c.size)
    kernel = np.ones(n) / n
    sma = np.convolve(c, kernel, mode="full")[:c.size]
    sma[:n - 1] = c[:n - 1]                    # warmup: track price
    dev = np.array([c[max(0, i - n + 1):i + 1].std() for i in range(c.size)])
    delta = np.diff(c, prepend=c[0])
    up = _np_ewm(np.maximum(delta, 0.0), 1.0 / 14.0, start=1)
    dn = _np_ewm(np.maximum(-delta, 0.0), 1.0 / 14.0, start=1)
    with np.errstate(divide="ignore", invalid="ignore"):
        rsi = np.where(dn == 0.0, np.where(up == 0.0, 50.0, 100.0),
                       100.0 - 100.0 / (1.0 + up / np.where(dn == 0.0, 1.0, dn)))
    macd = (_np_ewm(c, 2.0 / 13.0) - _np_ewm(c, 2.0 / 27.0))
    return {"bb_upper": sma + 2 * dev, "bb_middle": sma,
            "bb_lower": sma - 2 * dev, "rsi": rsi, "macd": macd}


def _svg_candlestick(klines, overlays: dict | None = None,
                     trades: list | None = None, width=920, height=300,
                     label="") -> str:
    """Candlestick chart with indicator overlays, trade markers, and a
    volume strip (the reference's main price panel, `dashboard.py:509-740`:
    go.Candlestick + BB traces + volume subplot; markers mirror its trade
    annotations). `klines` rows are the bus format [ts,o,h,l,c,vol,...]."""
    rows = list(klines or [])
    if len(rows) < 2:
        return "<svg/>"
    ts = np.asarray([r[0] for r in rows], dtype=float)
    o = np.asarray([r[1] for r in rows], dtype=float)
    h = np.asarray([r[2] for r in rows], dtype=float)
    l = np.asarray([r[3] for r in rows], dtype=float)
    c = np.asarray([r[4] for r in rows], dtype=float)
    vol = np.asarray([r[5] for r in rows], dtype=float) if len(rows[0]) > 5 \
        else np.zeros_like(c)
    n = len(rows)
    vol_h = 40
    price_h = height - vol_h - 8
    lo = float(np.nanmin([l.min()] + [np.nanmin(s) for k, s in (overlays or {}).items()
                                      if k.startswith("bb") and len(s) == n]))
    hi = float(np.nanmax([h.max()] + [np.nanmax(s) for k, s in (overlays or {}).items()
                                      if k.startswith("bb") and len(s) == n]))
    rng = hi - lo or 1.0

    def y(p):
        return 4 + (hi - p) / rng * (price_h - 8)

    step = (width - 8) / n
    cw = max(step * 0.6, 1.0)
    parts = []
    vmax = vol.max() or 1.0
    for i in range(n):
        x = 4 + i * step + step / 2
        up = c[i] >= o[i]
        color = "#2d5" if up else "#e55"
        parts.append(f'<line x1="{x:.1f}" y1="{y(h[i]):.1f}" x2="{x:.1f}" '
                     f'y2="{y(l[i]):.1f}" stroke="{color}" stroke-width="1"/>')
        top, bot = (c[i], o[i]) if up else (o[i], c[i])
        parts.append(
            f'<rect x="{x - cw / 2:.1f}" y="{y(top):.1f}" width="{cw:.1f}" '
            f'height="{max(y(bot) - y(top), 1.0):.1f}" fill="{color}"/>')
        vh = vol[i] / vmax * (vol_h - 4)
        parts.append(f'<rect x="{x - cw / 2:.1f}" y="{height - vh:.1f}" '
                     f'width="{cw:.1f}" height="{vh:.1f}" fill="#345" '
                     f'opacity="0.8"/>')
    overlay_colors = {"bb_upper": "#9cf", "bb_middle": "#ccc",
                      "bb_lower": "#9cf", "sma_20": "#fc6", "sma_50": "#c6f"}
    for name, series in (overlays or {}).items():
        s = np.asarray(series, dtype=float)
        if name in ("rsi", "macd") or len(s) != n:
            continue
        pts = " ".join(f"{4 + i * step + step / 2:.1f},{y(v):.1f}"
                       for i, v in enumerate(s) if np.isfinite(v))
        parts.append(f'<polyline fill="none" stroke='
                     f'"{overlay_colors.get(name, "#888")}" stroke-width="1" '
                     f'opacity="0.8" points="{pts}"/>')
    # trade markers: ▲ entry below the low, ▼ exit above the high
    # (time-matched into the visible window; clipped to the edge otherwise)
    for t in trades or []:
        for key, price_key, glyph, color in (
                ("opened_at", "entry_price", "▲", "#2d5"),
                ("closed_at", "exit_price", "▼", "#e55")):
            when = t.get(key)
            price = t.get(price_key)
            if when is None or price is None:
                continue
            # side='right' so a trade time exactly on a candle open lands
            # on THAT candle, not the one before
            i = int(np.clip(
                np.searchsorted(ts, float(when) * 1000.0, side="right") - 1,
                0, n - 1))
            x = 4 + i * step + step / 2
            yy = y(float(price))
            parts.append(
                f'<text x="{x:.1f}" y="{yy:.1f}" fill="{color}" '
                f'font-size="12" text-anchor="middle">{glyph}'
                f'<title>{html.escape(t.get("symbol", ""))} '
                f'{html.escape(key.split("_")[0])} @ {float(price):,.2f}'
                f'{" pnl " + format(t.get("pnl"), ",.2f") if key == "closed_at" and t.get("pnl") is not None else ""}'
                f'</title></text>')
    parts.append(f'<text x="8" y="16" fill="#999" font-size="11">'
                 f'{html.escape(label)} [{lo:.2f} … {hi:.2f}]</text>')
    return (f'<svg width="{width}" height="{height}" '
            f'style="background:#111;border-radius:6px">'
            + "".join(parts) + "</svg>")


def _svg_allocation(values: dict, width=420, height=26) -> str:
    """Portfolio allocation as a stacked horizontal bar + weights table
    (the reference's allocation panel, `dashboard.py:1131` family)."""
    vals = {k: float(v) for k, v in values.items() if v and v > 0}
    total = sum(vals.values())
    if total <= 0:
        return ""
    palette = ["#4af", "#2a7", "#fa4", "#e66", "#c6f", "#9cf", "#fc6"]
    x = 0.0
    segs = []
    rows = {}
    for i, (asset, v) in enumerate(sorted(vals.items(), key=lambda t: -t[1])):
        w = v / total * width
        color = palette[i % len(palette)]
        segs.append(f'<rect x="{x:.1f}" y="0" width="{w:.1f}" '
                    f'height="{height}" fill="{color}">'
                    f'<title>{html.escape(asset)}: {v:,.2f} '
                    f'({v / total:.1%})</title></rect>')
        rows[f"<span style='color:{color}'>■</span> {html.escape(asset)}"] = \
            f"{v:,.2f} ({v / total:.1%})"
        x += w
    bar = (f'<svg width="{width}" height="{height}" '
           f'style="border-radius:4px">' + "".join(segs) + "</svg>")
    body = "".join(f"<tr><td>{k}</td><td style='text-align:right'>"
                   f"{html.escape(v)}</td></tr>" for k, v in rows.items())
    return (f"<div class='card'><h3>Portfolio allocation</h3>{bar}"
            f"<table>{body}</table></div>")


def _model_comparison_html(versions: list, width=420) -> str:
    """Model-version comparison panel (the reference's AI-model performance
    chart + registry comparison, `dashboard.py:1174-1260`,
    `model_registry_service.py:355`): per-version bar of the ranking metric
    + status table."""
    rows = []
    for e in versions:
        perf = e.get("performance") or {}
        sharpe = perf.get("sharpe_ratio")
        rows.append((e.get("version", "?"), e.get("kind", "?"),
                     e.get("status", "?"),
                     float(sharpe) if sharpe is not None else None))
    if not rows:
        return ""
    rows = rows[-10:]                  # scale bars over the DISPLAYED rows
    scored = [r for r in rows if r[3] is not None]
    best = max((r[3] for r in scored), default=0.0)
    worst = min((r[3] for r in scored), default=0.0)
    rng = (best - worst) or 1.0
    parts = []
    for v, kind, status, sharpe in rows:
        if sharpe is None:
            bar = "<td style='color:#666'>unscored</td>"
        else:
            w = max((sharpe - worst) / rng * 160, 2)
            color = "#2a7" if sharpe == best else "#47a"
            bar = (f"<td><svg width='170' height='12'>"
                   f"<rect width='{w:.0f}' height='12' fill='{color}'/>"
                   f"</svg> {sharpe:.3f}</td>")
        parts.append(f"<tr><td>{html.escape(str(v))}</td>"
                     f"<td>{html.escape(str(kind))}</td>"
                     f"<td>{html.escape(str(status))}</td>{bar}</tr>")
    return ("<div class='card'><h3>Model versions</h3>"
            "<table><tr><th>version</th><th>kind</th><th>status</th>"
            "<th>sharpe</th></tr>" + "".join(parts) + "</table></div>")


def _explanations_html(explanations: list) -> str:
    """Explanation drill-down (the reference's AI-explanation modal,
    dashboard.py:1937): a <details> disclosure per signal with the factor
    table inside — click to drill in."""
    items = []
    for e in explanations[-8:][::-1]:
        head = (f"{e.get('symbol', '?')} {e.get('decision', '?')} "
                f"(conf {float(e.get('confidence') or 0.0):.2f})")
        factors = e.get("factors") or e.get("factor_weights") or {}

        def cell(v):
            if isinstance(v, dict):               # explain_signal factor row
                return (f"{v.get('value', 0):,.2f} ({v.get('reading', '')}) "
                        f"× {v.get('weight', 0):.2f}")
            return _fmt(v)

        rows = "".join(
            f"<tr><td>{html.escape(str(k))}</td>"
            f"<td style='text-align:right'>{html.escape(cell(v))}</td></tr>"
            for k, v in (factors.items() if isinstance(factors, dict)
                         else enumerate(factors)))
        summary = html.escape(str(e.get("narrative", ""))[:300])
        items.append(
            f"<details><summary>{html.escape(head)}</summary>"
            f"<p style='color:#999;font-size:12px'>{summary}</p>"
            f"<table>{rows}</table></details>")
    if not items:
        return ""
    return ("<div class='card'><h3>AI explanations</h3>"
            + "".join(items) + "</div>")


def _news_html(aggregate: dict | None, recent: list | None) -> str:
    """News panel (the reference's news feed + sentiment summary,
    `dashboard.py:91-99` news channel subscription and its rendered feed):
    impact-weighted aggregate header plus the recent-headline list with
    per-article direction/impact."""
    parts = []
    colors = {"bullish": "#2d5", "bearish": "#e55", "neutral": "#999"}
    if aggregate:
        direction = str(aggregate.get("direction", "neutral"))
        topics = ", ".join(aggregate.get("top_topics") or []) or "—"
        parts.append(
            f"<p><span style='color:{colors.get(direction, '#999')}'>"
            f"{html.escape(direction)}</span> · sentiment "
            f"{float(aggregate.get('sentiment') or 0.0):+.2f} · impact "
            f"{float(aggregate.get('market_impact') or 0.0):.2f} · "
            f"{int(aggregate.get('n_articles') or 0)} articles · topics: "
            f"{html.escape(topics)}</p>")
    for a in (recent or [])[-8:][::-1]:
        direction = str(a.get("direction", "neutral"))
        parts.append(
            f"<p style='margin:3px 0;font-size:12px'>"
            f"<span style='color:{colors.get(direction, '#999')}'>●</span> "
            f"{html.escape(str(a.get('title', ''))[:120])} "
            f"<span style='color:#777'>impact "
            f"{float(a.get('market_impact') or 0.0):.2f}</span></p>")
    if not parts:
        return ""
    return "<div class='card'><h3>News</h3>" + "".join(parts) + "</div>"


def _patterns_html(signal: dict | None, report: dict | None) -> str:
    """Pattern-signal panel (the reference subscribes `pattern_signals` and
    renders the recognition feed, `dashboard.py:91-99` + pattern panels):
    the symbol's latest actionable signal plus the combined report's
    per-symbol feed and summary counts."""
    parts = []
    colors = {"buy": "#2d5", "sell": "#e55", "neutral": "#999"}
    if signal and signal.get("signal", "neutral") != "neutral":
        sig = str(signal.get("signal"))
        parts.append(
            f"<p><span style='color:{colors.get(sig, '#999')}'>"
            f"{html.escape(sig.upper())}</span> "
            f"{html.escape(str(signal.get('pattern', '?')))} "
            f"({html.escape(str(signal.get('signal_strength', '')))}, "
            f"strength {float(signal.get('strength') or 0.0):.2f}, "
            f"completion {float(signal.get('completion') or 0.0):.0f}%)</p>")
        confirmation = signal.get("confirmation")
        if confirmation:
            parts.append(f"<p style='color:#777;font-size:12px'>confirm: "
                         f"{html.escape(str(confirmation))}</p>")
    if report:
        summary = report.get("summary") or {}
        if summary:
            parts.append(
                f"<p style='font-size:12px'>bullish "
                f"{summary.get('bullish_patterns', 0)} · bearish "
                f"{summary.get('bearish_patterns', 0)} · neutral "
                f"{summary.get('neutral_patterns', 0)}</p>")
        rows = {}
        for sym, s in (report.get("signals") or {}).items():
            rows[sym] = (f"{s.get('signal', '?')} {s.get('pattern', '')} "
                         f"({float(s.get('strength') or 0.0):.2f})")
        if rows:
            body = "".join(
                f"<tr><td>{html.escape(str(k))}</td>"
                f"<td style='text-align:right'>{html.escape(v)}</td></tr>"
                for k, v in rows.items())
            parts.append(f"<table>{body}</table>")
    if not parts:
        return ""
    return ("<div class='card'><h3>Pattern signals</h3>"
            + "".join(parts) + "</div>")


def _traces_html(traces: list) -> str:
    """Recent-traces card (the observability counterpart of the reference's
    unchecked Jaeger TODO): one <details> disclosure per trace with the
    span tree inside — stage, service, duration, compile/execute split
    where the span recorded one (model/backtest dispatches)."""
    items = []
    for t in traces[:8]:
        head = (f"{t.get('root', '?')} · {t.get('n_spans', 0)} spans · "
                f"{float(t.get('duration_s') or 0.0) * 1000:.1f} ms · "
                f"{str(t.get('trace_id', ''))[:8]}")
        rows = []
        spans = sorted(t.get("spans") or [], key=lambda s: s.get("start", 0))
        for s in spans:
            dur = ((s.get("end") or 0) - (s.get("start") or 0)) * 1000
            attrs = s.get("attributes") or {}
            extra = ""
            if "compile_s" in attrs:
                extra = (f" (compile {float(attrs['compile_s']) * 1000:.1f} ms"
                         f" / execute {float(attrs.get('execute_s') or 0.0) * 1000:.1f} ms)")
            elif attrs.get("symbol"):
                extra = f" [{attrs['symbol']}]"
            marker = "└ " if s.get("parent_id") else ""
            rows.append(
                f"<tr><td>{html.escape(marker + str(s.get('name', '?')))}</td>"
                f"<td>{html.escape(str(s.get('service') or ''))}</td>"
                f"<td style='text-align:right'>{dur:.2f} ms"
                f"{html.escape(extra)}</td></tr>")
        items.append(
            f"<details><summary>{html.escape(head)}</summary>"
            f"<table><tr><th>span</th><th>service</th><th>duration</th></tr>"
            + "".join(rows) + "</table></details>")
    if not items:
        return ""
    return ("<div class='card'><h3>Recent traces</h3>"
            + "".join(items) + "</div>")


def _table(rows: dict, title: str) -> str:
    body = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td style='text-align:right'>{html.escape(_fmt(v))}</td></tr>"
        for k, v in rows.items())
    return (f"<div class='card'><h3>{html.escape(title)}</h3>"
            f"<table>{body}</table></div>")


def _fmt(v):
    if isinstance(v, float):
        return f"{v:,.4f}" if abs(v) < 100 else f"{v:,.2f}"
    return str(v)


def render_dashboard(bus=None, *, price_series=None, equity_curve=None,
                     metrics: dict | None = None, mc_stats: dict | None = None,
                     signals: list | None = None, alerts: list | None = None,
                     regime: dict | None = None, refresh_s: float | None = None,
                     klines=None, trades: list | None = None,
                     allocation: dict | None = None,
                     model_versions: list | None = None,
                     symbol: str | None = None,
                     symbol_links: list | None = None,
                     traces: list | None = None,
                     decisions: list | None = None,
                     now_fn=time.time) -> str:
    """Return the dashboard HTML. Every section is optional — sections
    render from whatever state exists (like the reference's per-callback
    panels tolerating missing Redis keys). `refresh_s` adds a meta-refresh
    so a served page polls like the reference's 5 s Dash interval.

    `klines` (bus rows) renders the reference's main panel — candlestick
    with BB overlays, RSI/MACD subpanels, volume strip, and trade markers
    from `trades` records (`dashboard.py:509-740`); `allocation` the
    portfolio-allocation card; `model_versions` the registry comparison."""
    sections = []
    if symbol_links:
        links = " · ".join(
            f'<a style="color:#8ac" href="/?symbol={html.escape(s)}">'
            f'{html.escape(s)}</a>' for s in symbol_links)
        sections.append(f"<p>{links} &nbsp; <span style='color:#777'>"
                        "(window via ?window=N candles)</span></p>")
    if klines:
        closes = [row[4] for row in klines]
        ov = chart_overlays(closes)
        sections.append(_svg_candlestick(
            klines, ov, trades, label=symbol or "price"))
        if "rsi" in ov:
            sections.append(_svg_line(ov["rsi"], height=80, label="RSI 14",
                                      color="#fc6"))
        if "macd" in ov:
            sections.append(_svg_line(ov["macd"], height=80, label="MACD",
                                      color="#c6f"))
    elif price_series is not None:
        sections.append(_svg_line(price_series, label="price", color="#4af"))
    if allocation:
        sections.append(_svg_allocation(allocation))
    if model_versions:
        sections.append(_model_comparison_html(model_versions))
    if equity_curve is not None:
        sections.append(_svg_line(equity_curve, label="equity", color="#2a7"))
    if metrics:
        sections.append(_table(metrics, "Backtest / portfolio metrics"))
    if mc_stats:
        sections.append(_table(mc_stats, "Monte-Carlo risk"))
    if regime:
        sections.append(_table(regime, "Market regime"))
    if bus is not None:
        params = bus.get("strategy_params")
        if params:
            sections.append(_table(params, "Live strategy parameters"))
        structure = bus.get("strategy_structure")
        if structure and isinstance(structure.get("rules"), dict):
            # adopted rule-graph structure (GeneratorService hot swap) +
            # its live evaluation from the monitor's market_data columns;
            # non-numeric weights render as-is (a bad payload must degrade,
            # never take down the whole page)
            rows = {f"rule: {name}": (f"{weight:+.2f}"
                                      if isinstance(weight, (int, float))
                                      else str(weight))
                    for name, weight in sorted(structure["rules"].items(),
                                               key=lambda kv: str(kv[0]))}
            rows["thresholds"] = (f"buy ≥ {structure.get('buy_threshold', 0)}"
                                  f" / sell ≤ -{structure.get('sell_threshold', 0)}")
            rows["exits"] = (f"SL {structure.get('stop_loss', 0)}% / "
                             f"TP {structure.get('take_profit', 0)}%")
            if structure.get("version"):
                rows["version"] = structure["version"]
            md = bus.get(f"market_data_{symbol}") if symbol else None
            # only pair the live blend with the structure it was computed
            # against — right after a hot swap the monitor's last poll
            # still reflects the PREVIOUS structure. Version must be
            # truthy: registry-less adoptions carry version None on BOTH
            # sides, which would false-match across a swap.
            if (md and isinstance(md.get("structure_blend"), (int, float))
                    and structure.get("version")
                    and md.get("structure_version") == structure.get("version")):
                rows["live blend"] = (f"{md['structure_blend']:+.4f} → "
                                      f"{md.get('structure_signal', '?')}")
            sections.append(_table(rows, "Adopted strategy structure"))
        trades = bus.get("active_trades")
        if trades:
            sections.append(_table({s: f"entry {t.get('entry_price', 0):,.2f}"
                                    for s, t in trades.items()}, "Active trades"))
        # --- reference dashboard.py parity panels ---
        pv = bus.get("portfolio_value_history")
        if pv and len(pv) >= 2:                   # portfolio value chart
            sections.append(_svg_line([p["value"] for p in pv],
                                      label="portfolio value", color="#fa4"))
        live_regime = bus.get("market_regime")
        if (not regime and live_regime            # regime panel (skip when a
                and isinstance(live_regime, dict)):  # snapshot was passed in)
            sections.append(_table(
                {k: v for k, v in live_regime.items()
                 if isinstance(v, (int, float, str))}, "Market regime"))
        risk = bus.get("risk_metrics")
        if risk:
            sections.append(_table(risk, "Portfolio risk"))
        var_hist = bus.get("var_history")
        if var_hist and len(var_hist) >= 2:       # dashboard.py:1485
            sections.append(_svg_line([p["var_95"] for p in var_hist],
                                      label="VaR 95% history", color="#e66"))
        corr = bus.get("correlation_matrix")
        if corr and corr.get("symbols"):          # dashboard.py:1712
            sections.append(
                "<div class='card'><h3>Asset correlation</h3>"
                + _svg_heatmap(corr["matrix"], corr["symbols"]) + "</div>")
        expl = bus.get("explanations")
        if expl:                                  # dashboard.py:1937
            sections.append(_explanations_html(expl))
        # --- social / news / pattern feeds (reference dashboard.py:91-99
        # subscribes social_updates, news and pattern_signals channels) ---
        if symbol:
            sh = bus.get(f"social_history_{symbol}")
            if sh and len(sh) >= 2:               # sentiment time series
                sections.append(_svg_line(
                    [row[1] for row in sh], height=80,
                    label=f"social sentiment {symbol}", color="#4af"))
            soc = bus.get(f"social_metrics_{symbol}")
            if soc:                               # latest source breakdown
                sections.append(_table(
                    {k: v for k, v in soc.items()
                     if isinstance(v, (int, float, str, bool))},
                    "Social metrics"))
            news_panel = _news_html(bus.get(f"news_analysis_{symbol}"),
                                    bus.get(f"news_recent_{symbol}"))
            if news_panel:
                sections.append(news_panel)
        pattern_panel = _patterns_html(
            bus.get(f"pattern_signals_{symbol}") if symbol else None,
            bus.get("pattern_analysis_report"))
        if pattern_panel:
            sections.append(pattern_panel)
        # --- trading-quality observatory (obs/) ---
        attribution = bus.get("pnl_attribution")
        if attribution and attribution.get("family"):
            rows = {src: f"pnl {v['pnl']:+,.2f} · {v['trades']} trades · "
                         f"win {v['win_rate']:.0%}"
                    for src, v in sorted(
                        attribution["family"].items(),
                        key=lambda kv: -kv[1]["pnl"])}
            sections.append(_table(rows, "PnL attribution (signal family)"))
        scorecard = bus.get("model_scorecard")
        if scorecard:
            rows = {group: (f"dir {sc['directional_accuracy']:.0%} · hit "
                            f"{sc['hit_rate']:.0%} · brier {sc['brier']:.3f}"
                            f" · n={sc['n']}")
                    for group, sc in sorted(scorecard.items())}
            sections.append(_table(rows, "Model scorecard (live outcomes)"))
    if signals:
        rows = {f"{s.get('symbol')} @ {s.get('timestamp', 0):.0f}":
                f"{s.get('decision')} ({s.get('confidence', 0):.2f})"
                for s in signals[-10:]}
        sections.append(_table(rows, "Recent signals"))
    if decisions:
        from ai_crypto_trader_tpu.obs.flightrec import format_why

        rows = "".join(f"<div style='font-family:monospace;font-size:12px'>"
                       f"{html.escape(line)}</div>"
                       for line in format_why(decisions))
        sections.append(f"<div class='card'><h3>Recent decisions "
                        f"(flight recorder)</h3>{rows}</div>")
    if traces:
        trace_panel = _traces_html(traces)
        if trace_panel:
            sections.append(trace_panel)
    if alerts:
        rows = {a["name"]: f"{a['severity']} — {a['description']}" for a in alerts}
        sections.append(_table(rows, "Active alerts"))

    body = "\n".join(sections) or "<p>no data yet</p>"
    refresh = (f'<meta http-equiv="refresh" content="{refresh_s:g}">'
               if refresh_s else "")
    return f"""<!doctype html><html><head><meta charset="utf-8">{refresh}
<title>ai_crypto_trader_tpu</title><style>
body{{background:#0a0a0a;color:#ddd;font-family:system-ui;margin:24px}}
.card{{background:#161616;border-radius:6px;padding:12px;margin:10px 0;
display:inline-block;vertical-align:top;min-width:280px;margin-right:10px}}
table{{border-collapse:collapse;font-size:13px}}
td{{padding:2px 10px;border-bottom:1px solid #222}}
h3{{margin:0 0 8px 0;font-size:14px;color:#8ac}}
</style></head><body>
<h2>ai_crypto_trader_tpu dashboard</h2>
<p style="color:#777">generated {time.strftime('%Y-%m-%d %H:%M:%S', time.gmtime(now_fn()))} UTC</p>
{body}
</body></html>"""


def write_dashboard(path: str, **kw) -> str:
    html_text = render_dashboard(**kw)
    with open(path, "w") as f:
        f.write(html_text)
    return path


def dump_state_json(bus, path: str) -> str:
    """Machine-readable state dump (the Redis-keys equivalent surface)."""
    state = {k: bus.get(k) for k in bus.keys("*")
             if isinstance(bus.get(k), (int, float, str, list, dict))}
    with open(path, "w") as f:
        json.dump(state, f, indent=2, default=str)
    return path
