"""Live dashboard HTTP server.

Parity with the reference's continuously-refreshing Dash UI on :8050
(`dashboard.py:442-2266`, redis_listener :89-133, ~25 polling callbacks,
5 s refresh): a stdlib ThreadingHTTPServer that re-renders the dashboard
from live bus state on EVERY request — the polling pull model the Dash
callbacks implement, without taking on the Dash dependency. Endpoints:

  /            HTML dashboard (meta-refresh = the Dash interval component)
  /state.json  machine-readable bus state (the Redis-keys surface the
               reference's callbacks read)
  /metrics     Prometheus text exposition (reference: aiohttp /metrics,
               `services/utils/metrics.py:189-221`)
  /health      heartbeat/liveness JSON (reference: per-service TCP health
               listeners, e.g. `services/monte_carlo_service.py:825-845`)

The server runs in a daemon thread; `port=0` binds an ephemeral port
(tests). Reads of live bus dicts from the serving thread are safe under
the GIL (same consistency model as the reference's Redis polling — a
render may see a mid-tick snapshot, never a torn value).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ai_crypto_trader_tpu.shell.dashboard import render_dashboard


class DashboardServer:
    """Serve a TradingSystem's live state over HTTP."""

    def __init__(self, system, port: int = 8050, refresh_s: float = 5.0):
        self.system = system
        self.refresh_s = refresh_s
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):      # quiet: no stderr per request
                pass

            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Cache-Control", "no-store")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/":
                        self._send(outer.render_html().encode(),
                                   "text/html; charset=utf-8")
                    elif path == "/state.json":
                        self._send(json.dumps(outer.state(),
                                              default=str).encode(),
                                   "application/json")
                    elif path == "/metrics":
                        self._send(outer.system.metrics.exposition().encode(),
                                   "text/plain; version=0.0.4")
                    elif path == "/health":
                        self._send(json.dumps(outer.health()).encode(),
                                   "application/json")
                    else:
                        self._send(b"not found", "text/plain", 404)
                except Exception as exc:               # noqa: BLE001
                    self._send(f"render error: {exc}".encode(),
                               "text/plain", 500)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # --- view assembly ------------------------------------------------------
    def render_html(self) -> str:
        # Handler threads read ONLY launcher/bus state (GIL-safe snapshot
        # reads) — never the exchange: that would burn trading rate-limit
        # tokens and perturb virtual clocks from a foreign thread.
        system = self.system
        sym = system.symbols[0] if system.symbols else None
        klines = (system.bus.get(f"historical_data_{sym}_1m") or []) if sym else []
        prices = [row[4] for row in klines] if klines else None
        signals = [system.bus.get(f"latest_signal_{s}")
                   for s in system.symbols]
        status = system.status_cached()
        return render_dashboard(
            bus=system.bus,
            price_series=prices,
            metrics={"portfolio_value_usd": status.get(
                         "portfolio_value_usd",
                         status["balances"].get("USDC", 0.0)),
                     "closed_trades": status["closed_trades"],
                     "total_pnl": status["total_pnl"],
                     "open_positions": len(status["active_trades"])},
            signals=[s for s in signals if s],
            alerts=list(system.alerts.active.values()),
            refresh_s=self.refresh_s,
            now_fn=system.now_fn)

    def state(self) -> dict:
        system = self.system
        bus_state = {k: system.bus.get(k) for k in system.bus.keys("*")
                     if isinstance(system.bus.get(k),
                                   (int, float, str, list, dict))}
        return {"status": system.status_cached(), "bus": bus_state}

    def health(self) -> dict:
        return {"healthy": all(self.system.heartbeats.health().values())
                if self.system.heartbeats.health() else True,
                "services": self.system.heartbeats.health()}

    # --- lifecycle ----------------------------------------------------------
    def start(self) -> "DashboardServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dashboard", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
