"""Live dashboard HTTP server.

Parity with the reference's continuously-refreshing Dash UI on :8050
(`dashboard.py:442-2266`, redis_listener :89-133, ~25 polling callbacks,
5 s refresh): a stdlib ThreadingHTTPServer that re-renders the dashboard
from live bus state on EVERY request — the polling pull model the Dash
callbacks implement, without taking on the Dash dependency. Endpoints:

  /            HTML dashboard (meta-refresh = the Dash interval component)
  /state.json  machine-readable bus state (the Redis-keys surface the
               reference's callbacks read)
  /metrics     Prometheus text exposition (reference: aiohttp /metrics,
               `services/utils/metrics.py:189-221`)
  /health      heartbeat/liveness JSON (reference: per-service TCP health
               listeners, e.g. `services/monte_carlo_service.py:825-845`)
  /profile     on-demand device profiler capture: ?seconds=N runs
               `jax.profiler.trace` for N wall seconds WHILE the system
               keeps ticking and returns the TensorBoard-loadable XPlane
               artifact path (single-capture guard: a second concurrent
               request gets 409)

The server runs in a daemon thread; `port=0` binds an ephemeral port
(tests). Reads of live bus dicts from the serving thread are safe under
the GIL (same consistency model as the reference's Redis polling — a
render may see a mid-tick snapshot, never a torn value).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ai_crypto_trader_tpu.shell.dashboard import render_dashboard

# /profile bounds: a zero-length capture produces an empty artifact, an
# unbounded one wedges the handler thread (and the profiler) for hours
MIN_PROFILE_S = 0.05
MAX_PROFILE_S = 60.0


class DashboardServer:
    """Serve a TradingSystem's live state over HTTP."""

    def __init__(self, system, port: int = 8050, refresh_s: float = 5.0,
                 profile_dir: str = "profiles"):
        self.system = system
        self.refresh_s = refresh_s
        self.profile_dir = profile_dir
        self._profile_lock = threading.Lock()   # single-capture guard
        self._profile_seq = 0                   # unique artifact names
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):      # quiet: no stderr per request
                pass

            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Cache-Control", "no-store")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import parse_qs, urlsplit

                parts = urlsplit(self.path)
                path = parts.path
                q = parse_qs(parts.query)
                try:
                    if path == "/":
                        # ?symbol=X&window=N — the reference's symbol
                        # dropdown + historical window selection as query
                        # params (`dashboard.py` dcc.Dropdown / time range)
                        symbol = q.get("symbol", [None])[0]
                        try:
                            window = int(q.get("window", [0])[0])
                            window = window if window > 0 else None
                        except ValueError:
                            window = None
                        self._send(outer.render_html(
                            symbol=symbol, window=window).encode(),
                                   "text/html; charset=utf-8")
                    elif path == "/state.json":
                        self._send(json.dumps(outer.state(),
                                              default=str).encode(),
                                   "application/json")
                    elif path == "/traces":
                        # recent traces from the tracer's ring (empty list
                        # when tracing is disabled): the JSON twin of the
                        # span JSONL export, grouped by trace_id
                        try:
                            # floor at 0: a negative value would invert the
                            # ring slice and return everything BUT the
                            # newest traces
                            limit = max(int(q.get("limit", [20])[0]), 0)
                        except ValueError:
                            limit = 20
                        self._send(json.dumps(outer.traces(limit),
                                              default=str).encode(),
                                   "application/json")
                    elif path == "/decisions":
                        # decision-provenance query (obs/flightrec.py):
                        # ?symbol=X&trace_id=Y&lane=N&limit=M over the
                        # recorder's ring — signal→order→fill→PnL per
                        # decision; `lane` filters a vmapped tenant
                        # lane's sampled provenance (obs/fleetscope.py)
                        try:
                            limit = max(int(q.get("limit", [50])[0]), 0)
                        except ValueError:
                            limit = 50
                        try:
                            lane = (int(q["lane"][0]) if "lane" in q
                                    else None)
                        except ValueError:
                            lane = None
                        self._send(json.dumps(outer.decisions(
                            symbol=q.get("symbol", [None])[0],
                            trace_id=q.get("trace_id", [None])[0],
                            lane=lane,
                            limit=limit), default=str).encode(),
                                   "application/json")
                    elif path == "/profile":
                        try:
                            seconds = float(q.get("seconds", ["1"])[0])
                        except ValueError:
                            seconds = 1.0
                        if not math.isfinite(seconds):
                            seconds = 1.0   # nan/inf survive min/max clamps
                        seconds = min(max(seconds, MIN_PROFILE_S),
                                      MAX_PROFILE_S)
                        out = outer.profile(seconds)
                        if out is None:
                            self._send(json.dumps(
                                {"error": "capture already in progress"}
                            ).encode(), "application/json", 409)
                        else:
                            self._send(json.dumps(out).encode(),
                                       "application/json")
                    elif path == "/metrics":
                        self._send(outer.system.metrics.exposition().encode(),
                                   "text/plain; version=0.0.4")
                    elif path == "/health":
                        self._send(json.dumps(outer.health()).encode(),
                                   "application/json")
                    else:
                        self._send(b"not found", "text/plain", 404)
                except Exception as exc:               # noqa: BLE001
                    self._send(f"render error: {exc}".encode(),
                               "text/plain", 500)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # --- view assembly ------------------------------------------------------
    def render_html(self, symbol: str | None = None,
                    window: int | None = None) -> str:
        # Handler threads read ONLY launcher/bus state (GIL-safe snapshot
        # reads) — never the exchange: that would burn trading rate-limit
        # tokens and perturb virtual clocks from a foreign thread.
        system = self.system
        sym = (symbol if symbol in system.symbols else
               (system.symbols[0] if system.symbols else None))
        klines = (system.bus.get(f"historical_data_{sym}_1m") or []) if sym else []
        if window:
            klines = klines[-window:]
        signals = [system.bus.get(f"latest_signal_{s}")
                   for s in system.symbols]
        status = system.status_cached()
        # allocation: the same marking rule as the launcher's portfolio
        # gauge (shared helper — dedup by base, no hardcoded quote)
        from ai_crypto_trader_tpu.utils.symbols import mark_holdings

        allocation = mark_holdings(
            dict(status["balances"]), system.symbols,
            lambda s: system.bus.get(f"market_data_{s}"))
        # trade markers: closed + open trades from the executor's books
        # (atomic list() snapshots — the asyncio loop mutates these dicts
        # while handler threads render)
        trades = [t for t in list(system.executor.closed_trades)
                  if t.get("symbol") == sym]
        for s, t in list(system.executor.active_trades.items()):
            if s == sym:
                trades.append({"symbol": s, "entry_price": t.entry_price,
                               "opened_at": t.opened_at})
        registry = getattr(system, "registry", None)
        versions = (list(registry.entries.values())
                    if registry is not None else None)
        traces = self.traces(limit=8)
        return render_dashboard(
            decisions=self.decisions(symbol=sym, limit=8) or None,
            traces=traces or None,
            bus=system.bus,
            klines=klines,
            trades=trades,
            symbol=sym,
            symbol_links=(system.symbols
                          if len(system.symbols) > 1 else None),
            allocation=allocation,
            model_versions=versions,
            metrics={"portfolio_value_usd": status.get(
                         "portfolio_value_usd",
                         status["balances"].get("USDC", 0.0)),
                     "closed_trades": status["closed_trades"],
                     "total_pnl": status["total_pnl"],
                     "open_positions": len(status["active_trades"])},
            signals=[s for s in signals if s],
            alerts=list(system.alerts.active.values()),
            refresh_s=self.refresh_s,
            now_fn=system.now_fn)

    def traces(self, limit: int = 20) -> list:
        tracer = getattr(self.system, "tracer", None)
        return tracer.traces(limit=limit) if tracer is not None else []

    def decisions(self, symbol: str | None = None,
                  trace_id: str | None = None, limit: int = 50,
                  lane: int | None = None) -> list:
        fr = getattr(self.system, "flightrec", None)
        if fr is None:
            return []
        return fr.query(symbol=symbol, trace_id=trace_id, limit=limit,
                        lane=lane)

    def profile(self, seconds: float) -> dict | None:
        """On-demand XPlane capture: `jax.profiler.trace` for ``seconds``
        of wall clock while the system keeps ticking on its own loop.
        Returns None when a capture is already running (the guard: jax
        supports exactly one active profiler session per process)."""
        if not self._profile_lock.acquire(blocking=False):
            return None
        try:
            from ai_crypto_trader_tpu.utils import profiling

            self._profile_seq += 1
            artifact = os.path.join(
                self.profile_dir,
                time.strftime("xplane_%Y%m%d_%H%M%S")
                + f"_{self._profile_seq:03d}")
            os.makedirs(artifact, exist_ok=True)
            t0 = time.perf_counter()
            with profiling.trace(artifact):
                time.sleep(seconds)
            return {"artifact": artifact,
                    "seconds": round(time.perf_counter() - t0, 3),
                    "requested_s": seconds}
        finally:
            self._profile_lock.release()

    def state(self) -> dict:
        system = self.system
        bus_state = {k: system.bus.get(k) for k in system.bus.keys("*")
                     if isinstance(system.bus.get(k),
                                   (int, float, str, list, dict))}
        out = {"status": system.status_cached(), "bus": bus_state}
        devprof = getattr(system, "devprof", None)
        if devprof is not None:
            # cost cards / SLO summaries / donation results / watermarks
            out["devprof"] = devprof.status()
        flightrec = getattr(system, "flightrec", None)
        if flightrec is not None:
            out["flightrec"] = flightrec.status()
        # mesh runtime (utils/meshprof.py + parallel/partitioner.py): the
        # active partitioner layout is surfaced even when the observatory
        # is off — operators must be able to see mesh shape / device
        # kinds without a REPL (ISSUE 12 satellite) — and the sentinel /
        # layout-card state rides along when meshprof is enabled.
        mesh_block = {}
        try:
            from ai_crypto_trader_tpu.parallel import get_partitioner

            mesh_block["partitioner"] = get_partitioner().describe()
        except Exception:                      # noqa: BLE001 — backend
            pass                               # unavailable: sentinel-only
        meshprof = getattr(system, "meshprof", None)
        if meshprof is not None:
            mesh_block.update(meshprof.status())
        if mesh_block:
            out["mesh"] = mesh_block
        saturation = getattr(system, "saturation", None)
        if saturation is not None:
            # load & capacity observatory (utils/saturation.py): stage
            # duty cycles, bus utilization/watermarks, scatter occupancy,
            # host-readback share, event-loop lag
            out["capacity"] = saturation.status()
        fleet = getattr(system, "fleetscope", None)
        if fleet is not None:
            # fleet observatory (obs/fleetscope.py): device-aggregated
            # lane telemetry — gate mix, dispersion quantiles, top-k
            # rank table, starvation/drift — O(gates + quantiles + K)
            # JSON regardless of tenant count (`cli fleet --url` reads
            # this block)
            out["fleet"] = fleet.status()
        tickpath = getattr(system, "tickpath", None)
        if tickpath is not None:
            # decision critical-path observatory (obs/tickpath.py): per-tick
            # phase waterfall, overlap headroom, event→decision age, and the
            # per-program cold-start ledger (`cli latency --url` reads these
            # two blocks)
            out["tickpath"] = tickpath.status()
            out["coldstart"] = tickpath.coldstart_status()
        aot = getattr(system, "aot_cache", None)
        if aot is not None:
            # persistent AOT compile cache (utils/aotcache.py): whether
            # this restart replayed the hot set (warm), where the
            # provenance-keyed directory points, and why the cache is
            # off when it's off
            out["aot_cache"] = aot.status()
        build = getattr(system, "build_info", None)
        if build is not None:
            # process provenance: start time, jax version, backend, device
            # kind — pins *what* produced every number above (`cli status`)
            out["build"] = dict(build)
        scorecard = getattr(system, "scorecard", None)
        if scorecard is not None:
            sc = scorecard.status()
            out["scorecard"] = {k: v for k, v in sc.items() if k != "groups"} \
                | {"groups": {k: dict(v) for k, v in sc["groups"].items()}}
        # continuous PBT training service (rl/trainer_service.py): where
        # the fleet is, who is quarantined, checkpoint/recalibration age
        # (`cli status --url` renders this block)
        for svc in getattr(system, "extra_services", []) or []:
            if getattr(svc, "name", "") == "trainer" \
                    and hasattr(svc, "status"):
                out["training"] = svc.status()
                break
        return out

    def health(self) -> dict:
        return {"healthy": all(self.system.heartbeats.health().values())
                if self.system.heartbeats.health() else True,
                "services": self.system.heartbeats.health()}

    # --- lifecycle ----------------------------------------------------------
    def start(self) -> "DashboardServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dashboard", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._thread is not None:
            # shutdown() handshakes with serve_forever's loop — calling it
            # on a server that was never start()ed blocks forever on the
            # __is_shut_down event nothing will ever set
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
