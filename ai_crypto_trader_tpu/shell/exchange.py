"""Exchange adapters: the abstract interface, a deterministic fake, and a
network-gated Binance adapter.

Capability parity with `services/utils/exchange_interface.py:10-215`
(abstract ExchangeInterface + BinanceExchange + ExchangeFactory), plus the
fake backend the reference never had (its tests hit live Binance —
SURVEY §4): FakeExchange replays a synthetic (or loaded) OHLCV series with
a virtual clock, fills market/limit/stop orders against candle prices,
tracks balances, and is fully deterministic — the substrate for executor /
monitor / integration tests and paper trading.
"""

from __future__ import annotations

import itertools
import random
import time
import zlib
from abc import ABC, abstractmethod
from typing import Any, Callable

import numpy as np

from ai_crypto_trader_tpu.data.ingest import OHLCV
from ai_crypto_trader_tpu.utils import symbols as symbols_util


def _interval_minutes(interval: str) -> int:
    return int(interval[:-1]) * {"m": 1, "h": 60, "d": 1440}[interval[-1]]


def resample_klines(rows: list, factor: int) -> list:
    """Aggregate 1×-interval kline rows into factor×-interval bars (shared
    by FakeExchange's interval support and the monitor's local fallback).

    The trailing chunk may be partial — it is the venue's in-progress bar
    and is served as such (Binance includes the current incomplete candle);
    callers that align chunk starts to absolute time get stable bar
    boundaries across successive calls (round-4 advisor)."""
    out = []
    for i in range(0, len(rows), factor):
        chunk = rows[i: i + factor]
        out.append([chunk[0][0], chunk[0][1],
                    max(r[2] for r in chunk), min(r[3] for r in chunk),
                    chunk[-1][4], sum(r[5] for r in chunk)]
                   + list(chunk[-1][6:]))
    return out


class ExchangeInterface(ABC):
    """`exchange_interface.py:10-60` surface."""

    @abstractmethod
    def get_ticker(self, symbol: str) -> dict: ...

    @abstractmethod
    def get_order_book(self, symbol: str, limit: int = 20) -> dict: ...

    @abstractmethod
    def get_klines(self, symbol: str, interval: str = "1m",
                   limit: int = 100) -> list: ...

    @abstractmethod
    def place_order(self, symbol: str, side: str, order_type: str,
                    quantity: float, price: float | None = None,
                    stop_price: float | None = None,
                    client_order_id: str | None = None) -> dict: ...

    @abstractmethod
    def cancel_order(self, symbol: str, order_id: int) -> dict: ...

    @abstractmethod
    def get_balances(self) -> dict: ...

    def order_is_open(self, symbol: str, order_id: int) -> bool:
        """Whether a previously-placed order is still resting (False once
        filled or canceled). Default pessimistically True for adapters that
        don't track state."""
        return True

    def executed_qty(self, symbol: str, order_id: int,
                     assumed_total: float, is_open: bool) -> float:
        """Cumulative filled base quantity for one order.

        The default degrades to all-or-nothing from open/closed state — an
        adapter with real fill accounting MUST override: the default books
        a venue-cancelled/expired/rejected order as fully filled (round-4
        advisor), which fabricates inventory. `is_open` is the caller's
        single per-tick status read, passed in so the default costs no
        extra REST round-trip."""
        return 0.0 if is_open else assumed_total

    def order_state(self, symbol: str, order_id: int,
                    assumed_total: float) -> dict:
        """One combined per-tick status read: {"is_open", "executed_qty"}.
        Reconcilers call THIS (one venue round-trip per order per tick on
        adapters that override it); the default composes the two simpler
        queries for adapters where reads are local."""
        is_open = self.order_is_open(symbol, order_id)
        return {"is_open": is_open,
                "executed_qty": self.executed_qty(symbol, order_id,
                                                  assumed_total, is_open)}

    def list_symbols(self, quote: str | None = None) -> list[str]:
        """All tradable symbols, optionally filtered to one quote asset —
        the discovery surface `CryptoScanner.scan_market` builds from
        exchange info (`binance_ml_strategy.py:293-340`). Default empty for
        adapters without discovery."""
        return []

    def find_order_by_client_id(self, symbol: str,
                                client_order_id: str) -> dict | None:
        """Look an order up by the caller-chosen client id.

        This is how an AMBIGUOUS mutation failure ("place_order raised —
        but did the request reach the venue?") is resolved after a crash:
        the reconciler re-derives the deterministic client id from the
        journaled intent and asks the venue whether it knows the order.
        Default None = adapter cannot answer (callers must then treat the
        intent as unresolved and stand down, never re-enter blindly)."""
        return None

    def list_open_orders(self, symbol: str | None = None) -> list[dict]:
        """All resting orders (optionally one symbol) — the reconciler's
        orphan sweep. Default empty for adapters without order state."""
        return []


def load_depth_records(source) -> list[dict]:
    """Depth records from a capture: a JSONL journal path (the
    `utils/journal` record format `shell/stream.DepthCapture` writes — crc
    verified, torn tail tolerated), a list of already-parsed record dicts,
    or a `DepthCapture` instance (its ring).  Only SNAPSHOT records with
    both book sides are kept: ``@depth`` diff records are per-level
    CHANGES (zero-size removals included), not books — serving one as a
    book would feed the analytics garbage.  Subscribe the ``@depth20``
    snapshot channel for replayable/calibratable captures
    (`binance_kline_url(depth_symbols=…)` does both)."""
    if source is None:
        return []
    if hasattr(source, "records"):                  # a DepthCapture
        records = source.records()
    elif isinstance(source, str):
        from ai_crypto_trader_tpu.utils.journal import replay

        records = [r["data"] for r in replay(source)[0]
                   if r.get("kind") == "depth"]
    else:
        records = list(source)
    return [r for r in records
            if r.get("bids") and r.get("asks")
            and r.get("kind", "snapshot") == "snapshot"]


class FakeExchange(ExchangeInterface):
    """Deterministic candle-replay exchange with a virtual clock.

    `advance()` moves to the next candle; open limit/stop orders are
    evaluated against each new candle's high/low, like a real matching
    engine at candle granularity.

    ``depth_capture`` (a capture journal path, record list, or
    DepthCapture) switches `get_order_book` from crc32-synthesized books
    to REPLAYED captured depth — executor/analyzer tests run against
    real book shapes (level spacing, size distributions, holes) instead
    of the synthetic geometric ladder."""

    def __init__(self, series: dict[str, OHLCV], quote_balance: float = 10_000.0,
                 fee_rate: float = 0.001, max_fill_base: float | None = None,
                 depth_capture=None):
        self.series = series
        self.cursor = {s: 0 for s in series}
        self.balances: dict[str, float] = {"USDC": quote_balance}
        self.fee_rate = fee_rate
        # Per-candle liquidity cap (base units): a resting limit order fills
        # at most this much per candle, the remainder stays OPEN — the
        # partial-fill reality grid/DCA reconciliation must survive.
        self.max_fill_base = max_fill_base
        self.depth_records = load_depth_records(depth_capture)
        # bucketed once: get_order_book runs per symbol per tick and must
        # not rescan the whole capture on every call
        self._depth_by_symbol: dict[str, list] = {}
        for r in self.depth_records:
            self._depth_by_symbol.setdefault(r.get("symbol", ""),
                                             []).append(r)
        self.open_orders: dict[int, dict] = {}
        self.fills: list[dict] = []
        self._fills_by_oid: dict[int, list] = {}
        self._order_ids = itertools.count(1)

    # --- clock -------------------------------------------------------------
    def advance(self, symbol: str | None = None, steps: int = 1) -> None:
        for sym in ([symbol] if symbol else list(self.series)):
            self.cursor[sym] = min(self.cursor[sym] + steps,
                                   len(self.series[sym]) - 1)
            self._match_orders(sym)

    def _candle(self, symbol: str, offset: int = 0):
        i = max(self.cursor[symbol] - offset, 0)
        s = self.series[symbol]
        return {k: float(getattr(s, k)[i]) for k in
                ("open", "high", "low", "close", "volume")} | {
                    "timestamp": int(s.timestamp[i])}

    # --- market data -------------------------------------------------------
    def get_ticker(self, symbol: str) -> dict:
        c = self._candle(symbol)
        return {"symbol": symbol, "price": c["close"], "volume": c["volume"],
                "timestamp": c["timestamp"]}

    def get_order_book(self, symbol: str, limit: int = 20) -> dict:
        """Synthetic book around the candle close: geometric level spacing,
        sizes decaying with depth — enough structure for the order-book
        analytics (imbalance/walls/impact) to chew on.

        With a ``depth_capture`` attached, captured depth is REPLAYED
        instead: the record is picked deterministically by the virtual
        clock (cursor-indexed), so every consumer sees real book shapes
        and repeated calls at the same cursor stay bit-identical.  Only
        THIS symbol's records (or symbol-less ones — hand-built record
        lists) replay; a symbol absent from the capture falls back to
        the synthetic book rather than silently serving another
        symbol's price scale as ``captured``."""
        c = self._candle(symbol)
        mine = (self._depth_by_symbol.get(symbol)
                or self._depth_by_symbol.get(""))
        if mine:
            rec = mine[self.cursor[symbol] % len(mine)]
            return {"symbol": symbol,
                    "bids": [list(lv) for lv in rec["bids"][:limit]],
                    "asks": [list(lv) for lv in rec["asks"][:limit]],
                    "timestamp": c["timestamp"],
                    "captured": True, "capture_event_ms": rec.get("E", 0)}
        mid = c["close"]
        spread = max(mid * 1e-4, 1e-8)
        levels = np.arange(1, limit + 1)
        # deterministic per (symbol, candle): the symbol is mixed into the
        # seed (stable crc32, not salted hash()) so two symbols at the same
        # cursor don't serve identically-shaped books
        rng = np.random.default_rng(
            (zlib.crc32(symbol.encode()), self.cursor[symbol]))
        sizes = c["volume"] / limit * np.exp(-levels / limit) * (1 + 0.3 * rng.random(limit))
        bids = [[mid - spread * i, float(s)] for i, s in zip(levels, sizes)]
        asks = [[mid + spread * i, float(s)] for i, s in zip(levels, sizes)]
        return {"symbol": symbol, "bids": bids, "asks": asks,
                "timestamp": c["timestamp"]}

    def list_symbols(self, quote: str | None = None) -> list[str]:
        syms = sorted(self.series)
        if quote:
            syms = [s for s in syms if s.endswith(quote)]
        return syms

    def get_klines(self, symbol: str, interval: str = "1m",
                   limit: int = 100) -> list:
        """Candles at the requested interval — a real venue serves native
        3m/5m/15m bars capped at ~1000/request, so consumers fetch each
        frame separately instead of one giant 1m window; the fake honors
        the same contract by resampling its 1m series."""
        factor = _interval_minutes(interval)
        s = self.series[symbol]
        end = self.cursor[symbol] + 1
        start = max(end - limit * factor, 0)
        # align chunk starts to absolute time so 3m/5m/15m bar boundaries
        # are stable across ticks, like a real venue's fixed-boundary bars
        # (round-4 advisor: sliding anchors made HTF histories jitter)
        start -= start % factor
        rows = []
        for i in range(start, end):
            rows.append([int(s.timestamp[i]), float(s.open[i]), float(s.high[i]),
                         float(s.low[i]), float(s.close[i]), float(s.volume[i]),
                         0, 0.0, 0, 0.0, 0.0, 0])
        if factor > 1:
            rows = resample_klines(rows, factor)
        return rows[-limit:]

    # --- trading -----------------------------------------------------------
    def _base_asset(self, symbol: str) -> str:
        return symbols_util.base_asset(symbol)

    def _quote_asset(self, symbol: str) -> str:
        return symbols_util.quote_asset(symbol)

    def _fill(self, order: dict, price: float) -> dict:
        symbol, side, qty = order["symbol"], order["side"], order["quantity"]
        base, quote = self._base_asset(symbol), self._quote_asset(symbol)
        cost = qty * price
        fee = cost * self.fee_rate
        if side == "BUY":
            if self.balances.get(quote, 0.0) < cost + fee:
                return {**order, "status": "REJECTED", "reason": "insufficient_balance"}
            self.balances[quote] = self.balances.get(quote, 0.0) - cost - fee
            self.balances[base] = self.balances.get(base, 0.0) + qty
        else:
            if self.balances.get(base, 0.0) < qty:
                return {**order, "status": "REJECTED", "reason": "insufficient_balance"}
            self.balances[base] -= qty
            self.balances[quote] = self.balances.get(quote, 0.0) + cost - fee
        filled = {**order, "status": "FILLED", "price": price, "fee": fee}
        self.fills.append(filled)
        self._fills_by_oid.setdefault(order.get("order_id"), []).append(filled)
        return filled

    def place_order(self, symbol: str, side: str, order_type: str,
                    quantity: float, price: float | None = None,
                    stop_price: float | None = None,
                    client_order_id: str | None = None) -> dict:
        if not (np.isfinite(quantity) and quantity > 0.0):
            # a real venue rejects NaN/zero/negative quantities at the
            # filter layer — booking one here would poison the balances
            return {"symbol": symbol, "side": side.upper(),
                    "type": order_type.upper(), "status": "REJECTED",
                    "reason": "invalid_quantity"}
        if client_order_id is not None:
            # venue-side idempotency (Binance rejects duplicate
            # newClientOrderId): a retried/replayed placement returns the
            # original order instead of double-entering
            existing = self.find_order_by_client_id(symbol, client_order_id)
            if existing is not None:
                return {**existing, "duplicate": True}
        oid = next(self._order_ids)
        order = {"order_id": oid, "symbol": symbol, "side": side.upper(),
                 "type": order_type.upper(), "quantity": float(quantity),
                 "limit_price": price, "stop_price": stop_price,
                 "client_order_id": client_order_id}
        if order["type"] == "MARKET":
            return self._fill(order, self._candle(symbol)["close"])
        order["status"] = "OPEN"
        self.open_orders[oid] = order
        return dict(order)

    def _match_orders(self, symbol: str) -> None:
        c = self._candle(symbol)
        for oid, o in list(self.open_orders.items()):
            if o["symbol"] != symbol:
                continue
            t, side = o["type"], o["side"]
            fill_price = None
            if t == "LIMIT":
                if side == "BUY" and c["low"] <= o["limit_price"]:
                    fill_price = o["limit_price"]
                elif side == "SELL" and c["high"] >= o["limit_price"]:
                    fill_price = o["limit_price"]
            elif t in ("STOP_LOSS", "STOP_LOSS_LIMIT"):
                if side == "SELL" and c["low"] <= o["stop_price"]:
                    fill_price = o["limit_price"] or o["stop_price"]
                elif side == "BUY" and c["high"] >= o["stop_price"]:
                    fill_price = o["limit_price"] or o["stop_price"]
            if fill_price is not None:
                qty = o["quantity"]
                # `is not None`, not truthiness: a cap of exactly 0.0 means
                # NO liquidity this candle (the sim's schedule can drive the
                # cap to zero), not "uncapped"
                fill_qty = (min(qty, self.max_fill_base)
                            if self.max_fill_base is not None else qty)
                if fill_qty <= 0.0:
                    continue               # zero-liquidity candle: rests on
                result = self._fill({**o, "quantity": fill_qty}, fill_price)
                if result["status"] == "FILLED":
                    if fill_qty < qty:
                        o["quantity"] = qty - fill_qty   # partial: stays open
                    else:
                        del self.open_orders[oid]

    def cancel_order(self, symbol: str, order_id: int) -> dict:
        o = self.open_orders.pop(order_id, None)
        if o is None:
            return {"order_id": order_id, "status": "NOT_FOUND"}
        return {**o, "status": "CANCELED"}

    def order_is_open(self, symbol: str, order_id: int) -> bool:
        return order_id in self.open_orders

    def find_order_by_client_id(self, symbol, client_order_id):
        for o in self.open_orders.values():
            if (o.get("client_order_id") == client_order_id
                    and o["symbol"] == symbol):
                return dict(o)
        for f in reversed(self.fills):
            if (f.get("client_order_id") == client_order_id
                    and f["symbol"] == symbol):
                return dict(f)
        return None

    def list_open_orders(self, symbol: str | None = None) -> list[dict]:
        return [dict(o) for o in self.open_orders.values()
                if symbol is None or o["symbol"] == symbol]

    def last_fill(self, order_id: int) -> dict | None:
        for f in reversed(self.fills):
            if f.get("order_id") == order_id:
                return f
        return None

    def fills_for(self, order_id: int) -> list[dict]:
        """All (possibly partial) fills booked against one order — the
        executed-quantity ledger reconciliation reads (indexed: long paper
        runs reconcile every tracked order every tick)."""
        return list(self._fills_by_oid.get(order_id, ()))

    def executed_qty(self, symbol: str, order_id: int,
                     assumed_total: float, is_open: bool) -> float:
        return float(sum(f["quantity"] for f in self.fills_for(order_id)
                         if f.get("status") == "FILLED"))

    def get_balances(self) -> dict:
        return dict(self.balances)


class BinanceExchange(ExchangeInterface):
    """Live Binance adapter (`exchange_interface.py:61-180` surface).

    Network access is absent in this environment, so construction is gated:
    it raises with a clear message unless a client object is injected."""

    def __init__(self, client: Any = None):
        if client is None:
            raise RuntimeError(
                "BinanceExchange requires an injected client (e.g. "
                "binance.Client). This environment has no network; use "
                "FakeExchange for tests/paper trading.")
        self.client = client

    def get_ticker(self, symbol):
        t = self.client.get_symbol_ticker(symbol=symbol)
        return {"symbol": symbol, "price": float(t["price"])}

    def get_order_book(self, symbol, limit=20):
        return self.client.get_order_book(symbol=symbol, limit=limit)

    def get_klines(self, symbol, interval="1m", limit=100):
        return self.client.get_klines(symbol=symbol, interval=interval, limit=limit)

    def place_order(self, symbol, side, order_type, quantity, price=None,
                    stop_price=None, client_order_id=None):
        kw = dict(symbol=symbol, side=side, type=order_type, quantity=quantity)
        if price is not None:
            kw["price"] = price
        if stop_price is not None:
            kw["stopPrice"] = stop_price
        if client_order_id is not None:
            # venue-enforced idempotency key: a deterministic id makes an
            # ambiguous failure ("raised — did it reach Binance?")
            # resolvable via get_order(origClientOrderId=...) instead of a
            # silent double-order hazard
            kw["newClientOrderId"] = client_order_id
        return self.client.create_order(**kw)

    def cancel_order(self, symbol, order_id):
        return self.client.cancel_order(symbol=symbol, orderId=order_id)

    def find_order_by_client_id(self, symbol, client_order_id):
        try:
            o = self.client.get_order(symbol=symbol,
                                      origClientOrderId=client_order_id)
        except Exception as exc:                       # noqa: BLE001
            # ONLY "unknown order" means the venue never saw this id.
            # Anything else (timeout, rate limit, 5xx) must PROPAGATE —
            # ResilientExchange wraps it and the reconciler keeps the
            # intent parked; returning None here would make a network
            # blip indistinguishable from not-placed and unblock the
            # exact double-entry the client id exists to prevent.
            msg = str(exc).lower()
            if (getattr(exc, "code", None) == -2013     # binance NO_SUCH_ORDER
                    or "does not exist" in msg or "unknown order" in msg):
                return None
            raise
        executed = float(o.get("executedQty", 0.0) or 0.0)
        price = float(o.get("price", 0.0) or 0.0)
        if price <= 0.0 and executed > 0.0:
            # MARKET orders report price=0; the real average fill price
            # is cumulative quote volume over executed base
            price = float(o.get("cummulativeQuoteQty", 0.0) or 0.0) / executed
        return {"order_id": o.get("orderId"), "symbol": symbol,
                "status": o.get("status"), "side": o.get("side"),
                "quantity": float(o.get("origQty", 0.0)),
                "executed_qty": executed,
                "price": price,
                "client_order_id": client_order_id}

    def list_open_orders(self, symbol=None):
        kw = {"symbol": symbol} if symbol else {}
        return [{"order_id": o.get("orderId"), "symbol": o.get("symbol"),
                 "status": o.get("status"), "side": o.get("side"),
                 "type": o.get("type"),
                 "quantity": float(o.get("origQty", 0.0)),
                 "client_order_id": o.get("clientOrderId")}
                for o in self.client.get_open_orders(**kw)]

    def order_is_open(self, symbol, order_id):
        o = self.client.get_order(symbol=symbol, orderId=order_id)
        return o.get("status") in ("NEW", "PARTIALLY_FILLED")

    def executed_qty(self, symbol, order_id, assumed_total, is_open):
        """Binance's get_order returns executedQty for EVERY status —
        including CANCELED/EXPIRED/REJECTED and partial fills — so live
        reconciliation never books phantom inventory (round-4 advisor)."""
        o = self.client.get_order(symbol=symbol, orderId=order_id)
        return float(o.get("executedQty", 0.0))

    def order_state(self, symbol, order_id, assumed_total):
        """ONE get_order answers both questions — reconcilers polling
        order_is_open + executed_qty separately would double the REST
        volume through the rate limiter."""
        o = self.client.get_order(symbol=symbol, orderId=order_id)
        return {"is_open": o.get("status") in ("NEW", "PARTIALLY_FILLED"),
                "executed_qty": float(o.get("executedQty", 0.0))}

    def get_balances(self):
        acct = self.client.get_account()
        return {b["asset"]: float(b["free"]) for b in acct["balances"]}

    def list_symbols(self, quote=None):
        info = self.client.get_exchange_info()
        syms = [s["symbol"] for s in info.get("symbols", [])
                if s.get("status", "TRADING") == "TRADING"]
        if quote:
            syms = [s for s in syms if s.endswith(quote)]
        return sorted(syms)


class ExchangeUnavailable(RuntimeError):
    """Raised by ResilientExchange when the circuit is open or an operation
    has exhausted its retries — the caller's cycle should skip/abort."""


class _BlockingBudgetExceeded(RuntimeError):
    """Internal: a sleep would exceed ResilientExchange.max_block_s."""


class ResilientExchange(ExchangeInterface):
    """Resilience decorator around any ExchangeInterface.

    Wires the protections the reference puts around its Binance calls
    (`services/market_monitor_service.py:96-115`: breaker 3 failures/30 s;
    `services/utils/rate_limiter.py`; `circuit_breaker.py:227` backoff) at
    the adapter seam, so every consumer (monitor, executor, risk, CLI) gets
    them without wiring its own:

    - every call first passes the circuit breaker (an open circuit rejects
      at the door without burning tokens or wall-clock), then every
      PHYSICAL attempt — including each retry — acquires from a token
      bucket, sleeping out any deficit (Binance weight limits hold even
      during an error storm);
    - reads are retried with exponential backoff + jitter; a read counts
      as ONE breaker failure only once its retries are exhausted;
    - mutations (place_order / cancel_order) are NEVER retried — order
      placement is not idempotent; one attempt, and any raising attempt
      counts toward the breaker (the reference's breaker likewise wraps
      every Binance call, business errors included:
      `market_monitor_service.py:96-115`);
    - an open circuit or a final failure raises ExchangeUnavailable
      (executor cycles fail loudly instead of silently trading on None);
    - total BLOCKING time per public call is bounded by ``max_block_s``:
      backoff and token-bucket deficits sleep on the caller's thread —
      on the one shared event loop a retry storm would otherwise freeze
      every service, alert evaluation and heartbeat for up to
      ``max_delay_s``.  When the budget is exhausted the call fails as
      ExchangeUnavailable (a breaker failure) instead of sleeping on;
    - loop callers that cannot afford ANY blocking await ``acall(...)``,
      which runs the same protected call on a worker thread — the
      async-aware seam (sleeps happen off-loop, heartbeats keep beating).

    Deterministic: clock, sleep and jitter rng are injectable.
    """

    def __init__(self, inner: ExchangeInterface,
                 failure_threshold: int = 3, reset_timeout_s: float = 30.0,
                 rate_per_s: float = 20.0, burst: float = 40.0,
                 max_read_retries: int = 2, base_delay_s: float = 0.25,
                 max_delay_s: float = 30.0,
                 max_block_s: float | None = 30.0,
                 now_fn: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: random.Random | None = None):
        from ai_crypto_trader_tpu.utils.circuit_breaker import CircuitBreaker
        from ai_crypto_trader_tpu.utils.rate_limiter import TokenBucket

        self.inner = inner
        self.breaker = CircuitBreaker("exchange",
                                      failure_threshold=failure_threshold,
                                      reset_timeout_s=reset_timeout_s,
                                      now_fn=now_fn)
        self.bucket = TokenBucket(rate_per_s=rate_per_s, capacity=burst,
                                  now_fn=now_fn)
        self.max_read_retries = max_read_retries
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.max_block_s = max_block_s
        self._sleep = sleep
        self._rng = rng or random.Random(0)

    def __getattr__(self, name):
        # Delegate the inner adapter's extra surface (FakeExchange.advance /
        # fills / last_fill, client handles, …) so wrapping is transparent.
        if name == "inner":                 # pre-__init__ lookup guard
            raise AttributeError(name)
        return getattr(self.inner, name)

    def _gate(self):
        # Breaker first: an open circuit must not burn tokens or wall-clock.
        if not self.breaker.allow():
            raise ExchangeUnavailable(
                f"exchange circuit {self.breaker.state.value}")

    def _budget(self) -> list:
        """Per-public-call blocking allowance, consumed by every sleep."""
        return [float("inf") if self.max_block_s is None else self.max_block_s]

    def _budgeted_sleep(self, seconds: float, budget: list) -> None:
        if seconds > budget[0]:
            raise _BlockingBudgetExceeded(
                f"sleep of {seconds:.2f}s would exceed the per-call "
                f"blocking budget ({self.max_block_s}s)")
        budget[0] -= seconds
        self._sleep(seconds)

    def _acquire_token(self, budget: list):
        while not self.bucket.try_acquire():
            self._budgeted_sleep(max(self.bucket.wait_time(), 1e-3), budget)

    def _read(self, fn: Callable, *args, **kw):
        from ai_crypto_trader_tpu.utils.circuit_breaker import backoff_delays

        self._gate()
        self.breaker.stats["calls"] += 1
        delays = backoff_delays(self.max_read_retries, self.base_delay_s,
                                self.max_delay_s, rng=self._rng)
        budget = self._budget()
        last_exc: Exception | None = None
        for _attempt in range(self.max_read_retries + 1):
            try:
                self._acquire_token(budget)   # every physical attempt pays
                out = fn(*args, **kw)
            except _BlockingBudgetExceeded as exc:
                last_exc = exc
                break                         # no budget left to retry with
            except Exception as exc:                       # noqa: BLE001
                last_exc = exc
                delay = next(delays, None)
                if delay is not None:
                    try:
                        self._budgeted_sleep(delay, budget)
                    except _BlockingBudgetExceeded:
                        break
                continue
            self.breaker.record_success()
            return out
        self.breaker.record_failure()
        raise ExchangeUnavailable(f"read failed after "
                                  f"{self.max_read_retries + 1} attempts: "
                                  f"{last_exc}") from last_exc

    def _write(self, fn: Callable, *args, **kw):
        self._gate()
        try:
            self._acquire_token(self._budget())
        except _BlockingBudgetExceeded as exc:
            self.breaker.record_failure()
            raise ExchangeUnavailable(
                f"order operation blocked on rate limit: {exc}") from exc
        self.breaker.stats["calls"] += 1
        try:
            out = fn(*args, **kw)
        except Exception as exc:                           # noqa: BLE001
            self.breaker.record_failure()
            raise ExchangeUnavailable(f"order operation failed: {exc}") from exc
        self.breaker.record_success()
        return out

    async def acall(self, method: str, *args, **kw):
        """Async-aware seam for event-loop callers: run one protected
        call (``await ex.acall("get_klines", sym, "1m", 100)``) on a
        worker thread, so backoff/rate-limit sleeps never block the shared
        loop.  The inner adapter must be thread-compatible for the call
        (true of BinanceExchange's HTTP client; FakeExchange callers
        should keep using the sync surface on the loop)."""
        import asyncio

        return await asyncio.to_thread(getattr(self, method), *args, **kw)

    # --- reads: retried ----------------------------------------------------
    def get_ticker(self, symbol):
        return self._read(self.inner.get_ticker, symbol)

    def get_order_book(self, symbol, limit=20):
        return self._read(self.inner.get_order_book, symbol, limit)

    def get_klines(self, symbol, interval="1m", limit=100):
        return self._read(self.inner.get_klines, symbol, interval, limit)

    def get_balances(self):
        return self._read(self.inner.get_balances)

    def order_is_open(self, symbol, order_id):
        return self._read(self.inner.order_is_open, symbol, order_id)

    def executed_qty(self, symbol, order_id, assumed_total, is_open):
        return self._read(self.inner.executed_qty, symbol, order_id,
                          assumed_total, is_open)

    def order_state(self, symbol, order_id, assumed_total):
        return self._read(self.inner.order_state, symbol, order_id,
                          assumed_total)

    def find_order_by_client_id(self, symbol, client_order_id):
        return self._read(self.inner.find_order_by_client_id, symbol,
                          client_order_id)

    def list_open_orders(self, symbol=None):
        return self._read(self.inner.list_open_orders, symbol)

    # --- mutations: single attempt -----------------------------------------
    def place_order(self, symbol, side, order_type, quantity, price=None,
                    stop_price=None, client_order_id=None):
        kw = ({"client_order_id": client_order_id}
              if client_order_id is not None else {})
        return self._write(self.inner.place_order, symbol, side, order_type,
                           quantity, price, stop_price, **kw)

    def cancel_order(self, symbol, order_id):
        return self._write(self.inner.cancel_order, symbol, order_id)


def make_exchange(kind: str = "fake", resilient: bool | None = None,
                  resilient_opts: dict | None = None,
                  **kw) -> ExchangeInterface:
    """ExchangeFactory parity (`exchange_interface.py:181-215`).

    Live adapters are wrapped in ResilientExchange by default (the
    reference wires breakers around its Binance calls; here the factory
    guarantees it). Pass resilient=False to get the bare adapter.
    `resilient_opts` go to the ResilientExchange ctor — simulations on a
    virtual clock must pass their own now_fn/sleep so the token bucket
    doesn't throttle in real wall-clock time."""
    opts = resilient_opts or {}
    if kind == "fake":
        ex: ExchangeInterface = FakeExchange(**kw)
        return ResilientExchange(ex, **opts) if resilient else ex
    if kind == "binance":
        ex = BinanceExchange(**kw)
        return ex if resilient is False else ResilientExchange(ex, **opts)
    raise ValueError(f"unknown exchange kind {kind!r}")
