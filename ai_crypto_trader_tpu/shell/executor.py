"""Trade executor service: trading_signals → gated orders → SL/TP/trailing.

Capability parity with TradeExecutorService
(`services/trade_executor_service.py`):
  * `execute_trade` (:816-1046): confidence gate → market BUY → adaptive &
    socially-adjusted SL/TP percentages (:921-976) → protective
    STOP_LOSS_LIMIT + LIMIT take-profit orders (:978-999) → active-trade
    record (:1002-1015) → trailing-stop registration (:1017-1034);
  * trailing-stop maintenance on price updates with stop-order replacement
    (:333) — the stop math is the pure state machine in risk/stops.py;
  * `should_execute_trade` agreement gate (signal == decision == BUY,
    strength ≥ 70, confidence ≥ threshold — `strategy_tester.py:371-401`);
  * max-positions cap and holdings tracking.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ai_crypto_trader_tpu.backtest.signals import position_size as technical_position_size
from ai_crypto_trader_tpu.config import TradingParams, TrailingStopParams
from ai_crypto_trader_tpu.risk.social import SocialSnapshot, social_risk_adjustment
from ai_crypto_trader_tpu.risk.stops import (
    trailing_stop_init,
    trailing_stop_update,
)
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.exchange import (
    ExchangeInterface,
    ExchangeUnavailable,
)
from ai_crypto_trader_tpu.utils import tracing


@dataclass
class ActiveTrade:
    symbol: str
    entry_price: float
    quantity: float
    stop_loss_pct: float
    take_profit_pct: float
    stop_order_id: int | None
    tp_order_id: int | None
    trailing_state: object
    opened_at: float


@dataclass
class TradeExecutor:
    bus: EventBus
    exchange: ExchangeInterface
    trading: TradingParams = field(default_factory=TradingParams)
    trailing: TrailingStopParams = field(default_factory=TrailingStopParams)
    now_fn: any = time.time
    active_trades: dict = field(default_factory=dict)
    closed_trades: list = field(default_factory=list)

    # --- gates (strategy_tester.py:371-401 / trade_executor_service.py) ----
    def should_execute(self, signal: dict) -> bool:
        return (
            signal.get("confidence", 0.0) >= self.trading.ai_confidence_threshold
            and signal.get("signal_strength", 0.0) >= self.trading.min_signal_strength
            and signal.get("signal") == signal.get("decision")
            and signal.get("decision") == "BUY"
            and signal["symbol"] not in self.active_trades
            and len(self.active_trades) < self.trading.max_positions
        )

    def _social_factors(self, symbol: str) -> dict:
        snap = self.bus.get(f"social_snapshot_{symbol}")
        if snap is None:
            return {"position_size_factor": 1.0, "stop_loss_factor": 1.0,
                    "take_profit_factor": 1.0}
        if isinstance(snap, SocialSnapshot):
            return {k: float(v) for k, v in social_risk_adjustment(snap).items()
                    if k.endswith("_factor")}
        return snap

    async def handle_signal(self, signal: dict) -> ActiveTrade | None:
        """`execute_trade` (:816-1046)."""
        if not self.should_execute(signal):
            return None
        symbol = signal["symbol"]
        balance = self.exchange.get_balances().get("USDC", 0.0)

        plan = technical_position_size(balance, signal.get("volatility", 0.01),
                                       signal.get("avg_volume", 0.0))
        social = self._social_factors(symbol)
        size = float(np.asarray(plan.size)) * social["position_size_factor"]
        size = min(size, balance * 0.95)
        if size < self.trading.min_trade_amount:
            return None
        # sizer fractions interpreted as percent (the corrected semantics;
        # see engine.reference_quirks docs), then socially adjusted
        sl_pct = float(np.asarray(plan.stop_loss_pct)) * 100.0 * social["stop_loss_factor"]
        tp_pct = float(np.asarray(plan.take_profit_pct)) * 100.0 * social["take_profit_factor"]
        # Hot-swapped live params take precedence over the volatility sizer's
        # exits: the evolver / generator publish `strategy_params` on the bus
        # (`hot_swap_strategy`, strategy_evolution_service.py:349-362) and
        # the reference executor reads the current strategy at entry time.
        live = self.bus.get("strategy_params") or {}
        if isinstance(live.get("stop_loss"), (int, float)):
            sl_pct = float(live["stop_loss"]) * social["stop_loss_factor"]
        if isinstance(live.get("take_profit"), (int, float)):
            tp_pct = float(live["take_profit"]) * social["take_profit_factor"]

        order = self.exchange.place_order(symbol, "BUY", "MARKET",
                                          quantity=size / signal["current_price"])
        if order.get("status") != "FILLED":
            return None
        entry = order["price"]
        qty = order["quantity"]

        # Register the position BEFORE placing protective orders: if the
        # exchange dies between the fill and the stop placement, the trade
        # must exist on the books (unprotected but managed) rather than be
        # orphaned on the exchange. _ensure_protection retries on every
        # subsequent price update.
        stop_price = entry * (1 - sl_pct / 100.0)
        trade = ActiveTrade(
            symbol=symbol, entry_price=entry, quantity=qty,
            stop_loss_pct=sl_pct, take_profit_pct=tp_pct,
            stop_order_id=None, tp_order_id=None,
            trailing_state=trailing_stop_init(
                entry, stop_price, self.trailing.activation_threshold_pct),
            opened_at=self.now_fn(),
        )
        self.active_trades[symbol] = trade
        try:
            self._ensure_protection(trade)
        except ExchangeUnavailable:
            pass        # trade stays registered; protection retried later
        self.bus.set("active_trades", {s: vars(t) | {"trailing_state": None}
                                       for s, t in self.active_trades.items()})
        await self.bus.publish("trade_executions", {
            "symbol": symbol, "side": "BUY", "price": entry, "quantity": qty,
            "stop_loss_pct": sl_pct, "take_profit_pct": tp_pct})
        return trade

    def _ensure_protection(self, trade: ActiveTrade) -> None:
        """Place whichever protective orders are missing (initial placement
        and post-outage repair share this path). Raises ExchangeUnavailable
        if the exchange is down; callers decide whether to swallow."""
        symbol = trade.symbol
        if trade.stop_order_id is None:
            stop_price = float(np.asarray(trade.trailing_state.stop))
            o = self.exchange.place_order(
                symbol, "SELL", "STOP_LOSS_LIMIT", trade.quantity,
                price=stop_price * 0.999, stop_price=stop_price)
            trade.stop_order_id = o.get("order_id")
        if trade.tp_order_id is None:
            tp_price = trade.entry_price * (1 + trade.take_profit_pct / 100.0)
            o = self.exchange.place_order(
                symbol, "SELL", "LIMIT", trade.quantity, price=tp_price)
            trade.tp_order_id = o.get("order_id")

    @staticmethod
    def _protective_orders(trade: ActiveTrade):
        """(order_id, close reason, entry-price factor estimating the fill
        price when no fill record is available) for both protective legs."""
        return ((trade.tp_order_id, "Take Profit",
                 1 + trade.take_profit_pct / 100),
                (trade.stop_order_id, "Stop Loss",
                 1 - trade.stop_loss_pct / 100))

    def _reconcile_protective_fills(self, symbol: str, price: float):
        """Detect server-side fills of the protective SL/TP orders and
        finalize the trade — otherwise a filled TP leaves the trade in
        active_trades and a later trailing trigger double-sells."""
        trade = self.active_trades.get(symbol)
        if trade is None:
            return None
        for oid, reason, px_factor in self._protective_orders(trade):
            if oid is not None and not self.exchange.order_is_open(symbol, oid):
                fill = getattr(self.exchange, "last_fill", lambda _o: None)(oid)
                exit_price = (fill.get("price", trade.entry_price * px_factor)
                              if fill else trade.entry_price * px_factor)
                return (reason, exit_price)
        return None

    async def on_price(self, symbol: str, price: float) -> None:
        """Trailing-stop maintenance (`TrailingStopManager.update_price` +
        stop replacement, :142-333), after reconciling protective fills."""
        trade = self.active_trades.get(symbol)
        if trade is None:
            return
        # Reconcile BEFORE repairing: a protective order may have filled
        # server-side during an outage — repairing first would place sells
        # for inventory that is already gone.
        filled = self._reconcile_protective_fills(symbol, price)
        if filled is not None:
            reason, exit_price = filled
            await self._finalize_filled(symbol, exit_price, reason)
            return
        if trade.stop_order_id is None or trade.tp_order_id is None:
            # repair protection lost to an earlier exchange outage
            self._ensure_protection(trade)
        md = self.bus.get(f"market_data_{symbol}") or {}
        prev_stop = float(np.asarray(trade.trailing_state.stop))
        st, triggered = trailing_stop_update(
            trade.trailing_state, price,
            strategy=self.trailing.strategy,
            trail_percent=self.trailing.trail_percent,
            min_trail_distance_pct=self.trailing.min_trail_distance_pct,
            atr=md.get("atr", 0.0),
            atr_multiplier=self.trailing.atr_multiplier,
            volatility=md.get("volatility", 0.0) * price,
            volatility_multiplier=self.trailing.volatility_multiplier,
            fixed_trail_amount=self.trailing.fixed_trail_amount)
        trade.trailing_state = st
        new_stop = float(np.asarray(st.stop))
        if new_stop > prev_stop and trade.stop_order_id is not None:
            # replace the protective stop order at the ratcheted level;
            # id goes None between cancel and place so a mid-replacement
            # outage is repaired by _ensure_protection, not double-placed
            self.exchange.cancel_order(symbol, trade.stop_order_id)
            trade.stop_order_id = None
            o = self.exchange.place_order(symbol, "SELL", "STOP_LOSS_LIMIT",
                                          trade.quantity,
                                          price=new_stop * 0.999,
                                          stop_price=new_stop)
            trade.stop_order_id = o.get("order_id")
        if bool(triggered):
            await self.close_trade(symbol, price, "Trailing Stop")

    async def _finalize_filled(self, symbol: str, exit_price: float,
                               reason: str) -> None:
        """Close the books on a trade whose protective order already sold
        the position server-side — cancel the sibling order, no re-sell."""
        trade = self.active_trades.pop(symbol, None)
        if trade is None:
            return
        for oid in (trade.stop_order_id, trade.tp_order_id):
            if oid is not None and self.exchange.order_is_open(symbol, oid):
                self.exchange.cancel_order(symbol, oid)
        pnl = (exit_price - trade.entry_price) * trade.quantity
        record = {"symbol": symbol, "entry_price": trade.entry_price,
                  "exit_price": exit_price, "quantity": trade.quantity,
                  "pnl": pnl, "reason": reason, "opened_at": trade.opened_at,
                  "closed_at": self.now_fn()}
        self.closed_trades.append(record)
        await self.bus.publish("trade_closures", record)

    async def close_trade(self, symbol: str, price: float, reason: str) -> None:
        """Pop the trade only AFTER the exit sell succeeds: if the exchange
        dies mid-close the position stays on the books (cancelled
        protective orders are re-placed by _ensure_protection) and the
        close is re-attempted on the next trigger."""
        trade = self.active_trades.get(symbol)
        if trade is None:
            return
        # A protective order that is already not-open BEFORE we cancel it
        # filled server-side — finalize with that fill instead of selling
        # inventory that is no longer held.
        filled = self._reconcile_protective_fills(symbol, price)
        if filled is not None:
            fill_reason, exit_price = filled
            await self._finalize_filled(symbol, exit_price, fill_reason)
            return
        prot = self._protective_orders(trade)
        if trade.stop_order_id is not None:
            self.exchange.cancel_order(symbol, trade.stop_order_id)
            trade.stop_order_id = None
        if trade.tp_order_id is not None:
            self.exchange.cancel_order(symbol, trade.tp_order_id)
            trade.tp_order_id = None
        order = self.exchange.place_order(symbol, "SELL", "MARKET",
                                          trade.quantity)
        if order.get("status") != "FILLED":
            # Rejected exit. Either a protective order filled in the race
            # window between the reconcile above and our cancels (the ids
            # are cancelled now, so on_price reconciliation can no longer
            # see it — check the fills directly), or the rejection is
            # transient with inventory intact (keep the trade;
            # _ensure_protection re-places the protective orders next tick).
            last_fill = getattr(self.exchange, "last_fill", lambda _o: None)
            for oid, fill_reason, factor in prot:
                fill = last_fill(oid) if oid is not None else None
                if fill is not None:
                    await self._finalize_filled(
                        symbol, fill.get("price",
                                         trade.entry_price * factor),
                        fill_reason)
                    return
            return
        self.active_trades.pop(symbol, None)
        pnl = (price - trade.entry_price) * trade.quantity
        record = {"symbol": symbol, "entry_price": trade.entry_price,
                  "exit_price": price, "quantity": trade.quantity,
                  "pnl": pnl, "reason": reason, "opened_at": trade.opened_at,
                  "closed_at": self.now_fn()}
        self.closed_trades.append(record)
        await self.bus.publish("trade_closures", record)

    def _queue(self):
        # Persistent subscription (see analyzer._queue).
        if not hasattr(self, "_q"):
            self._q = self.bus.subscribe("trading_signals")
        return self._q

    async def run_once(self) -> int:
        """Drain pending trading_signals (test/launcher tick). A signal
        interrupted by an exchange outage is re-queued so the entry is
        retried once the circuit recovers, then the outage propagates to
        the launcher's skip-and-alert path."""
        n = 0
        q = self._queue()
        while not q.empty():
            env = q.get_nowait()
            try:
                with tracing.consumer_span(
                        env, "executor.handle_signal", service="executor",
                        attributes={"symbol": env["data"].get("symbol")}) as sp:
                    trade = await self.handle_signal(env["data"])
                    if trade:
                        sp.set_attribute("entry_price", trade.entry_price)
                        n += 1
                    else:
                        sp.set_attribute("gated", True)
            except ExchangeUnavailable:
                q.put_nowait(env)
                raise
        return n
