"""Trade executor service: trading_signals → gated orders → SL/TP/trailing.

Capability parity with TradeExecutorService
(`services/trade_executor_service.py`):
  * `execute_trade` (:816-1046): confidence gate → market BUY → adaptive &
    socially-adjusted SL/TP percentages (:921-976) → protective
    STOP_LOSS_LIMIT + LIMIT take-profit orders (:978-999) → active-trade
    record (:1002-1015) → trailing-stop registration (:1017-1034);
  * trailing-stop maintenance on price updates with stop-order replacement
    (:333) — the stop math is the pure state machine in risk/stops.py;
  * `should_execute_trade` agreement gate (signal == decision == BUY,
    strength ≥ 70, confidence ≥ threshold — `strategy_tester.py:371-401`);
  * max-positions cap and holdings tracking.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ai_crypto_trader_tpu.backtest.signals import position_size as technical_position_size
from ai_crypto_trader_tpu.config import TradingParams, TrailingStopParams
from ai_crypto_trader_tpu.risk.social import SocialSnapshot, social_risk_adjustment
from ai_crypto_trader_tpu.risk.stops import (
    trailing_stop_init,
    trailing_stop_update,
)
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.exchange import (
    ExchangeInterface,
    ExchangeUnavailable,
)
from ai_crypto_trader_tpu.utils import tracing


@dataclass
class ActiveTrade:
    symbol: str
    entry_price: float
    quantity: float
    stop_loss_pct: float
    take_profit_pct: float
    stop_order_id: int | None
    tp_order_id: int | None
    trailing_state: object
    opened_at: float
    # deterministic client order ids (journaled BEFORE placement): the
    # keys that make an ambiguous venue failure resolvable after a crash
    entry_coid: str | None = None
    stop_coid: str | None = None
    tp_coid: str | None = None
    # entry-signal provenance (decision id, dominant combination family,
    # structure/model versions) — journaled with the trade and carried
    # onto the closure record for PnL attribution (obs/attribution.py)
    source: dict | None = None


@dataclass
class TradeExecutor:
    bus: EventBus
    exchange: ExchangeInterface
    trading: TradingParams = field(default_factory=TradingParams)
    trailing: TrailingStopParams = field(default_factory=TrailingStopParams)
    now_fn: any = time.time
    active_trades: dict = field(default_factory=dict)
    closed_trades: list = field(default_factory=list)
    # Crash-safety (utils/journal.py): when a WriteAheadJournal is attached
    # every order intent is durable BEFORE it can hit the exchange and
    # every ack/fill/closure lands after — recover_from_journal() replays
    # this into books and reconciles them against venue ground truth.
    journal: object = None
    coid_prefix: str = "wj"
    # Tenant-lane tag (ROADMAP item 4 / testing/loadgen.py): a lane-scoped
    # executor subscribes to its own `trading_signals.<lane>` channel (the
    # analyzer publishes there — O(N) fanout for N tenants) and drains
    # only signals tagged with ITS lane (belt-and-braces against a
    # pattern-subscribed producer).  None = the one-tenant launcher: the
    # shared `trading_signals` channel, every signal processed, exactly
    # as before.
    lane: str | None = None
    # Decision-provenance flight recorder (obs/flightrec.py), wired by the
    # launcher; None = disabled (one attribute check per call site).
    flightrec: object = None
    # intents whose venue outcome is UNKNOWN (placement raised mid-flight,
    # or journaled intent with no ack found at recovery), keyed by
    # client_order_id; entry for a symbol is blocked while one is pending
    pending_intents: dict = field(default_factory=dict)
    # sibling protective orders whose cancel failed during finalization —
    # retried every tick until dead (a resting orphan that fills would
    # sell inventory backing a newer position)
    orphan_orders: list = field(default_factory=list)
    _coid_seq: int = 0
    _compacted_at: int = 0
    # closures rotated out of snapshots (see snapshot_state): the full
    # per-trade records live in the journal history that was compacted
    # away; count and PnL are conserved here so books stay truthful
    _closed_dropped_n: int = 0
    _closed_dropped_pnl: float = 0.0

    COMPACT_EVERY = 2048           # journal records between snapshots
    SNAPSHOT_CLOSED_TAIL = 1024    # closed trades embedded per snapshot

    # --- journal helpers ---------------------------------------------------
    def _j(self, kind: str, flush: bool = False, **data) -> None:
        if self.journal is not None:
            self.journal.append(kind, data, flush=flush)

    def maybe_compact(self) -> None:
        """Snapshot+compact once the journal grows past COMPACT_EVERY.
        Called only at SAFE points (top of run_once, end of recovery) —
        never mid-operation: a compaction between an order intent record
        and its ack would snapshot state that knows nothing of the
        in-flight order, losing the ambiguity-resolution key."""
        if (self.journal is not None
                and self.journal.seq - self._compacted_at >= self.COMPACT_EVERY):
            self.journal.compact(self.snapshot_state())
            self._compacted_at = self.journal.seq

    def _next_coid(self, tag: str, symbol: str) -> str:
        self._coid_seq += 1
        return f"{self.coid_prefix}-{tag}-{symbol}-{self._coid_seq}"

    # --- gates (strategy_tester.py:371-401 / trade_executor_service.py) ----
    def veto_reason(self, signal: dict) -> str | None:
        """WHICH gate rejects this signal (None = executable) — the single
        source of truth behind ``should_execute`` AND the flight
        recorder's per-decision rejection reason, so the recorded gate can
        never drift from the gate actually applied.

        Gate names AND their evaluation order are the flight recorder's
        shared vocabulary (`obs.flightrec.GATES` / `VETO_ORDER`): the
        vmapped tenant engine (ops/tenant_engine.py) re-expresses these
        same checks as traced predicates resolving in the same priority,
        and the gate-for-gate parity sweep in tests/test_tenant_engine.py
        pins the two paths equal.  Changing a check here without updating
        the traced twin (and VETO_ORDER) fails that sweep."""
        # poisoned-payload gate: a NaN/zero price reaching the sizer would
        # turn into a NaN-quantity order and poison the venue balances —
        # reject non-finite numerics at the door (docs/RESILIENCE.md)
        price = signal.get("current_price", 0.0)
        if not (np.isfinite(price) and price > 0.0):
            return "nan_gate"
        if not all(np.isfinite(signal.get(k, 0.0)) for k in
                   ("confidence", "signal_strength", "volatility",
                    "avg_volume")):
            return "nan_gate"
        if signal.get("confidence", 0.0) < self.trading.ai_confidence_threshold:
            return "confidence_floor"
        if signal.get("signal_strength", 0.0) < self.trading.min_signal_strength:
            return "strength_floor"
        if signal.get("decision") != "BUY":
            return "not_buy"
        if signal.get("signal") != signal.get("decision"):
            return "signal_disagreement"
        if signal["symbol"] in self.active_trades:
            return "position_open"
        # an unresolved intent means the venue MAY already hold a
        # position for this symbol — entering again would be the exact
        # double-order the journal exists to prevent
        if signal["symbol"] in {i.get("symbol")
                                for i in self.pending_intents.values()}:
            return "pending_intent"
        if len(self.active_trades) >= self.trading.max_positions:
            return "max_positions"
        return None

    def should_execute(self, signal: dict) -> bool:
        return self.veto_reason(signal) is None

    def _social_factors(self, symbol: str) -> dict:
        snap = self.bus.get(f"social_snapshot_{symbol}")
        if snap is None:
            return {"position_size_factor": 1.0, "stop_loss_factor": 1.0,
                    "take_profit_factor": 1.0}
        if isinstance(snap, SocialSnapshot):
            return {k: float(v) for k, v in social_risk_adjustment(snap).items()
                    if k.endswith("_factor")}
        return snap

    async def handle_signal(self, signal: dict) -> ActiveTrade | None:
        """`execute_trade` (:816-1046)."""
        fr = self.flightrec
        did = signal.get("decision_id")
        reason = self.veto_reason(signal)
        if reason is not None:
            if fr is not None:
                fr.veto(did, reason, symbol=signal.get("symbol"))
            return None
        symbol = signal["symbol"]
        balance = self.exchange.get_balances().get("USDC", 0.0)

        plan = technical_position_size(balance, signal.get("volatility", 0.01),
                                       signal.get("avg_volume", 0.0))
        social = self._social_factors(symbol)
        size = float(np.asarray(plan.size)) * social["position_size_factor"]
        size = min(size, balance * 0.95)
        if size < self.trading.min_trade_amount:
            if fr is not None:
                fr.veto(did, "risk_min_size", symbol=symbol,
                        detail=f"sized {size:.2f} < "
                               f"{self.trading.min_trade_amount:.2f}")
            return None
        # sizer fractions interpreted as percent (the corrected semantics;
        # see engine.reference_quirks docs), then socially adjusted
        sl_pct = float(np.asarray(plan.stop_loss_pct)) * 100.0 * social["stop_loss_factor"]
        tp_pct = float(np.asarray(plan.take_profit_pct)) * 100.0 * social["take_profit_factor"]
        # Hot-swapped live params take precedence over the volatility sizer's
        # exits: the evolver / generator publish `strategy_params` on the bus
        # (`hot_swap_strategy`, strategy_evolution_service.py:349-362) and
        # the reference executor reads the current strategy at entry time.
        live = self.bus.get("strategy_params") or {}
        if isinstance(live.get("stop_loss"), (int, float)):
            sl_pct = float(live["stop_loss"]) * social["stop_loss_factor"]
        if isinstance(live.get("take_profit"), (int, float)):
            tp_pct = float(live["take_profit"]) * social["take_profit_factor"]

        qty_req = size / signal["current_price"]
        coid = self._next_coid("ent", symbol)
        # entry-signal provenance: journaled with the intent and carried
        # through the trade onto its closure record (PnL attribution)
        source = {"decision_id": did,
                  "family": signal.get("top_family"),
                  "structure_version": signal.get("structure_version"),
                  "model_version": signal.get("model_version")}
        if fr is not None:
            # provenance durable BEFORE the venue can see the order, like
            # the journal intent below — a kill in the placement window
            # must not orphan the venue-side fill from its decision
            fr.execution(did, coid, symbol=symbol, quantity=qty_req,
                         sl_pct=sl_pct, tp_pct=tp_pct)
        # WAL property: the intent is durable BEFORE the order can reach
        # the venue — a crash in the placement window leaves a journaled
        # intent the reconciler resolves by client id (reached? adopt :
        # never arrived? discard), never a silent double-entry hazard.
        self._j("entry_intent", flush=True, symbol=symbol,
                client_order_id=coid, quantity=qty_req, sl_pct=sl_pct,
                tp_pct=tp_pct, coid_seq=self._coid_seq, source=source)
        try:
            order = self.exchange.place_order(symbol, "BUY", "MARKET",
                                              quantity=qty_req,
                                              client_order_id=coid)
        except ExchangeUnavailable:
            # AMBIGUOUS: the request may or may not have reached the venue.
            # Park the intent (blocks re-entry for this symbol) and let
            # resolve_pending_intents() ask the venue by client id once it
            # is reachable again.
            self.pending_intents[coid] = {
                "phase": "entry", "symbol": symbol, "client_order_id": coid,
                "quantity": qty_req, "sl_pct": sl_pct, "tp_pct": tp_pct,
                "source": source}
            self._j("entry_ambiguous", flush=True, symbol=symbol,
                    client_order_id=coid)
            raise
        if order.get("status") != "FILLED":
            self._j("entry_reject", symbol=symbol, client_order_id=coid,
                    status=order.get("status"))
            if fr is not None:
                fr.veto(did, "entry_rejected", symbol=symbol,
                        detail=str(order.get("status")))
            return None
        entry = order["price"]
        qty = order["quantity"]
        if fr is not None:
            fr.fill(coid, entry, qty, symbol=symbol)

        # Register the position BEFORE placing protective orders: if the
        # exchange dies between the fill and the stop placement, the trade
        # must exist on the books (unprotected but managed) rather than be
        # orphaned on the exchange. _ensure_protection retries on every
        # subsequent price update.
        stop_price = entry * (1 - sl_pct / 100.0)
        trade = ActiveTrade(
            symbol=symbol, entry_price=entry, quantity=qty,
            stop_loss_pct=sl_pct, take_profit_pct=tp_pct,
            stop_order_id=None, tp_order_id=None,
            trailing_state=trailing_stop_init(
                entry, stop_price, self.trailing.activation_threshold_pct),
            opened_at=self.now_fn(),
            entry_coid=coid,
            source=source,
        )
        self.active_trades[symbol] = trade
        self._j("entry_ack", flush=True, symbol=symbol, client_order_id=coid,
                order_id=order.get("order_id"), price=entry, quantity=qty,
                sl_pct=sl_pct, tp_pct=tp_pct, opened_at=trade.opened_at,
                stop=stop_price, coid_seq=self._coid_seq, source=source)
        try:
            self._ensure_protection(trade)
        except ExchangeUnavailable:
            pass        # trade stays registered; protection retried later
        self.bus.set("active_trades", {s: vars(t) | {"trailing_state": None}
                                       for s, t in self.active_trades.items()})
        await self.bus.publish("trade_executions", {
            "symbol": symbol, "side": "BUY", "price": entry, "quantity": qty,
            "stop_loss_pct": sl_pct, "take_profit_pct": tp_pct})
        return trade

    def _adopt_unacked_leg(self, trade: ActiveTrade, leg: str) -> bool:
        """A protective placement that raised mid-flight may still have
        landed on the venue.  Before placing AGAIN (double-protection =
        double inventory committed to sells), ask the venue about the last
        journaled client id for this leg."""
        coid = trade.stop_coid if leg == "stop" else trade.tp_coid
        if coid is None:
            return False
        found = self.exchange.find_order_by_client_id(trade.symbol, coid)
        if found is None or found.get("order_id") is None:
            return False
        # never adopt a venue-cancelled/expired leg as live protection;
        # FILLED is adopted so the reconcile pass finalizes off its fill
        if found.get("status") not in ("OPEN", "NEW", "PARTIALLY_FILLED",
                                       "FILLED"):
            return False
        oid = found["order_id"]
        if leg == "stop":
            trade.stop_order_id = oid
        else:
            trade.tp_order_id = oid
        self._j("protect_ack", symbol=trade.symbol, leg=leg, order_id=oid,
                client_order_id=coid, adopted=True)
        return True

    def _place_protective(self, trade: ActiveTrade, leg: str) -> None:
        """Place one missing protective leg, intent-journaled so a crash
        between placement and ack is resolvable by client id."""
        symbol = trade.symbol
        if self._adopt_unacked_leg(trade, leg):
            return
        coid = self._next_coid("stp" if leg == "stop" else "tp", symbol)
        if leg == "stop":
            stop_price = float(np.asarray(trade.trailing_state.stop))
            trade.stop_coid = coid
            self._j("protect_intent", flush=True, symbol=symbol, leg=leg,
                    client_order_id=coid, stop=stop_price,
                    coid_seq=self._coid_seq)
            o = self.exchange.place_order(
                symbol, "SELL", "STOP_LOSS_LIMIT", trade.quantity,
                price=stop_price * 0.999, stop_price=stop_price,
                client_order_id=coid)
            trade.stop_order_id = o.get("order_id")
            self._j("protect_ack", symbol=symbol, leg=leg,
                    order_id=trade.stop_order_id, client_order_id=coid,
                    stop=stop_price)
        else:
            tp_price = trade.entry_price * (1 + trade.take_profit_pct / 100.0)
            trade.tp_coid = coid
            self._j("protect_intent", flush=True, symbol=symbol, leg=leg,
                    client_order_id=coid, price=tp_price,
                    coid_seq=self._coid_seq)
            o = self.exchange.place_order(
                symbol, "SELL", "LIMIT", trade.quantity, price=tp_price,
                client_order_id=coid)
            trade.tp_order_id = o.get("order_id")
            self._j("protect_ack", symbol=symbol, leg=leg,
                    order_id=trade.tp_order_id, client_order_id=coid,
                    price=tp_price)

    def _ensure_protection(self, trade: ActiveTrade) -> None:
        """Place whichever protective orders are missing (initial placement
        and post-outage repair share this path). Raises ExchangeUnavailable
        if the exchange is down; callers decide whether to swallow."""
        if trade.stop_order_id is None:
            self._place_protective(trade, "stop")
        if trade.tp_order_id is None:
            self._place_protective(trade, "tp")

    @staticmethod
    def _protective_orders(trade: ActiveTrade):
        """(order_id, close reason, entry-price factor estimating the fill
        price when no fill record is available) for both protective legs."""
        return ((trade.tp_order_id, "Take Profit",
                 1 + trade.take_profit_pct / 100),
                (trade.stop_order_id, "Stop Loss",
                 1 - trade.stop_loss_pct / 100))

    def _reconcile_protective_fills(self, symbol: str, price: float):
        """Detect server-side fills of the protective SL/TP orders and
        finalize the trade — otherwise a filled TP leaves the trade in
        active_trades and a later trailing trigger double-sells."""
        trade = self.active_trades.get(symbol)
        if trade is None:
            return None
        for oid, reason, px_factor in self._protective_orders(trade):
            if oid is not None and not self.exchange.order_is_open(symbol, oid):
                fill = getattr(self.exchange, "last_fill", lambda _o: None)(oid)
                exit_price = (fill.get("price", trade.entry_price * px_factor)
                              if fill else trade.entry_price * px_factor)
                return (reason, exit_price)
        return None

    async def on_price(self, symbol: str, price: float) -> None:
        """Trailing-stop maintenance (`TrailingStopManager.update_price` +
        stop replacement, :142-333), after reconciling protective fills."""
        trade = self.active_trades.get(symbol)
        if trade is None:
            return
        # Reconcile BEFORE repairing: a protective order may have filled
        # server-side during an outage — repairing first would place sells
        # for inventory that is already gone.
        filled = self._reconcile_protective_fills(symbol, price)
        if filled is not None:
            reason, exit_price = filled
            await self._finalize_filled(symbol, exit_price, reason)
            return
        if trade.stop_order_id is None or trade.tp_order_id is None:
            # repair protection lost to an earlier exchange outage
            self._ensure_protection(trade)
        md = self.bus.get(f"market_data_{symbol}") or {}
        prev_stop = float(np.asarray(trade.trailing_state.stop))
        st, triggered = trailing_stop_update(
            trade.trailing_state, price,
            strategy=self.trailing.strategy,
            trail_percent=self.trailing.trail_percent,
            min_trail_distance_pct=self.trailing.min_trail_distance_pct,
            atr=md.get("atr", 0.0),
            atr_multiplier=self.trailing.atr_multiplier,
            volatility=md.get("volatility", 0.0) * price,
            volatility_multiplier=self.trailing.volatility_multiplier,
            fixed_trail_amount=self.trailing.fixed_trail_amount)
        trade.trailing_state = st
        new_stop = float(np.asarray(st.stop))
        if new_stop > prev_stop and trade.stop_order_id is not None:
            # replace the protective stop order at the ratcheted level;
            # id goes None between cancel and place so a mid-replacement
            # outage is repaired by _ensure_protection, not double-placed
            self.exchange.cancel_order(symbol, trade.stop_order_id)
            self._j("protect_cancel", symbol=symbol, leg="stop",
                    order_id=trade.stop_order_id, reason="trail_ratchet")
            trade.stop_order_id = None
            trade.stop_coid = None         # cancelled leg must not be adopted
            self._place_protective(trade, "stop")
        if bool(triggered):
            await self.close_trade(symbol, price, "Trailing Stop")

    async def _finalize_filled(self, symbol: str, exit_price: float,
                               reason: str) -> None:
        """Close the books on a trade whose protective order already sold
        the position server-side — cancel the sibling order, no re-sell.

        The closure is booked UNCONDITIONALLY: the inventory is already
        gone, so a failing sibling cancel must not abort finalization
        (that leaves the trade popped but unrecorded and the sibling
        resting — an orphan that later fills and sells inventory backing
        a NEWER position; found by the chaos soak).  Un-cancellable
        siblings are parked on ``orphan_orders`` for the per-tick reaper."""
        trade = self.active_trades.pop(symbol, None)
        if trade is None:
            return
        for oid in (trade.stop_order_id, trade.tp_order_id):
            if oid is None:
                continue
            try:
                if self.exchange.order_is_open(symbol, oid):
                    self.exchange.cancel_order(symbol, oid)
                    self._j("protect_cancel", symbol=symbol, order_id=oid,
                            reason="sibling_filled")
            except ExchangeUnavailable:
                self.orphan_orders.append({"symbol": symbol,
                                           "order_id": oid})
                self._j("orphan_order", flush=True, symbol=symbol,
                        order_id=oid)
        pnl = (exit_price - trade.entry_price) * trade.quantity
        record = self._closure_record(trade, exit_price, pnl, reason)
        self.closed_trades.append(record)
        self._j("trade_closed", flush=True, **record)
        await self.bus.publish("trade_closures", record)

    def _closure_record(self, trade: ActiveTrade, exit_price: float,
                        pnl: float, reason: str) -> dict:
        """One closure record, provenance included: entry_coid + source
        complete the flight recorder's signal→order→fill→PnL chain and
        feed PnL attribution — on the live path AND through journal
        replay after a restart."""
        record = {"symbol": trade.symbol, "entry_price": trade.entry_price,
                  "exit_price": exit_price, "quantity": trade.quantity,
                  "pnl": pnl, "reason": reason, "opened_at": trade.opened_at,
                  "closed_at": self.now_fn(),
                  "entry_coid": trade.entry_coid, "source": trade.source}
        if self.flightrec is not None:
            self.flightrec.closure(trade.entry_coid, trade.symbol,
                                   exit_price, pnl, reason)
        return record

    async def close_trade(self, symbol: str, price: float, reason: str) -> None:
        """Pop the trade only AFTER the exit sell succeeds: if the exchange
        dies mid-close the position stays on the books (cancelled
        protective orders are re-placed by _ensure_protection) and the
        close is re-attempted on the next trigger."""
        trade = self.active_trades.get(symbol)
        if trade is None:
            return
        # A protective order that is already not-open BEFORE we cancel it
        # filled server-side — finalize with that fill instead of selling
        # inventory that is no longer held.
        filled = self._reconcile_protective_fills(symbol, price)
        if filled is not None:
            fill_reason, exit_price = filled
            await self._finalize_filled(symbol, exit_price, fill_reason)
            return
        prot = self._protective_orders(trade)
        if trade.stop_order_id is not None:
            self.exchange.cancel_order(symbol, trade.stop_order_id)
            self._j("protect_cancel", symbol=symbol, leg="stop",
                    order_id=trade.stop_order_id, reason="closing")
            trade.stop_order_id = None
            trade.stop_coid = None
        if trade.tp_order_id is not None:
            self.exchange.cancel_order(symbol, trade.tp_order_id)
            self._j("protect_cancel", symbol=symbol, leg="tp",
                    order_id=trade.tp_order_id, reason="closing")
            trade.tp_order_id = None
            trade.tp_coid = None
        coid = self._next_coid("ext", symbol)
        self._j("close_intent", flush=True, symbol=symbol,
                client_order_id=coid, quantity=trade.quantity, reason=reason,
                coid_seq=self._coid_seq)
        try:
            order = self.exchange.place_order(symbol, "SELL", "MARKET",
                                              trade.quantity,
                                              client_order_id=coid)
        except ExchangeUnavailable:
            # ambiguous exit: the sell may have landed — park the intent
            # (inventory state unknown) for client-id resolution; the trade
            # stays on the books so nothing is silently dropped
            self.pending_intents[coid] = {
                "phase": "exit", "symbol": symbol, "client_order_id": coid,
                "quantity": trade.quantity, "reason": reason}
            self._j("close_ambiguous", flush=True, symbol=symbol,
                    client_order_id=coid)
            raise
        if order.get("status") != "FILLED":
            self._j("close_reject", symbol=symbol, client_order_id=coid,
                    status=order.get("status"))
            # Rejected exit. Either a protective order filled in the race
            # window between the reconcile above and our cancels (the ids
            # are cancelled now, so on_price reconciliation can no longer
            # see it — check the fills directly), or the rejection is
            # transient with inventory intact (keep the trade;
            # _ensure_protection re-places the protective orders next tick).
            last_fill = getattr(self.exchange, "last_fill", lambda _o: None)
            for oid, fill_reason, factor in prot:
                fill = last_fill(oid) if oid is not None else None
                if fill is not None:
                    await self._finalize_filled(
                        symbol, fill.get("price",
                                         trade.entry_price * factor),
                        fill_reason)
                    return
            return
        self.active_trades.pop(symbol, None)
        pnl = (price - trade.entry_price) * trade.quantity
        record = self._closure_record(trade, price, pnl, reason)
        self.closed_trades.append(record)
        self._j("trade_closed", flush=True, **record)
        await self.bus.publish("trade_closures", record)

    # --- crash recovery (utils/journal.py) ---------------------------------
    def _trade_dict(self, t: ActiveTrade) -> dict:
        return {"symbol": t.symbol, "entry_price": t.entry_price,
                "quantity": t.quantity, "stop_loss_pct": t.stop_loss_pct,
                "take_profit_pct": t.take_profit_pct,
                "stop_order_id": t.stop_order_id, "tp_order_id": t.tp_order_id,
                "stop": float(np.asarray(t.trailing_state.stop)),
                "opened_at": t.opened_at, "entry_coid": t.entry_coid,
                "stop_coid": t.stop_coid, "tp_coid": t.tp_coid,
                "source": t.source}

    def _trade_from_dict(self, d: dict) -> ActiveTrade:
        entry = float(d["entry_price"])
        stop = float(d.get("stop") or entry * (1 - d["stop_loss_pct"] / 100.0))
        return ActiveTrade(
            symbol=d["symbol"], entry_price=entry,
            quantity=float(d["quantity"]),
            stop_loss_pct=float(d["stop_loss_pct"]),
            take_profit_pct=float(d["take_profit_pct"]),
            stop_order_id=d.get("stop_order_id"),
            tp_order_id=d.get("tp_order_id"),
            # trailing watermark is re-anchored at the journaled stop level
            # (the highest-price watermark itself is not journaled; the
            # ratchet resumes from the last durable stop, never below it)
            trailing_state=trailing_stop_init(
                entry, stop, self.trailing.activation_threshold_pct),
            opened_at=float(d.get("opened_at", 0.0)),
            entry_coid=d.get("entry_coid"), stop_coid=d.get("stop_coid"),
            tp_coid=d.get("tp_coid"), source=d.get("source"))

    def closed_count(self) -> int:
        """Total closed trades over the process LINEAGE (snapshot rotation
        keeps only a tail of per-trade records in memory/journal)."""
        return self._closed_dropped_n + len(self.closed_trades)

    def closed_pnl(self) -> float:
        return self._closed_dropped_pnl + sum(r.get("pnl", 0.0)
                                              for r in self.closed_trades)

    def snapshot_state(self) -> dict:
        """Bounded snapshot: compaction must stay O(live state), not
        O(every trade ever) — only the last SNAPSHOT_CLOSED_TAIL closure
        records are embedded; older ones are rotated into conserved
        aggregates (count + PnL) so the ledger totals survive restarts."""
        tail = self.closed_trades[-self.SNAPSHOT_CLOSED_TAIL:]
        return {"coid_seq": self._coid_seq,
                "active": {s: self._trade_dict(t)
                           for s, t in self.active_trades.items()},
                "closed": list(tail),
                "closed_total_n": self.closed_count(),
                "closed_total_pnl": self.closed_pnl(),
                "pending": dict(self.pending_intents),
                "orphans": list(self.orphan_orders)}

    def _restore_closed(self, snap: dict) -> None:
        self.closed_trades = list(snap.get("closed", []))
        total_n = int(snap.get("closed_total_n", len(self.closed_trades)))
        total_pnl = float(snap.get(
            "closed_total_pnl",
            sum(r.get("pnl", 0.0) for r in self.closed_trades)))
        self._closed_dropped_n = max(total_n - len(self.closed_trades), 0)
        self._closed_dropped_pnl = total_pnl - sum(
            r.get("pnl", 0.0) for r in self.closed_trades)

    def restore_state(self, snap: dict) -> None:
        self._coid_seq = max(self._coid_seq, int(snap.get("coid_seq", 0)))
        self.active_trades = {s: self._trade_from_dict(d)
                              for s, d in snap.get("active", {}).items()}
        self._restore_closed(snap)
        self.pending_intents = dict(snap.get("pending", {}))
        self.orphan_orders = list(snap.get("orphans", []))

    def apply_journal(self, records: list[dict]) -> None:
        """Replay journal records into the in-memory books (pure state
        reconstruction; no exchange calls — reconcile() does those).

        Trades are tracked as raw dicts during the scan and materialized
        (with their JAX trailing-stop state) only for positions still
        OPEN at the end — replay cost stays O(records) host work, not
        O(records) device-array builds (the `recovery_ms` bench row)."""
        active: dict = {s: self._trade_dict(t)
                        for s, t in self.active_trades.items()}
        for rec in records:
            kind, d = rec.get("kind"), rec.get("data", {})
            coid = d.get("client_order_id")
            sym = d.get("symbol")
            trade = active.get(sym)
            if kind == "snapshot":
                self._coid_seq = max(self._coid_seq,
                                     int(d.get("coid_seq", 0)))
                active = {s: dict(t) for s, t in d.get("active", {}).items()}
                self._restore_closed(d)
                self.pending_intents = dict(d.get("pending", {}))
                self.orphan_orders = list(d.get("orphans", []))
            elif kind in ("entry_intent", "entry_ambiguous"):
                if kind == "entry_intent":
                    self.pending_intents[coid] = {"phase": "entry", **d}
            elif kind == "entry_ack":
                self.pending_intents.pop(coid, None)
                active[sym] = {**d, "entry_price": d["price"],
                               "stop_loss_pct": d["sl_pct"],
                               "take_profit_pct": d["tp_pct"],
                               "entry_coid": coid,
                               "stop_order_id": None, "tp_order_id": None}
            elif kind in ("entry_reject", "intent_resolved", "close_reject"):
                self.pending_intents.pop(coid, None)
            elif kind == "protect_intent" and trade is not None:
                trade["stop_coid" if d.get("leg") == "stop"
                      else "tp_coid"] = coid
            elif kind == "protect_ack" and trade is not None:
                if d.get("leg") == "stop":
                    trade["stop_order_id"] = d.get("order_id")
                    trade["stop_coid"] = coid
                    if d.get("stop") is not None:
                        trade["stop"] = float(d["stop"])
                else:
                    trade["tp_order_id"] = d.get("order_id")
                    trade["tp_coid"] = coid
            elif kind == "protect_cancel" and trade is not None:
                if trade.get("stop_order_id") == d.get("order_id"):
                    trade["stop_order_id"] = trade["stop_coid"] = None
                if trade.get("tp_order_id") == d.get("order_id"):
                    trade["tp_order_id"] = trade["tp_coid"] = None
            elif kind in ("close_intent", "close_ambiguous"):
                if kind == "close_intent":
                    self.pending_intents[coid] = {"phase": "exit", **d}
            elif kind == "orphan_order":
                self.orphan_orders.append({"symbol": sym,
                                           "order_id": d.get("order_id")})
            elif kind == "orphan_cancelled":
                self.orphan_orders = [o for o in self.orphan_orders
                                      if o.get("order_id") != d.get("order_id")]
            elif kind == "trade_closed":
                active.pop(sym, None)
                self.closed_trades.append(dict(d))
                # a recorded closure resolves any outstanding exit intent
                for c, i in list(self.pending_intents.items()):
                    if i.get("phase") == "exit" and i.get("symbol") == sym:
                        self.pending_intents.pop(c, None)
            if d.get("coid_seq"):
                self._coid_seq = max(self._coid_seq, int(d["coid_seq"]))
        self.active_trades = {s: self._trade_from_dict(t)
                              for s, t in active.items()}

    def reap_orphans(self) -> int:
        """Retry cancelling parked sibling orders (see _finalize_filled).
        Venue unreachable → keep them parked, never raise (the reaper must
        not turn a cleanup retry into a skipped tick)."""
        reaped = 0
        for o in list(self.orphan_orders):
            try:
                if self.exchange.order_is_open(o["symbol"], o["order_id"]):
                    self.exchange.cancel_order(o["symbol"], o["order_id"])
            except ExchangeUnavailable:
                continue
            self.orphan_orders.remove(o)
            self._j("orphan_cancelled", symbol=o["symbol"],
                    order_id=o["order_id"])
            reaped += 1
        return reaped

    async def resolve_pending_intents(self) -> dict:
        """Ask the venue about every parked ambiguous intent by its
        deterministic client id.  Entry that landed → adopt the position;
        entry that never arrived → discard (re-entry unblocks).  Exit that
        landed → finalize the trade off the real fill; exit that never
        arrived → the trade stays managed.  Raises ExchangeUnavailable if
        the venue still can't answer (intents stay parked)."""
        out = {"adopted": 0, "discarded": 0, "finalized": 0}
        LIVE = ("OPEN", "NEW", "PARTIALLY_FILLED")
        for coid, intent in list(self.pending_intents.items()):
            symbol = intent["symbol"]
            found = self.exchange.find_order_by_client_id(symbol, coid)
            status = (found or {}).get("status")
            executed = float((found or {}).get("executed_qty") or 0.0)
            if found is not None and status in LIVE:
                # the venue holds a LIVE order for this intent — neither
                # adopt nor discard yet; stay parked (entry stays blocked)
                # until it fills or dies
                continue
            if intent.get("phase") == "entry":
                filled_qty = (float(found.get("quantity")
                                    or intent["quantity"])
                              if status == "FILLED" else executed)
                if found is not None and filled_qty > 0.0:
                    entry = self._fill_price(found, symbol)
                    sl = float(intent.get("sl_pct", 2.0))
                    tp = float(intent.get("tp_pct", 4.0))
                    self.active_trades[symbol] = self._trade_from_dict({
                        "symbol": symbol, "entry_price": entry,
                        "quantity": filled_qty, "stop_loss_pct": sl,
                        "take_profit_pct": tp, "opened_at": self.now_fn(),
                        "entry_coid": coid, "source": intent.get("source")})
                    self._j("entry_ack", flush=True, symbol=symbol,
                            client_order_id=coid, price=entry,
                            quantity=filled_qty, sl_pct=sl, tp_pct=tp,
                            opened_at=self.now_fn(),
                            order_id=found.get("order_id"),
                            stop=entry * (1 - sl / 100.0),
                            source=intent.get("source"))
                    if self.flightrec is not None:
                        # the fill that landed while we were down completes
                        # the provenance chain for the recovered entry
                        self.flightrec.fill(coid, entry, filled_qty,
                                            symbol=symbol)
                    out["adopted"] += 1
                else:
                    self._j("intent_resolved", symbol=symbol,
                            client_order_id=coid, resolution="not_placed")
                    if self.flightrec is not None:
                        # the durable decision record says "executed" (it
                        # flushed before placement) but the order never
                        # reached the venue — finalize it as a veto so
                        # replay can't show a phantom execution
                        self.flightrec.veto(
                            (intent.get("source") or {}).get("decision_id"),
                            "entry_rejected", symbol=symbol,
                            detail="intent discarded: order never reached "
                                   "the venue")
                    out["discarded"] += 1
            else:                                           # exit
                trade = self.active_trades.get(symbol)
                fully = (status == "FILLED"
                         or (trade is not None
                             and executed >= trade.quantity * 0.999))
                if found is not None and fully:
                    price = self._fill_price(found, symbol)
                    trade = self.active_trades.pop(symbol, None)
                    if trade is not None:
                        pnl = (price - trade.entry_price) * trade.quantity
                        record = self._closure_record(
                            trade, price, pnl,
                            intent.get("reason", "Recovered Exit"))
                        self.closed_trades.append(record)
                        self._j("trade_closed", flush=True, **record)
                        await self.bus.publish("trade_closures", record)
                    out["finalized"] += 1
                else:
                    # never landed (or died unfilled): the trade stays
                    # managed; protection is repaired by the next tick
                    self._j("intent_resolved", symbol=symbol,
                            client_order_id=coid, resolution="not_placed")
                    out["discarded"] += 1
            self.pending_intents.pop(coid, None)
        return out

    def _fill_price(self, found: dict, symbol: str) -> float:
        """Average fill price of a resolved order, with a last-resort
        market-price estimate: some venues report price=0 on MARKET
        orders, and booking an entry/exit at 0 would poison the trailing
        stop, the TP leg and PnL."""
        price = float(found.get("price") or 0.0)
        if price > 0.0 and np.isfinite(price):
            return price
        return float(self.exchange.get_ticker(symbol)["price"])

    async def reconcile(self) -> dict:
        """Reconcile the in-memory books against exchange ground truth —
        the restart path after apply_journal, and safe to run any time.

        Per active trade × protective leg: live → re-adopt; filled while
        we were down → finalize the position off the fill; missing /
        venue-cancelled → re-place.  Then sweep the venue's open orders
        for protective orphans (our client-id namespace, no parent
        position) and cancel them."""
        report = {"finalized_while_down": 0, "repaired_protection": 0,
                  "orphans_cancelled": 0}
        report.update(await self.resolve_pending_intents())
        report["orphans_cancelled"] += self.reap_orphans()
        for symbol, trade in list(self.active_trades.items()):
            # unacked legs first: adopt whatever actually landed
            for leg in ("stop", "tp"):
                oid = trade.stop_order_id if leg == "stop" else trade.tp_order_id
                if oid is None:
                    self._adopt_unacked_leg(trade, leg)
            # per-leg ground truth via order_state: FILLED (executed qty)
            # closes the position; venue-CANCELLED/EXPIRED must NOT be
            # booked as a fill (that would fabricate an exit) — it is a
            # missing leg to re-place
            closed = None
            for oid, reason, px_factor in self._protective_orders(trade):
                if oid is None:
                    continue
                st = self.exchange.order_state(symbol, oid, trade.quantity)
                if st["is_open"]:
                    continue
                if st["executed_qty"] >= trade.quantity * 0.999:
                    fill = getattr(self.exchange, "last_fill",
                                   lambda _o: None)(oid)
                    exit_price = (fill.get("price",
                                           trade.entry_price * px_factor)
                                  if fill else trade.entry_price * px_factor)
                    closed = (reason, exit_price)
                    break
                # dead leg: clear id + coid so _ensure_protection re-places
                if oid == trade.stop_order_id:
                    trade.stop_order_id = trade.stop_coid = None
                if oid == trade.tp_order_id:
                    trade.tp_order_id = trade.tp_coid = None
            if closed is not None:
                reason, exit_price = closed
                await self._finalize_filled(symbol, exit_price,
                                            f"{reason} (recovered)")
                report["finalized_while_down"] += 1
                continue
            if trade.stop_order_id is None or trade.tp_order_id is None:
                self._ensure_protection(trade)
                report["repaired_protection"] += 1
        # orphan sweep: protective orders in OUR namespace whose parent
        # position is gone (closed while down, or books lost their ack)
        referenced = {oid for t in self.active_trades.values()
                      for oid in (t.stop_order_id, t.tp_order_id)
                      if oid is not None}
        for o in self.exchange.list_open_orders():
            coid = o.get("client_order_id") or ""
            if not coid.startswith(f"{self.coid_prefix}-"):
                continue                   # not ours (grid/DCA/manual)
            if o.get("order_id") in referenced:
                continue
            sym = o.get("symbol")
            if (sym in self.active_trades
                    and coid in (self.active_trades[sym].stop_coid,
                                 self.active_trades[sym].tp_coid)):
                continue                   # adoptable, not an orphan
            self.exchange.cancel_order(sym, o["order_id"])
            self._j("protect_cancel", symbol=sym, order_id=o.get("order_id"),
                    reason="orphan")
            report["orphans_cancelled"] += 1
        self.bus.set("active_trades", {s: vars(t) | {"trailing_state": None}
                                       for s, t in self.active_trades.items()})
        return report

    async def recover_from_journal(self, journal=None) -> dict:
        """Full restart recovery: replay the write-ahead journal into the
        books, reconcile against the exchange, then compact the journal to
        one snapshot so the NEXT restart replays O(live state)."""
        from ai_crypto_trader_tpu.utils import journal as journal_mod

        journal = journal or self.journal
        initial = getattr(journal, "initial_records", None)
        if (initial is not None
                and journal.seq == (initial[-1]["seq"] if initial else 0)):
            # nothing appended since open: the constructor's replay IS the
            # journal content — no second pass over the file
            records, stats = initial, journal.replay_stats
        else:
            records, stats = journal_mod.replay(journal.path)
        journal.initial_records = None     # release; compact() follows anyway
        self.apply_journal(records)
        report = {"journal": stats, "replayed_records": len(records),
                  "active_after_replay": len(self.active_trades)}
        report.update(await self.reconcile())
        journal.compact(self.snapshot_state())
        self._compacted_at = journal.seq
        return report

    def _queue(self):
        # Persistent subscription (see analyzer._queue).
        if not hasattr(self, "_q"):
            channel = ("trading_signals" if self.lane is None
                       else f"trading_signals.{self.lane}")
            self._q = self.bus.subscribe(channel)
        return self._q

    async def run_once(self) -> int:
        """Drain pending trading_signals (test/launcher tick). A signal
        interrupted by an exchange outage is re-queued so the entry is
        retried once the circuit recovers, then the outage propagates to
        the launcher's skip-and-alert path."""
        n = 0
        self.maybe_compact()
        if self.pending_intents:
            # self-heal ambiguous placements as soon as the venue answers
            # again — until resolved, entry for those symbols stays blocked
            await self.resolve_pending_intents()
        if self.orphan_orders:
            self.reap_orphans()
        q = self._queue()
        while not q.empty():
            env = q.get_nowait()
            if (self.lane is not None
                    and env["data"].get("lane") is not None
                    and env["data"]["lane"] != self.lane):
                continue                   # another tenant's decision lane
            try:
                with tracing.consumer_span(
                        env, "executor.handle_signal", service="executor",
                        attributes={"symbol": env["data"].get("symbol")}) as sp:
                    trade = await self.handle_signal(env["data"])
                    if trade:
                        sp.set_attribute("entry_price", trade.entry_price)
                        n += 1
                    else:
                        sp.set_attribute("gated", True)
            except ExchangeUnavailable:
                q.put_nowait(env)
                raise
        return n
